"""Model metadata + serving-store construction.

Every learner stamps its checkpoints with a ``learner`` marker
(store/local.py save, learners/lbfgs.py, learners/bcd.py); older files
are sniffed by their key layout. ``model_meta`` resolves the prefix the
CLI users pass (the sgd learner writes ``<prefix>_part-<rank>``, the
flat learners ``<prefix>.npz``) to an actual file and reports what
produced it — the routing information behind the task=pred error message
(__main__.py) and the task=serve loader below.

``open_serving_store`` is the serving entry: a read-only SlotStore with
a weights-only load (no optimizer state ever touches host RAM, and
``push`` raises — store/local.py read_only).
"""

from __future__ import annotations

import dataclasses
import logging
from dataclasses import dataclass, field
from typing import Optional, Tuple

from ..config import KWArgs, Param
from ..utils import stream

log = logging.getLogger("difacto_tpu")


@dataclass
class ServingShardParam(Param):
    """Mesh knobs of the serving-store open path (docs/serving.md).

    ``serve_mesh_fs > 1`` places the read-only table fs-sharded over a
    (1, serve_mesh_fs) device mesh (parallel/mesh.py) — the serving
    analog of training's ``mesh_fs``: each device holds one contiguous
    key-range shard, so a model bigger than one device's HBM serves
    from N devices. Power of two, must divide ``hash_capacity``, and
    must be threaded through hot-reloads (run_serve passes the same
    kwargs to the ModelReloader so a reload rebuilds the same mesh)."""
    serve_mesh_fs: int = field(default=1, metadata=dict(lo=1))


def store_geometry(param) -> Tuple[int, int, str]:
    """(V_dim, hash_capacity, slot_dtype) — the contract the compiled
    predict programs were traced against (step.py make_predict_fn over
    make_fns(param)); slot_dtype changes the fused-row container dtype
    and width (updaters/sgd_updater.row_layout), so a dtype flip is a
    geometry change. An in-place hot reload (serve/executor.py
    swap_store) requires it unchanged; a mismatch routes through the
    blue/green executor swap (serve/reload.py) instead of a restart."""
    return (param.V_dim, param.hash_capacity, param.slot_dtype)


def resolve_model_path(uri: str) -> str:
    """The actual checkpoint file behind a model prefix: learners append
    ``_part-<rank>`` (sgd, store/local.py) or ``.npz`` (lbfgs/bcd)."""
    for cand in (uri, uri + "_part-0", uri + ".npz", uri + "_part-0.npz"):
        if stream.isfile(cand):
            return cand
    raise FileNotFoundError(f"no model file found for {uri!r} "
                            f"(tried _part-0 / .npz suffixes)")


def model_meta(uri: str) -> dict:
    """{'path', 'learner', 'hashed', 'hash_capacity', 'V_dim', 'save_aux'}
    for a saved model. ``learner`` comes from the checkpoint's own marker
    when present, else from the key layout each learner writes; None when
    the file is not a recognizable difacto model."""
    path = resolve_model_path(uri)
    with stream.load_npz(path) as z:
        files = set(z.files)
        if "learner" in files:
            learner: Optional[str] = str(z["learner"])
        elif "hash_capacity" in files or "keys" in files:
            learner = "sgd"      # SlotStore layouts (store/local.py save)
        elif "lens" in files and "weights" in files:
            learner = "lbfgs"    # learners/lbfgs.py save
        elif "feaids" in files and "w" in files:
            learner = "bcd"      # learners/bcd.py save
        else:
            learner = None
        return {
            "path": path,
            "learner": learner,
            "hashed": "hash_capacity" in files,
            "hash_capacity": (int(z["hash_capacity"])
                              if "hash_capacity" in files else 0),
            "V_dim": int(z["V_dim"]) if "V_dim" in files else 0,
            "save_aux": bool(z["save_aux"]) if "save_aux" in files else False,
            # per-key-range shard count of the save (store/local.py
            # _save_sharded); 1 = single-file table
            "fs_count": int(z["fs_count"]) if "fs_count" in files else 1,
            # storage dtype of the fused slot rows the producing store
            # ran with (ISSUE 19 capacity levers); arrays are always
            # logical f32 — the stamp tells loaders to re-quantize so
            # serving matches the training-time representation
            "slot_dtype": (str(z["slot_dtype"])
                           if "slot_dtype" in files else "fp32"),
        }


def open_serving_store(model_in: str, kwargs: KWArgs = (),
                       fallback: bool = True
                       ) -> Tuple["SlotStore", dict, KWArgs]:
    """Read-only SlotStore loaded weights-only from ``model_in``.

    The store geometry (V_dim, hash_capacity) comes from the checkpoint
    itself, not the config — a serve process points at a model file and
    gets the right table without repeating training knobs. Remaining
    updater keys (V_dtype, l1_shrk, ...) are still consumed from
    ``kwargs`` so the gather-side semantics can be overridden when
    needed. Returns (store, meta, leftover kwargs).

    Every candidate is manifest-verified IN the load itself — the store
    hashes npz members as they stream in (store/local.py load over
    utils/manifest.VerifiedNpz), so a serving load costs one IO pass
    instead of the old verify-then-load double read. When the resolved
    file is corrupt/torn and ``fallback`` is on (serve startup), the
    loader walks the checkpoint family back to the newest generation
    that verifies — a torn final save must not take a replica down when
    a good interval checkpoint sits next to it. ``fallback=False`` (hot
    reload) raises instead: a failed reload keeps the CURRENT in-memory
    model, never silently regresses to an older file."""
    from ..utils import manifest as mft
    from ..utils.manifest import CheckpointCorrupt

    path = resolve_model_path(model_in)
    candidates = [path]
    if fallback:
        candidates += [p for p in mft.generation_paths(path) if p != path]
    last_err: Optional[CheckpointCorrupt] = None
    for cand in candidates:
        try:
            out = _open_verified(cand, kwargs)
        except FileNotFoundError:
            continue
        except CheckpointCorrupt as e:
            log.warning("serving model candidate failed verification: %s", e)
            last_err = e
            continue
        if cand != path:
            log.warning("model %s is corrupt; serving previous verified "
                        "generation %s instead", path, cand)
        return out
    if last_err is None:
        raise FileNotFoundError(path)
    raise last_err


def _open_verified(path: str, kwargs: KWArgs
                   ) -> Tuple["SlotStore", dict, KWArgs]:
    from ..store.local import SlotStore
    from ..updaters.sgd_updater import SGDUpdaterParam

    meta = model_meta(path)
    if meta["learner"] not in (None, "sgd"):
        raise ValueError(
            f"model {path!r} was produced by "
            f"learner={meta['learner']!r}; the serving executor loads sgd "
            "SlotStore checkpoints only — re-train with learner=sgd to "
            "serve this data")
    sparam, kwargs = ServingShardParam.init_allow_unknown(list(kwargs))
    uparam, remain = SGDUpdaterParam.init_allow_unknown(kwargs)
    # geometry comes from the checkpoint: V_dim/hash_capacity always,
    # slot_dtype so a quantized trainer's model serves from the same
    # 8-bit representation (weights-only; the load re-quantizes the
    # logical f32 arrays through build_rows). cold_tier_rows is NEVER
    # adopted: a serving replica holds the full logical table — the
    # tier is a training-side residency optimisation
    uparam = dataclasses.replace(uparam, V_dim=meta["V_dim"],
                                 hash_capacity=meta["hash_capacity"],
                                 slot_dtype=meta["slot_dtype"],
                                 cold_tier_rows=0)
    mesh = None
    if sparam.serve_mesh_fs > 1:
        # fs-sharded serving: the same (dp, fs) mesh machinery as
        # training, dp pinned to 1 — the read-only table splits into
        # contiguous key-range shards and the predict programs pull rows
        # across shards with XLA collectives (any checkpoint layout
        # loads into any serve_mesh_fs, the shard files are just IO)
        from ..parallel import make_mesh
        mesh = make_mesh(dp=1, fs=sparam.serve_mesh_fs)
    store = SlotStore(uparam, read_only=True, mesh=mesh)
    # single-pass verified load: members hash while they stream in
    # (manifest.VerifiedNpz) — no separate verify read
    n = store.load(meta["path"])
    log.info("serving store: %s (%s, V_dim=%d, fs=%d, %d non-empty "
             "entries, weights-only)", meta["path"],
             "hashed" if meta["hashed"] else "dictionary", meta["V_dim"],
             store.fs_count, n)
    return store, meta, remain
