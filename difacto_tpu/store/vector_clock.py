"""Vector clock over per-node counters — the bounded-delay (SSP/BSP) gadget.

Equivalent of the reference's VectorClock (src/store/vector_clock.h:9-58),
which was reserved for the *unimplemented* sync modes of KVStoreDist
(sync_mode/max_delay, LOG(FATAL) "SSP BSP TODO",
src/store/kvstore_dist.h:137-150). Here it is functional and usable by a
multi-host pipeline to bound staleness: each host ticks its clock per
completed step; a host may proceed while ``min() >= my_clock - max_delay``.
"""

from __future__ import annotations

from typing import List


class VectorClock:
    def __init__(self, num_nodes: int):
        if num_nodes <= 0:
            raise ValueError("num_nodes must be positive")
        self._clock: List[int] = [0] * num_nodes

    def update(self, node: int, t: int = -1) -> bool:
        """Advance node's clock (to t, or +1); returns True when the global
        min advanced — the reference's signal that a blocked pull may
        proceed (vector_clock.h:24-43)."""
        old_min = self.min()
        if t < 0:
            self._clock[node] += 1
        else:
            if t < self._clock[node]:
                raise ValueError("clock must be monotone")
            self._clock[node] = t
        return self.min() > old_min

    def min(self) -> int:
        return min(self._clock)

    def max(self) -> int:
        return max(self._clock)

    def get(self, node: int) -> int:
        return self._clock[node]

    def may_proceed(self, node: int, max_delay: int) -> bool:
        """Bounded-staleness check: node may start step clock[node]+1 iff
        the slowest node is within max_delay steps (SSP; 0 = BSP)."""
        return self._clock[node] - self.min() <= max_delay
