from .local import K_FEACOUNT, K_GRADIENT, K_WEIGHT, SlotStore

__all__ = ["SlotStore", "K_FEACOUNT", "K_WEIGHT", "K_GRADIENT"]
