"""Slot store: host feature dictionary + device slot table.

This is the TPU-native "parameter server". The reference's Store
(include/difacto/store.h) routes Push/Pull KV messages to server-side
updaters; here the model lives in device arrays and the host keeps only the
feature-id -> slot mapping:

- ``map_keys(uniq_ids)``: bulk lookup-or-insert of a batch's sorted unique
  (byte-reversed) feature ids -> int32 slot array. This replaces ps-lite's
  key->server-range slicing (kvstore_dist.h:90-118); the "message" is just a
  gather/scatter index vector.
- value-type channels kFeaCount/kWeight/kGradient (include/difacto/store.h:
  33-35) survive as the three jitted entry points apply_count / get_rows(pull)
  / apply_grad(push).
- checkpoint save/load with optional aux state (Updater::Save/Load,
  src/sgd/sgd_updater.h:84-106) and TSV dump (sgd_updater.h:108-139).

Capacity grows by doubling (shape change => one re-jit per doubling,
log2(total/initial) times overall).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..base import FEAID_DTYPE, reverse_bytes
from ..utils import stream
from ..utils import manifest as mft
from ..utils.manifest import CheckpointCorrupt  # noqa: F401 (re-export)
from ..updaters.sgd_updater import (SGDState, SGDUpdaterParam, TRASH_SLOT,
                                    grow_state, init_state, make_fns)

# store value-type channel tags (include/difacto/store.h:33-35)
K_FEACOUNT = 1
K_WEIGHT = 2
K_GRADIENT = 3


def fs_shard_path(path: str, shard: int, count: int) -> str:
    """Per-shard checkpoint member name: ``<path>_fs-<i>-of-<n>``. The
    decoration is stripped by manifest.family_prefix (like ``_iter-k`` /
    ``_part-r``), so shard members prune and generation-walk with their
    family; only the undecorated stub is a load entry point."""
    return f"{path}_fs-{shard}-of-{count}"


def pad_slots_oob(slots: np.ndarray, cap: int, capacity: int) -> np.ndarray:
    """int32[cap]: sorted unique ``slots`` followed by ascending
    out-of-bounds padding (capacity, capacity+1, ...)."""
    out = np.arange(capacity, capacity + cap, dtype=np.int64)
    out[:len(slots)] = slots
    return out.astype(np.int32)


def hash_slots(rev_ids: np.ndarray, hash_capacity: int) -> np.ndarray:
    """Byte-REVERSED uint64 ids -> int32 slots: the hashed store's single
    slot-assignment rule (modulo into [1, capacity); row 0 stays
    TRASH_SLOT). One definition shared by map_keys, the producer fast
    paths (learners/sgd.py) and collision_stats, so the diagnostic can
    never quietly diverge from the table."""
    cap = np.uint64(hash_capacity - 1)
    return (np.asarray(rev_ids, FEAID_DTYPE) % cap
            + np.uint64(1)).astype(np.int32)


def collision_stats(ids: np.ndarray, hash_capacity: int) -> dict:
    """Hashed-store collision accounting for a set of distinct feature ids.

    The reference's distributed SGD keys the model by exact 64-bit id
    (unbounded unordered_maps, src/sgd/sgd_updater.h:141-176) so no two
    features ever alias; the multi-host hashed store trades that for a
    fixed capacity (SURVEY §7 hard part (d)). This quantifies the trade:
    ``collided_frac`` is the fraction of distinct ids that share their
    slot with at least one other id (those features' gradients merge
    permanently). tools/collision_study.py turns this into measured AUC
    at varying load factors.
    """
    ids = np.unique(np.asarray(ids, dtype=FEAID_DTYPE))
    slots = hash_slots(reverse_bytes(ids), hash_capacity)
    n = len(ids)
    # O(n) accounting — a bincount over the table would allocate
    # O(hash_capacity) (2 GB at a 2^28-row table) for any id count
    _, occ = np.unique(slots, return_counts=True)
    n_slots = len(occ)
    collided = n - int((occ == 1).sum())
    return {
        "n_ids": n,
        "hash_capacity": hash_capacity,
        "load_factor": round(n / max(hash_capacity - 1, 1), 4),
        "slots_used": n_slots,
        "collided_frac": round(collided / max(n, 1), 4),
    }


class SlotStore:
    """Single-controller store over one (possibly sharded) slot table.

    With ``mesh`` set, every state array is placed feature-axis-sharded over
    the mesh's ``fs`` axis (parallel/mesh.py) — the TPU analog of ps-lite's
    key-range server sharding. The learner's jit steps then carry matching
    in/out shardings so the table never leaves its layout.
    """

    def __init__(self, param: SGDUpdaterParam,
                 initial_capacity: Optional[int] = None, mesh=None,
                 read_only: bool = False):
        self.param = param
        # the mesh gates fused_kernel backend resolution: the pallas
        # table kernels require an unsharded table (ops/fused.py)
        self.fns = make_fns(param, mesh=mesh)
        self.mesh = mesh
        # read-only stores serve inference (serve/, task=pred): lookups
        # never insert into the dictionary, push/apply paths raise, and
        # load() defaults to a weights-only view that never materializes
        # optimizer state (z/sqrt_g/Vg) on the host
        self.read_only = read_only
        # feature dictionary as parallel sorted arrays (id -> slot); bulk
        # lookup/insert is vectorised via searchsorted + merge — the host-side
        # analog of ps-lite's sorted-key requirement (kvstore_dist.h:95).
        # hash_capacity > 0 replaces the dictionary with stateless modular
        # hashing (deterministic across hosts; SURVEY §7 hashed table).
        self.hashed = param.hash_capacity > 0
        self._keys = np.empty(0, dtype=FEAID_DTYPE)
        self._slots = np.empty(0, dtype=np.int64)
        self._next_slot = TRASH_SLOT + 1
        if initial_capacity is None:
            initial_capacity = param.init_capacity
        cap = param.hash_capacity if self.hashed else initial_capacity
        # host-RAM cold tier (capacity/tier.py): the DEVICE table holds
        # only hash_capacity - cold_tier_rows hot rows; logical slots
        # route through the tier's residency map on every pull/push.
        # Read-only (serving) stores ignore the knob — serving holds the
        # full logical table (serve/model.py forces it to 0 anyway).
        tiered = param.cold_tier_rows > 0 and not read_only
        if tiered:
            if not self.hashed:
                raise ValueError("cold_tier_rows requires the hashed "
                                 "store (hash_capacity > 0): dictionary "
                                 "slots have no fixed logical space to "
                                 "tier over")
            if param.V_dim == 0:
                raise ValueError("cold_tier_rows requires V_dim > 0: the "
                                 "tier moves fused rows, the flat layout "
                                 "has none")
            if mesh is not None:
                raise ValueError("cold_tier_rows is single-device only: "
                                 "tier routing runs on the dispatch "
                                 "thread against an unsharded table (use "
                                 "mesh_fs for sharded capacity, or "
                                 "combine fs with slot_dtype)")
            if param.cold_tier_rows >= cap - 1:
                raise ValueError(
                    f"cold_tier_rows={param.cold_tier_rows} must leave at "
                    f"least 2 hot rows of hash_capacity={cap} (trash row "
                    "+ one working row)")
            cap = cap - param.cold_tier_rows
        if self.fs_count > 1:
            # uneven NamedShardings are a jax error at device_put time —
            # fail at construction with the knob to fix (doubling growth
            # preserves divisibility, so checking the initial capacity
            # covers the dictionary store's whole life)
            from ..parallel import validate_fs_capacity
            validate_fs_capacity(cap, self.fs_count)
        self.state: SGDState = self._place(init_state(param, cap))
        self.tier = None
        if tiered:
            from ..capacity.tier import ColdTier
            self.tier = ColdTier(self)

    @property
    def fs_count(self) -> int:
        """Feature-shard degree: how many contiguous key-range shards
        the table's capacity axis splits into (1 = single device)."""
        from ..parallel import fs_size
        return fs_size(self.mesh)

    def _place(self, state: SGDState) -> SGDState:
        if self.mesh is None:
            return state
        from ..parallel import shard_pytree, state_sharding
        return shard_pytree(state, state_sharding(self.mesh))

    # ------------------------------------------------------------- keys
    @property
    def num_features(self) -> int:
        return len(self._keys)

    @property
    def next_slot(self) -> int:
        """One past the highest assigned slot — deferred-growth callers
        (map_keys(grow=False)) compare this against the device capacity."""
        return self._next_slot

    def map_keys(self, keys: np.ndarray, insert: bool = True,
                 grow: bool = True) -> np.ndarray:
        """Map *unique* uint64 ids -> int32 slots; unknown ids are inserted
        (the reference's operator[] inserts on Get too, sgd_updater.cc:46) or
        mapped to TRASH_SLOT when insert=False. New slots are assigned in the
        input's appearance order.

        ``grow=False`` records the inserted keys but does NOT grow the
        device state — for callers on a lookahead thread (the SPMD control
        plane) that must not swap the table buffers under an in-flight
        step; they call :meth:`grow_to` from the dispatch thread before
        the first step that uses the new slots."""
        if self.read_only:
            # serving lookups must not mutate the dictionary: unknown ids
            # map to TRASH_SLOT (whose row is all-zero, so they contribute
            # nothing to a prediction)
            insert = False
        keys = np.asarray(keys, dtype=FEAID_DTYPE)
        if self.hashed:
            return hash_slots(keys, self.param.hash_capacity)
        n = len(self._keys)
        out = np.full(len(keys), TRASH_SLOT, dtype=np.int32)
        if n:
            idx = np.searchsorted(self._keys, keys)
            safe = np.minimum(idx, n - 1)
            hit = (idx < n) & (self._keys[safe] == keys)
            out[hit] = self._slots[idx[hit]]
        else:
            hit = np.zeros(len(keys), dtype=bool)
        if insert:
            miss = ~hit
            n_new = int(miss.sum())
            if n_new:
                new_keys = keys[miss]
                new_slots = self._next_slot + np.arange(n_new, dtype=np.int64)
                out[miss] = new_slots.astype(np.int32)
                self._next_slot += n_new
                order = np.argsort(new_keys, kind="stable")
                nk, ns = new_keys[order], new_slots[order]
                pos = np.searchsorted(self._keys, nk)
                self._keys = np.insert(self._keys, pos, nk)
                self._slots = np.insert(self._slots, pos, ns)
                if grow:
                    self._ensure_capacity(self._next_slot)
        return out

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        """Slots for known ids, TRASH_SLOT for unknown (no insertion)."""
        return self.map_keys(keys, insert=False)

    def map_keys_dedup(self, keys: np.ndarray,
                       counts: Optional[np.ndarray] = None):
        """map_keys + in-batch collision dedup (hashed mode).

        Returns ``(slots, remap, counts)`` with ``slots`` SORTED unique —
        the device step's scatter/gather kernels declare
        ``indices_are_sorted + unique_indices`` (a measured ~20% step win),
        so this invariant is load-bearing. ``remap`` is None when the raw
        slots already satisfy it; otherwise ``remap[i]`` is the new position
        of input key ``i`` — the caller rewrites its localized COO indices
        through it. In hashed mode distinct ids can also collide into one
        slot within a batch; the same remap merges them, so colliding
        features genuinely alias (their gradients segment-sum into the
        shared row) instead of nondeterministically dropping one update.
        ``counts`` are aggregated the same way.
        """
        slots = self.map_keys(keys)
        n = len(slots)
        if n > 1 and (slots[1:] <= slots[:-1]).any():
            uniq, inv = np.unique(slots, return_inverse=True)
            if counts is not None:
                counts = np.bincount(
                    inv, weights=counts, minlength=len(uniq)
                ).astype(np.float32)
            return uniq.astype(np.int32), inv, counts
        return slots, None, counts

    def capacity_for(self, need: int, current: Optional[int] = None) -> int:
        """The table capacity after growing ``current`` (default: the live
        capacity) to hold ``need`` slots — the single definition of the
        doubling rule, shared with deferred-growth callers (the SPMD
        exchange computes OOB slot padding against the capacity the
        dispatch thread WILL have, so both sites must agree)."""
        cap = self.state.capacity if current is None else current
        while cap < need:
            cap *= 2
        return cap

    def _ensure_capacity(self, need: int) -> None:
        cap = self.capacity_for(need)
        if cap == self.state.capacity:
            return
        self.state = self._place(grow_state(self.param, self.state, cap))

    def grow_to(self, capacity: int) -> None:
        """Grow the device state to exactly ``capacity`` rows (a power-of-two
        multiple of the current capacity, as tracked by a deferred-growth
        caller — see map_keys(grow=False)). No-op when already there."""
        if capacity > self.state.capacity:
            self.state = self._place(grow_state(self.param, self.state,
                                                capacity))

    def pad_slots(self, slots: np.ndarray, cap: int) -> jnp.ndarray:
        """Pad sorted unique slots to ``cap`` with ASCENDING out-of-bounds
        indices — keeps the device kernels' indices_are_sorted +
        unique_indices declarations truthful; OOB lanes gather zeros and
        scatter to nowhere (mode fill/drop)."""
        out = pad_slots_oob(slots, cap, self.state.capacity)
        if self.mesh is not None:
            from ..parallel import put_global, replicated
            return put_global(out, replicated(self.mesh))
        return jnp.asarray(out)

    # ------------------------------------------------------------- KV API
    # Reference-shaped Push/Pull for learners that want the explicit KV
    # contract (L-BFGS/BCD); the SGD hot path fuses these into its jit step.
    def pull(self, keys: np.ndarray) -> Tuple[np.ndarray, Optional[np.ndarray],
                                              Optional[np.ndarray]]:
        # get_rows declares sorted+unique indices, but raw map_keys output is
        # insertion-ordered (dictionary mode) and can repeat (hashed
        # collisions) — dedup to the sorted unique slot set and remap the
        # returned rows back to the caller's key order, mirroring push
        slots_np, remap, _ = self.map_keys_dedup(keys)
        perm = None
        if self.tier is not None:
            # logical slots -> device hot rows (promoting cold rows);
            # gather results come back in routed order, perm maps them
            # to the sorted-slot order the remap step expects
            slots_np, _, perm = self.tier.route(slots_np)
        w, V, vmask = self.fns.get_rows(self.state, jnp.asarray(slots_np))
        w = np.asarray(w)
        V = None if V is None else np.asarray(V)
        vmask = None if vmask is None else np.asarray(vmask)
        if perm is not None:
            w = w[perm]
            V = None if V is None else V[perm]
            vmask = None if vmask is None else vmask[perm]
        if remap is not None:
            w = w[remap]
            V = None if V is None else V[remap]
            vmask = None if vmask is None else vmask[remap]
        return w, V, vmask

    def push(self, keys: np.ndarray, val_type: int,
             gw: np.ndarray, gV: Optional[np.ndarray] = None,
             vmask: Optional[np.ndarray] = None) -> None:
        if self.read_only:
            raise RuntimeError(
                "push on a read-only store: this SlotStore was opened "
                "weights-only for inference (serve/task=pred) and carries "
                "no optimizer state to update")
        slots_np, remap, _ = self.map_keys_dedup(keys)
        if remap is not None:
            # hashed-mode in-batch collisions: sum the colliding values so
            # aliased features accumulate (scatter .set requires unique slots)
            n = len(slots_np)
            gw = np.bincount(remap, weights=np.asarray(gw, np.float64),
                             minlength=n).astype(np.float32)
            if gV is not None:
                agg = np.zeros((n,) + np.asarray(gV).shape[1:],
                               dtype=np.float32)
                np.add.at(agg, remap, np.asarray(gV))
                gV = agg
            if vmask is not None:
                vm = np.zeros(n, dtype=np.float32)
                np.maximum.at(vm, remap, np.asarray(vmask, np.float32))
                vmask = vm
        if self.tier is not None:
            # route to device rows and carry the per-slot values along
            # (order[j] = slot position now at routed position j); a
            # degraded slot (promote fault) lands on an OOB lane whose
            # scatter is dropped — that update is lost, the row is not
            slots_np, order, _ = self.tier.route(slots_np)
            gw = np.asarray(gw)[order]
            if gV is not None:
                gV = np.asarray(gV)[order]
            if vmask is not None:
                vmask = np.asarray(vmask)[order]
        slots = jnp.asarray(slots_np)
        if val_type == K_FEACOUNT:
            self.state = self.fns.apply_count(self.state, slots,
                                              jnp.asarray(gw))
        elif val_type == K_GRADIENT:
            self.state = self.fns.apply_grad(
                self.state, slots, jnp.asarray(gw),
                None if gV is None else jnp.asarray(gV),
                None if vmask is None else jnp.asarray(vmask))
        else:
            raise ValueError(f"unknown val_type {val_type}")

    def evaluate(self) -> Tuple[float, float]:
        penalty, nnz = self.evaluate_dev()
        return float(penalty), float(nnz)

    def evaluate_dev(self):
        """(penalty, nnz) as DEVICE scalars — callers batch the fetch with
        other pending metrics (a sync fetch costs a full RTT on tunneled
        chips, docs/perf_notes.md)."""
        if not hasattr(self, "_eval_jit"):
            from ..utils import jaxtrace
            self._eval_jit = jaxtrace.jit(self.fns.evaluate)
        return self._eval_jit(self.state)

    # ------------------------------------------------------------- ckpt
    def _sorted_items(self) -> Tuple[np.ndarray, np.ndarray]:
        return self._keys, self._slots

    def _state_np(self, state: SGDState,
                  keys: Optional[Tuple[str, ...]] = None) -> dict:
        """Host view with the logical V/Vg split (state stores fused VVg,
        halves padded to v_half lanes; the split slices back to the
        logical V_dim columns so checkpoints/dumps are pad-free and
        layout-independent). Multi-host: the table is fs-sharded within
        each host (dp replicates across hosts), so every piece is locally
        addressable."""
        from ..parallel.multihost import to_local_numpy
        from ..updaters.sgd_updater import (col_V, col_Vg, emb_cols_f32,
                                            quantized, scal_cols)
        # build and fetch ONLY what the caller writes: the device->host
        # link is the cost (~8 MB/s tunneled; a full 4.2M-row V16 state
        # is ~600 MB), a non-aux save/dump never touches z/sqrt_g/Vg,
        # and the V/Vg slices materialize full [capacity, k] copies in
        # HBM if dispatched (the scal unpack is one pass serving all
        # five scalar columns, so it always runs)
        w, zz, sg, cnt, live = scal_cols(self.param, state)
        cols = {"w": w, "z": zz, "sqrt_g": sg, "cnt": cnt, "v_live": live}
        if quantized(self.param):
            # 8-bit rows hold codes, not values: the host view must
            # dequantize through the per-row scale lanes so checkpoints
            # and dumps stay layout-independent logical f32
            if keys is None or "V" in keys or "Vg" in keys:
                Vf, Vgf = emb_cols_f32(self.param, state)
                cols["V"], cols["Vg"] = Vf, Vgf
        else:
            if keys is None or "V" in keys:
                cols["V"] = col_V(self.param, state)
            if keys is None or "Vg" in keys:
                cols["Vg"] = col_Vg(self.param, state)
        if keys is not None:
            cols = {f: cols[f] for f in keys}
        d = {f: to_local_numpy(a) for f, a in cols.items()}
        # bf16 storage (V_dtype) becomes float32 on the host: numpy/npz
        # have no bfloat16
        for f in ("V", "Vg"):
            if f in d:
                d[f] = d[f].astype(np.float32)
        return d

    def _logical_np(self, keys: Optional[Tuple[str, ...]] = None) -> dict:
        """_state_np over the LOGICAL slot space: identical to the device
        view for untiered stores; with a cold tier the [device_rows]
        columns expand to the full [hash_capacity] rows (hot rows at
        their owning slot, demoted rows decoded from their host bytes,
        virgin tail rows with their deterministic V init) — the dense
        view every checkpoint/dump writes, so artifacts never depend on
        the tier's residency at save time."""
        st = self._state_np(self.state, keys=keys)
        if self.tier is not None:
            st = self.tier.logical_cols(st)
        return st

    def maybe_evict(self) -> int:
        """Occupancy-pressure eviction (``evict_occupancy`` knob): when
        the occupied fraction of device rows exceeds the threshold,
        demote the lowest-count occupied rows until occupancy drops to
        0.9x the threshold. With the cold tier on, evicted rows move to
        host RAM and stay fully addressable (a pure capacity lever);
        without it their FTRL/AdaGrad scalars reset to virgin (the V
        codes and quant scales survive, masked by live=False). COLD
        path — epoch boundaries (learners/sgd.py), never the dispatch
        loop. Returns rows evicted; counted into
        ``store_evictions_total``."""
        thr = self.param.evict_occupancy
        if thr <= 0:
            return 0
        st = self._state_np(self.state, keys=("w", "cnt", "v_live"))
        occupied = (st["w"] != 0) | (st["cnt"] != 0)
        if self.param.V_dim > 0:
            occupied |= np.asarray(st["v_live"], bool)
        occupied[TRASH_SLOT] = False
        cap = self.state.capacity
        n_occ = int(occupied.sum())
        if n_occ / max(cap - 1, 1) <= thr:
            return 0
        target = int(0.9 * thr * (cap - 1))
        n_evict = n_occ - target
        rows = np.nonzero(occupied)[0]
        order = np.argsort(st["cnt"][rows], kind="stable")
        victims = np.sort(rows[order[:n_evict]])
        if self.tier is not None:
            n = self.tier.demote_rows(victims)
        else:
            n = self._reset_rows(victims)
        if n:
            from ..obs import REGISTRY
            REGISTRY.counter(
                "store_evictions_total",
                "table rows evicted under occupancy pressure "
                "(evict_occupancy)").inc(n)
        return n

    def _reset_rows(self, victims: np.ndarray) -> int:
        """Reset the FTRL/AdaGrad scalars of the given sorted device
        rows to virgin (w=z=sqrt_g=cnt=0, live=False) — the no-tier
        eviction: the rows stay allocated (the hashed table is dense)
        but stop contributing to predictions and restart their FTRL
        trajectory on next touch. Embedding codes and quant scales are
        left in place; live=False masks them."""
        n = len(victims)
        if n == 0:
            return 0
        from ..updaters.sgd_updater import pack_scal, row_layout, scal_f32
        from ..ops import fused
        if self.param.V_dim == 0:
            vj = jnp.asarray(victims)
            st = self.state
            self.state = self._place(st._replace(
                w=st.w.at[vj].set(0.0), z=st.z.at[vj].set(0.0),
                sqrt_g=st.sqrt_g.at[vj].set(0.0),
                cnt=st.cnt.at[vj].set(0.0),
                v_live=st.v_live.at[vj].set(False)))
            return n
        _, _, _, off = row_layout(self.param, self.state.capacity)
        from ..ops.batch import bucket
        pad = pad_slots_oob(victims.astype(np.int32), bucket(n),
                            self.state.capacity)
        sl = jnp.asarray(pad)
        rows = fused.gather_rows(self.state.VVg, sl)
        f = scal_f32(rows[:, off:])
        zero = jnp.zeros(rows.shape[0], jnp.float32)
        scal = pack_scal(zero, zero, zero, zero,
                         jnp.zeros(rows.shape[0], bool), rows.dtype,
                         scale_V=f[:, 5], scale_Vg=f[:, 6])
        out = jnp.concatenate([rows[:, :off], scal], axis=1)
        self.state = self._place(self.state._replace(
            VVg=fused.scatter_rows(self.state.VVg, sl, out)))
        return n

    # --------------------------------------------------- WAL row surgery
    def wal_geometry(self) -> dict:
        """The geometry stamp every WAL segment carries and replay
        validates before applying (durability/wal.py): a delta logged
        against a different capacity / layout / quantization must stop
        replay typed, never scatter into the wrong rows."""
        return {"hash_capacity": int(self.param.hash_capacity),
                "capacity": int(self.state.capacity),
                "V_dim": int(self.param.V_dim),
                "slot_dtype": self.param.slot_dtype,
                "row_width": int(self.state.VVg.shape[1])}

    def wal_touched_rows(self, slots: np.ndarray) -> dict:
        """Host copies of the given device rows EXACTLY as the table
        stores them — fused VVg CONTAINER rows for V_dim > 0 (so a
        quantized ``slot_dtype`` table logs container bytes and replay
        is bit-exact with no dequantize round-trip), or the five flat
        columns of the V_dim = 0 layout. The WAL's append-side read;
        one small host gather per flush window, off the jit step."""
        slots = np.asarray(slots, dtype=np.int32)
        n = len(slots)
        if n == 0:
            return {}
        if self.param.V_dim == 0:
            sl = jnp.asarray(slots)
            st = self.state
            return {k: np.asarray(getattr(st, k)[sl])
                    for k in ("w", "z", "sqrt_g", "cnt", "v_live")}
        from ..ops import fused
        from ..ops.batch import bucket
        pad = pad_slots_oob(slots, bucket(n), self.state.capacity)
        rows = fused.gather_rows(self.state.VVg, jnp.asarray(pad))
        return {"VVg": np.asarray(rows[:n])}

    def apply_wal_rows(self, slots: np.ndarray, arrays: dict) -> int:
        """Scatter replayed WAL rows back into the table — the inverse
        of :meth:`wal_touched_rows`, byte-exact by construction (the
        logged container/column bytes land unchanged). Replay-path only
        (durability/recover.py), never concurrent with dispatch."""
        slots = np.asarray(slots, dtype=np.int32)
        n = len(slots)
        if n == 0:
            return 0
        st = self.state
        if self.param.V_dim == 0:
            cols = ("w", "z", "sqrt_g", "cnt", "v_live")
            for k in cols:
                if len(arrays[k]) != n:
                    raise ValueError(
                        f"WAL column {k!r} has {len(arrays[k])} rows "
                        f"for {n} slots")
            sl = jnp.asarray(slots)
            self.state = self._place(st._replace(**{
                k: getattr(st, k).at[sl].set(
                    jnp.asarray(np.asarray(arrays[k]).astype(
                        getattr(st, k).dtype)))
                for k in cols}))
            return n
        from ..ops import fused
        from ..ops.batch import bucket
        width = st.VVg.shape[1]
        rows = np.asarray(arrays["VVg"]).reshape(n, width)
        if rows.dtype != st.VVg.dtype:
            raise ValueError(
                f"WAL rows are {rows.dtype} but the table stores "
                f"{st.VVg.dtype}: geometry mismatch")
        pad = pad_slots_oob(slots, bucket(n), st.capacity)
        full = np.zeros((len(pad), width), dtype=rows.dtype)
        full[:n] = rows
        self.state = self._place(st._replace(
            VVg=fused.scatter_rows(st.VVg, jnp.asarray(pad),
                                   jnp.asarray(full))))
        return n

    def capacity_stats(self) -> dict:
        """Effective-capacity accounting of the three levers
        (bench.py --capacity; docs/perf_notes.md "Table capacity"):
        logical addressable rows vs what an fp32/no-tier table of the
        SAME per-device byte budget would hold."""
        import dataclasses
        from ..updaters.sgd_updater import state_bytes
        dev_rows = self.state.capacity
        logical = self.param.hash_capacity if self.hashed else dev_rows
        fs = self.fs_count
        bytes_total = state_bytes(self.param, dev_rows)
        base = dataclasses.replace(self.param, slot_dtype="fp32",
                                   V_dtype="float32", cold_tier_rows=0)
        base_bpr = state_bytes(base, dev_rows) / max(dev_rows, 1)
        baseline_rows = bytes_total / max(base_bpr, 1e-9)
        out = {
            "slot_dtype": self.param.slot_dtype,
            "logical_rows": logical,
            "device_rows": dev_rows,
            "table_bytes_per_device": bytes_total // fs,
            "effective_rows_per_device": logical // fs,
            "capacity_multiplier": round(logical / max(baseline_rows,
                                                       1e-9), 3),
        }
        if self.tier is not None:
            out["tier"] = self.tier.stats()
        return out

    def _assemble_state(self, arr: dict, capacity: int) -> SGDState:
        """Inverse of _state_np: dict with logical-width V/Vg -> SGDState
        with the (possibly lane-padded) fused VVg. ``capacity`` is the
        LIVE table capacity the state is being assembled for — the
        pad_v_rows layout decision must match the table that will train,
        not the artifact's row count (a partial/sharded save with fewer
        rows would otherwise silently re-enable padding on a table that
        runs unpadded for memory reasons, round-4 advisor finding)."""
        from ..updaters.sgd_updater import build_rows
        V = np.asarray(arr.pop("V"), dtype=np.float32)
        Vg = np.asarray(arr.pop("Vg"), dtype=np.float32)
        if V.shape[0] != capacity:
            raise ValueError(
                f"checkpoint arrays have {V.shape[0]} rows but the table "
                f"capacity is {capacity}: partial-state loads are not "
                "supported (the v_half layout decision would diverge)")
        if self.param.V_dim == 0:
            return SGDState(VVg=jnp.zeros((capacity, 0), jnp.float32),
                            **{f: jnp.asarray(a) for f, a in arr.items()})
        T = build_rows(self.param, capacity, V, Vg, arr["w"], arr["z"],
                       arr["sqrt_g"], arr["cnt"], arr["v_live"])
        empty = jnp.zeros(0, jnp.float32)
        return SGDState(w=empty, z=empty + 0, sqrt_g=empty + 0,
                        cnt=empty + 0, VVg=T,
                        v_live=jnp.zeros(0, dtype=bool))

    def save(self, path: str, save_aux: bool = False,
             epoch: Optional[int] = None, keep: int = 0,
             shards: Optional[int] = None) -> int:
        """Checkpoint non-empty entries, sorted by key. Hashed mode has no
        id dictionary — the full dense table is saved instead.

        Every save leaves a ``<path>.manifest.json`` sidecar (per-array
        sha256, row count, learner, epoch, monotonically increasing
        generation; utils/manifest.py) written AFTER the npz finalizes —
        the commit marker a torn write can't fake. ``keep > 0`` retires
        interval (``_iter-k``) checkpoints of this family older than the
        newest ``keep`` epochs; the final undecorated model is never
        pruned.

        ``shards`` (default: the mesh's fs degree) splits a HASHED
        table's dense arrays into per-key-range member files
        ``<path>_fs-<i>-of-<n>`` — one per fs shard, each with its own
        verifying manifest — plus an array-free stub at ``<path>``
        written LAST as the generation's commit marker. An fs-sharded
        table bigger than one device's HBM round-trips through these
        without the artifact ever pretending to be a one-device array,
        and a corrupt shard fails typed so loaders walk back a
        generation (load below, serve/model.py)."""
        saved = ("w", "cnt", "v_live", "V") + (
            ("z", "sqrt_g", "Vg") if save_aux else ())
        if shards is None:
            shards = self.fs_count if self.hashed else 1
        if self.hashed and shards > 1:
            return self._save_sharded(path, saved, save_aux, epoch, keep,
                                      shards)
        if self.hashed:
            # logical view: a tiered store saves the full
            # [hash_capacity]-row table (hot + host-RAM rows), so the
            # artifact is residency-independent. slot_dtype /
            # cold_tier_rows stamps travel for loaders (serve/model.py
            # adopts the quantization, never the tier — serving holds
            # the whole table); arrays are ALWAYS logical f32
            st = self._logical_np(keys=saved)
            arrays = dict(hash_capacity=np.array(self.param.hash_capacity),
                          V_dim=np.array(self.param.V_dim),
                          save_aux=np.array(save_aux),
                          learner=np.array("sgd"),
                          slot_dtype=np.array(self.param.slot_dtype),
                          cold_tier_rows=np.array(
                              self.param.cold_tier_rows),
                          **{k: st[k] for k in saved})
            n = int((st["w"] != 0).sum())
        else:
            keys, slots = self._sorted_items()
            st = self._state_np(self.state, keys=saved)
            live = (st["w"][slots] != 0) | (st["cnt"][slots] != 0)
            if self.param.V_dim > 0:
                live |= st["v_live"][slots]
            keys, slots = keys[live], slots[live]
            arrays = dict(
                keys=keys,
                w=st["w"][slots],
                cnt=st["cnt"][slots],
                v_live=st["v_live"][slots],
                V=st["V"][slots],
                save_aux=np.array(save_aux),
                V_dim=np.array(self.param.V_dim),
                learner=np.array("sgd"),
                slot_dtype=np.array(self.param.slot_dtype),
            )
            if save_aux:
                arrays.update(z=st["z"][slots], sqrt_g=st["sqrt_g"][slots],
                              Vg=st["Vg"][slots])
            n = len(keys)
        man = {"learner": "sgd", "rows": n, "save_aux": bool(save_aux),
               "generation": mft.next_generation(path)}
        if epoch is not None:
            man["epoch"] = int(epoch)
        # uncompressed: a trained 4.2M-row V16 state is ~300 MB and
        # np.savez_compressed writes it at ~6 MB/s — ~50 s added to
        # every epoch checkpoint (the rec data cache dropped zlib
        # for the same reason, docs/perf_notes.md streamed regime)
        stream.save_npz(path, compress=False, manifest=man,
                        fault_point="ckpt.write", **arrays)
        if keep > 0:
            import re
            m = re.search(r"_part-(\d+)", path)
            mft.prune_checkpoints(path, keep,
                                  rank=int(m.group(1)) if m else None)
        return n

    def _save_sharded(self, path: str, saved, save_aux: bool,
                      epoch: Optional[int], keep: int, shards: int) -> int:
        """Per-key-range checkpoint of the hashed table (see save):
        shard files carry rows [lo, hi) of every column plus their own
        geometry stamp; the stub closes the generation."""
        from ..parallel import fs_shard_bounds
        cap = self.param.hash_capacity
        bounds = fs_shard_bounds(cap, shards)
        st = self._logical_np(keys=saved)
        gen = mft.next_generation(path)
        n = int((st["w"] != 0).sum())
        geom = dict(hash_capacity=np.array(cap),
                    V_dim=np.array(self.param.V_dim),
                    save_aux=np.array(save_aux),
                    learner=np.array("sgd"),
                    slot_dtype=np.array(self.param.slot_dtype),
                    cold_tier_rows=np.array(self.param.cold_tier_rows),
                    fs_count=np.array(shards))
        for i, (lo, hi) in enumerate(bounds):
            man = {"learner": "sgd",
                   "rows": int((st["w"][lo:hi] != 0).sum()),
                   "save_aux": bool(save_aux), "generation": gen,
                   "fs_shard": i, "fs_count": shards}
            if epoch is not None:
                man["epoch"] = int(epoch)
            stream.save_npz(
                fs_shard_path(path, i, shards), compress=False,
                manifest=man, fault_point="ckpt.write",
                row_lo=np.array(lo), row_hi=np.array(hi), **geom,
                **{k: st[k][lo:hi] for k in saved})
        # array-free stub LAST: its manifest is the generation's commit
        # marker — a save torn between shard files leaves no stub
        # manifest, so the generation reads as incomplete, never as a
        # half-written table
        man = {"learner": "sgd", "rows": n, "save_aux": bool(save_aux),
               "generation": gen, "fs_count": shards}
        if epoch is not None:
            man["epoch"] = int(epoch)
        stream.save_npz(path, compress=False, manifest=man,
                        fault_point="ckpt.write", **geom)
        if keep > 0:
            import re
            m = re.search(r"_part-(\d+)", path)
            mft.prune_checkpoints(path, keep,
                                  rank=int(m.group(1)) if m else None)
        return n

    def load(self, path: str, weights_only: Optional[bool] = None,
             verify: bool = True, require_manifest: bool = False) -> int:
        """Restore a checkpoint. ``weights_only`` (default: the store's
        read_only flag) loads just what inference reads — w / cnt /
        v_live / V — and never materializes optimizer state (z, sqrt_g,
        Vg) on the host even when the checkpoint carries it: aux columns
        are stride-0 zero views, so a serving process pays no host RAM
        for state it will never update.

        ``verify`` (default on) raises a typed
        :class:`CheckpointCorrupt` on truncation / digest mismatch
        instead of crashing in numpy — in ONE IO pass: members hash as
        they decompress for the load and the few the load skips are
        swept before any state commits (utils/manifest.py VerifiedNpz —
        the old separate verify pass read every byte twice).
        ``verify=False`` skips digesting for callers that already
        verified the exact file. ``require_manifest`` additionally
        treats a missing sidecar as corruption — the contract for files
        this codebase wrote (auto_resume candidates always have one)."""
        if weights_only is None:
            weights_only = self.read_only
        loaded = (("w", "cnt", "v_live", "V") if weights_only
                  else ("w", "cnt", "v_live", "V", "z", "sqrt_g", "Vg"))

        def _aux(shape):
            # stride-0 zeros: a weights-only load allocates no aux memory
            return np.broadcast_to(np.float32(0.0), shape)

        ctx = (mft.open_verified(path, require_manifest=require_manifest,
                                 fault_point="ckpt.read") if verify
               else stream.load_npz(path, fault_point="ckpt.read"))
        # digest sweep of manifest members the load never touched; runs
        # BEFORE state commits so a corrupt file can't leave a half-
        # loaded store behind (plain npz ctx: nothing to sweep)
        fin = getattr(ctx, "finish", lambda: None)
        with ctx as z:
            if self.hashed != ("hash_capacity" in z.files):
                raise ValueError(
                    "checkpoint store mode mismatch: "
                    f"checkpoint is {'hashed' if not self.hashed else 'a dictionary model'}, "
                    f"store is {'hashed' if self.hashed else 'dictionary-based'}")
            if "hash_capacity" in z.files:
                if int(z["hash_capacity"]) != self.param.hash_capacity:
                    raise ValueError("hashed checkpoint needs a store with "
                                     "the same hash_capacity")
                ck_vdim = int(z["V_dim"]) if "V_dim" in z.files else 0
                if ck_vdim != self.param.V_dim:
                    raise ValueError(
                        f"checkpoint V_dim={ck_vdim} != configured "
                        f"V_dim={self.param.V_dim} ({path})")
                if "fs_count" in z.files and "w" not in z.files:
                    # per-key-range stub (save shards > 1): the table
                    # lives in <path>_fs-<i>-of-<n> members — sweep the
                    # stub's digests, then assemble from the shards
                    fin()
                    return self._load_sharded(
                        path, int(z["fs_count"]), loaded, weights_only,
                        verify)
                # host-side zeros template — no device round trip: every
                # key the checkpoint carries overwrites it in full, and
                # the aux keys a non-aux checkpoint omits (z, sqrt_g, Vg)
                # are zero at init anyway. (The dictionary load below
                # keeps the device init_state template: its rows beyond
                # the checkpoint retain their random V init.)
                cap, k_dim = self.param.hash_capacity, self.param.V_dim
                az = _aux if weights_only else \
                    (lambda s: np.zeros(s, np.float32))
                arr = {"w": np.zeros(cap, np.float32),
                       "z": az(cap),
                       "sqrt_g": az(cap),
                       "cnt": np.zeros(cap, np.float32),
                       "v_live": np.zeros(cap, bool),
                       "V": np.zeros((cap, k_dim), np.float32),
                       "Vg": az((cap, k_dim))}
                for k in loaded:
                    if k in z.files:
                        arr[k] = z[k]
                nnz = int((np.asarray(arr["w"]) != 0).sum())
                fin()
                self._commit_hashed(arr)
                return nnz
            ck_vdim = int(z["V_dim"]) if "V_dim" in z.files else 0
            if ck_vdim != self.param.V_dim:
                raise ValueError(
                    f"checkpoint V_dim={ck_vdim} != configured "
                    f"V_dim={self.param.V_dim} ({path})")
            keys = np.asarray(z["keys"], dtype=FEAID_DTYPE)  # saved sorted
            n = len(keys)
            cap = self.state.capacity
            while cap < n + 1:
                cap *= 2
            st = init_state(self.param, cap)
            if weights_only:
                arr = {f: a.copy() for f, a in self._state_np(
                    st, keys=("w", "cnt", "v_live", "V")).items()}
                arr["z"] = _aux((cap,))
                arr["sqrt_g"] = _aux((cap,))
                arr["Vg"] = _aux(arr["V"].shape)
            else:
                arr = {f: a.copy() for f, a in self._state_np(st).items()}
            sl = np.arange(1, n + 1)
            arr["w"][sl] = z["w"]
            arr["cnt"][sl] = z["cnt"]
            arr["v_live"][sl] = z["v_live"]
            if z["V"].size:
                arr["V"][sl] = z["V"]
            if not weights_only and "z" in z.files:
                arr["z"][sl] = z["z"]
                arr["sqrt_g"][sl] = z["sqrt_g"]
                if z["Vg"].size:
                    arr["Vg"][sl] = z["Vg"]
            fin()
            # commit only after the digest sweep: the host dictionary and
            # device state move together or not at all
            self.state = self._place(self._assemble_state(arr, cap))
            self._keys = keys
            self._slots = np.arange(1, n + 1, dtype=np.int64)
            self._next_slot = n + 1
        return n

    def _commit_hashed(self, arr: dict) -> None:
        """Commit loaded LOGICAL hashed-table columns [hash_capacity
        rows]: untiered stores assemble the full table on device; a
        tiered store splits at its device capacity — the hot prefix
        becomes device state (residency resets to the identity prefix)
        and the tail re-seeds the host tier (capacity/tier.load_cold).
        Checkpoints therefore round-trip across tier configurations:
        tiered saves load into untiered stores and vice versa."""
        if self.tier is None:
            self.state = self._place(self._assemble_state(
                arr, self.param.hash_capacity))
            return
        dev_cap = self.tier.D
        dev = {k: np.asarray(a)[:dev_cap] for k, a in arr.items()}
        self.state = self._place(self._assemble_state(dev, dev_cap))
        self.tier.load_cold(arr)

    def _load_sharded(self, path: str, fs_count: int, loaded,
                      weights_only: bool, verify: bool) -> int:
        """Assemble the hashed table from its per-key-range shard files
        (save shards > 1). Every shard is digest-verified BEFORE any
        state commits; a missing or mismatched member raises the typed
        :class:`CheckpointCorrupt` so loaders (auto_resume, task=serve)
        walk back to the previous verified generation instead of
        serving a half-assembled table. The assembled host columns are
        placed back through ``_place`` — per-shard slices land straight
        on their owning devices (parallel/mesh.py put_global), so the
        round trip never builds a one-device global array."""
        cap, k_dim = self.param.hash_capacity, self.param.V_dim
        from ..parallel import fs_shard_bounds
        try:
            bounds = fs_shard_bounds(cap, fs_count)
        except ValueError as e:
            raise CheckpointCorrupt(path, str(e)) from e

        def _aux(shape):
            return np.broadcast_to(np.float32(0.0), shape)

        az = _aux if weights_only else (lambda s: np.zeros(s, np.float32))
        arr = {"w": np.zeros(cap, np.float32),
               "z": az(cap),
               "sqrt_g": az(cap),
               "cnt": np.zeros(cap, np.float32),
               "v_live": np.zeros(cap, bool),
               "V": np.zeros((cap, k_dim), np.float32),
               "Vg": az((cap, k_dim))}
        for i, (lo, hi) in enumerate(bounds):
            sp = fs_shard_path(path, i, fs_count)
            try:
                # shard members are always this codebase's writes: the
                # stub declared fs_count, so a manifest-less shard is a
                # torn save, not a legacy file
                sctx = (mft.open_verified(sp, require_manifest=True,
                                          fault_point="ckpt.read")
                        if verify
                        else stream.load_npz(sp, fault_point="ckpt.read"))
            except FileNotFoundError as e:
                raise CheckpointCorrupt(
                    path, f"shard member {sp!r} is missing (torn or "
                          f"partially pruned {fs_count}-shard save)") \
                    from e
            sfin = getattr(sctx, "finish", lambda: None)
            with sctx as sz:
                if (int(sz["hash_capacity"]) != cap
                        or int(sz["fs_count"]) != fs_count
                        or int(sz["row_lo"]) != lo
                        or int(sz["row_hi"]) != hi):
                    raise CheckpointCorrupt(
                        sp, f"shard geometry disagrees with its stub "
                            f"(expected rows [{lo}, {hi}) of {cap} over "
                            f"{fs_count} shards)")
                for k in loaded:
                    if k in sz.files:
                        a = sz[k]
                        if np.asarray(a).shape[0] != hi - lo:
                            raise CheckpointCorrupt(
                                sp, f"array {k!r} has "
                                    f"{np.asarray(a).shape[0]} rows, "
                                    f"shard owns {hi - lo}")
                        arr[k][lo:hi] = a
                sfin()
        nnz = int((arr["w"] != 0).sum())
        self._commit_hashed(arr)
        return nnz

    def shard_stats(self) -> list:
        """Per-key-range shard occupancy: [{shard, row_lo, row_hi, rows,
        occupancy, table_bytes}] — ``rows`` counts non-zero-w slots in
        the shard's range, ``table_bytes`` is the per-device HBM the
        shard pins (updaters.state_bytes / fs). COLD path: reads the
        full w column to the host — epoch boundaries, bench legs and
        stats endpoints, never the dispatch loop."""
        from ..updaters.sgd_updater import state_bytes
        from ..parallel import fs_shard_bounds
        st = self._state_np(self.state, keys=("w",))
        fs = self.fs_count
        bounds = fs_shard_bounds(self.state.capacity, fs)
        per_dev = state_bytes(self.param, self.state.capacity) // fs
        out = []
        for i, (lo, hi) in enumerate(bounds):
            rows = int((st["w"][lo:hi] != 0).sum())
            out.append({"shard": i, "row_lo": lo, "row_hi": hi,
                        "rows": rows,
                        "occupancy": round(rows / max(hi - lo, 1), 6),
                        "table_bytes": per_dev})
        return out

    def publish_shard_stats(self) -> list:
        """shard_stats() pushed into the global metric registry
        (``store_shard_rows`` / ``store_shard_occupancy`` gauges,
        docs/observability.md) — called from cold paths only (see
        shard_stats)."""
        from ..obs import gauge
        stats = self.shard_stats()
        rows_g = gauge("store_shard_rows",
                       "non-empty slot-table rows per fs key-range shard")
        occ_g = gauge("store_shard_occupancy",
                      "filled fraction of each fs key-range shard")
        for s in stats:
            rows_g.labels(shard=str(s["shard"])).set(s["rows"])
            occ_g.labels(shard=str(s["shard"])).set(s["occupancy"])
        return stats

    def dump(self, path: str, dump_aux: bool = False,
             need_reverse: bool = True) -> int:
        """Human-readable TSV export (Updater::Dump, sgd_updater.h:108-139):
        ``feaid size w [sqrt_g z] V... [Vg...]`` per line, skipping empty
        entries. need_reverse un-reverses ids back to the original space.
        Hashed mode has no id dictionary: the first column is the slot id
        and need_reverse is ignored."""
        st = self._logical_np(keys=("w", "v_live", "V") + (
            ("sqrt_g", "z", "Vg") if dump_aux else ()))
        if self.hashed:
            keep = st["w"] != 0
            if self.param.V_dim > 0:  # keep l1-shrunk rows with live V
                keep |= st["v_live"]
            keep[TRASH_SLOT] = False
            slots = np.nonzero(keep)[0]
            keys = slots.astype(FEAID_DTYPE)
            need_reverse = False
        else:
            keys, slots = self._sorted_items()
        n = 0
        with stream.open_stream(path, "w") as f:
            for k, s in zip(keys, slots):
                w = st["w"][s]
                live = bool(st["v_live"][s]) and self.param.V_dim > 0
                if w == 0 and not live:
                    continue
                key = reverse_bytes(int(k)) if need_reverse else int(k)
                size = 1 + (self.param.V_dim if live else 0)
                cols = [str(key), str(size), repr(float(w))]
                if dump_aux:
                    cols += [repr(float(st["sqrt_g"][s])),
                             repr(float(st["z"][s]))]
                if live:
                    cols += [repr(float(v)) for v in st["V"][s]]
                    if dump_aux:
                        cols += [repr(float(v)) for v in st["Vg"][s]]
                f.write("\t".join(cols) + "\n")
                n += 1
        return n
