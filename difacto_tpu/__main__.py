"""CLI entry: ``python -m difacto_tpu config_file key1=val1 key2=val2 ...``

Equivalent of the reference binary's main (src/main.cc:54-90): parse the
config file + CLI overrides into KWArgs, dispatch on ``task``:

- ``train`` (default) — build the learner named by ``learner`` (default sgd),
  init with the remaining kwargs, run.
- ``pred`` — prediction with a saved model (routes to the learner's predict
  task, main.cc:70-77 sets task=pred and requires model_in).
- ``dump`` — binary model -> readable TSV (src/reader/dump.h).
- ``convert`` — data format conversion (src/reader/converter.h).
- ``serve`` — online inference server over a saved model (serve/: dynamic
  micro-batching over the bucketed predict executor; no reference analog —
  the WSDM'16 system trained the models its production stack served).
- ``online`` — continuous learning: tail a serve-fleet training log,
  checkpoint on a wall-clock cadence, push each generation to the fleet
  (online/: the serve→log→train→reload loop, docs/serving.md).

Unknown leftover keys warn, as in main.cc:40-46.
"""

from __future__ import annotations

import logging
import os
import sys
from dataclasses import dataclass, field

from .config import KWArgs, Param, parse_cli_args, warn_unknown
from .learners import Learner

log = logging.getLogger("difacto_tpu")


@dataclass
class DifactoParam(Param):
    task: str = field(default="train", metadata=dict(
        enum=["train", "dump", "pred", "convert", "serve", "online"]))
    learner: str = "sgd"


def _pred_routing_error(learner: str, kwargs: KWArgs) -> ValueError:
    """task=pred with a non-sgd learner: name the learner that actually
    produced model_in (from the checkpoint's own meta) and route the user
    at the tasks that exist, instead of the bare 'only supported by sgd'
    dead end."""
    model_in = next((v for k, v in reversed(kwargs) if k == "model_in"), "")
    produced = ""
    if model_in:
        try:
            from .serve.model import model_meta
            meta = model_meta(model_in)
            if meta["learner"]:
                produced = (f"; model_in={model_in!r} was produced by "
                            f"learner={meta['learner']!r}")
        except Exception as e:  # unreadable/missing model: keep the
            # base message, but leave a trace for whoever debugs it
            log.debug("model meta unreadable for %s: %s", model_in, e)
    return ValueError(
        f"task=pred runs the bucketed sgd predict executor and is not "
        f"implemented by learner={learner!r}{produced}. Batch-score sgd "
        f"models with learner=sgd, or use task=serve for online scoring "
        f"(docs/serving.md)")


@dataclass
class DumpParam(Param):
    """src/reader/dump.h:12-31."""
    updater: str = "sgd"
    model_in: str = ""
    name_dump: str = "dump.txt"
    need_reverse: bool = False
    dump_aux: bool = False


def run_dump(kwargs: KWArgs) -> KWArgs:
    from .store.local import SlotStore
    from .updaters.sgd_updater import SGDUpdaterParam

    param, remain = DumpParam.init_allow_unknown(kwargs)
    if not param.model_in:
        raise ValueError("please set model_in")
    if param.updater != "sgd":
        raise ValueError(f"unknown updater: {param.updater}")
    # V_dim is recorded in the checkpoint; probe it so the store allocates
    # the right row width before load
    from .utils import stream
    with stream.load_npz(param.model_in) as z:
        v_dim = int(z["V_dim"]) if "V_dim" in z.files else 0
    uparam, remain = SGDUpdaterParam.init_allow_unknown(remain)
    import dataclasses
    store = SlotStore(dataclasses.replace(uparam, V_dim=v_dim))
    store.load(param.model_in)
    n = store.dump(param.name_dump, param.dump_aux, param.need_reverse)
    log.info("dumped %d features to %s", n, param.name_dump)
    return remain


def main(argv: list[str] | None = None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="[%(asctime)s] %(levelname)s %(message)s")
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: python -m difacto_tpu config_file key1=val1 ...",
              file=sys.stderr)
        return 1

    # honor an explicit JAX_PLATFORMS=cpu (virtual-mesh runs) before the
    # first backend touch — multihost initialize below binds devices
    from .utils.platform import apply_env_platform
    apply_env_platform()

    if "DIFACTO_NPROCS" in os.environ:
        from .parallel.multihost import initialize
        initialize()

    kwargs = parse_cli_args(argv)
    param, remain = DifactoParam.init_allow_unknown(kwargs)

    if param.task in ("train", "pred"):
        if param.task == "pred" and param.learner != "sgd":
            # only the sgd learner implements the prediction task (like the
            # reference, where pred routes through SGDLearner's job types);
            # the error names the learner that made the model and points
            # at the serve path
            raise _pred_routing_error(param.learner, remain)
        learner = Learner.create(param.learner)
        if param.task == "pred":
            remain.append(("task", "2"))
        remain = learner.init(remain)
        warn_unknown(remain)
        from .parallel.fault import HostFailure, exit_code_for
        try:
            learner.run()
        except HostFailure as e:
            # a peer host died; exit with the recovery code so the
            # launcher (launch.py --max-restarts) evicts it and resumes
            # from the last checkpoint (parallel/fault.py)
            log.error("aborting for restart: %s", e)
            return exit_code_for(e.dead)
    elif param.task == "serve":
        from .serve import run_serve
        warn_unknown(run_serve(remain))
    elif param.task == "online":
        from .online import run_online
        warn_unknown(run_online(remain))
    elif param.task == "dump":
        warn_unknown(run_dump(remain))
    elif param.task == "convert":
        from .data.converter import Converter
        conv = Converter()
        warn_unknown(conv.init(remain))
        conv.run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
