"""Device batch representation: padded COO segments.

The bridge between the host CSR pipeline and XLA's static-shape world. A
localized row block (data/localizer.py) becomes a :class:`DeviceBatch` of
fixed-bucket-size arrays:

- ``rows[NNZ]`` int32 segment ids, ``cols[NNZ]`` int32 local feature slots,
  ``vals[NNZ]`` float32 (zero on padding — padded entries contribute nothing
  to any segment sum);
- ``labels/rweight/row_mask [B]`` per-row arrays.

Bucketing pads NNZ, U (distinct features) and B (rows) up to the next
power-of-two-ish bucket so jit recompiles only per bucket, not per batch —
this is the TPU answer to the reference's fully dynamic per-batch shapes
(its SArray messages can be any length; XLA cannot).

The reference analog of this file is the implicit contract between
Localizer's compact CSR and the SpMV/SpMM kernels (src/common/spmv.h:16-40).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

from ..base import REAL_DTYPE
from ..data.rowblock import RowBlock


class DeviceBatch(NamedTuple):
    """Padded COO batch; all leaves are jnp arrays, shapes static per bucket.

    ``cols`` address the batch's sorted-unique slot vector directly: every
    producer resolves in-batch collisions on the HOST (store.map_keys_dedup
    or the producer-thread np.unique), rewriting the O(nnz) index array
    once per batch. A device-side remap permutation used to carry this for
    the cached reader; it cost an unsorted u_cap-row permute + scatter-add
    per step — more than the host gather it saved (docs/perf_notes.md,
    round-5 "host dedup").
    """
    rows: jnp.ndarray      # int32[NNZ] row of each nonzero (pad: last real row)
    cols: jnp.ndarray      # int32[U-index] of each nonzero (pad: 0)
    vals: jnp.ndarray      # f32[NNZ] (pad: 0)
    labels: jnp.ndarray    # f32[B]
    rweight: jnp.ndarray   # f32[B] per-row example weights (pad: 0)
    row_mask: jnp.ndarray  # f32[B] 1 for real rows
    num_rows: jnp.ndarray  # i32[] actual batch size
    num_uniq: jnp.ndarray  # i32[] actual distinct-feature count

    @property
    def batch_cap(self) -> int:
        return self.labels.shape[0]

    @property
    def nnz_cap(self) -> int:
        return self.vals.shape[0]


class PanelBatch(NamedTuple):
    """Fixed-width row panel: the TPU-preferred batch layout.

    Criteo rows have exactly 39 features (13 int + 26 categorical,
    src/reader/criteo_parser.h:25-115); a [B, F] index matrix turns the
    forward into one gather + dense reductions and the backward into pure
    broadcasts + ONE segment reduction — no per-token COO gathers at all.
    Ragged data still packs here when rows are near-uniform (pad cells:
    idx 0 with val 0); heavily skewed rows fall back to DeviceBatch COO.
    """
    idx: jnp.ndarray       # int32[B, F] positions into the slot vector
    vals: Optional[jnp.ndarray]  # f32[B, F] or None (binary, no padding)
    labels: jnp.ndarray    # f32[B]
    rweight: jnp.ndarray   # f32[B]
    row_mask: jnp.ndarray  # f32[B] 1 for real rows
    num_rows: jnp.ndarray  # i32[]
    num_uniq: jnp.ndarray  # i32[]
    # chunked-run layout (panel_chunk_tokens): the fastest backward. Each
    # lane's token run is padded into fixed-L gather chunks; the per-token
    # sorted scatter (a serial ~10 ns/row update loop, half the fused step
    # at bench shapes) becomes a dense vectorised gather+reduce to per-chunk
    # partials plus a scatter of only ~U + B*F/L rows (docs/perf_notes.md,
    # round-4 "chunked backward"). Staged once per batch like sorted_*.
    chunk_idx: Optional[jnp.ndarray] = None   # i32[C, L] token row ids
    chunk_lane: Optional[jnp.ndarray] = None  # i32[C] ascending lanes
    chunk_vals: Optional[jnp.ndarray] = None  # f32[C, L] (None if binary)

    @property
    def batch_cap(self) -> int:
        return self.labels.shape[0]

    @property
    def width(self) -> int:
        return self.idx.shape[1]


def panel_width(blk: RowBlock, batch_cap: int) -> Optional[int]:
    """Fixed panel width for this block, or None when the COO layout is
    denser. Panel wins when B*F_max stays within ~1.5x the COO nnz pad."""
    counts = np.diff(blk.offset)
    if len(counts) == 0:
        return None
    fmax = int(counts.max())
    if fmax == 0:
        return None
    coo_cells = bucket(blk.nnz)
    if batch_cap * fmax <= 1.5 * coo_cells:
        return fmax
    return None


def _panel_arrays(blk: RowBlock, batch_cap: int, width: int):
    """Host-side panel arrays: (idx[B,F], vals[B,F] or None, labels,
    rweight, row_mask)."""
    b = blk.size
    counts = np.diff(blk.offset).astype(np.int64)
    if counts.size and counts.max() > width:
        raise ValueError(f"row nnz {counts.max()} exceeds panel width "
                         f"{width}")
    uniform = counts.size and (counts == width).all()
    if uniform and b == batch_cap:
        idx = blk.index.reshape(b, width).astype(np.int32)
        vals = (None if blk.value is None
                else blk.value.reshape(b, width).astype(REAL_DTYPE))
    else:
        idx = np.zeros((batch_cap, width), dtype=np.int32)
        vals = np.zeros((batch_cap, width), dtype=REAL_DTYPE)
        starts = np.asarray(blk.offset[:-1], dtype=np.int64)
        cell = (np.arange(blk.nnz, dtype=np.int64)
                - np.repeat(starts - blk.offset[0], counts))
        rows_coo = np.repeat(np.arange(b, dtype=np.int64), counts)
        idx[rows_coo, cell] = blk.index.astype(np.int32)
        vals[rows_coo, cell] = blk.values_or_ones()

    labels = np.zeros(batch_cap, dtype=REAL_DTYPE)
    labels[:b] = blk.label
    rweight = np.zeros(batch_cap, dtype=REAL_DTYPE)
    rweight[:b] = blk.weight if blk.weight is not None else 1.0
    row_mask = np.zeros(batch_cap, dtype=REAL_DTYPE)
    row_mask[:b] = 1.0
    return idx, vals, labels, rweight, row_mask


def pad_panel(blk: RowBlock, num_uniq: int, batch_cap: int, width: int
              ) -> PanelBatch:
    """Pack a *localized* row block into a PanelBatch."""
    idx, vals, labels, rweight, row_mask = _panel_arrays(blk, batch_cap,
                                                         width)
    return PanelBatch(
        idx=jnp.asarray(idx),
        vals=None if vals is None else jnp.asarray(vals),
        labels=jnp.asarray(labels), rweight=jnp.asarray(rweight),
        row_mask=jnp.asarray(row_mask),
        num_rows=jnp.asarray(blk.size, dtype=jnp.int32),
        num_uniq=jnp.asarray(num_uniq, dtype=jnp.int32),
    )


def pack_panel(blk: RowBlock, num_uniq: int, slots: np.ndarray,
               batch_cap: int, width: int, u_cap: int,
               counts: Optional[np.ndarray] = None):
    """Panel equivalent of pack_batch: TWO host buffers per batch.

    i32 = [idx(B*F) | slots(u_cap, pre-padded via pad_slots_oob) | b, nu];
    f32 = [vals(B*F)? | labels(B) | rweight(B) | row_mask(B) | counts(u)?].
    ``idx`` addresses slot rows directly (collision dedup happens on the
    host before packing).
    """
    if len(slots) != u_cap:
        raise ValueError(f"slots must arrive pre-padded to u_cap={u_cap}")
    idx, vals, labels, rweight, row_mask = _panel_arrays(blk, batch_cap,
                                                         width)
    binary = vals is None
    cells = batch_cap * width
    i32 = np.empty(cells + u_cap + 2, dtype=np.int32)
    i32[:cells] = idx.reshape(-1)
    i32[cells:cells + u_cap] = slots
    i32[cells + u_cap:] = (blk.size, num_uniq)
    vals_n = 0 if binary else cells
    nf32 = vals_n + 3 * batch_cap + (u_cap if counts is not None else 0)
    f32 = np.zeros(max(nf32, 1), dtype=REAL_DTYPE)
    o = 0
    if not binary:
        f32[:cells] = vals.reshape(-1)
        o = cells
    f32[o:o + batch_cap] = labels
    o += batch_cap
    f32[o:o + batch_cap] = rweight
    o += batch_cap
    f32[o:o + batch_cap] = row_mask
    o += batch_cap
    if counts is not None:
        f32[o:o + len(counts)] = counts
    return i32, f32, binary


def unpack_panel(i32, f32, batch_cap: int, width: int, u_cap: int,
                 has_counts: bool = False, binary: bool = False):
    """jit-traceable inverse of pack_panel ->
    (PanelBatch, slots, counts-or-None)."""
    cells = batch_cap * width
    idx = i32[:cells].reshape(batch_cap, width)
    slots = i32[cells:cells + u_cap]
    meta = i32[cells + u_cap:]
    o = 0
    vals = None
    if not binary:
        vals = f32[:cells].reshape(batch_cap, width)
        o = cells
    labels = f32[o:o + batch_cap]
    o += batch_cap
    rweight = f32[o:o + batch_cap]
    o += batch_cap
    row_mask = f32[o:o + batch_cap]
    o += batch_cap
    counts = f32[o:o + u_cap] if has_counts else None
    pb = PanelBatch(idx=idx, vals=vals, labels=labels, rweight=rweight,
                    row_mask=row_mask, num_rows=meta[0], num_uniq=meta[1])
    return pb, slots, counts


def pack_panel_raw(blk: RowBlock, num_uniq: int, batch_cap: int,
                   width: int):
    """Device-dedup panel payload (ISSUE 13): the block's index cells are
    RAW hashed slot tokens (hash_slots output, NOT localized lanes) and
    there is no slots section — the jit step derives the sorted-unique
    slot vector and the inverse map on device (ops/fused.dedup_tokens),
    so the producer skips the O(nnz log nnz) host ``np.unique``.

    i32 = [tok(B*F) | b, num_uniq]; f32 = [vals(B*F)? | labels(B) |
    rweight(B) | row_mask(B)]. ``num_uniq`` is the host's cheap distinct
    count (pack_stream._count_distinct) — it sizes the sticky u-cap, the
    device recomputes the exact lane count. Pad cells carry token 0
    (TRASH_SLOT), whose gathered row is the all-zero trash row and whose
    gradient contribution is zero (vals 0), so the extra lane it may add
    is trajectory-inert. No counts section: the raw path only engages on
    epochs past the count push (pack_stream.prepare_hashed)."""
    idx, vals, labels, rweight, row_mask = _panel_arrays(blk, batch_cap,
                                                         width)
    binary = vals is None
    cells = batch_cap * width
    i32 = np.empty(cells + 2, dtype=np.int32)
    i32[:cells] = idx.reshape(-1)
    i32[cells:] = (blk.size, num_uniq)
    vals_n = 0 if binary else cells
    f32 = np.zeros(max(vals_n + 3 * batch_cap, 1), dtype=REAL_DTYPE)
    o = 0
    if not binary:
        f32[:cells] = vals.reshape(-1)
        o = cells
    f32[o:o + batch_cap] = labels
    o += batch_cap
    f32[o:o + batch_cap] = rweight
    o += batch_cap
    f32[o:o + batch_cap] = row_mask
    return i32, f32, binary


def unpack_panel_raw(i32, f32, batch_cap: int, width: int,
                     binary: bool = False):
    """jit-traceable inverse of pack_panel_raw -> (PanelBatch with RAW
    token idx cells, num_uniq meta). The caller runs dedup_tokens over
    the flat cells and rewrites ``idx`` to the localized inverse."""
    cells = batch_cap * width
    idx = i32[:cells].reshape(batch_cap, width)
    meta = i32[cells:]
    o = 0
    vals = None
    if not binary:
        vals = f32[:cells].reshape(batch_cap, width)
        o = cells
    labels = f32[o:o + batch_cap]
    o += batch_cap
    rweight = f32[o:o + batch_cap]
    o += batch_cap
    row_mask = f32[o:o + batch_cap]
    return PanelBatch(idx=idx, vals=vals, labels=labels, rweight=rweight,
                      row_mask=row_mask, num_rows=meta[0],
                      num_uniq=meta[1])


# Chunk length of the run-chunked backward layout. L=16 measured fastest at
# bench shapes (L=8: more chunks to scatter; L=32/64: more gather padding
# on the zipf run-length distribution — docs/perf_notes.md).
CHUNK_L = 16


def chunk_cap(u_cap: int, cells: int, L: int = CHUNK_L) -> int:
    """Static chunk-count bound: every one of the <= u_cap lane runs wastes
    less than one chunk of padding, plus cells/L full chunks."""
    return u_cap + cells // L + 2


def panel_chunk_tokens_flat(flat_idx: jnp.ndarray,
                            flat_vals: Optional[jnp.ndarray],
                            u_cap: int, b_cap: int, width: int,
                            L: int = CHUNK_L):
    """Chunked-run backward layout from flat panel lanes (jit-traceable;
    run ONCE per batch at device-cache staging time).

    Tokens are lane-sorted; each lane's contiguous run is split into
    ceil(len/L) chunks of exactly L gather slots (pad -> ``b_cap``, an
    out-of-bounds row that gather-fills 0). Returns

      chunk_idx  i32[C, L]  token row ids per chunk,
      chunk_lane i32[C]     ascending output lane per chunk (pad -> u_cap,
                            dropped by the reduction's mode="drop"),
      chunk_vals f32[C, L]  per-token values (None when ``flat_vals`` is),

    with C = chunk_cap(u_cap, cells, L) — a function of static shapes only,
    so one jit signature serves every batch of a shape schedule. Used
    chunks form a prefix and their lanes are ascending; runs split across
    chunks simply scatter-add multiple partials into the same lane."""
    cells = flat_idx.shape[0]
    C = chunk_cap(u_cap, cells, L)
    order = jnp.argsort(flat_idx)
    lane = flat_idx[order].astype(jnp.int32)             # ascending
    rows = (order // width).astype(jnp.int32)
    ari = jnp.arange(cells, dtype=jnp.int32)
    prev = jnp.concatenate([jnp.full((1,), -1, lane.dtype), lane[:-1]])
    start = lane != prev                                  # run-start flags
    rid = jnp.cumsum(start.astype(jnp.int32)) - 1         # [cells] run ids
    RC = u_cap + 1                                        # lanes < u_cap
    run_start = jnp.full((RC,), cells, jnp.int32).at[rid].min(
        jnp.where(start, ari, cells), mode="drop")
    run_len = jnp.zeros((RC,), jnp.int32).at[rid].add(1, mode="drop")
    n_chunks = (run_len + L - 1) // L
    chunk_base = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(n_chunks)[:-1]])
    q = ari - run_start[rid]                              # pos within run
    c = chunk_base[rid] + q // L                          # ascending
    cell = c * L + q % L                                  # ascending unique
    ci = jnp.full((C * L,), b_cap, jnp.int32).at[cell].set(
        rows, indices_are_sorted=True, unique_indices=True, mode="drop")
    cl = jnp.full((C,), u_cap, jnp.int32).at[c].set(
        lane, indices_are_sorted=True, mode="drop")
    cv = None
    if flat_vals is not None:
        cv = jnp.zeros((C * L,), flat_vals.dtype).at[cell].set(
            flat_vals[order], indices_are_sorted=True, unique_indices=True,
            mode="drop").reshape(C, L)
    return ci.reshape(C, L), cl, cv


def panel_chunk_tokens_np(flat_idx: np.ndarray,
                          flat_vals: Optional[np.ndarray],
                          u_cap: int, b_fill: int, width: int,
                          L: int = CHUNK_L, C: Optional[int] = None,
                          row_base: int = 0):
    """Host-side (numpy) twin of :func:`panel_chunk_tokens_flat`, for the
    mesh/SPMD paths where the chunk layout is built per host at batch-prep
    time rather than on device at cache-staging time:

    - ``row_base`` offsets token row ids into the GLOBAL dp-concatenated
      row space (this host's rows live at [row_base, row_base + b_local));
    - ``b_fill`` is the out-of-bounds pad row (the GLOBAL batch cap for
      sharded batches), so pad cells gather 0 under mode="fill";
    - ``C`` pins the chunk count explicitly — mesh callers round it up to
      a multiple of the dp axis so the [C, L] arrays shard evenly and
      every host ships identical shapes.

    Tokens are lane-sorted per host, so each host's chunk_lane block is
    ascending — but the dp-concatenation of blocks is NOT globally
    sorted, which is why the mesh step drops the ``indices_are_sorted``
    promise (losses/fm.py ``chunks_sorted``)."""
    cells = len(flat_idx)
    if C is None:
        C = chunk_cap(u_cap, cells, L)
    order = np.argsort(flat_idx, kind="stable")
    lane = flat_idx[order].astype(np.int32)
    rows = (order // width).astype(np.int32) + row_base
    start = np.empty(cells, dtype=bool)
    if cells:
        start[0] = True
        start[1:] = lane[1:] != lane[:-1]
    rid = np.cumsum(start) - 1                       # run ids per token
    run_start = np.nonzero(start)[0]                 # first token of run
    q = np.arange(cells, dtype=np.int64) - run_start[rid]  # pos in run
    run_len = np.diff(np.append(run_start, cells))
    n_chunks = (run_len + L - 1) // L
    chunk_base = np.concatenate([[0], np.cumsum(n_chunks)[:-1]])
    c = chunk_base[rid] + q // L
    cell = c * L + q % L
    if len(c) and c[-1] >= C:
        raise ValueError(f"chunk count {c[-1] + 1} exceeds cap {C}")
    ci = np.full(C * L, b_fill, dtype=np.int32)
    ci[cell] = rows
    cl = np.full(C, u_cap, dtype=np.int32)
    cl[c] = lane
    cv = None
    if flat_vals is not None:
        cv = np.zeros(C * L, dtype=flat_vals.dtype)
        cv[cell] = flat_vals[order]
        cv = cv.reshape(C, L)
    return ci.reshape(C, L), cl, cv


def panel_chunk_tokens(pb: PanelBatch, u_cap: int,
                       L: int = CHUNK_L) -> PanelBatch:
    """Attach the chunked-run backward layout to a panel batch. ``u_cap``
    is the batch's lane-space size (its slot vector length)."""
    B, F = pb.idx.shape
    flat = pb.idx.reshape(B * F)
    fv = None if pb.vals is None else pb.vals.reshape(B * F)
    ci, cl, cv = panel_chunk_tokens_flat(flat, fv, u_cap, B, F, L)
    return pb._replace(chunk_idx=ci, chunk_lane=cl, chunk_vals=cv)


def bucket(n: int, minimum: int = 8) -> int:
    """Round up to the next bucket rung (>= minimum).

    Rungs are {m*2^j, 1.5*m*2^j} for m = ``minimum``: at most 33% padding
    waste instead of 2x. Every rung is divisible by d whenever ``minimum``
    is a multiple of 2*d (1.5*m*2^j = 3*(m/2)*2^j) — callers sharding the
    dimension over a mesh axis must pass ``mesh_dim_min(d)``."""
    b = minimum
    while b < n:
        if n <= b + b // 2:
            return b + b // 2
        b *= 2
    return b


def mesh_dim_min(dp: int, floor: int = 8) -> int:
    """Bucket minimum that keeps every rung divisible by ``dp``: the
    smallest multiple of 2*dp that is >= floor. Needed because bucket()'s
    1.5x rungs are only divisible by dp when the floor carries a factor of
    2*dp (e.g. dp=3, floor 8 would yield rungs 8, 12, 16 — 8 and 16 split
    unevenly over a 3-way axis)."""
    base = 2 * dp
    return base * ((max(floor, base) + base - 1) // base)


def pack_batch(blk: RowBlock, num_uniq: int, slots: np.ndarray,
               batch_cap: int, nnz_cap: int, u_cap: int,
               counts: Optional[np.ndarray] = None):
    """Pack a localized block + slot vector into TWO host buffers
    (int32 + float32) so staging costs two device transfers instead of
    eight — on tunneled/remote devices per-transfer latency dominates.

    Layout (static per bucket): i32 = [rows(nnz) | cols(nnz) | slots(u)];
    f32 = [vals(nnz)? | labels(B) | rweight(B) | row_mask(B) |
    counts(u)?]. Binary blocks (value is None — e.g. criteo) omit the vals
    section and reconstruct ones*row-validity on device, halving the f32
    payload. ``cols`` address slot rows directly (host-side dedup).
    ``unpack_batch`` is the jit-side inverse.
    """
    b, nnz = blk.size, blk.nnz
    if b > batch_cap or nnz > nnz_cap:
        raise ValueError("batch exceeds caps")
    if len(slots) != u_cap:
        # the device kernels declare sorted+unique indices; a short vector
        # zero-padded here would put TRASH_SLOT=0 after larger slots and
        # break both declarations — callers must pre-pad with
        # store.local.pad_slots_oob (ascending out-of-bounds padding)
        raise ValueError(
            f"slots must arrive pre-padded to u_cap={u_cap} "
            f"(got {len(slots)}); use pad_slots_oob")
    binary = blk.value is None
    # trailing 3 ints: [b, num_uniq, nnz] — kept in the i32 buffer so they
    # stay exact (f32 would round past 2^24)
    i32 = np.zeros(2 * nnz_cap + u_cap + 3, dtype=np.int32)
    i32[:nnz] = blk.row_ids()
    i32[nnz:nnz_cap] = max(b - 1, 0)  # pad rows -> a real segment, vals 0
    i32[nnz_cap:nnz_cap + nnz] = blk.index.astype(np.int32)
    i32[2 * nnz_cap:2 * nnz_cap + u_cap] = slots
    i32[2 * nnz_cap + u_cap:] = (b, num_uniq, nnz)

    vals_n = 0 if binary else nnz_cap
    nf32 = vals_n + 3 * batch_cap \
        + (u_cap if counts is not None else 0)
    f32 = np.zeros(max(nf32, 1), dtype=REAL_DTYPE)
    o = 0
    if not binary:
        f32[:nnz] = blk.value
        o = nnz_cap
    f32[o:o + b] = blk.label
    o += batch_cap
    f32[o:o + b] = blk.weight if blk.weight is not None else 1.0
    o += batch_cap
    f32[o:o + b] = 1.0
    o += batch_cap
    if counts is not None:
        f32[o:o + len(counts)] = counts
    return i32, f32, binary


def unpack_batch(i32, f32, batch_cap: int, nnz_cap: int, u_cap: int,
                 has_counts: bool = False, binary: bool = False):
    """jit-traceable inverse of pack_batch ->
    (DeviceBatch, slots, counts-or-None)."""
    import jax.numpy as jnp

    rows = i32[:nnz_cap]
    cols = i32[nnz_cap:2 * nnz_cap]
    slots = i32[2 * nnz_cap:2 * nnz_cap + u_cap]
    meta = i32[2 * nnz_cap + u_cap:]  # [b, num_uniq, nnz], exact int32
    if binary:
        # all-ones values, zeroed on padding entries (value elision,
        # src/reader/batch_reader.cc:71-73 carried to the device side)
        iota = jnp.arange(nnz_cap, dtype=jnp.int32)
        vals = (iota < meta[2]).astype(jnp.float32)
        o = 0
    else:
        vals = f32[:nnz_cap]
        o = nnz_cap
    labels = f32[o:o + batch_cap]
    o += batch_cap
    rweight = f32[o:o + batch_cap]
    o += batch_cap
    row_mask = f32[o:o + batch_cap]
    o += batch_cap
    counts = None
    if has_counts:
        counts = f32[o:o + u_cap]
    batch = DeviceBatch(
        rows=rows, cols=cols, vals=vals, labels=labels, rweight=rweight,
        row_mask=row_mask,
        num_rows=meta[0],
        num_uniq=meta[1],
    )
    return batch, slots, counts


def pad_batch(blk: RowBlock, num_uniq: int,
              batch_cap: Optional[int] = None,
              nnz_cap: Optional[int] = None) -> DeviceBatch:
    """Pack a *localized* row block (uint32 indices) into a DeviceBatch."""
    b, nnz = blk.size, blk.nnz
    bc = batch_cap or bucket(b)
    nc = nnz_cap or bucket(nnz)
    if b > bc or nnz > nc:
        raise ValueError(f"batch ({b},{nnz}) exceeds caps ({bc},{nc})")

    rows = np.zeros(nc, dtype=np.int32)
    rows[:nnz] = blk.row_ids()
    rows[nnz:] = max(b - 1, 0)  # pad rows point at a real segment; vals=0
    cols = np.zeros(nc, dtype=np.int32)
    cols[:nnz] = blk.index.astype(np.int32)
    vals = np.zeros(nc, dtype=REAL_DTYPE)
    vals[:nnz] = blk.values_or_ones()

    labels = np.zeros(bc, dtype=REAL_DTYPE)
    labels[:b] = blk.label
    rweight = np.zeros(bc, dtype=REAL_DTYPE)
    rweight[:b] = blk.weight if blk.weight is not None else 1.0
    row_mask = np.zeros(bc, dtype=REAL_DTYPE)
    row_mask[:b] = 1.0

    return DeviceBatch(
        rows=jnp.asarray(rows), cols=jnp.asarray(cols), vals=jnp.asarray(vals),
        labels=jnp.asarray(labels), rweight=jnp.asarray(rweight),
        row_mask=jnp.asarray(row_mask),
        num_rows=jnp.asarray(b, dtype=jnp.int32),
        num_uniq=jnp.asarray(num_uniq, dtype=jnp.int32),
    )
