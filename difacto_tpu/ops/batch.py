"""Device batch representation: padded COO segments.

The bridge between the host CSR pipeline and XLA's static-shape world. A
localized row block (data/localizer.py) becomes a :class:`DeviceBatch` of
fixed-bucket-size arrays:

- ``rows[NNZ]`` int32 segment ids, ``cols[NNZ]`` int32 local feature slots,
  ``vals[NNZ]`` float32 (zero on padding — padded entries contribute nothing
  to any segment sum);
- ``labels/rweight/row_mask [B]`` per-row arrays.

Bucketing pads NNZ, U (distinct features) and B (rows) up to the next
power-of-two-ish bucket so jit recompiles only per bucket, not per batch —
this is the TPU answer to the reference's fully dynamic per-batch shapes
(its SArray messages can be any length; XLA cannot).

The reference analog of this file is the implicit contract between
Localizer's compact CSR and the SpMV/SpMM kernels (src/common/spmv.h:16-40).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

from ..base import REAL_DTYPE
from ..data.rowblock import RowBlock


class DeviceBatch(NamedTuple):
    """Padded COO batch; all leaves are jnp arrays, shapes static per bucket."""
    rows: jnp.ndarray      # int32[NNZ] row of each nonzero (pad: last real row)
    cols: jnp.ndarray      # int32[U-index] of each nonzero (pad: 0)
    vals: jnp.ndarray      # f32[NNZ] (pad: 0)
    labels: jnp.ndarray    # f32[B]
    rweight: jnp.ndarray   # f32[B] per-row example weights (pad: 0)
    row_mask: jnp.ndarray  # f32[B] 1 for real rows
    num_rows: jnp.ndarray  # i32[] actual batch size
    num_uniq: jnp.ndarray  # i32[] actual distinct-feature count

    @property
    def batch_cap(self) -> int:
        return self.labels.shape[0]

    @property
    def nnz_cap(self) -> int:
        return self.vals.shape[0]


def bucket(n: int, minimum: int = 8) -> int:
    """Round up to the next power of two (>= minimum)."""
    b = minimum
    while b < n:
        b *= 2
    return b


def pad_batch(blk: RowBlock, num_uniq: int,
              batch_cap: Optional[int] = None,
              nnz_cap: Optional[int] = None) -> DeviceBatch:
    """Pack a *localized* row block (uint32 indices) into a DeviceBatch."""
    b, nnz = blk.size, blk.nnz
    bc = batch_cap or bucket(b)
    nc = nnz_cap or bucket(nnz)
    if b > bc or nnz > nc:
        raise ValueError(f"batch ({b},{nnz}) exceeds caps ({bc},{nc})")

    rows = np.zeros(nc, dtype=np.int32)
    rows[:nnz] = blk.row_ids()
    rows[nnz:] = max(b - 1, 0)  # pad rows point at a real segment; vals=0
    cols = np.zeros(nc, dtype=np.int32)
    cols[:nnz] = blk.index.astype(np.int32)
    vals = np.zeros(nc, dtype=REAL_DTYPE)
    vals[:nnz] = blk.values_or_ones()

    labels = np.zeros(bc, dtype=REAL_DTYPE)
    labels[:b] = blk.label
    rweight = np.zeros(bc, dtype=REAL_DTYPE)
    rweight[:b] = blk.weight if blk.weight is not None else 1.0
    row_mask = np.zeros(bc, dtype=REAL_DTYPE)
    row_mask[:b] = 1.0

    return DeviceBatch(
        rows=jnp.asarray(rows), cols=jnp.asarray(cols), vals=jnp.asarray(vals),
        labels=jnp.asarray(labels), rweight=jnp.asarray(rweight),
        row_mask=jnp.asarray(row_mask),
        num_rows=jnp.asarray(b, dtype=jnp.int32),
        num_uniq=jnp.asarray(num_uniq, dtype=jnp.int32),
    )
