"""Fused sparse-FM kernel backends + on-device key dedup (ROADMAP item 3).

The SGD hot path is gather -> FM interaction -> scatter-update over the
fused slot-table rows (updaters/sgd_updater.py). This module owns the
TABLE-FACING halves of that program behind a ``fused_kernel`` knob
(``auto|pallas|jnp|off``, SGDUpdaterParam):

- ``jnp`` — the carefully fused single-program path: the step gathers
  the fused rows ONCE (step.py threads them from pull to push instead
  of relying on XLA CSE to merge the pull/push gathers), the
  FTRL/AdaGrad epilogue runs on the threaded rows, and one scatter
  writes them back. Identical primitives to ``off``, so trajectories
  are byte-identical by construction.
- ``pallas`` — the same dataflow with the gather and the
  epilogue+scatter as ``pl.pallas_call`` kernels: scalar-prefetched
  slot indices drive per-row async DMAs between the HBM-resident table
  and VMEM row tiles, and the scatter kernel folds the per-row
  FTRL/AdaGrad update into its epilogue before the write-back — the
  table row moves through HBM exactly twice per step (out on the pull,
  back on the push) with no composed-op round trips between. The
  update math is the SAME ``row_epilogue`` function the jnp path
  scatters (traced into the kernel per tile), so the backends cannot
  drift. Off-TPU the kernels run in Pallas interpret mode — that is
  the parity harness, not a fast path (``make kernel-parity``).
- ``off`` — the pre-ISSUE-13 composed path (get_rows + apply_grad as
  separate gather/scatter programs, merged only by XLA CSE).

History note (docs/perf_notes.md "Pallas resolution"): the round-3
per-row-DMA scaffold was measured latency-bound and deleted — it moved
BARE rows, so it competed with one XLA gather. This kernel revisits the
design with the update folded into the scatter's epilogue (halving the
table traffic the composed path pays) and R-row tiles whose DMAs issue
before any wait; ``auto`` still resolves to ``jnp`` until a driver
bench (BENCH_r*, the per-backend ``kernel`` block) shows the pallas
path ahead on real hardware.

On-device dedup (:func:`dedup_tokens`): the streamed producer's
``np.unique`` over the batch's O(nnz) hashed tokens is the dominant
remaining host pack cost (data/pack_stream.py). With
``device_dedup=1`` the producer ships RAW token lanes and this sort +
run-length pass builds the sorted-unique slot vector (OOB-padded, the
ops/batch.py contract) and the inverse index map inside the jit step.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from ..utils import jaxtrace

log = logging.getLogger("difacto_tpu")

# rows per pallas grid step: every ShapeSchedule/bucket rung >= 8 is
# divisible by 4 (ops/batch.bucket — {8*2^j, 12*2^j} rungs), so a tile
# of 8 or 4 rows always divides u_cap and the kernels need no tail
# masking. 8 row-DMAs in flight per tile amortizes the per-copy latency
# that killed the round-3 single-row scaffold.
_TILE_ROWS = 8

_BACKENDS = ("auto", "pallas", "jnp", "off")


def pallas_importable() -> bool:
    try:
        from jax.experimental import pallas  # noqa: F401
        from jax.experimental.pallas import tpu  # noqa: F401
    except ImportError:  # pragma: no cover - jax always bundles pallas
        return False
    return True


def interpret_mode() -> bool:
    """Pallas kernels compile through Mosaic only on TPU backends;
    everywhere else they run interpreted — bit-exact, slow, and only
    meant for the parity tests."""
    return jax.default_backend() != "tpu"


def resolve_backend(knob: str, mesh=None, V_dim: int = 0) -> str:
    """``fused_kernel`` knob -> concrete backend for this store.

    - ``off`` (or a flat ``V_dim == 0`` table, which has no fused row
      to kernel over) keeps the composed path;
    - ``jnp`` is the fused single-program path, valid everywhere
      (mesh included — same primitives, GSPMD partitions them);
    - ``pallas`` requires an unsharded table (a pallas_call is opaque
      to GSPMD: under fs-sharding it would force the table through a
      replicated intermediate, exactly what state_constrainer exists
      to prevent) and fails typed rather than silently degrading;
    - ``auto`` resolves to ``jnp`` — the measured-fastest backend
      until a driver bench shows the pallas kernels ahead (module
      docstring); it never picks pallas on its own.
    """
    if knob not in _BACKENDS:
        raise ValueError(
            f"unknown fused_kernel {knob!r} (expected auto|pallas|jnp|off)")
    if knob == "off" or V_dim == 0:
        reason = ("fused_kernel=off" if knob == "off"
                  else "flat table (V_dim=0) has no fused row")
        return _log_resolution(knob, "off", reason)
    if knob == "pallas":
        if mesh is not None:
            raise ValueError(
                "fused_kernel=pallas does not support a sharded table "
                "(mesh_fs/mesh_dp > 1 or mesh_force): pallas_call is "
                "opaque to GSPMD partitioning — use fused_kernel=jnp "
                "for mesh runs")
        if not pallas_importable():
            raise ValueError(
                "fused_kernel=pallas but jax.experimental.pallas is "
                "not importable in this jax build")
        return _log_resolution(knob, "pallas",
                               "interpret mode (parity harness)"
                               if interpret_mode() else "TPU Mosaic")
    if knob == "jnp":
        return _log_resolution(knob, "jnp", "explicit knob")
    return _log_resolution(
        knob, "jnp",
        "auto never picks pallas (docs/perf_notes.md); "
        + ("mesh run — GSPMD partitions the jnp primitives"
           if mesh is not None else "measured-fastest backend"))


def _log_resolution(knob: str, backend: str, reason: str) -> str:
    """One INFO line per resolution (i.e. once per learner/store —
    make_fns resolves once): ``auto`` silently landing on ``jnp`` under
    a mesh confused the BENCH_r05->r06 comparison, so the resolved
    backend and why are now in the run log."""
    log.info("fused_kernel: %s -> %s (%s)", knob, backend, reason)
    return backend


# --------------------------------------------------------------- dedup
def dedup_tokens(tok: jnp.ndarray, u_cap: int, capacity: int
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """On-device twin of the producer's ``np.unique`` + ``pad_slots_oob``
    (data/pack_stream.prepare_hashed, store/local.py): sort the batch's
    raw int32 token lanes, mark run starts, and run-length segment ids
    become the inverse map.

    Returns ``(slots, inverse, n_uniq)``:

    - ``slots`` int32[u_cap] — the sorted unique token values followed
      by ASCENDING out-of-bounds padding (``capacity + j``), exactly
      the pad_slots_oob layout, so the table kernels' sorted+unique
      index declarations stay truthful;
    - ``inverse`` int32[len(tok)] — each lane's position in ``slots``
      (the localized column index the host dedup used to compute);
    - ``n_uniq`` i32[] — the number of real (non-pad) slots.

    The caller guarantees ``n_uniq <= u_cap`` (the producer counts
    distinct tokens with an O(nnz + capacity) flag pass and sizes the
    sticky u-cap with a +1 margin for the TRASH lane pad cells
    introduce — pack_stream.prepare_hashed).
    """
    cells = tok.shape[0]
    order = jnp.argsort(tok)
    st = tok[order]
    start = jnp.concatenate(
        [jnp.ones((1,), bool), st[1:] != st[:-1]])
    seg = jnp.cumsum(start.astype(jnp.int32)) - 1
    n = seg[-1] + 1
    inverse = jnp.zeros(cells, jnp.int32).at[order].set(seg)
    # scatter each run's FIRST token to its segment position (unique
    # writes; non-starts aim at the dropped OOB lane u_cap)
    first = jnp.where(start, seg, u_cap)
    slots = jnp.zeros(u_cap, jnp.int32).at[first].set(st, mode="drop")
    j = jnp.arange(u_cap, dtype=jnp.int32)
    # pad value = capacity + POSITION, byte-identical to the host's
    # pad_slots_oob (arange overwritten by the real prefix)
    slots = jnp.where(j < n, slots, capacity + j)
    return slots, inverse, n


# ------------------------------------------------------- quantized slots
# Per-row symmetric quantization of the fused-row embedding halves
# (capacity lever (a), difacto_tpu/capacity/): codes live in an int8
# container (fp8 bit patterns are bitcast into it — one table dtype for
# both kinds), the per-row f32 scale rides the spare scalar lanes of the
# SAME fused row (updaters/sgd_updater.pack_scal lanes 5/6), so the hot
# path stays exactly one gather + one scatter: dequant/requant are
# elementwise epilogue ops on the already-gathered tile, traced into the
# pallas scatter kernel like the rest of row_epilogue.
_Q_MAX = {"int8": 127.0, "fp8": 448.0}  # fp8 = float8_e4m3fn finite max


def quant_half(x: jnp.ndarray, kind: str):
    """f32 [n, m] half -> (int8 codes [n, m], f32 scale [n]).

    Symmetric per-row scaling: ``scale = max|row| / qmax`` (1.0 for
    all-zero rows so the dequant is well-defined), int8 codes round to
    [-127, 127], fp8 codes cast to float8_e4m3fn and bitcast into the
    int8 container. Zero-padded lane columns encode as 0 either way."""
    amax = jnp.max(jnp.abs(x), axis=1)
    scale = jnp.where(amax > 0, amax / _Q_MAX[kind], 1.0)
    y = x / scale[:, None]
    if kind == "int8":
        codes = jnp.clip(jnp.round(y), -127.0, 127.0).astype(jnp.int8)
    else:
        codes = jax.lax.bitcast_convert_type(
            y.astype(jnp.float8_e4m3fn), jnp.int8)
    return codes, scale


def dequant_half(codes: jnp.ndarray, scale: jnp.ndarray, kind: str
                 ) -> jnp.ndarray:
    """Inverse of :func:`quant_half`: int8 container codes + per-row
    scale -> f32 values."""
    if kind == "int8":
        f = codes.astype(jnp.float32)
    else:
        f = jax.lax.bitcast_convert_type(
            codes, jnp.float8_e4m3fn).astype(jnp.float32)
    return f * scale[:, None]


# ------------------------------------------------------------- backends
def gather_rows(table: jnp.ndarray, slots: jnp.ndarray,
                backend: str = "jnp") -> jnp.ndarray:
    """ONE fused-row gather of the batch's sorted unique slots.

    The jnp form is the kernel contract every backend must match: the
    store guarantees sorted unique slots with ascending out-of-bounds
    padding (pad_slots_oob), the flags let XLA skip duplicate handling
    (~20% off the fused step, updaters/sgd_updater.py), and padded
    lanes read zeros (mode=fill)."""
    if backend == "pallas" and table.ndim == 2:
        return _pallas_gather(table, slots)
    return table.at[slots].get(indices_are_sorted=True,
                               unique_indices=True,
                               mode="fill", fill_value=0)


def scatter_rows(table: jnp.ndarray, slots: jnp.ndarray,
                 rows: jnp.ndarray, backend: str = "jnp") -> jnp.ndarray:
    """Write ``rows`` back at ``slots`` (padded OOB entries dropped)."""
    if backend == "pallas" and table.ndim == 2:
        return _pallas_scatter(table, slots, rows)
    return table.at[slots].set(rows, indices_are_sorted=True,
                               unique_indices=True, mode="drop")


def _tile_rows(u: int) -> int:
    for r in (_TILE_ROWS, 4, 2, 1):
        if u % r == 0:
            return r
    return 1  # pragma: no cover - unreachable (1 divides everything)


def _pallas_gather(table: jnp.ndarray, slots: jnp.ndarray) -> jnp.ndarray:
    """Row-gather kernel: scalar-prefetched slots drive R async row DMAs
    per grid step from the HBM table into the VMEM output tile; OOB pad
    lanes are zero-filled in VMEM (the mode=fill contract)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    C, W = table.shape
    u = slots.shape[0]
    R = _tile_rows(u)

    def kern(slots_ref, tbl_ref, out_ref, sems):
        i = pl.program_id(0)
        base = i * R
        for j in range(R):
            s = slots_ref[base + j]

            @pl.when(s < C)
            def _(j=j, s=s):
                pltpu.make_async_copy(tbl_ref.at[s], out_ref.at[j],
                                      sems.at[j]).start()

            @pl.when(jnp.logical_not(s < C))
            def _(j=j):
                out_ref[j, :] = jnp.zeros((W,), out_ref.dtype)
        for j in range(R):
            s = slots_ref[base + j]

            @pl.when(s < C)
            def _(j=j, s=s):
                pltpu.make_async_copy(tbl_ref.at[s], out_ref.at[j],
                                      sems.at[j]).wait()

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(u // R,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec((R, W), lambda i, s: (i, 0)),
        scratch_shapes=[pltpu.SemaphoreType.DMA((R,))],
    )
    return jaxtrace.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((u, W), table.dtype),
        interpret=interpret_mode())(slots, table)


def _pallas_scatter(table: jnp.ndarray, slots: jnp.ndarray,
                    rows: jnp.ndarray) -> jnp.ndarray:
    """Plain row scatter-back (no epilogue): the write half of
    :func:`fm_update_rows`, kept separate for apply_count-style
    callers. Table is aliased in place (input_output_aliases)."""
    return _scatter_epilogue(table, slots, rows, extras=(),
                             epilogue=None)


def fm_update_rows(table: jnp.ndarray, slots: jnp.ndarray,
                   rows: jnp.ndarray, gw: jnp.ndarray,
                   gV: jnp.ndarray, vmask: jnp.ndarray,
                   epilogue: Callable, backend: str = "jnp"
                   ) -> jnp.ndarray:
    """The fused scatter-update: run ``epilogue(rows, gw, gV, vmask)``
    — the per-row FTRL/AdaGrad update (updaters.sgd_updater
    row_epilogue, single-sourced so backends cannot drift) — and write
    the result back at ``slots``.

    jnp backend: epilogue in XLA + one scatter. pallas backend: the
    epilogue is traced INTO the scatter kernel and applied per R-row
    VMEM tile before the row DMAs write back — the "update folds into
    the kernel epilogue" half of ISSUE 13."""
    if backend == "pallas" and table.ndim == 2:
        u = slots.shape[0]
        extras = (gw.reshape(u, 1), gV,
                  vmask.reshape(u, 1))

        def tile_epilogue(rows_t, gw_t, gv_t, vm_t):
            return epilogue(rows_t, gw_t[:, 0], gv_t, vm_t[:, 0])

        return _scatter_epilogue(table, slots, rows, extras,
                                 tile_epilogue)
    new = epilogue(rows, gw, gV, vmask)
    return scatter_rows(table, slots, new, backend="jnp")


def _scatter_epilogue(table: jnp.ndarray, slots: jnp.ndarray,
                      rows: jnp.ndarray, extras: tuple,
                      epilogue: Optional[Callable]) -> jnp.ndarray:
    """Shared pallas scatter kernel: per grid step, compute the new
    R-row tile (``epilogue`` over the rows tile + per-row ``extras``
    blocks, or the rows verbatim) into VMEM scratch, then DMA each
    in-bounds row back to its HBM table slot. The table input aliases
    the output, so the update is in place — composed with the jit-level
    ``donate_argnums`` the step already declares."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    C, W = table.shape
    u = slots.shape[0]
    R = _tile_rows(u)
    n_extra = len(extras)

    def kern(*refs):
        slots_ref = refs[0]
        rows_ref = refs[1]
        extra_refs = refs[2:2 + n_extra]
        tbl_ref = refs[2 + n_extra]      # aliased input (unused: the
        del tbl_ref                      # DMA targets the out ref)
        out_ref = refs[3 + n_extra]
        scratch, sems = refs[4 + n_extra], refs[5 + n_extra]
        i = pl.program_id(0)
        base = i * R
        if epilogue is None:
            scratch[...] = rows_ref[...]
        else:
            scratch[...] = epilogue(rows_ref[...],
                                    *(r[...] for r in extra_refs))
        for j in range(R):
            s = slots_ref[base + j]

            @pl.when(s < C)
            def _(j=j, s=s):
                pltpu.make_async_copy(scratch.at[j], out_ref.at[s],
                                      sems.at[j]).start()
        for j in range(R):
            s = slots_ref[base + j]

            @pl.when(s < C)
            def _(j=j, s=s):
                pltpu.make_async_copy(scratch.at[j], out_ref.at[s],
                                      sems.at[j]).wait()

    in_specs = [pl.BlockSpec((R, W), lambda i, s: (i, 0))]
    for e in extras:
        w_e = e.shape[1]
        in_specs.append(pl.BlockSpec((R, w_e), lambda i, s: (i, 0)))
    in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))   # table
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(u // R,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[pltpu.VMEM((R, W), table.dtype),
                        pltpu.SemaphoreType.DMA((R,))],
    )
    # operand order: slots(0) rows(1) extras(2..) table(last) — the
    # alias key counts every operand including the scalar prefetch
    return jaxtrace.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((C, W), table.dtype),
        input_output_aliases={2 + n_extra: 0},
        interpret=interpret_mode())(slots, rows, *extras, table)
