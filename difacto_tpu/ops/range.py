"""Range: the universal [begin, end) work/key partitioner.

Equivalent of the reference's Range (src/common/range.h:11-60) — even
segmentation drives file-part sharding, feature-block partition, and
key-space slicing throughout the framework.
"""

from __future__ import annotations

from typing import NamedTuple


class Range(NamedTuple):
    begin: int
    end: int

    @property
    def size(self) -> int:
        return self.end - self.begin

    def valid(self) -> bool:
        return 0 <= self.begin <= self.end

    def has(self, x: int) -> bool:
        return self.begin <= x < self.end

    def segment(self, idx: int, nparts: int) -> "Range":
        """The idx-th of nparts even segments (Segment, range.h:46-52)."""
        if not (0 <= idx < nparts):
            raise ValueError(f"idx {idx} out of range of {nparts}")
        span = self.size
        return Range(self.begin + span * idx // nparts,
                     self.begin + span * (idx + 1) // nparts)

    def __mul__(self, k: int) -> "Range":
        return Range(self.begin * k, self.end * k)
