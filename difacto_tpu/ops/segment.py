"""Sparse matrix kernels as segment reductions.

TPU-native replacements for the reference's OpenMP CSR kernels:

- ``spmv`` / ``spmv_t``   <- SpMV::Times / TransTimes (src/common/spmv.h:16-203)
- ``spmm`` / ``spmm_t``   <- SpMM::Times / TransTimes (src/common/spmm.h:19-181)

The reference threads over row/column ranges; on TPU the same contractions are
``jax.ops.segment_sum`` over the COO expansion, which XLA lowers to sorted
scatter-adds and fuses with the surrounding elementwise work. The position-
indirection variants (pos[i] == -1 meaning "absent", spmv.h:60-100) become
multiplicative masks — absent rows carry zero weight and masked gradients —
see losses/fm.py's ``v_mask``.

All kernels are shape-static (COO padded by ops/batch.py; padding has val=0 so
it contributes nothing to any segment).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def spmv(vals, rows, cols, x, num_rows: int):
    """y[r] = sum_k vals[k] * x[cols[k]] over nonzeros with rows[k]==r."""
    return jax.ops.segment_sum(vals * x[cols], rows, num_segments=num_rows)


def spmv_t(vals, rows, cols, p, num_cols: int):
    """y[c] = sum_k vals[k] * p[rows[k]] — the transpose product."""
    return jax.ops.segment_sum(vals * p[rows], cols, num_segments=num_cols)


def spmm(vals, rows, cols, X, num_rows: int):
    """Y[r, :] = sum_k vals[k] * X[cols[k], :] for an (U, k) dense rhs."""
    return jax.ops.segment_sum(vals[:, None] * X[cols], rows,
                               num_segments=num_rows)


def spmm_t(vals, rows, cols, P, num_cols: int):
    """Y[c, :] = sum_k vals[k] * P[rows[k], :]."""
    return jax.ops.segment_sum(vals[:, None] * P[rows], cols,
                               num_segments=num_cols)
