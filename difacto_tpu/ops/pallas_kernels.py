"""Pallas TPU kernels for the slot-table Push/Pull hot ops.

The SGD hot path gathers the batch's [w, V] rows from a large HBM-resident
slot table and scatter-adds gradient rows back (store/local.py — the TPU
analog of ps-lite ZPull/ZPush). XLA lowers these to generic gather/scatter;
these kernels stream the arbitrarily-indexed rows with explicit per-row
async DMAs (HBM -> VMEM scratch -> output), indices scalar-prefetched into
SMEM to drive the copies. Blocks of ``BLK`` rows per grid step keep >= 8
in-flight DMAs, and the grid pipeline overlaps successive steps.

- ``gather_rows(table, idx)``            -> table[idx]              (Pull)
- ``scatter_add_rows(table, idx, upd)``  -> table.at[idx].add(upd)  (Push);
  indices MUST be unique (the per-batch unique slot contract,
  data/localizer.py) — each row is read-modified-written exactly once.

Gated: callers opt in (use_pallas); ``interpret=True`` runs on CPU for
tests. idx length must be a multiple of BLK (pad with a trash row id and
zero updates, like the rest of the padded-batch pipeline).

MEASURED (v5e single chip, 2026-07-29, 256x128 f32 rows from a 2^16-row
table): this per-row-DMA kernel runs ~3.3 ms vs XLA's native gather at
~0.047 ms — XLA wins by ~70x because 512 B row copies are DMA-latency-bound
while XLA batches them into vectorized dynamic-gathers. The default hot
path therefore stays on XLA (updaters/sgd_updater.py uses plain indexing);
these kernels remain as the scaffold for wider-row / fused variants where
a hand pipeline can pay off (e.g. fused gather+FM when rows >= 8x128 tiles).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLK = 8  # rows per grid step (sublane-aligned)


def _gather_kernel(idx_ref, tbl_hbm, out_ref, scratch, sems):
    i = pl.program_id(0)
    for j in range(BLK):
        row = idx_ref[i * BLK + j]
        pltpu.make_async_copy(
            tbl_hbm.at[pl.ds(row, 1), :],
            scratch.at[pl.ds(j, 1), :],
            sems.at[j],
        ).start()
    for j in range(BLK):
        row = idx_ref[i * BLK + j]
        pltpu.make_async_copy(
            tbl_hbm.at[pl.ds(row, 1), :],
            scratch.at[pl.ds(j, 1), :],
            sems.at[j],
        ).wait()
    out_ref[:] = scratch[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def gather_rows(table: jnp.ndarray, idx: jnp.ndarray,
                interpret: bool = False) -> jnp.ndarray:
    """out[i, :] = table[idx[i], :]; len(idx) % BLK == 0."""
    n = idx.shape[0]
    if n % BLK:
        raise ValueError(f"idx length {n} must be a multiple of {BLK}")
    w = table.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // BLK,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],  # table in HBM
        out_specs=pl.BlockSpec((BLK, w), lambda i, idx_ref: (i, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((BLK, w), table.dtype),
            pltpu.SemaphoreType.DMA((BLK,)),
        ],
    )
    return pl.pallas_call(
        _gather_kernel,
        out_shape=jax.ShapeDtypeStruct((n, w), table.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(idx, table)


def _scatter_kernel(idx_ref, upd_ref, tbl_hbm, out_hbm, scratch, in_sems,
                    out_sems):
    i = pl.program_id(0)
    for j in range(BLK):
        row = idx_ref[i * BLK + j]
        pltpu.make_async_copy(
            out_hbm.at[pl.ds(row, 1), :],
            scratch.at[pl.ds(j, 1), :],
            in_sems.at[j],
        ).start()
    for j in range(BLK):
        row = idx_ref[i * BLK + j]
        pltpu.make_async_copy(
            out_hbm.at[pl.ds(row, 1), :],
            scratch.at[pl.ds(j, 1), :],
            in_sems.at[j],
        ).wait()
    scratch[:] = scratch[:] + upd_ref[:]
    for j in range(BLK):
        row = idx_ref[i * BLK + j]
        pltpu.make_async_copy(
            scratch.at[pl.ds(j, 1), :],
            out_hbm.at[pl.ds(row, 1), :],
            out_sems.at[j],
        ).start()
    for j in range(BLK):
        row = idx_ref[i * BLK + j]
        pltpu.make_async_copy(
            scratch.at[pl.ds(j, 1), :],
            out_hbm.at[pl.ds(row, 1), :],
            out_sems.at[j],
        ).wait()


@functools.partial(jax.jit, static_argnames=("interpret",),
                   donate_argnums=0)
def scatter_add_rows(table: jnp.ndarray, idx: jnp.ndarray,
                     upd: jnp.ndarray, interpret: bool = False
                     ) -> jnp.ndarray:
    """table.at[idx].add(upd) for UNIQUE idx; table donated (in place)."""
    n = idx.shape[0]
    if n % BLK:
        raise ValueError(f"idx length {n} must be a multiple of {BLK}")
    w = table.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // BLK,),
        in_specs=[
            pl.BlockSpec((BLK, w), lambda i, idx_ref: (i, 0),
                         memory_space=pltpu.VMEM),     # updates
            pl.BlockSpec(memory_space=pltpu.ANY),      # table (aliased)
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[
            pltpu.VMEM((BLK, w), table.dtype),
            pltpu.SemaphoreType.DMA((BLK,)),
            pltpu.SemaphoreType.DMA((BLK,)),
        ],
    )
    return pl.pallas_call(
        _scatter_kernel,
        out_shape=jax.ShapeDtypeStruct(table.shape, table.dtype),
        grid_spec=grid_spec,
        # arg order incl. prefetch: 0=idx, 1=upd, 2=table -> alias to out 0
        input_output_aliases={2: 0},
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
        interpret=interpret,
    )(idx, upd, table)
