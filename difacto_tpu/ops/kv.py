"""Sorted key->value list algebra (host side, vectorised numpy).

Equivalents of the reference's merge kernels — the glue of its KV plane:

- ``find_position``  <- FindPosition (src/common/find_position.h:15-58)
- ``kv_match``       <- KVMatch fixed- and variable-length
  (src/common/kv_match.h:77-163, kv_match-inl.h:22-123)
- ``kv_union``       <- KVUnion (src/common/kv_union.h:34-94)

The reference threads these recursively over key ranges; here each is one
searchsorted/merge pass. Keys must be sorted and unique (the ps-lite
requirement, kvstore_dist.h:95 — asserted cheaply).

Ops: "assign", "add" (the reference's ASSIGN/PLUS, kv_match.h:23-30).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def _check_sorted_unique(keys: np.ndarray, name: str) -> None:
    if len(keys) > 1 and not (keys[1:] > keys[:-1]).all():
        raise ValueError(f"{name} keys must be sorted and unique")


def expand_ranges(starts: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Flat indices covering each [starts[i], starts[i]+lens[i]) range,
    concatenated — the ragged-gather expansion used by variable-length KV
    matching, warm starts, and sampled stats. Empty-safe."""
    lens = np.asarray(lens, dtype=np.int64)
    starts = np.asarray(starts, dtype=np.int64)
    total = int(lens.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64)
    out_starts = np.zeros(len(lens), dtype=np.int64)
    np.cumsum(lens[:-1], out=out_starts[1:])
    return (np.repeat(starts - out_starts, lens)
            + np.arange(total, dtype=np.int64))


def find_position(src_keys: np.ndarray, dst_keys: np.ndarray) -> np.ndarray:
    """int32 positions of each dst key within src (-1 if absent)."""
    _check_sorted_unique(src_keys, "src")
    _check_sorted_unique(dst_keys, "dst")
    n = len(src_keys)
    pos = np.searchsorted(src_keys, dst_keys).astype(np.int64)
    safe = np.minimum(pos, max(n - 1, 0))
    hit = (pos < n)
    if n:
        hit &= src_keys[safe] == dst_keys
    out = np.where(hit, pos, -1).astype(np.int32)
    return out


def kv_match(src_keys: np.ndarray, src_vals: np.ndarray,
             dst_keys: np.ndarray, dst_vals: np.ndarray,
             op: str = "assign", val_len: int = 1) -> int:
    """dst_vals[i] op= src_vals[j] where dst_keys[i] == src_keys[j].

    ``val_len`` values per key (kv_match.h:77-118). Mutates dst_vals in
    place; returns the number of matched *values* like the reference's
    ``matched`` output.
    """
    if dst_vals.ndim != 1 or src_vals.ndim != 1:
        raise ValueError("kv_match expects flat value arrays")
    pos = find_position(src_keys, dst_keys)
    hit = np.nonzero(pos >= 0)[0]
    src_rows = pos[hit].astype(np.int64)
    # fancy-index the caller's array directly (a reshape could silently
    # return a copy for non-contiguous inputs and drop the writes)
    k = np.arange(val_len, dtype=np.int64)
    s_idx = (src_rows[:, None] * val_len + k).ravel()
    d_idx = (hit[:, None].astype(np.int64) * val_len + k).ravel()
    if op == "assign":
        dst_vals[d_idx] = src_vals[s_idx]
    elif op == "add":
        dst_vals[d_idx] += src_vals[s_idx]
    else:
        raise ValueError(f"unknown op {op!r}")
    return int(len(hit)) * val_len


def kv_match_varlen(src_keys: np.ndarray, src_vals: np.ndarray,
                    src_lens: np.ndarray,
                    dst_keys: np.ndarray, dst_vals: np.ndarray,
                    dst_lens: np.ndarray, op: str = "assign") -> int:
    """Variable-length KVMatch (kv_match.h:120-163): key i owns
    ``lens[i]`` consecutive values. Matched keys must agree on length
    (CHECK_EQ in kv_match-inl.h:100). Mutates dst_vals; returns matched
    value count."""
    pos = find_position(src_keys, dst_keys)
    hit = pos >= 0
    src_rows = pos[hit].astype(np.int64)
    if not hit.any():
        return 0
    if not (src_lens[src_rows] == dst_lens[hit]).all():
        raise ValueError("matched keys disagree on value lengths")
    src_off = np.zeros(len(src_keys) + 1, dtype=np.int64)
    np.cumsum(src_lens, out=src_off[1:])
    dst_off = np.zeros(len(dst_keys) + 1, dtype=np.int64)
    np.cumsum(dst_lens, out=dst_off[1:])
    lens = np.asarray(dst_lens)[hit].astype(np.int64)
    # expand each matched key's [start, start+len) value range
    s_idx = expand_ranges(src_off[src_rows], lens)
    d_idx = expand_ranges(dst_off[:-1][hit], lens)
    if op == "assign":
        dst_vals[d_idx] = src_vals[s_idx]
    elif op == "add":
        dst_vals[d_idx] += src_vals[s_idx]
    else:
        raise ValueError(f"unknown op {op!r}")
    return int(lens.sum())


def kv_union(keys_a: np.ndarray, vals_a: np.ndarray,
             keys_b: np.ndarray, vals_b: np.ndarray,
             op: str = "add", val_len: int = 1
             ) -> Tuple[np.ndarray, np.ndarray]:
    """Merged sorted union of two KV lists; duplicate keys combine by
    ``op`` (kv_union.h:34-94). Returns (keys, vals)."""
    _check_sorted_unique(keys_a, "a")
    _check_sorted_unique(keys_b, "b")
    keys = np.union1d(keys_a, keys_b)
    va = vals_a.reshape(len(keys_a), val_len)
    vb = vals_b.reshape(len(keys_b), val_len)
    out = np.zeros((len(keys), val_len), dtype=va.dtype)
    pa = np.searchsorted(keys, keys_a)
    pb = np.searchsorted(keys, keys_b)
    out[pa] = va
    if op == "add":
        np.add.at(out, pb, vb)
    elif op == "assign":
        out[pb] = vb
    else:
        raise ValueError(f"unknown op {op!r}")
    return keys, out.reshape(-1) if val_len == 1 else out
