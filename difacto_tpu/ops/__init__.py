from .batch import DeviceBatch, bucket, pad_batch
from .segment import spmm, spmm_t, spmv, spmv_t

__all__ = ["DeviceBatch", "bucket", "pad_batch",
           "spmm", "spmm_t", "spmv", "spmv_t"]
