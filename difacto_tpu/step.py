"""The fused SGD train/eval step — the single source of truth for the hot path.

One device program replaces the reference's 3-thread worker pipeline
(src/sgd/sgd_learner.h:85-102): gather [w, V] rows from the slot table
("Pull"), FM/logit forward, objective + AUC, backward, FTRL/AdaGrad scatter
update ("Push"). The learner (learners/sgd.py), the driver entry
(__graft_entry__.py) and the benchmark (bench.py) all build their steps here
so they can never drift apart.
"""

from __future__ import annotations

from typing import Tuple

from .losses import FMParams, LossSpec
from .losses.metrics import auc_times_n_jnp


def make_step_fns(fns, loss: LossSpec) -> Tuple:
    """(forward, train_step, eval_step) over (state, batch, slots).

    ``fns`` is the updater namespace from updaters.sgd_updater.make_fns;
    all three returned callables are pure and jit-ready.
    """

    def forward(state, batch, slots):
        w, V, vmask = fns.get_rows(state, slots)
        params = FMParams(w=w, V=V, v_mask=vmask)
        pred = loss.predict(params, batch)
        objv = loss.evaluate(pred, batch)
        auc = auc_times_n_jnp(batch.labels, pred, batch.row_mask)
        return params, pred, objv, auc

    def train_step(state, batch, slots):
        params, pred, objv, auc = forward(state, batch, slots)
        gw, gV = loss.calc_grad(params, batch, pred)
        state = fns.apply_grad(state, slots, gw, gV, params.v_mask)
        return state, objv, auc

    def eval_step(state, batch, slots):
        _, pred, objv, auc = forward(state, batch, slots)
        return pred, objv, auc

    return forward, train_step, eval_step
