"""The fused SGD train/eval step — the single source of truth for the hot path.

One device program replaces the reference's 3-thread worker pipeline
(src/sgd/sgd_learner.h:85-102): gather [w, V] rows from the slot table
("Pull"), FM/logit forward, objective + AUC, backward, FTRL/AdaGrad scatter
update ("Push"). The learner (learners/sgd.py), the driver entry
(__graft_entry__.py) and the benchmark (bench.py) all build their steps here
so they can never drift apart.

Batches address the sorted-unique slot vector directly: in-batch collision
dedup happens on the HOST (store.map_keys_dedup / the producer-thread
np.unique), which rewrites the O(nnz) index array once per batch. The
device-side remap permutation that used to carry this for the cached
reader cost an unsorted u_cap-row permute + scatter-add per step — more
than the host gather it saved (docs/perf_notes.md, round-5 "host dedup").

``train_auc`` picks the per-step training metric: "binned" (default) is the
O(B) histogram AUC — the sort-based exact AUC costs ~10 ms at 64k batches,
~12% of the step; "exact" restores the argsort; "none" skips it. Validation
always uses the exact metric (early stopping compares val-AUC deltas,
sgd_learner.cc:92-110).

**Bounded-delay contract** (``bounded_delay``/τ, learners/sgd.py): the
windowed schedule delays the HOST pipeline only — staging, the DCN
control exchange and the clock barrier all move off the device critical
path, while every gradient application still happens inside this fused
pull→step→push program against the state the previous step returned.
Delayed gradients therefore never bypass the kernel: there is no
host-side apply path, no second writer to the donated table, and τ>0
reuses these exact programs unchanged (the reference applies τ-stale
gradients server-side the same single-writer way, bounded by max_delay).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .losses import FMParams, LossSpec
from .losses.metrics import auc_times_n_binned_jnp, auc_times_n_jnp


def state_constrainer(state_shardings):
    """Pin a returned SGDState to its fs-sharded layout INSIDE the jitted
    program (``state_shardings`` is the NamedSharding pytree from
    parallel.sharding_tree(state, state_sharding(mesh))).

    This is how the mesh layout is threaded through the fused programs
    rather than left to GSPMD inference: the donated state argument
    arrives fs-sharded and the constrained output is guaranteed the SAME
    key-range layout, so XLA's buffer donation keeps the in-place table
    update across shards — the table never round-trips through a
    replicated or re-partitioned intermediate, whatever the surrounding
    batch shardings make the propagation pass prefer. ``None`` (no mesh)
    is the identity."""
    if state_shardings is None:
        return lambda state: state
    return lambda state: jax.lax.with_sharding_constraint(
        state, state_shardings)


def make_step_fns(fns, loss: LossSpec, train_auc: str = "binned",
                  state_shardings=None) -> Tuple:
    """(forward, train_step, eval_step) over (state, batch, slots).

    ``fns`` is the updater namespace from updaters.sgd_updater.make_fns;
    all three returned callables are pure and jit-ready.
    ``state_shardings`` (mesh runs) pins the returned state to the
    table's fs key-range layout — see :func:`state_constrainer`.

    With a fused table backend (``fns.fused`` — fused_kernel=jnp or
    pallas, ops/fused.py) the train step takes the fused dataflow:
    ONE row gather whose result is THREADED from the pull to the push
    (apply_grad_rows), so the push never re-gathers — the composed
    ("off") path instead relies on XLA CSE to merge its two gathers.
    Identical primitives either way: trajectories are byte-identical
    across backends (tests/test_fused.py).
    """
    constrain = state_constrainer(state_shardings)
    fused = bool(getattr(fns, "fused", False))

    def pull(state, batch, slots):
        """(params, slot_vmask, rows-or-None): the fused backends keep
        the gathered rows so train_step can hand them to the push."""
        if fused:
            rows = fns.pull_rows(state, slots)
            w, V, vmask = fns.rows_to_params(state, rows)
            return FMParams(w=w, V=V, v_mask=vmask), vmask, rows
        w, V, vmask = fns.get_rows(state, slots)
        return FMParams(w=w, V=V, v_mask=vmask), vmask, None

    def forward(state, batch, slots):
        params, _, _ = pull(state, batch, slots)
        pred = loss.predict(params, batch)
        objv = loss.evaluate(pred, batch)
        auc = auc_times_n_jnp(batch.labels, pred, batch.row_mask)
        return params, pred, objv, auc

    def train_step(state, batch, slots):
        params, slot_vmask, rows = pull(state, batch, slots)
        # the forward hands its X·V to the backward so the fused step
        # gathers the [U, 1+k] token rows exactly once (round-4 profile:
        # the duplicate gather was ~15% of the step)
        pred, xv = loss.predict_xv(params, batch)
        objv = loss.evaluate(pred, batch)
        if train_auc == "binned":
            auc = auc_times_n_binned_jnp(batch.labels, pred, batch.row_mask)
        elif train_auc == "exact":
            auc = auc_times_n_jnp(batch.labels, pred, batch.row_mask)
        else:
            auc = jnp.float32(0.0)
        gw, gV = loss.calc_grad(params, batch, pred, xv)
        if fused:
            state = fns.apply_grad_rows(state, slots, rows, gw, gV,
                                        slot_vmask)
        else:
            state = fns.apply_grad(state, slots, gw, gV, slot_vmask)
        return constrain(state), objv, auc

    def eval_step(state, batch, slots):
        _, pred, objv, auc = forward(state, batch, slots)
        return pred, objv, auc

    return forward, train_step, eval_step


def fire_step_fault() -> None:
    """Chaos-harness injection point ``step.device`` (utils/faultinject):
    traversed on the HOST once per dispatched device step (the jitted
    programs themselves are pure and cannot host an injection site).
    ``err`` models a poisoned program / lost device surfacing at dispatch
    — it raises the same OSError-derived FaultInjected the IO paths use,
    so the learner's failure handling is exercised end to end; every
    armed fire also counts into ``faults_fired_total{point,kind}``."""
    from .utils import faultinject
    faultinject.act_default(faultinject.fire("step.device"))


def make_predict_fn(fns, loss: LossSpec):
    """Predict-only forward over (state, batch, slots) -> (pred, objv, auc).

    The serving subsystem's step (serve/executor.py): identical ops to
    make_step_fns' eval_step — gather [w, V] rows, loss forward, objective
    + exact AUC — without building the train step, so a read-only store
    (no optimizer state) can serve it. Sharing the op sequence is
    load-bearing: task=pred and task=serve dispatch the SAME program for
    the same batch shapes, which is what makes their outputs bit-identical
    (tests/test_serve.py golden test)."""

    def predict_step(state, batch, slots):
        w, V, vmask = fns.get_rows(state, slots)
        params = FMParams(w=w, V=V, v_mask=vmask)
        pred = loss.predict(params, batch)
        objv = loss.evaluate(pred, batch)
        auc = auc_times_n_jnp(batch.labels, pred, batch.row_mask)
        return pred, objv, auc

    return predict_step
