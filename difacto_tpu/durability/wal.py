"""Write-ahead delta log: CRC'd touched-row segments between checkpoints.

Full verified checkpoints (store/local.py save + utils/manifest.py) bound
a crash's data loss to one ``ckpt_interval`` of work — every batch since
the last generation replays after a SIGKILL. The reference parameter
server does better by construction: server state is replicated across
machines as it mutates, so a dead host loses (almost) nothing (PAPER.md
scheduler/server/worker roles). This module is the single-host half of
that story: between full checkpoints, the trainer appends the *touched
fused rows* of the last ``wal_flush_batches`` dispatched steps as one
CRC'd segment, so recovery = base generation + ordered deltas and the
recovery point objective drops from ``ckpt_interval`` epochs to
``wal_flush_batches`` batches.

Segment format (little-endian, rec2's framing idiom — data/rec2.py):

    [0]   magic  b"DFWAL1\\0\\0"                     8 bytes
    [8]   u32 version (=1) | u32 n_sections
    [16]  u32 table_crc32 (over the section table) | u32 pad
    [24]  n_sections x section entry (48 bytes each):
              name   16 bytes (ascii, NUL padded)
              dtype  16 bytes (numpy/ml_dtypes dtype NAME, NUL padded)
              u64    byte offset (64-aligned, from file start)
              u64    nbytes
    [..]  u32 crc32 per section
    [..]  sections, each aligned to 64

Section ``meta`` is a JSON document (uint8 bytes) carrying the chain
position (generation / seq / rank), the covered step window (epoch,
step_lo, step_hi, boundary) and the table geometry stamp (hash_capacity,
V_dim, slot_dtype, row width) that replay validates before applying.
Section ``slots`` is the sorted unique i32 row ids the window touched;
the remaining sections are the row payload exactly as the device stores
it — ``VVg`` CONTAINER rows for the fused layout (so int8/fp8/bf16
``slot_dtype`` tables log container bytes, not dequantized f32: the log
is quantization-aware and replay is bit-exact by construction), or the
five flat f32/bool columns when ``V_dim == 0``.

Integrity mirrors rec2: header CRC over the section table, one CRC per
section, tmp + atomic rename so a torn write is never observable at the
final name. :func:`read_segment` raises a typed :class:`WalCorrupt` on
truncation, bit flips or a bad magic — :func:`replay` treats a corrupt
or missing segment as the end of the verified prefix (torn-tail
tolerant, like online/log.py's sealed segments) and NEVER applies bytes
past it, so recovery lands on a consistent earlier batch boundary
instead of a silently-wrong state.

Chaos: appends traverse the ``wal.append`` injection point, replays
``wal.replay`` (utils/faultinject.py — the catalog there documents the
per-kind semantics).
"""

from __future__ import annotations

import json
import logging
import os
import re
import struct
import zlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

log = logging.getLogger("difacto_tpu")

MAGIC = b"DFWAL1\0\0"
VERSION = 1
SUFFIX = ".dfwal"
ALIGN = 64

_HEAD = struct.Struct("<8sIIII")     # magic, version, n_sections, crc, pad
_SECT = struct.Struct("<16s16sQQ")   # name, dtype name, offset, nbytes
_SEG_RE = re.compile(r"r(\d+)-g(\d+)-s(\d+)\.dfwal$")

# the only sections a segment may carry: the chain meta, the touched
# slot ids, and the row payload of either state layout (fused VVg
# container rows, or the five flat columns of the V_dim=0 layout)
SECTION_NAMES = ("meta", "slots", "VVg", "w", "z", "sqrt_g", "cnt",
                 "v_live")


class WalCorrupt(ValueError):
    """A WAL segment failed structural or checksum validation (torn
    write, truncation, bit flip) or disagrees with the chain it claims
    to extend. Typed so replay stops at the verified prefix — the delta
    log's analog of store.local.CheckpointCorrupt."""


def wal_dir(model_out: str) -> str:
    """The delta-log directory of a model family: ``<model_out>.wal``."""
    return model_out + ".wal"


def segment_name(rank: int, generation: int, seq: int) -> str:
    return f"r{rank:03d}-g{generation:06d}-s{seq:06d}{SUFFIX}"


def _resolve_dtype(name: str) -> np.dtype:
    """Dtype by NAME, including the ml_dtypes containers numpy itself
    cannot parse (bfloat16, float8_e4m3fn, ...) — jax always ships
    ml_dtypes, so quantized WAL rows round-trip without new deps."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        try:
            return np.dtype(getattr(ml_dtypes, name))
        except AttributeError as e:
            raise WalCorrupt(f"unknown WAL section dtype {name!r}") from e


def _align(n: int) -> int:
    return (n + ALIGN - 1) // ALIGN * ALIGN


def _corrupt(path: str, why: str) -> WalCorrupt:
    return WalCorrupt(f"corrupt WAL segment {path!r}: {why}")


def _encode(meta: dict, arrays: Dict[str, np.ndarray]) -> bytes:
    sects = {"meta": np.frombuffer(
        json.dumps(meta, sort_keys=True).encode(), dtype=np.uint8)}
    sects.update(arrays)
    names = list(sects)
    for n in names:
        if n not in SECTION_NAMES:
            raise ValueError(f"unknown WAL section {n!r} "
                             f"(one of {SECTION_NAMES})")
    header_len = _HEAD.size + len(names) * _SECT.size + len(names) * 4
    off = _align(header_len)
    entries, crcs, mats = [], [], []
    for n in names:
        a = np.ascontiguousarray(sects[n])
        # tobytes, not a.data: ml_dtypes containers (bfloat16, fp8)
        # have no buffer-protocol format char
        raw = a.tobytes()
        mats.append(raw)
        entries.append((n.encode().ljust(16, b"\0"),
                        a.dtype.name.encode().ljust(16, b"\0"),
                        off, len(raw)))
        crcs.append(zlib.crc32(raw))
        off = _align(off + len(raw))
    table = b"".join(_SECT.pack(*e) for e in entries) \
        + b"".join(struct.pack("<I", c) for c in crcs)
    out = bytearray(_HEAD.pack(MAGIC, VERSION, len(names),
                               zlib.crc32(table), 0))
    out += table
    for (_, _, o, _), raw in zip(entries, mats):
        out += b"\0" * (o - len(out))
        out += raw
    return bytes(out)


def write_segment(path: str, meta: dict,
                  arrays: Dict[str, np.ndarray]) -> int:
    """Atomically write one delta segment (tmp + rename); returns the
    byte size. Traverses the ``wal.append`` fault point: ``err`` raises
    (the caller retains its window and retries at the next flush
    boundary), ``truncate`` tears the segment at its final name — the
    torn-tail shape replay's CRCs must reject — ``kill`` dies before
    any bytes land (the honest crash mid-window)."""
    from ..utils import faultinject
    kind = faultinject.fire("wal.append")
    if kind is not None and kind != "truncate":
        faultinject.act_default(kind)
    buf = _encode(meta, arrays)
    if kind == "truncate":
        buf = buf[:max(len(buf) // 2, 1)]
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(buf)
    os.replace(tmp, path)
    return len(buf)


def read_segment(path: str, verify: bool = True) -> Tuple[dict, dict]:
    """Read one segment -> (meta, {name: array}). Raises the typed
    :class:`WalCorrupt` on any structural or checksum failure — never a
    crash or a silent short read. Traverses ``wal.replay``: ``err`` is
    a failed disk read, ``truncate`` reads a half-length view which the
    CRCs reject."""
    from ..utils import faultinject
    kind = faultinject.fire("wal.replay")
    if kind == "err":  # pragma: no cover - fire() raises for err itself
        raise _corrupt(path, "injected read error")
    try:
        with open(path, "rb") as f:
            buf = f.read()
    except OSError as e:
        if isinstance(e, FileNotFoundError):
            raise
        raise _corrupt(path, f"unreadable ({e})") from e
    if kind == "truncate":
        buf = buf[:max(len(buf) // 2, 1)]
    elif kind is not None:
        faultinject.act_default(kind)
    if len(buf) < _HEAD.size:
        raise _corrupt(path, f"file too short ({len(buf)} bytes)")
    magic, version, n_sections, head_crc, _ = _HEAD.unpack_from(buf, 0)
    if magic != MAGIC:
        raise _corrupt(path, f"bad magic {magic!r}")
    if version != VERSION:
        raise _corrupt(path, f"unsupported version {version}")
    if not 0 < n_sections <= len(SECTION_NAMES):
        raise _corrupt(path, f"implausible section count {n_sections}")
    table_len = n_sections * _SECT.size + n_sections * 4
    if len(buf) < _HEAD.size + table_len:
        raise _corrupt(path, "truncated section table")
    table = buf[_HEAD.size:_HEAD.size + table_len]
    if zlib.crc32(table) != head_crc:
        raise _corrupt(path, "section table checksum mismatch")
    crc_base = _HEAD.size + n_sections * _SECT.size
    arrays: Dict[str, np.ndarray] = {}
    for i in range(n_sections):
        name_b, dtype_b, off, nbytes = _SECT.unpack_from(
            buf, _HEAD.size + i * _SECT.size)
        name = name_b.rstrip(b"\0").decode("ascii", "replace")
        if name not in SECTION_NAMES:
            raise _corrupt(path, f"unknown section {name!r}")
        dt = _resolve_dtype(dtype_b.rstrip(b"\0").decode("ascii",
                                                         "replace"))
        if off % ALIGN or off + nbytes > len(buf):
            raise _corrupt(
                path, f"section {name!r} [{off}, {off + nbytes}) outside "
                f"file of {len(buf)} bytes")
        if dt.itemsize == 0 or nbytes % dt.itemsize:
            raise _corrupt(path, f"section {name!r} nbytes {nbytes} not "
                           f"a multiple of dtype {dt.name}")
        view = buf[off:off + nbytes]
        if verify:
            want, = struct.unpack_from("<I", buf, crc_base + 4 * i)
            if zlib.crc32(view) != want:
                raise _corrupt(path, f"section {name!r} checksum "
                               "mismatch")
        arrays[name] = np.frombuffer(view, dtype=dt)
    if "meta" not in arrays or "slots" not in arrays:
        raise _corrupt(path, "meta/slots section missing")
    try:
        meta = json.loads(bytes(arrays.pop("meta")).decode())
    except ValueError as e:
        raise _corrupt(path, f"unreadable meta ({e})") from e
    return meta, arrays


def chain_segments(dir_: str, rank: int,
                   generation: int) -> List[Tuple[int, str]]:
    """[(seq, path)] of the chain rooted at ``generation``, seq order.
    A seq gap is NOT resolved here — replay stops at it typed."""
    out = []
    try:
        names = os.listdir(dir_)
    except FileNotFoundError:
        return []
    for name in names:
        m = _SEG_RE.match(name)
        if m and int(m.group(1)) == rank and int(m.group(2)) == generation:
            out.append((int(m.group(3)), os.path.join(dir_, name)))
    out.sort()
    return out


def chain_generations(dir_: str, rank: int) -> List[int]:
    """Generations with at least one segment for ``rank``, descending."""
    gens = set()
    try:
        names = os.listdir(dir_)
    except FileNotFoundError:
        return []
    for name in names:
        m = _SEG_RE.match(name)
        if m and int(m.group(1)) == rank:
            gens.add(int(m.group(2)))
    return sorted(gens, reverse=True)


@dataclass
class ReplayResult:
    """What :func:`replay` applied: the verified contiguous prefix of
    the chain. ``epoch``/``step`` are the batch boundary the recovered
    state now sits at; ``boundary`` marks an epoch-complete head."""
    generation: int
    epoch: int = -1
    step: int = 0
    boundary: bool = True
    batches: int = 0
    segments: int = 0
    next_seq: int = 0
    stopped: str = ""  # "" = clean head; else torn|gap|geometry|chain


def _geom_ok(meta: dict, geom: dict) -> bool:
    return all(meta.get(k) == v for k, v in geom.items())


def replay(store, dir_: str, rank: int, generation: int,
           base_epoch: int = -1) -> ReplayResult:
    """Apply the chain rooted at ``generation`` onto ``store`` (already
    holding the base state), in seq order, stopping TYPED at the first
    gap, corruption or geometry mismatch — everything before the stop is
    a consistent batch boundary; nothing after it is applied. Counted
    into ``wal_replay_batches`` / ``wal_replay_dropped_total``."""
    from ..obs import counter
    geom = store.wal_geometry()
    res = ReplayResult(generation=generation, epoch=base_epoch)
    segs = chain_segments(dir_, rank, generation)
    want_seq = 0
    for seq, path in segs:
        if seq != want_seq:
            log.warning("wal replay: seq gap at %s (want seq %d); "
                        "stopping at the verified prefix", path, want_seq)
            counter("wal_replay_dropped_total",
                    "WAL segments dropped at replay, by reason"
                    ).labels(reason="gap").inc(len(segs) - res.segments)
            res.stopped = "gap"
            return res
        try:
            meta, arrays = read_segment(path)
        except (WalCorrupt, OSError) as e:
            log.warning("wal replay: %s; stopping at the verified "
                        "prefix", e)
            counter("wal_replay_dropped_total",
                    "WAL segments dropped at replay, by reason"
                    ).labels(reason="torn").inc(len(segs) - res.segments)
            res.stopped = "torn"
            return res
        if not _geom_ok(meta, geom) or meta.get("generation") != generation \
                or meta.get("rank") != rank or meta.get("seq") != seq:
            log.warning("wal replay: %s geometry/chain stamp disagrees "
                        "with the live table; stopping", path)
            counter("wal_replay_dropped_total",
                    "WAL segments dropped at replay, by reason"
                    ).labels(reason="geometry").inc(
                        len(segs) - res.segments)
            res.stopped = "geometry"
            return res
        epoch, lo, hi = (int(meta["epoch"]), int(meta["step_lo"]),
                         int(meta["step_hi"]))
        contiguous = (
            (epoch == res.epoch and lo == res.step)
            or (res.boundary and epoch == res.epoch + 1 and lo == 0))
        if not contiguous:
            log.warning("wal replay: %s covers (%d, %d..%d) but the "
                        "head is (%d, %d); stopping", path, epoch, lo,
                        hi, res.epoch, res.step)
            counter("wal_replay_dropped_total",
                    "WAL segments dropped at replay, by reason"
                    ).labels(reason="chain").inc(len(segs) - res.segments)
            res.stopped = "chain"
            return res
        slots = arrays.pop("slots").astype(np.int32)
        store.apply_wal_rows(slots, arrays)
        res.epoch, res.step = epoch, hi
        res.boundary = bool(meta.get("boundary"))
        res.batches += hi - lo
        res.segments += 1
        res.next_seq = seq + 1
        want_seq = seq + 1
    if res.batches:
        counter("wal_replay_batches",
                "training batches recovered from WAL deltas instead of "
                "re-executed").inc(res.batches)
    return res


@dataclass
class WalWriter:
    """Per-rank append head of the delta log. The learner owns the
    flush cadence; this class owns naming, chain position and retention.
    Single-threaded by contract: every call rides the dispatch thread
    (appends) or startup (rebase/adopt), never concurrently."""
    dir: str
    rank: int
    geom: dict
    generation: int = 0
    seq: int = 0
    # epoch of the checkpoint the live chain is rooted at; None until
    # the first rebase — prune protection (utils/manifest.py
    # prune_checkpoints) reads this so a live chain's base generation
    # is never retired under it
    base_epoch: Optional[int] = None
    keep_generations: int = 2
    _bytes_c: object = field(default=None, repr=False)

    def append(self, slots: np.ndarray, arrays: Dict[str, np.ndarray],
               epoch: int, step_lo: int, step_hi: int,
               boundary: bool = False) -> Optional[str]:
        """Write one segment covering steps [step_lo, step_hi) of
        ``epoch``; returns its path (None for an empty non-boundary
        window). Raises FaultInjected/OSError on a failed write — the
        caller retains the window and retries at the next boundary."""
        if len(slots) == 0 and not boundary:
            return None
        meta = dict(self.geom)
        meta.update(generation=self.generation, seq=self.seq,
                    rank=self.rank, epoch=int(epoch),
                    step_lo=int(step_lo), step_hi=int(step_hi),
                    boundary=bool(boundary))
        path = os.path.join(
            self.dir, segment_name(self.rank, self.generation, self.seq))
        sects = {"slots": np.asarray(slots, dtype=np.int32)}
        sects.update(arrays)
        nbytes = write_segment(path, meta, sects)
        self.seq += 1
        if self._bytes_c is None:
            from ..obs import counter
            self._bytes_c = counter(
                "wal_bytes_total",
                "bytes appended to the write-ahead delta log")
        self._bytes_c.inc(nbytes)
        return path

    def rebase(self, generation: int, epoch: Optional[int]) -> None:
        """Root the chain at a freshly committed checkpoint generation
        and retire chains older than ``keep_generations`` bases back
        (the newest checkpoint supersedes their deltas; one extra base
        is kept so a corrupt newest generation still walks back to a
        base+chain pair)."""
        self.generation = generation
        self.seq = 0
        self.base_epoch = epoch
        keep = generation - (self.keep_generations - 1)
        try:
            names = os.listdir(self.dir)
        except FileNotFoundError:
            return
        for name in names:
            m = _SEG_RE.match(name)
            if m and int(m.group(1)) == self.rank \
                    and int(m.group(2)) < keep:
                try:
                    os.remove(os.path.join(self.dir, name))
                except OSError:  # pragma: no cover - concurrent cleanup
                    pass

    def adopt(self, generation: int, next_seq: int,
              base_epoch: Optional[int]) -> None:
        """Continue an existing chain after replay: new appends extend
        the verified prefix. Segments at/past ``next_seq`` (the dead
        tail past a stop, superseded by the recovery decision) are
        removed so the chain stays gap-free."""
        self.generation = generation
        self.seq = next_seq
        self.base_epoch = base_epoch
        for seq, path in chain_segments(self.dir, self.rank, generation):
            if seq >= next_seq:
                try:
                    os.remove(path)
                except OSError:  # pragma: no cover - concurrent cleanup
                    pass
