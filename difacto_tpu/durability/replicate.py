"""Peer replication of checkpoint families and their live WAL chains.

The reference parameter server survives a dead server because the
key-range it owned is *replicated on peer machines* — recovery is a
fetch, not a recompute (PAPER.md PS architecture). This module is that
leg for the reproduction: after each verified commit, a rank's shard
family files (``<model>_iter-k_part-r`` / ``_fs-i-of-n`` + manifests +
the ``.meta`` progress stub) and its live WAL segments are pushed
asynchronously to ``replica_k`` of the ``replica_peers`` destinations —
OFF the step path, so training throughput never waits on replication.

Peers are directories: a shared filesystem path, or a per-peer mount of
another host's disk (remote URI transports are out of scope — the
stream layer's file:// is the only transport the container guarantees).
The push preserves the path's shape relative to the model's directory
(``model_iter-0_part-0.npz`` and ``model.wal/r000-...dfwal`` land under
the same names at the peer), so :func:`fetch_family` can restore a lost
local dir byte-for-byte and the recovery ladder (durability/recover.py)
resumes from it exactly as from a local checkpoint.

Every copy is tmp + atomic rename with a sha256 readback compare, so a
peer never exposes a torn file under its final name. The anti-entropy
:meth:`Replicator.scrub` re-verifies what the peer actually holds —
npz members against their manifests (utils/manifest.py), WAL segments
through their CRCs (durability/wal.py), byte-compare for sidecars —
and re-pushes anything missing or corrupt, counted in
``replica_scrub_repairs_total``. Staleness is observable as the
``replica_lag_generations{peer}`` gauge: committed generations the peer
has not finished receiving (0 = caught up).

Chaos: pushes traverse the ``replica.push`` injection point and fetches
``replica.fetch`` (utils/faultinject.py catalog for per-kind semantics).
"""

from __future__ import annotations

import hashlib
import logging
import os
import shutil
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..obs import counter, gauge
from ..utils import faultinject
from ..utils.locktrace import condition
from . import wal as _wal

log = logging.getLogger("difacto_tpu")


def parse_peers(spec: str) -> List[str]:
    """``replica_peers`` knob -> peer directory list (comma-separated,
    blanks dropped)."""
    return [p.strip() for p in spec.split(",") if p.strip()]


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def push_file(src: str, peer: str, root: str) -> str:
    """Copy one file to ``peer`` preserving its path relative to
    ``root``, tmp + rename + sha256 readback. Traverses the
    ``replica.push`` fault point: ``err`` is a failed copy (the caller
    counts it and moves on — the scrub repairs later), ``truncate``
    lands a half-length file at the final name, exactly the torn
    artifact the scrub's verification must catch."""
    kind = faultinject.fire("replica.push")
    if kind is not None and kind != "truncate":
        faultinject.act_default(kind)
    rel = os.path.relpath(src, root)
    dst = os.path.join(peer, rel)
    os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
    tmp = dst + ".tmp"
    if kind == "truncate":
        with open(src, "rb") as f:
            buf = f.read()
        with open(tmp, "wb") as f:
            f.write(buf[:max(len(buf) // 2, 1)])
        os.replace(tmp, dst)
        return dst
    want = _sha256(src)
    shutil.copyfile(src, tmp)
    if _sha256(tmp) != want:  # pragma: no cover - needs a racing writer
        os.remove(tmp)
        raise OSError(f"replica copy of {src} to {peer} failed readback")
    os.replace(tmp, dst)
    return dst


def fetch_file(peer: str, rel: str, root: str) -> str:
    """Copy ``peer``'s copy of ``rel`` back into the local ``root``
    (tmp + rename; content verification is the caller's job — the
    recovery ladder runs the fetched family through the same manifest /
    CRC gates a local checkpoint passes). Traverses ``replica.fetch``:
    ``err`` is a dead peer / failed read and must surface typed so the
    ladder tries the next peer."""
    kind = faultinject.fire("replica.fetch")
    if kind is not None:
        faultinject.act_default(kind)
    src = os.path.join(peer, rel)
    dst = os.path.join(root, rel)
    os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
    tmp = dst + ".tmp"
    shutil.copyfile(src, tmp)
    os.replace(tmp, dst)
    return dst


def family_files(model_out: str) -> List[str]:
    """Every local file of ``model_out``'s durable state: checkpoint
    family members + manifests + the ``.meta`` progress stub + live WAL
    segments. This is the replication unit AND the fetch unit."""
    import glob as _glob
    out = sorted(_glob.glob(model_out + "_iter-*")) \
        + sorted(_glob.glob(model_out + ".meta")) \
        + sorted(_glob.glob(model_out + ".recovery.json"))
    d = _wal.wal_dir(model_out)
    if os.path.isdir(d):
        out += sorted(os.path.join(d, n) for n in os.listdir(d)
                      if n.endswith(_wal.SUFFIX))
    return out


def fetch_family(model_out: str, peers: Sequence[str]) -> Optional[str]:
    """Restore ``model_out``'s family from the first peer holding one
    (newest-generation peer wins when several do). Returns the peer
    used, or None. Typed per-file failures (FaultInjected/OSError) fail
    that peer and move to the next — a half-fetched family is then
    overwritten by the next peer or rejected by the ladder's verify."""
    from ..utils import manifest as mft
    root = os.path.dirname(model_out) or "."
    base = os.path.basename(model_out)
    ranked: List[Tuple[int, str]] = []
    for peer in peers:
        try:
            names = os.listdir(peer)
        except OSError:
            continue
        gen = -1
        for n in names:
            if n.startswith(base + "_iter-") \
                    and n.endswith(mft.MANIFEST_SUFFIX):
                man = mft.read(os.path.join(peer, n[:-len(
                    mft.MANIFEST_SUFFIX)]))
                if man:
                    gen = max(gen, int(man.get("generation", 0)))
        if gen >= 0:
            ranked.append((gen, peer))
    ranked.sort(reverse=True)
    fetch_fail = counter(
        "replica_fetch_failures_total",
        "files a recovery fetch failed to pull from a peer")
    for _, peer in ranked:
        rels = [n for n in os.listdir(peer)
                if n.startswith(base + "_iter-") or n == base + ".meta"]
        wdir = os.path.join(peer, base + ".wal")
        if os.path.isdir(wdir):
            rels += [os.path.join(base + ".wal", n)
                     for n in os.listdir(wdir)
                     if n.endswith(_wal.SUFFIX)]
        ok = True
        for rel in sorted(rels):
            try:
                fetch_file(peer, rel, root)
            except (faultinject.FaultInjected, OSError) as e:
                fetch_fail.inc()
                log.warning("replica fetch of %s from %s failed: %s; "
                            "trying the next peer", rel, peer, e)
                ok = False
                break
        if ok and rels:
            log.info("recovered %d family files for %s from peer %s",
                     len(rels), model_out, peer)
            return peer
    return None


class Replicator:
    """Async push worker: the learner enqueues (files, generation,
    epoch) after each verified commit / WAL append; one daemon thread
    drains the queue and copies to ``k`` peers, never holding the lock
    across IO. ``close()`` drains and joins."""

    def __init__(self, peers: Sequence[str], k: int, rank: int,
                 root: str):
        self.peers = list(peers)
        self.k = max(1, min(int(k), len(self.peers)) if self.peers
                     else int(k))
        self.rank = rank
        self.root = root or "."
        self._cv = condition()
        self._queue: List[Tuple[List[str], int, Optional[int]]] = []
        self._inflight_epochs: Set[int] = set()
        self._closed = False
        self._enqueued_gen = 0
        self._pushed_gen: Dict[str, int] = {p: 0 for p in self.peers}
        self._push_fail = counter(
            "replica_push_failures_total",
            "files an async replica push failed to land on a peer")
        self._lag = gauge(
            "replica_lag_generations",
            "committed generations a replica peer has not finished "
            "receiving (0 = caught up)")
        self._thread = threading.Thread(
            target=self._run, name=f"replica-push-r{rank}", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------ API
    def push(self, files: Iterable[str], generation: int = 0,
             epoch: Optional[int] = None) -> None:
        """Enqueue a file set for replication (returns immediately —
        the copy happens on the worker thread, off the step path)."""
        files = [f for f in files if os.path.exists(f)]
        if not files or not self.peers:
            return
        with self._cv:
            self._queue.append((files, generation, epoch))
            if epoch is not None:
                self._inflight_epochs.add(epoch)
            self._enqueued_gen = max(self._enqueued_gen, generation)
            self._update_lag_locked()
            self._cv.notify()

    def protected_epochs(self) -> Set[int]:
        """Epochs with queued or in-flight pushes — ``ckpt_keep``
        pruning must not retire these while a peer is still receiving
        them (utils/manifest.py prune_checkpoints ``protect``)."""
        with self._cv:
            return set(self._inflight_epochs)

    def flush(self, timeout: float = 30.0) -> bool:
        """Block until the queue drains (True) or ``timeout`` elapses.
        Commit boundaries call this only where durability beats latency
        (final save, shutdown)."""
        with self._cv:
            return self._cv.wait_for(
                lambda: not self._queue and not self._inflight_epochs,
                timeout=timeout)

    def close(self, timeout: float = 30.0) -> None:
        self.flush(timeout)
        with self._cv:
            self._closed = True
            self._cv.notify()
        self._thread.join(timeout=timeout)

    def scrub(self, model_out: str) -> int:
        """Anti-entropy pass: verify every family file at every peer —
        npz members against their manifest digests, WAL segments
        through their CRCs, byte-compare for sidecars — and re-push
        anything missing or failing. Returns the repair count (also in
        ``replica_scrub_repairs_total``)."""
        from ..utils import manifest as mft
        repairs = 0
        repair_c = counter(
            "replica_scrub_repairs_total",
            "peer replica files re-pushed by the anti-entropy scrub")
        for src in family_files(model_out):
            rel = os.path.relpath(src, self.root)
            for peer in self.peers[:self.k]:
                dst = os.path.join(peer, rel)
                if self._peer_copy_ok(src, dst, mft):
                    continue
                try:
                    push_file(src, peer, self.root)
                    repairs += 1
                    repair_c.inc()
                except (faultinject.FaultInjected, OSError) as e:
                    self._push_fail.inc()
                    log.warning("scrub re-push of %s to %s failed: %s",
                                rel, peer, e)
        return repairs

    # -------------------------------------------------------- worker
    def _peer_copy_ok(self, src: str, dst: str, mft) -> bool:
        if not os.path.exists(dst):
            return False
        # a checkpoint member (it has a manifest sidecar locally) gets
        # the real digest verification — the same gate a loader applies
        if not src.endswith(mft.MANIFEST_SUFFIX) \
                and os.path.exists(src + mft.MANIFEST_SUFFIX):
            try:
                mft.verify(dst, require_manifest=True)
                return True
            except (mft.CheckpointCorrupt, OSError):
                return False
        if dst.endswith(_wal.SUFFIX):
            try:
                _wal.read_segment(dst)
                return True
            except (_wal.WalCorrupt, OSError):
                return False
        try:
            with open(src, "rb") as a, open(dst, "rb") as b:
                return a.read() == b.read()
        except OSError:
            return False

    def _update_lag_locked(self) -> None:
        for p in self.peers[:self.k]:
            self._lag.labels(peer=os.path.basename(p.rstrip("/")) or p
                             ).set(max(0, self._enqueued_gen
                                       - self._pushed_gen.get(p, 0)))

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait()
                if not self._queue and self._closed:
                    return
                files, generation, epoch = self._queue.pop(0)
            # copy OUTSIDE the lock: replication IO must never block
            # the enqueueing (training) thread
            for peer in self.peers[:self.k]:
                ok = True
                for src in files:
                    try:
                        push_file(src, peer, self.root)
                    except (faultinject.FaultInjected, OSError) as e:
                        ok = False
                        self._push_fail.inc()
                        log.warning(
                            "replica push of %s to %s failed: %s (the "
                            "anti-entropy scrub repairs it)", src, peer,
                            e)
                if ok and generation:
                    self._pushed_gen[peer] = max(
                        self._pushed_gen.get(peer, 0), generation)
            with self._cv:
                if epoch is not None and not any(
                        e == epoch for _, _, e in self._queue):
                    self._inflight_epochs.discard(epoch)
                self._update_lag_locked()
                self._cv.notify_all()
