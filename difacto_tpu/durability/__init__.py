"""Durable training state (ISSUE 20): the "kill anything, lose (almost)
nothing" guarantee the reference parameter server gets from key-range
replication, rebuilt for the reproduction's checkpoint-centric world.

Three legs, composing with — never replacing — the verified-checkpoint
machinery (utils/manifest.py, store/local.py save/load):

- :mod:`.wal` — a write-ahead delta log: between full checkpoints each
  rank appends the touched fused rows of the last ``wal_flush_batches``
  steps as CRC'd segments; recovery = base generation + ordered deltas,
  so the recovery point objective shrinks from ``ckpt_interval`` to
  ``wal_flush_batches`` batches.
- :mod:`.replicate` — async peer push of the shard family + live WAL
  chain after each verified commit, with an anti-entropy scrub; a lost
  local disk recovers by fetching the newest verifying peer copy.
- :mod:`.recover` — the recovery ladder ``auto_resume`` climbs: local
  generation walk-back -> peer fetch -> WAL replay to head, each
  failure typed, each rung counted (``recovery_rung_total{rung}``).

Knobs: ``wal_flush_batches`` / ``replica_peers`` / ``replica_k``
(learners/sgd.py SGDLearnerParam; README knob table). All default OFF:
the defaults-off build is byte-identical to the pre-durability code
path. Runbook: docs/serving.md "Durability & recovery".
"""

from . import recover, replicate, wal  # noqa: F401
from .replicate import Replicator, fetch_family  # noqa: F401
from .wal import ReplayResult, WalCorrupt, WalWriter  # noqa: F401
