"""Recovery ladder: local walk-back -> peer fetch -> WAL replay to head.

``auto_resume``'s original rung — load the newest locally-verifying
checkpoint generation, walking back over corrupt ones — bounded a
crash's loss to one ``ckpt_interval``. With the durability subsystem on
(``wal_flush_batches`` / ``replica_peers``), resume climbs a ladder:

    rung "local" : the classic generation walk-back
                   (learners/sgd.py _try_resume_base)
    rung "peer"  : nothing local verifies (disk loss, fresh host) ->
                   fetch the newest verifying peer replica of the whole
                   family + its WAL chain (replicate.fetch_family), then
                   re-run the local walk-back over the fetched files
    rung "wal"   : replay the delta chain rooted at the loaded base
                   generation to its verified head
                   (wal.replay — torn/gap/geometry stops are typed and
                   land on a consistent earlier batch boundary)

Every failure on the way is TYPED (CheckpointCorrupt / WalCorrupt /
FaultInjected / OSError) and demotes to the next rung; every rung that
contributes is counted in ``recovery_rung_total{rung}`` and recorded in
the ``<model_out>.recovery.json`` stamp, so a post-incident read shows
exactly how the process came back and how much work replay recovered
(``wal_replay_batches``). ``launch.py`` relaunch and the bounded-delay
restart attempt (parallel/fault.py) compose unchanged: they re-exec the
process, and this ladder is simply what its ``auto_resume`` now does.

The resume contract with the epoch loop stays the reference's: the
ladder returns the last COMPLETED epoch (run() restarts at the next
one) and arms ``learner._wal_skip`` when the WAL head sits mid-epoch —
the re-entered epoch skips the batches whose effects the replay already
applied, so the continued trajectory is byte-identical to an unkilled
run at the same batch boundary (the deterministic data order makes the
skipped prefix exactly the replayed prefix).
"""

from __future__ import annotations

import json
import logging
import os
from typing import Optional

from ..obs import counter
from . import replicate, wal as _wal

log = logging.getLogger("difacto_tpu")


def _rung_counter():
    return counter(
        "recovery_rung_total",
        "recovery-ladder rungs that contributed to a resume, per rung "
        "(fresh = nothing recoverable, started from scratch)")


def run_ladder(learner) -> Optional[int]:
    """Climb the ladder for ``learner`` (an SGDLearner with the
    durability knobs resolved). Mutates the store to the recovered
    state, re-roots the learner's WalWriter, sets ``learner._wal_skip``
    and writes the recovery stamp. Returns the last completed epoch
    (−1 = WAL-only progress on a virgin base), or None to start
    fresh."""
    p = learner.param
    rungs = []
    rung_c = _rung_counter()

    got = learner._try_resume_base()
    if got is not None:
        rungs.append("local")
        rung_c.labels(rung="local").inc()

    peers = replicate.parse_peers(p.replica_peers)
    if got is None and peers:
        peer = replicate.fetch_family(p.model_out, peers)
        if peer is not None:
            got = learner._try_resume_base()
            if got is not None:
                rungs.append("peer")
                rung_c.labels(rung="peer").inc()
            else:
                log.warning("recovery: peer %s family fetched but no "
                            "generation verified locally", peer)

    if learner._wal is None:
        if got is None:
            rung_c.labels(rung="fresh").inc()
            _write_stamp(p.model_out, rungs, None, None)
            return None
        _write_stamp(p.model_out, rungs, got[0], None)
        return got[0]

    return _replay_rung(learner, got, rungs, rung_c)


def _replay_rung(learner, got, rungs, rung_c) -> Optional[int]:
    from ..utils import manifest as mft
    p = learner.param
    writer: _wal.WalWriter = learner._wal
    if got is not None:
        base_epoch, path = got
        man = mft.read(path) or {}
        generation = int(man.get("generation", 0))
    else:
        # virgin base: init_state(seed) is deterministic
        # (updaters/sgd_updater.py), so a chain rooted at generation 0
        # replays onto the freshly initialized table with no checkpoint
        # at all — mid-epoch-0 crashes still recover to the WAL head
        base_epoch, generation = -1, 0
        if not _wal.chain_segments(_wal.wal_dir(p.model_out),
                                   learner._host_rank, 0):
            rung_c.labels(rung="fresh").inc()
            _write_stamp(p.model_out, rungs, None, None)
            return None

    res = _wal.replay(learner.store, _wal.wal_dir(p.model_out),
                      learner._host_rank, generation,
                      base_epoch=base_epoch)
    writer.adopt(generation, res.next_seq, base_epoch)
    if res.segments:
        rungs.append("wal")
        rung_c.labels(rung="wal").inc()
        log.info("recovery: WAL replayed %d batches (%d segments) to "
                 "(epoch %d, step %d%s) on generation %d",
                 res.batches, res.segments, res.epoch, res.step,
                 ", boundary" if res.boundary else "", generation)

    if res.epoch < 0 or (res.epoch == base_epoch and res.segments == 0):
        # no delta progress past the base checkpoint
        resumed, skip = (None if base_epoch < 0 else base_epoch), 0
    elif res.boundary:
        # the head closes its epoch: it IS a completed epoch
        resumed, skip = res.epoch, 0
    else:
        # mid-epoch head: re-enter epoch res.epoch and skip the batches
        # replay already applied
        resumed = res.epoch - 1 if res.epoch > 0 else -1
        skip = res.step
        if res.epoch == 0:
            resumed = -1
    learner._wal_skip = skip
    if not rungs and resumed is None:
        rung_c.labels(rung="fresh").inc()
    _write_stamp(p.model_out, rungs, resumed, res, skip)
    return resumed


def _write_stamp(model_out: str, rungs, resumed, res,
                 skip: int = 0) -> None:
    """``<model_out>.recovery.json``: how the last resume came back —
    the post-incident audit record (docs/serving.md runbook)."""
    doc = {"rungs": rungs, "resumed_epoch": resumed}
    if res is not None:
        doc.update(base_generation=res.generation,
                   wal_replay_batches=res.batches,
                   wal_segments=res.segments,
                   head={"epoch": res.epoch, "step": res.step,
                         "boundary": res.boundary},
                   stopped=res.stopped, skip_batches=skip)
    tmp = model_out + ".recovery.json.tmp"
    try:
        os.makedirs(os.path.dirname(model_out) or ".", exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(doc, f, sort_keys=True)
        os.replace(tmp, model_out + ".recovery.json")
    except OSError as e:  # pragma: no cover - stamp is best-effort
        log.warning("recovery stamp write failed: %s", e)
