"""Count-min sketch admission over the hashed token stream (ISSUE 19).

The reference system's frequency-adaptive filter drops features below a
count threshold before they ever cost server memory (PAPER.md; the
kFeaCount pass). The TPU-native twin runs at INGEST, on the producers:
every batch's hashed tokens update a count-min sketch and only tokens
whose (over-)estimate has reached ``admit_min_count`` are admitted to
the slot table — the rest are remapped to an out-of-bounds sentinel
lane, which gathers zeros and scatters to nowhere (the pad_slots_oob
contract), so a rare feature costs neither a table row nor a branch in
the jit step.

Determinism: the sketch is created per part-iterator, seeded by
``(seed, epoch, part)``, and sees exactly that part's token stream in
order — thread-pool and process-pool producers therefore build
IDENTICAL sketches and admit identical token sets (the trajectory-test
contract; tests/test_capacity.py). A count-min estimate never
undercounts, so admission can only err toward admitting early — the
safe direction (a row is allocated a few occurrences sooner), and the
same direction on every transport.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class CountMinSketch:
    """Vectorised count-min sketch over int token streams.

    ``depth`` rows of ``width`` uint32 counters (width rounded up to a
    power of two); per-row multiply-shift hashes with odd multipliers
    drawn from a seeded PCG64 stream — pure numpy, deterministic, no
    per-token Python loop. ~0.5 MB at the 2^16 x 2 default: small
    enough that every producer part carries its own.
    """

    def __init__(self, width: int = 1 << 16, depth: int = 2,
                 seed: int = 0) -> None:
        self.log2w = max(int(width - 1).bit_length(), 1)
        self.width = 1 << self.log2w
        self.depth = depth
        self.counts = np.zeros((depth, self.width), dtype=np.uint32)
        rng = np.random.Generator(np.random.PCG64(seed))
        # odd 64-bit multipliers: multiply-shift h(x) = (a*x) >> (64-l)
        self._mult = (rng.integers(1, 1 << 63, size=depth,
                                   dtype=np.uint64) << np.uint64(1)) \
            | np.uint64(1)

    def _idx(self, tok: np.ndarray) -> np.ndarray:
        """[depth, n] counter indices of each token."""
        t = np.asarray(tok, dtype=np.uint64)
        sh = np.uint64(64 - self.log2w)
        return ((self._mult[:, None] * t[None, :]) >> sh).astype(np.int64)

    def add(self, tok: np.ndarray) -> np.ndarray:
        """Count one occurrence of every element of ``tok`` (duplicates
        within the batch each count), then return the post-update
        estimate per element — the one-pass form admission uses."""
        idx = self._idx(tok)
        est = np.full(len(tok), np.iinfo(np.uint32).max, dtype=np.uint64)
        for d in range(self.depth):
            np.add.at(self.counts[d], idx[d], 1)
            np.minimum(est, self.counts[d][idx[d]], out=est,
                       casting="unsafe")
        return est

    def estimate(self, tok: np.ndarray) -> np.ndarray:
        """Point estimate (>= true count) without updating."""
        idx = self._idx(tok)
        est = np.full(len(tok), np.iinfo(np.uint32).max, dtype=np.uint64)
        for d in range(self.depth):
            np.minimum(est, self.counts[d][idx[d]], out=est,
                       casting="unsafe")
        return est


class AdmissionFilter:
    """The producer-side admission gate (data/pack_stream.prepare_hashed).

    Tokens whose sketch estimate is below ``min_count`` are remapped to
    the sentinel value ``hash_capacity`` — out of bounds for the device
    table, and sorting BETWEEN the real slots (< hash_capacity) and the
    producer pads (>= hash_capacity), so the sorted-unique slot
    invariant the table kernels declare survives unchanged. Dropped
    occurrences are counted into ``store_admit_drops_total``.
    """

    def __init__(self, hash_capacity: int, min_count: int,
                 seed: int = 0, width: int = 1 << 16,
                 depth: int = 2) -> None:
        self.sentinel = np.int32(hash_capacity)
        self.min_count = int(min_count)
        self.sketch = CountMinSketch(width=width, depth=depth, seed=seed)
        self._drops = None  # lazy: obs registry import stays off the ctor

    def filter(self, tok: np.ndarray) -> np.ndarray:
        """Hashed int32 tokens -> tokens with unadmitted occurrences
        remapped to the OOB sentinel. Updates the sketch first, so the
        occurrence that crosses the threshold is itself admitted."""
        est = self.sketch.add(tok)
        keep = est >= self.min_count
        n_drop = int(len(tok) - keep.sum())
        if n_drop:
            if self._drops is None:
                from ..obs import REGISTRY
                self._drops = REGISTRY.counter(
                    "store_admit_drops_total",
                    "token occurrences below admit_min_count routed to "
                    "the OOB lane instead of a table slot")
            self._drops.inc(n_drop)
            tok = np.where(keep, tok, self.sentinel)
        return tok


def make_admission(hash_capacity: int, admit_min_count: int,
                   seed: int, epoch: int, part: int
                   ) -> Optional[AdmissionFilter]:
    """Per-part admission filter, or None when the knob is off. One
    definition of the (seed, epoch, part) -> sketch-seed mix shared by
    the thread-mode producer (learners/sgd.py make_iter) and the
    process-mode worker (data/pack_stream.spec_iter), so the two
    transports can never diverge on the admitted set."""
    if admit_min_count <= 0:
        return None
    mix = (int(seed) * 0x9E3779B97F4A7C15
           + int(epoch) * 0xBF58476D1CE4E5B9
           + int(part)) & ((1 << 63) - 1)
    return AdmissionFilter(hash_capacity, admit_min_count, seed=mix)
