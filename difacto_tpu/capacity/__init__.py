"""Table-capacity levers (ISSUE 19; ROADMAP item 3 "capacity = hosts x
fs x quantization"): the three composable multipliers of effective slot
rows per device behind SlotStore knobs.

- quantized slots (``slot_dtype`` int8/fp8): 8-bit codes with per-row
  scales riding the fused rows' spare scalar lanes — 4x rows per HBM
  byte, dequant/requant folded into the fused gather/scatter epilogue
  (ops/fused.quant_half, updaters/sgd_updater.row_epilogue);
- frequency-adaptive admission (``admit_min_count``; :mod:`.sketch`): a
  count-min sketch over the producers' hashed token stream gates slot
  allocation, so the zipf tail never costs a row; occupancy-pressure
  eviction (``evict_occupancy``, SlotStore.maybe_evict) reclaims stale
  rows;
- host-RAM cold tier (``cold_tier_rows``; :mod:`.tier`): the device
  table holds only the hot rows, the tail lives in host RAM, and rows
  promote/demote in batches on the dispatch thread.

All three default off; the defaults are byte-identical to the
pre-capacity trajectory (docs/perf_notes.md "Table capacity").
"""

from .sketch import CountMinSketch, AdmissionFilter  # noqa: F401
from .tier import ColdTier  # noqa: F401
