"""Host-RAM cold tier behind the device slot table (ISSUE 19).

With ``cold_tier_rows = R > 0`` the hashed store's LOGICAL slot space
keeps its full ``hash_capacity = L`` rows, but the device table holds
only ``D = L - R`` HOT rows; the tail lives in host RAM (this module)
and rows move between the two in batches on the dispatch thread:

- every batch's sorted-unique logical slots are ROUTED to device rows
  before staging (:meth:`ColdTier.route` / :func:`route_payload`): a
  resident slot is a tier HIT; a miss PROMOTES the slot's row from host
  RAM (or builds its virgin init row) into a free device row, demoting
  the least-recently-touched resident rows to host RAM when the hot set
  is full. The routed row vector is re-sorted and the payload's index
  cells are rewritten through the position permutation, so the table
  kernels' sorted+unique declarations stay truthful.
- promotes/demotes are batched gathers/scatters over the SAME fused-row
  ops the step uses (ops/fused.gather_rows/scatter_rows, OOB-padded to
  bucketed shapes so they reuse a handful of compiled programs), riding
  the dispatch thread between steps — no background thread, no lock.
- fault points ``store.demote`` / ``store.promote`` (utils/faultinject):
  a failed demote keeps its victims HOT (still serving; this batch's
  misses degrade to the OOB lane and read zeros), a failed promote
  degrades only the missing slots. Both leave the table consistent.

Counters (docs/observability.md): ``store_tier_hits_total``,
``store_tier_misses_total``, ``store_tier_promotes_total``,
``store_tier_demotes_total``.

The tier is exact, not approximate: a demoted row's container bytes
round-trip bit-identically (quantization scales included), and virgin
cold rows get deterministic per-slot init values — but note the DEVICE
table is smaller than the untiered one, so the init value stream (keyed
by table shape) differs from an untiered run at the same
hash_capacity. Requires the hashed store and V_dim > 0 (the fused-row
layout); the learner forces device_dedup / stream_chunks / batch-cache
replay off while routing is active (learners/sgd.py).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.faultinject import FaultInjected, fire
from ..updaters.sgd_updater import (TRASH_SLOT, build_rows, row_layout,
                                    v_dtype)


def _bucket(n: int) -> int:
    from ..ops.batch import bucket
    return bucket(n)


class ColdTier:
    """Residency maps + host row storage for one SlotStore.

    Single-threaded by design: every method runs on the store's dispatch
    (or serve-executor) thread, interleaved with step dispatch — the
    same thread that owns ``store.state``.
    """

    def __init__(self, store) -> None:
        param = store.param
        self.store = store
        self.param = param
        self.L = param.hash_capacity
        self.D = self.L - param.cold_tier_rows
        self.layout = row_layout(param, self.D)
        self._np_dtype = np.dtype(v_dtype(param))
        # residency: logical slot -> device row (-1 = cold); device row
        # -> owning slot (-1 = free). Identity prefix at init: slots
        # [0, D) hot at row == slot, tail [D, L) cold.
        self._resident = np.full(self.L, -1, dtype=np.int64)
        self._resident[:self.D] = np.arange(self.D)
        self._owner = np.arange(self.D, dtype=np.int64)
        # logical LRU clock (no wall time — lint wall-clock rule)
        self._clock = np.zeros(self.D, dtype=np.int64)
        self._tick = 0
        # demoted rows: logical slot -> fused device-layout row bytes
        self._rows: dict = {}
        # deterministic virgin V init for the cold tail [D, L): a
        # distinct PRNG stream from the device table's init (the table
        # shapes differ, so matching the untiered stream is impossible
        # anyway; determinism across hosts is what matters)
        k = param.V_dim
        key = jax.random.fold_in(jax.random.PRNGKey(param.seed), 1)
        self._virgin_V = np.asarray(
            (jax.random.uniform(key, (self.L - self.D, k),
                                dtype=jnp.float32) - 0.5)
            * param.V_init_scale)
        from ..obs import REGISTRY
        self._hits = REGISTRY.counter(
            "store_tier_hits_total",
            "batch slots already resident in the device hot tier")
        self._misses = REGISTRY.counter(
            "store_tier_misses_total",
            "batch slots that were cold (host tier) when requested")
        self._promotes = REGISTRY.counter(
            "store_tier_promotes_total",
            "rows promoted host tier -> device hot rows")
        self._demotes = REGISTRY.counter(
            "store_tier_demotes_total",
            "rows demoted device hot rows -> host tier")

    # ------------------------------------------------------------ stats
    def stats(self) -> dict:
        return {
            "logical_rows": self.L,
            "device_rows": self.D,
            "resident": int((self._owner >= 0).sum()),
            "cold_stored": len(self._rows),
        }

    # ------------------------------------------------------------ route
    def route(self, slots: np.ndarray
              ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Sorted-unique logical slots (producer pads >= L welcome) ->
        ``(routed, order, perm)``: ``routed`` is the same-length device
        row vector, re-sorted ascending with canonical OOB padding
        (``D + position``) for pads and degraded slots; ``order[j]`` is
        the input position now living at routed position ``j``;
        ``perm[p]`` is the routed position of input position ``p`` (the
        index-cell rewrite map)."""
        s = np.asarray(slots, dtype=np.int64)
        n = len(s)
        self._tick += 1
        out = np.empty(n, dtype=np.int64)
        real_idx = np.nonzero(s < self.L)[0]
        # pads (and any degraded slot below) get a big distinct value so
        # the sort keeps them unique; canonicalized to D + j after
        out[s >= self.L] = 2 * self.L + np.nonzero(s >= self.L)[0]
        rows = self._resident[s[real_idx]]
        hit = rows >= 0
        out[real_idx[hit]] = rows[hit]
        self._hits.inc(int(hit.sum()))
        miss_idx = real_idx[~hit]
        if len(miss_idx):
            self._misses.inc(len(miss_idx))
            granted = self._promote(s[miss_idx], protect=rows[hit])
            ok = granted >= 0
            out[miss_idx[ok]] = granted[ok]
            out[miss_idx[~ok]] = 2 * self.L + miss_idx[~ok]
        dev = out[out < self.D]
        self._clock[dev] = self._tick
        order = np.argsort(out, kind="stable")
        routed = out[order]
        n_pad = int((routed >= self.D).sum())
        if n_pad:
            routed[n - n_pad:] = self.D + np.arange(n - n_pad, n)
        perm = np.empty(n, dtype=np.int64)
        perm[order] = np.arange(n)
        return routed.astype(np.int32), order, perm

    # -------------------------------------------------- promote / demote
    def _tier_values(self, slots: np.ndarray) -> np.ndarray:
        """Host fused-row values for ``slots`` (device layout): demoted
        bytes verbatim, virgin init rows for never-trained tail slots."""
        _, _, Wx, _ = self.layout
        vals = np.zeros((len(slots), Wx), dtype=self._np_dtype)
        virgin_i, virgin_s = [], []
        for i, sl in enumerate(np.asarray(slots, np.int64)):
            row = self._rows.pop(int(sl), None)
            if row is not None:
                vals[i] = row
            else:
                virgin_i.append(i)
                virgin_s.append(int(sl) - self.D)
        if virgin_i:
            V = self._virgin_V[np.asarray(virgin_s)]
            z = jnp.zeros(len(virgin_i), jnp.float32)
            built = build_rows(self.param, self.D, V, np.zeros_like(V),
                               z, z, z, z,
                               jnp.zeros(len(virgin_i), dtype=bool))
            vals[np.asarray(virgin_i)] = np.asarray(built)
        return vals

    def _promote(self, miss_slots: np.ndarray,
                 protect: np.ndarray) -> np.ndarray:
        """Bring ``miss_slots`` (sorted unique, all cold) on-device.
        Returns the granted device row per slot, -1 where the slot
        stays cold this batch (promote/demote fault, or no evictable
        row left). Fires ``store.promote``; demotes LRU victims via
        :meth:`_demote` (``store.demote``) when the hot set is full."""
        need = len(miss_slots)
        grant = np.full(need, -1, dtype=np.int64)
        free = np.nonzero(self._owner < 0)[0]
        if len(free) < need:
            self._demote_lru(need - len(free), protect)
            free = np.nonzero(self._owner < 0)[0]
        m = min(len(free), need)
        if m == 0:
            return grant
        try:
            fire("store.promote")
        except FaultInjected:
            # the missing slots stay cold and this batch reads zeros
            # for them (OOB lanes); nothing was moved, nothing torn
            return grant
        dest = free[:m]
        vals = self._tier_values(miss_slots[:m])
        cap = _bucket(m)
        from ..store.local import pad_slots_oob
        from ..ops import fused
        pad = pad_slots_oob(dest.astype(np.int32), cap, self.D)
        _, _, Wx, _ = self.layout
        vp = np.zeros((cap, Wx), dtype=self._np_dtype)
        vp[:m] = vals
        st = self.store.state
        VVg = fused.scatter_rows(st.VVg, jnp.asarray(pad), jnp.asarray(vp))
        self.store.state = self.store._place(st._replace(VVg=VVg))
        self._resident[miss_slots[:m]] = dest
        self._owner[dest] = miss_slots[:m]
        self._clock[dest] = self._tick
        self._promotes.inc(m)
        grant[:m] = dest
        return grant

    def _demote_lru(self, count: int, protect: np.ndarray) -> int:
        """Demote up to ``count`` least-recently-touched resident rows,
        never touching ``protect`` (this batch's hit rows) or the trash
        row."""
        cand = self._owner >= 0
        cand[TRASH_SLOT] = False
        cand[np.asarray(protect, dtype=np.int64)] = False
        rows = np.nonzero(cand)[0]
        if not len(rows):
            return 0
        count = min(count, len(rows))
        if count < len(rows):
            part = np.argpartition(self._clock[rows], count - 1)[:count]
            victims = rows[part]
        else:
            victims = rows
        victims = np.sort(victims)
        return self._demote(victims)

    def _demote(self, victims: np.ndarray) -> int:
        """Demote the given device rows (sorted unique) to host RAM.
        On an injected ``store.demote`` fault the victims stay hot and
        keep serving — the move is fetch-then-forget, so a failure
        before the fetch leaves the device row untouched."""
        n = len(victims)
        if n == 0:
            return 0
        try:
            fire("store.demote")
        except FaultInjected:
            return 0
        from ..store.local import pad_slots_oob
        from ..ops import fused
        cap = _bucket(n)
        pad = pad_slots_oob(victims.astype(np.int32), cap, self.D)
        rows_j = fused.gather_rows(self.store.state.VVg, jnp.asarray(pad))
        vals = np.asarray(rows_j)[:n]
        owners = self._owner[victims]
        for sl, val in zip(owners, vals):
            self._rows[int(sl)] = val
        self._resident[owners] = -1
        self._owner[victims] = -1
        self._demotes.inc(n)
        return n

    def demote_rows(self, victims: np.ndarray) -> int:
        """Occupancy-pressure eviction entry (SlotStore.maybe_evict):
        demote specific device rows to the host tier. The rows remain
        fully addressable — eviction under a tier loses nothing."""
        victims = np.asarray(victims, dtype=np.int64)
        victims = victims[(victims != TRASH_SLOT)
                          & (self._owner[victims] >= 0)]
        return self._demote(np.sort(victims))

    # ------------------------------------------------------- checkpoint
    def logical_cols(self, device_cols: dict) -> dict:
        """Device-table columns [D rows] -> LOGICAL columns [L rows] for
        checkpointing: hot rows land at their owning slot, demoted rows
        decode from their stored fused bytes, virgin tail slots carry
        their init V (zero scalars) — the same dense view an untiered
        store of capacity L would save."""
        from ..updaters.sgd_updater import scal_f32, quantized
        from ..ops import fused
        k, h, _, off = self.layout
        out = {}
        for name, a in device_cols.items():
            shape = (self.L,) + a.shape[1:]
            out[name] = np.zeros(shape, dtype=a.dtype)
        own = self._owner >= 0
        rows = np.nonzero(own)[0]
        for name, a in device_cols.items():
            out[name][self._owner[rows]] = a[rows]
        # virgin tail V init (scalars stay zero): tail slots neither
        # resident on device nor demoted to a host row
        if "V" in out:
            virgin = np.ones(self.L - self.D, dtype=bool)
            hot = self._owner[rows]
            virgin[hot[hot >= self.D] - self.D] = False
            for sl in self._rows:
                if sl >= self.D:
                    virgin[sl - self.D] = False
            vs = np.nonzero(virgin)[0]
            out["V"][self.D + vs] = self._virgin_V[vs]
        if self._rows:
            slots = np.fromiter(self._rows.keys(), dtype=np.int64,
                                count=len(self._rows))
            slots.sort()
            rows_np = np.stack([self._rows[int(s)] for s in slots])
            rj = jnp.asarray(rows_np)
            f = np.asarray(scal_f32(rj[:, off:]))
            cols = {"w": f[:, 0], "z": f[:, 1], "sqrt_g": f[:, 2],
                    "cnt": f[:, 3], "v_live": f[:, 4] > 0}
            if quantized(self.param):
                cols["V"] = np.asarray(fused.dequant_half(
                    rj[:, :k], jnp.asarray(f[:, 5]), self.param.slot_dtype))
                cols["Vg"] = np.asarray(fused.dequant_half(
                    rj[:, h:h + k], jnp.asarray(f[:, 6]),
                    self.param.slot_dtype))
            else:
                cols["V"] = np.asarray(rj[:, :k], dtype=np.float32)
                cols["Vg"] = np.asarray(rj[:, h:h + k], dtype=np.float32)
            for name in out:
                out[name][slots] = cols[name][: len(slots)]
        return out

    def load_cold(self, arr: dict) -> None:
        """Seed the tier from a LOGICAL checkpoint column dict [L rows]:
        residency resets to the identity prefix (slots [0, D) hot) and
        the tail [D, L) is re-packed into host fused rows. Rows whose
        columns are all-zero stay virtual (virgin) — no host bytes."""
        self._resident[:] = -1
        self._resident[:self.D] = np.arange(self.D)
        self._owner = np.arange(self.D, dtype=np.int64)
        self._clock[:] = 0
        self._tick = 0
        self._rows = {}
        lo, hi = self.D, self.L
        touched = ((arr["w"][lo:hi] != 0) | (arr["cnt"][lo:hi] != 0)
                   | np.asarray(arr["v_live"][lo:hi], bool))
        idx = np.nonzero(touched)[0]
        if not len(idx):
            return
        built = np.asarray(build_rows(
            self.param, self.D,
            np.asarray(arr["V"][lo:hi][idx], np.float32),
            np.asarray(arr["Vg"][lo:hi][idx], np.float32),
            arr["w"][lo:hi][idx], arr["z"][lo:hi][idx],
            arr["sqrt_g"][lo:hi][idx], arr["cnt"][lo:hi][idx],
            np.asarray(arr["v_live"][lo:hi][idx], bool)))
        for j, s in enumerate(idx):
            self._rows[int(lo + s)] = built[j]


def route_payload(tier: Optional[ColdTier], payload):
    """Rewrite a packed host payload through the cold tier before H2D
    staging (learners/sgd.py _stage_payload): the slots section becomes
    device rows (promoting as needed), the index/cols cells route
    through the position permutation, and the per-position counts
    section re-orders with its slots. Pass-through when the tier is
    off. ``panel_raw``/``panel_chunked`` are rejected — the learner
    forces device_dedup and stream_chunks off while the tier is on."""
    if tier is None:
        return payload
    kind = payload[0]
    if kind == "panel":
        _, i32, f32, binary, b_cap, width, u_cap = payload
        cells = b_cap * width
        slot_off = cells
        vals_n = 0 if binary else cells
    elif kind == "coo":
        _, i32, f32, binary, b_cap, nnz_cap, u_cap = payload
        slot_off = 2 * nnz_cap
        vals_n = 0 if binary else nnz_cap
    else:
        raise ValueError(
            f"cold tier cannot route payload kind {kind!r} "
            "(device_dedup / stream_chunks must be off under "
            "cold_tier_rows > 0)")
    routed, order, perm = tier.route(i32[slot_off:slot_off + u_cap])
    i32 = i32.copy()
    i32[slot_off:slot_off + u_cap] = routed
    if kind == "panel":
        i32[:cells] = perm[i32[:cells]].astype(np.int32)
    else:
        i32[nnz_cap:2 * nnz_cap] = \
            perm[i32[nnz_cap:2 * nnz_cap]].astype(np.int32)
    counts_off = vals_n + 3 * b_cap
    if len(f32) >= counts_off + u_cap:
        f32 = f32.copy()
        f32[counts_off:counts_off + u_cap] = \
            f32[counts_off:counts_off + u_cap][order]
    return (kind, i32, f32, binary, b_cap, payload[5], u_cap)
