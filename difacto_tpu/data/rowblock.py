"""CSR row-block container (numpy).

The universal data unit, equivalent to ``dmlc::RowBlock`` /
``RowBlockContainer`` (used throughout the reference, e.g.
src/reader/reader.h:18-55) and the zero-copy
``SharedRowBlockContainer`` (src/data/shared_row_block_container.h:16-101) —
numpy arrays already give us shared-ownership zero-copy slices.

Layout: ``offset[n+1]`` int64 row pointers, ``label[n]`` float32, optional
``weight[n]``, ``index[nnz]`` uint64 feature ids (or uint32 after
localization), optional ``value[nnz]`` float32 (None == all-ones / binary,
matching the reference's value elision, src/reader/batch_reader.cc:71-73).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from ..base import FEAID_DTYPE, REAL_DTYPE


@dataclass
class RowBlock:
    offset: np.ndarray                 # int64[n+1]
    label: np.ndarray                  # float32[n]
    index: np.ndarray                  # uint64[nnz] (or uint32 localized)
    value: Optional[np.ndarray] = None  # float32[nnz] or None (binary)
    weight: Optional[np.ndarray] = None  # float32[n] or None

    @property
    def size(self) -> int:
        return len(self.offset) - 1

    @property
    def nnz(self) -> int:
        return int(self.offset[-1] - self.offset[0])

    def __len__(self) -> int:
        return self.size

    def slice(self, begin: int, end: int) -> "RowBlock":
        """Zero-copy row range [begin, end)."""
        off = self.offset[begin:end + 1]
        lo, hi = off[0], off[-1]
        return RowBlock(
            offset=off - lo,
            label=self.label[begin:end],
            index=self.index[lo:hi],
            value=None if self.value is None else self.value[lo:hi],
            weight=None if self.weight is None else self.weight[begin:end],
        )

    def values_or_ones(self) -> np.ndarray:
        if self.value is not None:
            return self.value
        return np.ones(self.nnz, dtype=REAL_DTYPE)

    def row_ids(self) -> np.ndarray:
        """int32[nnz] row index of each nonzero (COO expansion of offset)."""
        n = self.size
        counts = np.diff(self.offset)
        return np.repeat(np.arange(n, dtype=np.int32), counts)

    @staticmethod
    def concat(blocks: List["RowBlock"]) -> "RowBlock":
        if not blocks:
            return empty_block()
        offs = [np.asarray(b.offset) - b.offset[0] for b in blocks]
        out_off = [offs[0]]
        base = offs[0][-1]
        for o in offs[1:]:
            out_off.append(o[1:] + base)
            base += o[-1]
        any_val = any(b.value is not None for b in blocks)
        any_wt = any(b.weight is not None for b in blocks)
        return RowBlock(
            offset=np.concatenate(out_off),
            label=np.concatenate([b.label for b in blocks]),
            index=np.concatenate([b.index for b in blocks]),
            value=(np.concatenate([b.values_or_ones() for b in blocks])
                   if any_val else None),
            weight=(np.concatenate([
                b.weight if b.weight is not None
                else np.ones(b.size, dtype=REAL_DTYPE) for b in blocks])
                if any_wt else None),
        )

    def drop_binary_values(self) -> "RowBlock":
        """If every value == 1, drop the value array (batch_reader.cc:71-73)."""
        if self.value is not None and (self.value == 1).all():
            return RowBlock(self.offset, self.label, self.index, None, self.weight)
        return self


def empty_block() -> RowBlock:
    return RowBlock(
        offset=np.zeros(1, dtype=np.int64),
        label=np.zeros(0, dtype=REAL_DTYPE),
        index=np.zeros(0, dtype=FEAID_DTYPE),
    )


class RowBlockBuilder:
    """Incremental builder (equivalent of dmlc::data::RowBlockContainer::Push)."""

    def __init__(self) -> None:
        self._rows: List[RowBlock] = []

    def push(self, blk: RowBlock) -> None:
        if blk.size:
            self._rows.append(blk)

    def push_rows(self, blk: RowBlock, rows: np.ndarray) -> None:
        """Push an arbitrary subset/permutation of rows from blk."""
        if len(rows) == 0:
            return
        counts = np.diff(blk.offset)[rows]
        starts = np.asarray(blk.offset[rows], dtype=np.int64)
        off = np.zeros(len(rows) + 1, dtype=np.int64)
        np.cumsum(counts, out=off[1:])
        # vectorised gather of each selected row's nnz range:
        # position j within the output maps to starts[r] + (j - off[r])
        total = int(off[-1])
        nnz_idx = (np.repeat(starts - off[:-1], counts)
                   + np.arange(total, dtype=np.int64))
        self._rows.append(RowBlock(
            offset=off,
            label=blk.label[rows],
            index=blk.index[nnz_idx],
            value=None if blk.value is None else blk.value[nnz_idx],
            weight=None if blk.weight is None else blk.weight[rows],
        ))

    @property
    def num_rows(self) -> int:
        return sum(b.size for b in self._rows)

    def build(self) -> RowBlock:
        return RowBlock.concat(self._rows)

    def clear(self) -> None:
        self._rows.clear()
