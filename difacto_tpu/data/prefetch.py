"""Threaded batch prefetcher: overlap host parsing with device compute.

The reference's worker hides data loading behind compute with its 3-thread
pipeline and the dmlc ThreadedParser (src/sgd/sgd_learner.h:85-102,
src/reader/reader.h:42-44). Here a producer thread runs the (reader ->
localize -> slot-map) host work while the main thread dispatches device
steps; a bounded queue of ``depth`` items is the analog of the <=2 in-flight
minibatches backpressure (sgd_learner.cc:310-312).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, TypeVar

T = TypeVar("T")

_DONE = object()


def prefetch(it: Iterable[T], depth: int = 2) -> Iterator[T]:
    """Iterate ``it`` on a background thread, ``depth`` items ahead.

    Early consumer exit (break / close) sets a stop flag the producer checks
    on every put, so teardown is O(depth), not O(remaining items).
    """
    q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
    stop = threading.Event()
    err = []

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for item in it:
                if not _put(item):
                    return
        except BaseException as e:  # re-raised on the consumer side
            err.append(e)
        finally:
            _put(_DONE)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _DONE:
                break
            yield item
    finally:
        stop.set()
        t.join()
    if err:
        raise err[0]
