"""Minibatching with shuffle buffer and negative downsampling.

Equivalent of the reference's ``BatchReader`` (src/reader/batch_reader.{h,cc}):

- fixed ``batch_size`` batches over an underlying :class:`Reader`
  (batch_reader.cc:29-69); the final batch may be short;
- ``shuffle`` > 0 builds a buffer of ``batch_size * shuffle`` rows and emits a
  random permutation of it (batch_reader.cc:18-27,37-46);
- ``neg_sampling`` < 1 *drops* each negative row with probability
  ``neg_sampling`` (positives always kept). Counter-intuitive but exactly the
  reference's arithmetic: it skips a negative when ``p > 1 - neg_sampling``
  (batch_reader.cc:58-64), i.e. keep probability is ``1 - neg_sampling``;
  ``neg_sampling == 1.0`` disables sampling entirely (the ``< 1.0`` gate);
- all-ones value arrays are dropped to the binary representation
  (batch_reader.cc:71-73).
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from .reader import Reader
from .rowblock import RowBlock, RowBlockBuilder


class BatchReader:
    def __init__(self, uri: str, data_format: str = "libsvm",
                 part_idx: int = 0, num_parts: int = 1,
                 batch_size: int = 100, shuffle_buf_size: int = 0,
                 neg_sampling: float = 1.0, seed: int = 0,
                 chunk_bytes: int = 1 << 26):
        if shuffle_buf_size:
            if shuffle_buf_size < batch_size:
                raise ValueError("shuffle buffer must be >= batch_size")
            # a BatchReader of the buffer size feeds the shuffler, like the
            # recursive construction in batch_reader.cc:18-22
            self._src: BatchReader | Reader = BatchReader(
                uri, data_format, part_idx, num_parts,
                batch_size=shuffle_buf_size, chunk_bytes=chunk_bytes)
        else:
            self._src = Reader(uri, data_format, part_idx, num_parts,
                               chunk_bytes)
        self.batch_size = batch_size
        self.shuffle_buf_size = shuffle_buf_size
        self.neg_sampling = neg_sampling
        self._rng = np.random.RandomState(seed)

    def __iter__(self) -> Iterator[RowBlock]:
        builder = RowBlockBuilder()
        for blk in self._src:
            rows = np.arange(blk.size)
            if self.shuffle_buf_size:
                self._rng.shuffle(rows)
            if self.neg_sampling < 1.0:
                # keep a negative iff p <= 1 - neg_sampling (batch_reader.cc:58-64)
                keep = (blk.label[rows] > 0) | (
                    self._rng.random_sample(len(rows))
                    <= 1.0 - self.neg_sampling)
                rows = rows[keep]
            start = 0
            while start < len(rows):
                take = min(self.batch_size - builder.num_rows,
                           len(rows) - start)
                builder.push_rows(blk, rows[start:start + take])
                start += take
                if builder.num_rows >= self.batch_size:
                    yield builder.build().drop_binary_values()
                    builder.clear()
        if builder.num_rows:
            yield builder.build().drop_binary_values()
