"""Feature-id localization: per-batch compaction of sparse ids.

Equivalent of the reference's ``Localizer`` (src/data/localizer.{h,cc}): map a
batch's raw uint64 feature ids to a dense [0, n) range, producing

- ``uniq_ids``: the batch's distinct *reversed* feature ids, sorted ascending
  — exactly the KV keys the reference sends to servers (localizer.cc:22-29
  applies ReverseBytes before sorting, so the sorted dictionary is in
  reversed-id order; ps-lite requires sorted keys, kvstore_dist.h:95);
- optional per-id occurrence counts (for the epoch-0 kFeaCount push);
- a compacted RowBlock whose ``index`` is uint32 positions into ``uniq_ids``.

On TPU this is the boundary between the host pipeline and the device: the
compact CSR plus ``uniq_ids`` become the gather/scatter indices of the fused
train step — localization *is* the "pull request construction".

``np.unique(return_inverse, return_counts)`` replaces the sort+scan
(localizer.cc:22-50) and ``RemapIndex`` (localizer.cc:53-107) in one shot.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..base import reverse_bytes
from .rowblock import RowBlock


def compact(blk: RowBlock, need_counts: bool = False,
            max_index_bits: int = -1
            ) -> Tuple[RowBlock, np.ndarray, Optional[np.ndarray]]:
    """Localize a row block.

    Returns (compacted block, uniq reversed ids sorted asc, counts or None).
    ``max_index_bits`` >= 0 masks ids to that many bits first (the reference's
    ``max_index_`` modulo, localizer.cc:24).
    """
    ids = blk.index
    if max_index_bits >= 0 and max_index_bits < 64:
        ids = ids & np.uint64((1 << max_index_bits) - 1)
    rev = reverse_bytes(ids)
    uniq, inverse, counts = np.unique(rev, return_inverse=True,
                                      return_counts=True)
    out = RowBlock(
        offset=blk.offset.copy(),
        label=blk.label,
        index=inverse.astype(np.uint32),
        value=blk.value,
        weight=blk.weight,
    )
    return out, uniq, (counts.astype(np.float32) if need_counts else None)
