from .rowblock import RowBlock, RowBlockBuilder, empty_block
from .reader import Reader, expand_uri
from .batch_reader import BatchReader
from .localizer import compact
from .rec import RecWriter, read_rec_block, write_rec_block

__all__ = [
    "RowBlock", "RowBlockBuilder", "empty_block", "Reader", "expand_uri",
    "BatchReader", "compact", "RecWriter", "read_rec_block", "write_rec_block",
]
