"""Ordered multi-worker batch production over a WorkloadPool.

The single-host analog of the reference's pull-based worker self-scheduling
(src/tracker/dist_tracker.h:136-156 RespHandle hands a finishing node its
next part from the WorkloadPool): N producer threads request file parts from
a shared :class:`tracker.workload_pool.WorkloadPool`, run the host pipeline
(read -> localize -> slot-map -> pack) for their part, and push prepared
batches into per-part bounded queues. The consumer (the learner's dispatch
loop) drains parts in canonical order, so training trajectories stay
deterministic regardless of worker count or scheduling — the TPU-first trade
replacing the reference's nondeterministic async dispatch.

Memory is bounded: each part queue holds <= depth items and a worker blocks
once its queue fills, so at most (workers + completed-but-unconsumed parts)
x depth batches are in flight.

A worker that raises re-queues its part via ``pool.reset`` (the dead-node
path, workload_pool.h:88-105) so another worker can retry it; the retry
skips the items the failed attempt already enqueued, and the error is
re-raised to the consumer only if the part keeps failing (max_retries).

**API contract: ``make_iter(part)`` MUST be deterministic** — calling it
twice for the same part must yield the same item sequence, because the
retry path resumes via ``islice(make_iter(part), n_delivered)``. A
nondeterministic iterator (unseeded shuffle, IO-dependent chunking) would
silently skip or duplicate batches on retry. The learner satisfies this by
seeding its shuffle/sampling streams per (epoch, part)
(learners/sgd.py _make_reader).
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from typing import Callable, Iterator, Optional

from ..tracker.workload_pool import WorkloadPool, WorkloadPoolParam

_END = object()


class OrderedProducerPool:
    """Iterate items of ``make_iter(part)`` for part 0..n_parts-1, in order,
    produced by ``n_workers`` background threads."""

    def __init__(self, n_parts: int, make_iter: Callable[[int], Iterator],
                 n_workers: int = 2, depth: int = 4,
                 pool: Optional[WorkloadPool] = None, max_retries: int = 1):
        self.n_parts = n_parts
        self.make_iter = make_iter
        self.n_workers = max(1, min(n_workers, n_parts))
        self.depth = depth
        self.pool = pool or WorkloadPool(WorkloadPoolParam())
        self.pool.clear()
        self.pool.add(n_parts)
        self.max_retries = max_retries
        self._queues = [queue.Queue(maxsize=depth) for _ in range(n_parts)]
        self._stop = threading.Event()
        self._errors: list = []
        self._fail_counts = [0] * n_parts
        self._enqueued = [0] * n_parts  # items already delivered per part
        self._threads = [
            threading.Thread(target=self._work, args=(w,), daemon=True)
            for w in range(self.n_workers)
        ]

    def _put(self, part: int, item) -> bool:
        while not self._stop.is_set():
            try:
                self._queues[part].put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _work(self, node: int) -> None:
        while not self._stop.is_set():
            part = self.pool.get(node)
            if part == -2:
                if self.pool.num_remains() == 0:
                    return
                time.sleep(0.02)  # a failed part may be re-queued
                continue
            try:
                # a retry resumes after the items the failed attempt already
                # enqueued (deterministic per-part iteration)
                it = itertools.islice(self.make_iter(part),
                                      self._enqueued[part], None)
                for item in it:
                    if not self._put(part, item):
                        self.pool.reset(node)
                        return
                    self._enqueued[part] += 1
                if not self._put(part, _END):
                    self.pool.reset(node)
                    return
                self.pool.finish(node)
            except BaseException as e:  # re-queue, escalate if persistent
                self._fail_counts[part] += 1
                if self._fail_counts[part] > self.max_retries:
                    self._errors.append(e)
                    self._put(part, _END)
                    self.pool.finish(node)
                else:
                    self.pool.reset(node)

    def __iter__(self) -> Iterator:
        for t in self._threads:
            t.start()
        try:
            for part in range(self.n_parts):
                while True:
                    item = self._queues[part].get()
                    if item is _END:
                        break
                    yield part, item
                if self._errors:
                    raise self._errors[0]
        finally:
            self._stop.set()
            for t in self._threads:
                t.join()
