"""Ordered multi-worker batch production over a WorkloadPool.

The single-host analog of the reference's pull-based worker self-scheduling
(src/tracker/dist_tracker.h:136-156 RespHandle hands a finishing node its
next part from the WorkloadPool): N producer threads request file parts from
a shared :class:`tracker.workload_pool.WorkloadPool`, run the host pipeline
(read -> localize -> slot-map -> pack) for their part, and push prepared
batches into per-part bounded queues. The consumer (the learner's dispatch
loop) drains parts in canonical order, so training trajectories stay
deterministic regardless of worker count or scheduling — the TPU-first trade
replacing the reference's nondeterministic async dispatch.

Memory is bounded: each part queue holds <= depth items and a worker blocks
once its queue fills, so at most (workers + completed-but-unconsumed parts)
x depth batches are in flight.

Failure and straggler handling (workload_pool.h:88-105, 155-176):

- a worker that RAISES re-queues its part via ``pool.reset`` so another
  worker retries it, escalating to the consumer after ``max_retries``;
- a part STUCK on a worker (hung IO) is re-issued by ``remove_stragglers``
  — idle workers poll it, so a straggling part is reclaimed as soon as the
  pool's 10x-mean criterion trips.

Both paths deliver every item exactly once through a per-part GENERATION:
taking a part bumps its generation and snapshots the delivered-item count
(both under the part lock); every enqueue re-checks the generation, so a
superseded attempt — failed, stalled-then-woken, or raced — abandons
instead of double-delivering, and the new attempt resumes exactly after
the items already enqueued.

**API contract: ``make_iter(part)`` MUST be deterministic** — calling it
twice for the same part must yield the same item sequence, because retries
and re-issues resume via ``islice(make_iter(part), n_delivered)``. A
nondeterministic iterator (unseeded shuffle, IO-dependent chunking) would
silently skip or duplicate batches. The learner satisfies this by seeding
its shuffle/sampling streams per (epoch, part) (learners/sgd.py
_make_reader).
"""

from __future__ import annotations

import itertools
import os
import pickle
import queue
import threading
import time
from typing import Callable, Iterator, Optional

from ..tracker.workload_pool import WorkloadPool, WorkloadPoolParam
from ..utils.locktrace import mutex

_END = object()


class OrderedProducerPool:
    """Iterate items of ``make_iter(part)`` for part 0..n_parts-1, in order,
    produced by ``n_workers`` background threads."""

    def __init__(self, n_parts: int, make_iter: Callable[[int], Iterator],
                 n_workers: int = 2, depth: int = 4,
                 pool: Optional[WorkloadPool] = None, max_retries: int = 1,
                 obs_registry=None):
        from ..obs import REGISTRY
        self._obs = obs_registry if obs_registry is not None else REGISTRY
        self.n_parts = n_parts
        self.make_iter = make_iter
        self.n_workers = max(1, min(n_workers, n_parts))
        self.depth = depth
        self.pool = pool or WorkloadPool(WorkloadPoolParam())
        self.pool.clear()
        self.pool.add(n_parts)
        self.max_retries = max_retries
        self._queues = [queue.Queue(maxsize=depth) for _ in range(n_parts)]
        self._stop = threading.Event()
        self._errors: list = []
        self._fail_counts = [0] * n_parts
        self._enqueued = [0] * n_parts  # items already delivered per part
        self._gen = [0] * n_parts       # per-part attempt generation
        self._locks = [mutex() for _ in range(n_parts)]
        self._threads = [
            threading.Thread(target=self._work, args=(w,), daemon=True)
            for w in range(self.n_workers)
        ]

    def _deliver(self, part: int, node: int, my_gen: int, item) -> str:
        """Enqueue under the generation guard: 'ok', 'superseded' (another
        attempt took over this part) or 'stopped'.

        The part lock is held only for the non-blocking enqueue + count
        update (the exactly-once critical section) — never across a wait.
        While back-pressured on a full queue we wait OUTSIDE the lock and
        ``touch`` the pool, so (a) a replacement worker is never parked on
        the lock and (b) a healthy, merely-blocked part does not trip the
        straggler criterion."""
        while True:
            with self._locks[part]:
                if self._gen[part] != my_gen:
                    return "superseded"
                try:
                    self._queues[part].put_nowait(item)
                    if item is not _END:
                        self._enqueued[part] += 1
                    return "ok"
                except queue.Full:
                    pass
            if self._stop.is_set():
                return "stopped"
            self.pool.touch(node)
            time.sleep(0.05)

    def _work(self, node: int) -> None:
        while not self._stop.is_set():
            part = self.pool.get(node)
            if part == -2:
                if self.pool.num_remains() == 0:
                    return
                # idle workers double as the straggler poller (the
                # reference used a 2 s monitor thread,
                # workload_pool.h:155-176); a re-queued part is picked up
                # by the next get()
                self.pool.remove_stragglers()
                time.sleep(0.02)
                continue
            with self._locks[part]:
                # supersede any earlier (stalled) attempt and resume after
                # the items it already delivered
                self._gen[part] += 1
                my_gen = self._gen[part]
                start = self._enqueued[part]
            try:
                # chaos harness (utils/faultinject.py): an injected
                # ``err`` here rides the exact escalation path a real
                # parse/read failure takes — re-queue the part, escalate
                # after max_retries
                from ..utils import faultinject
                faultinject.act_default(faultinject.fire("producer.part"))
                it = itertools.islice(self.make_iter(part), start, None)
                abandoned = False
                for item in it:
                    st = self._deliver(part, node, my_gen, item)
                    if st == "superseded":
                        abandoned = True
                        break
                    if st == "stopped":
                        self.pool.reset(node)
                        return
                if abandoned:
                    continue  # re-issued elsewhere; not ours to finish
                st = self._deliver(part, node, my_gen, _END)
                if st == "stopped":
                    self.pool.reset(node)
                    return
                if st == "ok":
                    self.pool.finish(node)
            except BaseException as e:  # re-queue, escalate if persistent
                self._fail_counts[part] += 1
                self._obs.counter(
                    "producer_part_retries_total",
                    "producer part attempts that failed and were "
                    "re-queued (or escalated)").inc()
                if self._fail_counts[part] > self.max_retries:
                    self._errors.append(e)
                    self._deliver(part, node, my_gen, _END)
                    self.pool.finish(node)
                else:
                    self.pool.reset(node)

    def __iter__(self) -> Iterator:
        for t in self._threads:
            t.start()
        try:
            for part in range(self.n_parts):
                while True:
                    item = self._queues[part].get()
                    if item is _END:
                        break
                    yield part, item
                if self._errors:
                    raise self._errors[0]
        finally:
            self._stop.set()
            for t in self._threads:
                t.join()


# --------------------------------------------------------------------------
# Process-based producers: the same pool contract, across the GIL boundary.
# --------------------------------------------------------------------------

_STOP_ITER = object()
# most items a worker coalesces into one ring slot (bounds both the
# group's decode burst on the consumer and the per-slot latency)
_MAX_COALESCE = 16


def _pp_worker_main(worker_id: int, make_iter_bytes: bytes, ring_desc,
                    free_q, cmd_q, done_q, stop_ev, env: dict) -> None:
    """Worker-process entry point (module-level: spawn pickles a reference).

    Runs one part at a time: receives ("part", part, gen, start) commands,
    resumes ``make_iter(part)`` at item ``start`` (the deterministic-
    iterator contract shared with OrderedProducerPool), writes each item's
    arrays into a leased ring slot and reports it on ``done_q``. The env
    overrides are applied BEFORE unpickling ``make_iter`` — that unpickle
    is what pulls in the heavy imports (numpy/jax via the packing helpers),
    so a worker on a TPU host comes up as a CPU-only process instead of
    fighting the consumer for the chip.

    Observability: the worker instruments against its own process-global
    registry (spec_iter accounts parse/pack; this loop accounts ring-slot
    waits) and publishes a cumulative snapshot + collected trace spans
    through ``done_q`` after every finished part and on exit
    (obs/proc.py) — that is how per-stage seconds survive the process
    boundary into the consumer's stage table.
    """
    os.environ.update(env or {})
    import traceback

    from .shm_ring import ShmRing, SlotOverflow, _align, encode_item
    make_iter = pickle.loads(make_iter_bytes)
    from ..obs import REGISTRY, proc, trace
    ring_wait_c = REGISTRY.counter(
        "stage_seconds_total",
        "seconds spent per streamed-pipeline stage, summed over threads"
    ).labels(stage="ring_wait")
    ring_wait_h = REGISTRY.histogram(
        "ring_slot_wait_seconds",
        "producer wait for a free shm-ring slot (the backpressure point)")

    def publish() -> None:
        try:
            done_q.put(("obs", worker_id, proc.publish_blob()))
        except (ValueError, OSError):  # pragma: no cover - queue closed
            pass

    ring = ShmRing.attach(ring_desc)
    try:
        while not stop_ev.is_set():
            try:
                cmd = cmd_q.get(timeout=0.2)
            except queue.Empty:
                continue
            if cmd[0] == "stop":
                return
            _, part, gen, start = cmd
            try:
                # multi-part-per-slot coalescing: items far smaller than
                # a slot share one (header count > 1), so small batches
                # pay one lease + one consumer wakeup per GROUP and
                # ring_wait amortizes. Items over half the usable budget
                # ship immediately — coalescing them would delay the
                # in-flight batch by a whole pack cycle for nothing.
                budget = ring.slot_bytes * 3 // 4
                pend: list = []  # [(seq, item, pack_dt, span)]
                pend_bytes = 0

                def est_bytes(it_) -> int:
                    arrays: list = []
                    encode_item(it_, arrays)
                    return sum(_align(a.nbytes) for a in arrays) + 4096

                def lease_slot(seq):
                    t_wait = time.perf_counter()
                    s = None
                    with trace.span("producer.ring_wait", part=part,
                                    seq=seq):
                        while not stop_ev.is_set():  # backpressure point
                            try:
                                s = free_q.get(timeout=0.1)
                                break
                            except queue.Empty:
                                continue
                    wait_dt = time.perf_counter() - t_wait
                    ring_wait_c.inc(wait_dt)
                    ring_wait_h.observe(wait_dt)
                    return s

                def send_single(seq, it_, dt, span, slot=None) -> bool:
                    if slot is None:
                        slot = lease_slot(seq)
                        if slot is None:
                            return False  # stopping
                    try:
                        ring.write(slot, it_, part=part, seq=seq, gen=gen,
                                   span=span)
                        done_q.put(("item", worker_id, part, gen, seq,
                                    slot, None, dt, 1))
                    except SlotOverflow:
                        # oversize item: fall back to the pickled channel
                        # — slower, never wrong. The unused slot rides
                        # the message for the CONSUMER to release: a
                        # worker writing to free_q would share that
                        # queue's write lock with the consumer, and a
                        # kill while holding it would wedge the
                        # consumer's releases.
                        done_q.put(("ovf", worker_id, part, gen, seq,
                                    slot, pickle.dumps(it_), dt, 1))
                    return True

                def flush() -> bool:
                    nonlocal pend, pend_bytes
                    if not pend:
                        return True
                    group, pend = pend, []
                    pend_bytes = 0
                    if len(group) == 1:
                        return send_single(*group[0])
                    seq0, _, _, span0 = group[0]
                    slot = lease_slot(seq0)
                    if slot is None:
                        return False
                    try:
                        ring.write(slot, [g[1] for g in group], part=part,
                                   seq=seq0, gen=gen, span=span0,
                                   count=len(group))
                        done_q.put(("item", worker_id, part, gen, seq0,
                                    slot, None,
                                    sum(g[2] for g in group), len(group)))
                        return True
                    except SlotOverflow:
                        # the estimate undercounted (meta overhead):
                        # degrade to one item per slot, reusing the lease
                        if not send_single(*group[0], slot=slot):
                            return False
                        for g in group[1:]:
                            if not send_single(*g):
                                return False
                        return True

                it = itertools.islice(make_iter(part), start, None)
                n = start
                while True:
                    t0 = time.perf_counter()
                    item = next(it, _STOP_ITER)
                    if item is _STOP_ITER:
                        break
                    pack_dt = time.perf_counter() - t0
                    span = trace.last_span_id()
                    sz = est_bytes(item)
                    if sz > budget // 2:
                        if not flush() or not send_single(n, item,
                                                          pack_dt, span):
                            return
                    else:
                        if pend and (pend_bytes + sz > budget
                                     or len(pend) >= _MAX_COALESCE):
                            if not flush():
                                return
                        pend.append((n, item, pack_dt, span))
                        pend_bytes += sz
                    n += 1
                if not flush():
                    return
                if not stop_ev.is_set():
                    done_q.put(("end", worker_id, part, gen, n))
                    publish()
            except BaseException:
                done_q.put(("err", worker_id, part, gen,
                            traceback.format_exc()))
                publish()
    finally:
        publish()
        ring.close()


class ProcessProducerPool:
    """OrderedProducerPool's process-based sibling: N ``spawn`` worker
    PROCESSES run ``make_iter(part)`` and ship finished items through a
    shared-memory ring (data/shm_ring.py), so the host pipeline genuinely
    overlaps the consumer's dispatch loop instead of time-slicing the GIL
    with it.

    Same contract as the thread pool:

    - parts are pulled from a shared :class:`WorkloadPool` and consumed in
      canonical order (deterministic trajectories);
    - ``make_iter(part)`` MUST be deterministic AND picklable (a module-
      level callable or ``functools.partial`` over picklable state):
      retries and straggler re-issues resume via
      ``islice(make_iter(part), n_delivered)``;
    - exactly-once through per-part GENERATIONS: every reassignment bumps
      the part's generation, deliveries tagged with a stale generation are
      dropped (their ring slots released), and the new attempt resumes
      exactly after the items already accepted — a worker killed mid-part
      (process death = the thread pool's raise) neither duplicates nor
      skips a batch;
    - a worker that RAISES re-queues its part via ``pool.reset`` and
      escalates to the consumer after ``max_retries``; parts stuck on a
      hung worker are re-issued via ``pool.remove_stragglers`` whenever a
      worker sits idle.

    Item lifetime: a yielded item's arrays are zero-copy VIEWS into the
    ring. By default the slot is auto-released when the NEXT item is
    yielded (items are valid for one iteration). A consumer that stages
    the arrays asynchronously (the learner's double-buffered device_put)
    calls :meth:`pop_lease` after each item and releases the lease itself
    once the transfer has completed.

    All pool/queue state is driven by the single consumer thread inside
    ``__iter__`` — no internal threads, no cross-thread races.
    """

    def __init__(self, n_parts: int, make_iter: Callable[[int], Iterator],
                 n_workers: int = 2, depth: int = 4,
                 pool: Optional[WorkloadPool] = None, max_retries: int = 1,
                 slot_bytes: int = 8 << 20, worker_env: Optional[dict] = None,
                 join_timeout: float = 5.0, obs_registry=None):
        import multiprocessing as mp

        from ..obs import REGISTRY, proc as obs_proc
        from .shm_ring import ShmRing
        # workers publish registry snapshots through done_q; they attach
        # here (keyed per worker) and fold into the base at shutdown, so
        # the consumer's registry reports exact cross-process totals
        self._obs = obs_registry if obs_registry is not None else REGISTRY
        self._obs_key = None  # set once the ring name exists
        self.n_parts = n_parts
        self.n_workers = max(1, min(n_workers, n_parts))
        self.depth = max(2, depth)
        self.pool = pool or WorkloadPool(WorkloadPoolParam())
        self.pool.clear()
        self.pool.add(n_parts)
        self.max_retries = max_retries
        self._join_timeout = join_timeout
        # JAX_PLATFORMS=cpu by default: workers do host work only and must
        # never bind the accelerator (callers may override/extend).
        # DIFACTO_OBS_CHILD marks the worker as an obs child: it collects
        # trace spans in memory and ships them through done_q instead of
        # installing its own trace-file writer (obs/trace.py)
        self._env = {"JAX_PLATFORMS": "cpu", obs_proc.CHILD_ENV: "1"}
        self._env.update(worker_env or {})
        self._ctx = mp.get_context("spawn")  # JAX state must never fork
        self._ring = ShmRing(n_slots=self.n_workers * self.depth,
                             slot_bytes=slot_bytes,
                             n_queues=self.n_workers, ctx=self._ctx)
        self._stop_ev = self._ctx.Event()
        # one done-queue PER worker: queues' write locks are plain (non-
        # robust) semaphores, so a worker killed mid-put would wedge every
        # other writer of a shared queue; with per-worker queues a kill
        # can only wedge the dead worker's own channel — exactly the
        # failure the liveness check already handles
        self._done_qs = [self._ctx.Queue() for _ in range(self.n_workers)]
        self._cmd_qs = [self._ctx.Queue() for _ in range(self.n_workers)]
        mi_bytes = pickle.dumps(make_iter)
        self._procs = [
            self._ctx.Process(
                target=_pp_worker_main,
                args=(w, mi_bytes, self._ring.descriptor(),
                      self._ring.free_qs[w], self._cmd_qs[w],
                      self._done_qs[w], self._stop_ev, self._env),
                daemon=True)
            for w in range(self.n_workers)
        ]
        self._last_lease = None
        self._obs_key = ("ppworker", self._ring.name)
        self.pack_s = 0.0          # producer-side seconds, summed
        self.overflow_items = 0    # items that missed the ring (pickled)
        self.last_producer_span = 0  # trace span that packed the last item
        self._finished = False

    # ------------------------------------------------------------- API
    def pop_lease(self):
        """Take ownership of the last yielded item's slot lease (None if
        that item traveled the pickled fallback channel). The caller must
        ``release()`` it; un-popped leases auto-release on the next
        iteration."""
        lease, self._last_lease = self._last_lease, None
        return lease

    def __iter__(self) -> Iterator:
        for p in self._procs:
            p.start()
        try:
            yield from self._consume()
        finally:
            self._shutdown()

    # -------------------------------------------------------- consumer
    def _consume(self) -> Iterator:
        n = self.n_parts
        accepted = [0] * n      # items handed to the consumer, per part
        gen = [0] * n           # current attempt generation, per part
        complete = [False] * n
        fail_counts = [0] * n
        buffers = [[] for _ in range(n)]   # decoded, awaiting consumption
        errors: dict = {}
        self._worker_part = [None] * self.n_workers  # (part, gen) | None
        dead = [False] * self.n_workers

        def feed(w: int) -> None:
            part = self.pool.get(w)
            if part == -2:
                return
            gen[part] += 1
            self._worker_part[w] = (part, gen[part])
            self._cmd_qs[w].put(("part", part, gen[part], accepted[part]))

        def drop(slot: int) -> None:
            if slot >= 0:
                self._ring.release(slot)

        def handle(msg) -> None:
            kind = msg[0]
            if kind == "obs":
                # a worker's cumulative registry snapshot + trace spans
                # (obs/proc.py): keep the newest per worker
                from ..obs import proc as obs_proc
                obs_proc.absorb_blob(self._obs, self._obs_key + (msg[1],),
                                     msg[2])
                return
            _, w, part, g = msg[:4]
            if kind in ("item", "ovf"):
                _, _, _, _, seq, slot, blob, pack_dt, _cnt = msg
                self.pack_s += pack_dt
                if kind == "ovf":
                    # pickled fallback: the leased-but-unused slot comes
                    # back through the consumer (see _pp_worker_main)
                    drop(slot)
                    slot = -1
                if g != gen[part] or complete[part]:
                    drop(slot)  # superseded attempt — exactly-once guard
                    return
                span = 0
                if slot >= 0:
                    from .shm_ring import SlotLease
                    _, _, _, span, cnt = self._ring.read_header(slot)
                    item, _, _, _ = self._ring.read(slot)
                    # a multi-item slot fans out into per-item entries
                    # sharing one refcounted lease: the slot recycles
                    # when the LAST item's consumer is done with it
                    subs = item if cnt > 1 else [item]
                    handles = SlotLease(self._ring, slot).split(len(subs))
                else:
                    subs = [pickle.loads(blob)]
                    handles = [None]
                    self.overflow_items += 1
                    self._obs.counter(
                        "producer_overflow_total",
                        "items too large for a ring slot (pickled "
                        "fallback)").inc()
                accepted[part] += len(subs)
                for it_, h in zip(subs, handles):
                    buffers[part].append((it_, h, span))
            elif kind == "end":
                if g == gen[part]:
                    complete[part] = True
                    self.pool.finish(w)
                self._worker_part[w] = None
                feed(w)
            elif kind == "err":
                tb = msg[4]
                if g == gen[part]:
                    fail_counts[part] += 1
                    self._obs.counter(
                        "producer_part_retries_total",
                        "producer part attempts that failed and were "
                        "re-queued (or escalated)").inc()
                    if fail_counts[part] > self.max_retries:
                        errors[part] = RuntimeError(
                            f"producer worker failed part {part} "
                            f"{fail_counts[part]}x:\n{tb}")
                        complete[part] = True
                        self.pool.finish(w)
                    else:
                        self.pool.reset(w)
                self._worker_part[w] = None
                feed(w)

        def pump(timeout: float) -> None:
            got = False
            for dq in self._done_qs:
                while True:
                    try:
                        msg = dq.get_nowait()
                    except queue.Empty:
                        break
                    got = True
                    handle(msg)
            if not got:
                time.sleep(timeout)
                self._check_liveness(gen, feed, dead)

        for w in range(self.n_workers):
            feed(w)

        cur = 0
        while cur < n:
            if buffers[cur]:
                item, lease, span = buffers[cur].pop(0)
                if self._last_lease is not None:
                    # consumer didn't pop the previous lease: items are
                    # valid for one iteration by default
                    self._last_lease.release()
                self._last_lease = lease
                self.last_producer_span = span
                yield cur, item
                continue
            if complete[cur]:
                if cur in errors:
                    raise errors[cur]
                cur += 1
                continue
            # idle workers double as the straggler poller (the thread
            # pool's idle loop); a re-queued part is picked up below
            idle = [w for w in range(self.n_workers)
                    if self._worker_part[w] is None and not dead[w]]
            if idle:
                self.pool.remove_stragglers()
                for w in idle:
                    feed(w)
            elif not any(wp and wp[0] == cur
                         for wp in self._worker_part):
                # the current part lost its worker (death / straggler
                # re-issue) and every live worker is busy — likely
                # backpressure-blocked on a future part's full slot
                # quota. Evict buffered future-part items from their
                # ring slots (one memcpy each) so a busy worker can
                # finish its part, go idle, and pick up the re-queued
                # current part; without this the ring deadlocks.
                from .shm_ring import materialize_item
                for pbuf in buffers:
                    for j, (it_, lease, span_) in enumerate(pbuf):
                        if lease is not None:
                            pbuf[j] = (materialize_item(it_), None, span_)
                            lease.release()
            pump(timeout=0.1)
        self._finished = True

    def _check_liveness(self, gen: list, feed, dead: list) -> None:
        """A worker that died mid-part (killed, OOM) is the process
        analog of a raising thread: re-queue its part (pool.reset) and
        bump the generation so any of its in-flight deliveries that
        arrive later are dropped; the replacement resumes after the
        items already accepted."""
        any_alive = False
        for w, p in enumerate(self._procs):
            if dead[w]:
                continue
            if p.is_alive():
                any_alive = True
                continue
            dead[w] = True
            self._obs.counter(
                "producer_worker_deaths_total",
                "producer worker processes that died mid-run").inc()
            wp = self._worker_part[w]
            self._worker_part[w] = None
            if wp is not None:
                part, _ = wp
                self.pool.reissue_dead(w)
                gen[part] += 1  # invalidate its still-queued deliveries
        if not any_alive and not self._finished:
            alive_assignments = [wp for wp in self._worker_part if wp]
            if self.pool.num_remains() > 0 or alive_assignments:
                raise RuntimeError(
                    "all producer worker processes died with parts "
                    "remaining")

    # -------------------------------------------------------- teardown
    def _shutdown(self) -> None:
        if self._last_lease is not None:
            self._last_lease.release()
            self._last_lease = None
        self._stop_ev.set()
        for q_ in self._cmd_qs:
            try:
                q_.put_nowait(("stop",))
            except (ValueError, OSError):  # pragma: no cover
                pass
        deadline = time.monotonic() + self._join_timeout
        for p in self._procs:
            if p.pid is None:
                continue
            p.join(timeout=max(0.1, deadline - time.monotonic()))
            if p.is_alive():  # pragma: no cover - hung worker
                p.terminate()
                p.join(timeout=1.0)
                if p.is_alive():
                    p.kill()
                    p.join(timeout=1.0)
        # drain pending queue items so their feeder threads release —
        # absorbing any final obs snapshots the workers published on
        # their way out — then drop the segment; unlink is idempotent
        # and atexit-backed, so no /dev/shm entry survives any exit path
        from ..obs import proc as obs_proc
        for dq in self._done_qs:
            try:
                while True:
                    msg = dq.get_nowait()
                    if msg and msg[0] == "obs":
                        obs_proc.absorb_blob(
                            self._obs, self._obs_key + (msg[1],), msg[2])
            except (queue.Empty, ValueError, OSError):
                pass
        self._ring.unlink()
        # retire the per-worker snapshots into the base series so the
        # totals survive this pool object (and accumulate across epochs)
        self._obs.fold_children(self._obs_key)
