"""Ordered multi-worker batch production over a WorkloadPool.

The single-host analog of the reference's pull-based worker self-scheduling
(src/tracker/dist_tracker.h:136-156 RespHandle hands a finishing node its
next part from the WorkloadPool): N producer threads request file parts from
a shared :class:`tracker.workload_pool.WorkloadPool`, run the host pipeline
(read -> localize -> slot-map -> pack) for their part, and push prepared
batches into per-part bounded queues. The consumer (the learner's dispatch
loop) drains parts in canonical order, so training trajectories stay
deterministic regardless of worker count or scheduling — the TPU-first trade
replacing the reference's nondeterministic async dispatch.

Memory is bounded: each part queue holds <= depth items and a worker blocks
once its queue fills, so at most (workers + completed-but-unconsumed parts)
x depth batches are in flight.

Failure and straggler handling (workload_pool.h:88-105, 155-176):

- a worker that RAISES re-queues its part via ``pool.reset`` so another
  worker retries it, escalating to the consumer after ``max_retries``;
- a part STUCK on a worker (hung IO) is re-issued by ``remove_stragglers``
  — idle workers poll it, so a straggling part is reclaimed as soon as the
  pool's 10x-mean criterion trips.

Both paths deliver every item exactly once through a per-part GENERATION:
taking a part bumps its generation and snapshots the delivered-item count
(both under the part lock); every enqueue re-checks the generation, so a
superseded attempt — failed, stalled-then-woken, or raced — abandons
instead of double-delivering, and the new attempt resumes exactly after
the items already enqueued.

**API contract: ``make_iter(part)`` MUST be deterministic** — calling it
twice for the same part must yield the same item sequence, because retries
and re-issues resume via ``islice(make_iter(part), n_delivered)``. A
nondeterministic iterator (unseeded shuffle, IO-dependent chunking) would
silently skip or duplicate batches. The learner satisfies this by seeding
its shuffle/sampling streams per (epoch, part) (learners/sgd.py
_make_reader).
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from typing import Callable, Iterator, Optional

from ..tracker.workload_pool import WorkloadPool, WorkloadPoolParam

_END = object()


class OrderedProducerPool:
    """Iterate items of ``make_iter(part)`` for part 0..n_parts-1, in order,
    produced by ``n_workers`` background threads."""

    def __init__(self, n_parts: int, make_iter: Callable[[int], Iterator],
                 n_workers: int = 2, depth: int = 4,
                 pool: Optional[WorkloadPool] = None, max_retries: int = 1):
        self.n_parts = n_parts
        self.make_iter = make_iter
        self.n_workers = max(1, min(n_workers, n_parts))
        self.depth = depth
        self.pool = pool or WorkloadPool(WorkloadPoolParam())
        self.pool.clear()
        self.pool.add(n_parts)
        self.max_retries = max_retries
        self._queues = [queue.Queue(maxsize=depth) for _ in range(n_parts)]
        self._stop = threading.Event()
        self._errors: list = []
        self._fail_counts = [0] * n_parts
        self._enqueued = [0] * n_parts  # items already delivered per part
        self._gen = [0] * n_parts       # per-part attempt generation
        self._locks = [threading.Lock() for _ in range(n_parts)]
        self._threads = [
            threading.Thread(target=self._work, args=(w,), daemon=True)
            for w in range(self.n_workers)
        ]

    def _deliver(self, part: int, node: int, my_gen: int, item) -> str:
        """Enqueue under the generation guard: 'ok', 'superseded' (another
        attempt took over this part) or 'stopped'.

        The part lock is held only for the non-blocking enqueue + count
        update (the exactly-once critical section) — never across a wait.
        While back-pressured on a full queue we wait OUTSIDE the lock and
        ``touch`` the pool, so (a) a replacement worker is never parked on
        the lock and (b) a healthy, merely-blocked part does not trip the
        straggler criterion."""
        while True:
            with self._locks[part]:
                if self._gen[part] != my_gen:
                    return "superseded"
                try:
                    self._queues[part].put_nowait(item)
                    if item is not _END:
                        self._enqueued[part] += 1
                    return "ok"
                except queue.Full:
                    pass
            if self._stop.is_set():
                return "stopped"
            self.pool.touch(node)
            time.sleep(0.05)

    def _work(self, node: int) -> None:
        while not self._stop.is_set():
            part = self.pool.get(node)
            if part == -2:
                if self.pool.num_remains() == 0:
                    return
                # idle workers double as the straggler poller (the
                # reference used a 2 s monitor thread,
                # workload_pool.h:155-176); a re-queued part is picked up
                # by the next get()
                self.pool.remove_stragglers()
                time.sleep(0.02)
                continue
            with self._locks[part]:
                # supersede any earlier (stalled) attempt and resume after
                # the items it already delivered
                self._gen[part] += 1
                my_gen = self._gen[part]
                start = self._enqueued[part]
            try:
                it = itertools.islice(self.make_iter(part), start, None)
                abandoned = False
                for item in it:
                    st = self._deliver(part, node, my_gen, item)
                    if st == "superseded":
                        abandoned = True
                        break
                    if st == "stopped":
                        self.pool.reset(node)
                        return
                if abandoned:
                    continue  # re-issued elsewhere; not ours to finish
                st = self._deliver(part, node, my_gen, _END)
                if st == "stopped":
                    self.pool.reset(node)
                    return
                if st == "ok":
                    self.pool.finish(node)
            except BaseException as e:  # re-queue, escalate if persistent
                self._fail_counts[part] += 1
                if self._fail_counts[part] > self.max_retries:
                    self._errors.append(e)
                    self._deliver(part, node, my_gen, _END)
                    self.pool.finish(node)
                else:
                    self.pool.reset(node)

    def __iter__(self) -> Iterator:
        for t in self._threads:
            t.start()
        try:
            for part in range(self.n_parts):
                while True:
                    item = self._queues[part].get()
                    if item is _END:
                        break
                    yield part, item
                if self._errors:
                    raise self._errors[0]
        finally:
            self._stop.set()
            for t in self._threads:
                t.join()
