"""Data converter: any input format -> libsvm text or the binary rec cache.

Equivalent of the reference's ``task=convert`` (src/reader/converter.h:41-124)
with the same parameters: data_in/data_format -> data_out/data_out_format,
``chunk_size`` MB read granularity, optional ``part_size`` MB output splitting
(-1 = single output). The rec output is the npz-shard cache of rec.py — the
fast binary path that keeps TPU chips fed (SURVEY §7 hard part (e)).

Two rec upgrades over the reference's CRB converter:

- ``rec_localize`` (default on) stores members *pre-localized* (compacted
  uint32 index + sorted reversed-id ``uniq``, like CRB's compacted CSR,
  src/reader/crb_parser.h:16-47) so training epochs skip parse + unique;
- ``rec_batch_size`` aligns member row counts to the training batch size so
  cached batches never straddle members, and ``convert_threads`` text
  chunks are parsed/localized/compressed in parallel (the dmlc
  ThreadedParser role, src/reader/reader.h:42-44).
"""

from __future__ import annotations

import logging
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

import numpy as np

from ..config import KWArgs, Param
from ..utils import stream
from ..utils.locktrace import mutex
from .localizer import compact
from .reader import Reader
from .rec import write_rec_block
from .rowblock import RowBlock, RowBlockBuilder

log = logging.getLogger("difacto_tpu")


@dataclass
class _ConvertSpec:
    """Everything a convert worker process needs to convert ITS byte-range
    part of the input into member files — plain picklable values only."""
    data_in: str
    data_format: str
    out_dir: str
    member_suffix: str
    member_rows: int
    rec_localize: bool
    rec_compress: bool
    chunk_bytes: int
    n_parts: int


def _convert_member_arrays(blk: RowBlock, localize: bool):
    if localize:
        cblk, uniq, _ = compact(blk)
        return cblk, uniq
    return blk, None


def convert_part_iter(spec: _ConvertSpec, part: int):
    """Process-pool ``make_iter`` for the parallel text->rec convert: parse
    this part's byte range, slice into member-row blocks, localize and
    write each member directly from the worker (the OUTPUT is the file
    set, so nothing heavy rides the ring — just per-member stats).
    Deterministic per part (fixed byte ranges, fixed member naming), so
    the pool's retry/straggler re-issue contract holds: a re-run rewrites
    the same members through atomic renames."""
    from .parsers import get_parser
    from .reader import _byte_ranges, _iter_text_chunks, expand_uri

    if spec.data_format.lower() == "rec":
        blocks = iter(Reader(spec.data_in, "rec", part, spec.n_parts,
                             chunk_bytes=spec.chunk_bytes))

        def timed_blocks():
            it = blocks
            while True:
                t0 = time.perf_counter()
                blk = next(it, None)
                dt = time.perf_counter() - t0
                if blk is None:
                    return
                yield blk, dt
    else:
        parse = get_parser(spec.data_format)
        files, sizes = expand_uri(spec.data_in, with_sizes=True)
        ranges = _byte_ranges(files, sizes, part, spec.n_parts)

        def timed_blocks():
            for path, b, e in ranges:
                for ch in _iter_text_chunks(path, b, e, spec.chunk_bytes):
                    t0 = time.perf_counter()
                    blk = parse(ch)
                    dt = time.perf_counter() - t0
                    if blk.size:
                        yield blk, dt

    builder = RowBlockBuilder()
    pending_parse = 0.0
    n_member = 0
    bs = spec.member_rows

    def write_member(blk: RowBlock, parse_s: float):
        nonlocal n_member
        path = stream.join(
            spec.out_dir, f"part-{part:03d}-{n_member:05d}"
                          f"{spec.member_suffix}")
        t0 = time.perf_counter()
        cblk, uniq = _convert_member_arrays(blk, spec.rec_localize)
        write_rec_block(path, cblk, uniq=uniq,
                        compress=spec.rec_compress)
        n_member += 1
        return ("member", blk.size, stream.getsize(path), parse_s,
                time.perf_counter() - t0)

    for blk, dt in timed_blocks():
        if bs <= 0:  # -1: one member per read chunk
            yield write_member(blk, dt)
            continue
        pending_parse += dt
        start = 0
        while start < blk.size:
            take = min(bs - builder.num_rows, blk.size - start)
            builder.push(blk.slice(start, start + take))
            start += take
            if builder.num_rows >= bs:
                yield write_member(builder.build(), pending_parse)
                pending_parse = 0.0
                builder.clear()
    if builder.num_rows:
        yield write_member(builder.build(), pending_parse)


@dataclass
class ConverterParam(Param):
    data_in: str = ""
    data_format: str = ""
    data_out: str = ""
    data_out_format: str = ""
    part_size: int = -1      # MB per output part; -1 = one output
    chunk_size: float = 512  # MB per read chunk
    rec_localize: bool = True
    # rows per rec member. 0 (default) = auto: align to ``batch_size`` when
    # the convert config carries one (so converting with the training conf
    # yields batch-aligned members — the cached fast path's best layout),
    # else DEFAULT_MEMBER_ROWS. -1 = one member per read chunk (the old
    # default — members of millions of rows defeat the cached reader's
    # whole-member fast path, round-3 advisor medium).
    rec_batch_size: int = 0
    # training batch size, accepted here so ``task=convert`` with the
    # training config auto-aligns members (see rec_batch_size)
    batch_size: int = 0
    convert_threads: int = 0  # 0 = auto
    # worker PROCESSES for the text->rec convert; 0 = auto (process
    # workers on hosts with >= 4 cores, threads below — same heuristic as
    # the learner's producer_mode), 1 = force the in-process threaded
    # path. Parallel convert shards the input by byte range across the
    # existing ProcessProducerPool (each worker parses + localizes +
    # writes its members directly), so the one-time convert stops being
    # bounded by one interpreter (it measured 146k ex/s single-process —
    # slower than training itself, ISSUE 7).
    convert_procs: int = 0
    # member encoding: "rec2" (default — the zero-copy page-aligned
    # framing of rec2.py, mmap'd at read time) or "npz" (legacy v1)
    rec_encoding: str = "rec2"
    # zlib-compress rec members (npz encoding only). Default OFF: the rec
    # format exists to make STREAMING fast (the reference picked LZ4 for
    # the same reason, src/data/compressed_row_block.h:20-142) and zlib
    # decompress measured 68% of the streamed-epoch host-pack pass (1.32
    # of 1.93 s per 600k rows, docs/perf_notes.md "the streamed regime");
    # uncompressed members are ~2.6x larger but read at page-cache speed.
    # rec2 members are always raw.
    rec_compress: bool = False


# auto member size when no batch_size is given: large enough that member
# metadata amortizes, small enough that the cached reader's whole-member
# path stays in reach for common batch sizes
DEFAULT_MEMBER_ROWS = 8192


class Converter:
    def __init__(self) -> None:
        self.param: ConverterParam | None = None
        # filled by run(): rows, eps, parse_s, write_s, procs, members —
        # the per-stage convert accounting bench.py reports (convert.*)
        self.stats: dict = {}
        self._stage_lock = mutex()

    def member_rows(self) -> int:
        """Resolved rows-per-member (see ConverterParam.rec_batch_size):
        explicit > 0 wins; 0 = batch_size if given else
        DEFAULT_MEMBER_ROWS; -1 = chunk granularity (returns -1)."""
        p = self.param
        if p.rec_batch_size > 0:
            return p.rec_batch_size
        if p.rec_batch_size == 0:
            return p.batch_size or DEFAULT_MEMBER_ROWS
        return -1

    def init(self, kwargs: KWArgs) -> KWArgs:
        self.param, remain = ConverterParam.init_allow_unknown(kwargs)
        for req in ("data_in", "data_format", "data_out", "data_out_format"):
            if not getattr(self.param, req):
                raise ValueError(f"converter requires {req}")
        if self.param.data_out_format not in ("libsvm", "rec"):
            raise ValueError(
                f"unknown output format: {self.param.data_out_format}")
        if self.param.rec_encoding not in ("rec2", "npz"):
            raise ValueError(
                f"unknown rec_encoding: {self.param.rec_encoding!r} "
                "(rec2|npz)")
        return remain

    def member_suffix(self) -> str:
        from .rec2 import SUFFIX
        return SUFFIX if self.param.rec_encoding == "rec2" else ".npz"

    def _acc_stage(self, key: str, dt: float) -> None:
        # summed across parse/write worker threads (tiny critical section)
        with self._stage_lock:
            self.stats[key] = round(self.stats.get(key, 0.0) + dt, 4)

    def resolve_procs(self) -> int:
        """Worker-process count for the rec convert. Explicit wins; auto
        (0) engages processes only when cores can actually overlap (the
        learner's producer_mode heuristic) and the output is a single
        part (part_size splitting stays on the threaded path — its
        rollover bookkeeping is inherently serial)."""
        import os
        p = self.param
        if p.part_size > 0 or p.data_out_format != "rec":
            return 1
        if p.convert_procs > 0:
            return p.convert_procs
        ncpu = os.cpu_count() or 1
        return min(ncpu, 8) if ncpu >= 4 else 1

    def run(self) -> None:
        t0 = time.perf_counter()
        if self.param.data_out_format == "rec":
            procs = self.resolve_procs()
            if procs > 1:
                self._run_rec_parallel(procs)
            else:
                self._run_rec()
        else:
            self._run_libsvm()
        self.stats["convert_s"] = round(time.perf_counter() - t0, 3)
        self.stats["rows"] = self.num_rows
        if self.stats["convert_s"] > 0:
            self.stats["eps"] = round(
                self.num_rows / self.stats["convert_s"], 1)

    def _run_rec_parallel(self, procs: int) -> None:
        """Parallel text->rec convert across the existing
        ProcessProducerPool (ISSUE 7 satellite): the input is sharded by
        byte range over ``procs`` worker processes, each parsing +
        localizing + writing its own members (named ``part-PPP-NNNNN``),
        so the one-time convert scales with cores instead of being
        pinned to one interpreter. Members stay batch-aligned within
        each part; only each part's tail member runs short — the same
        shape the ``part_size`` splitter always produced."""
        import functools

        from .producer_pool import ProcessProducerPool
        p = self.param
        out_dir = self._open_rec_part(0, False)
        spec = _ConvertSpec(
            data_in=p.data_in, data_format=p.data_format,
            out_dir=out_dir, member_suffix=self.member_suffix(),
            member_rows=self.member_rows(),
            rec_localize=p.rec_localize, rec_compress=p.rec_compress,
            chunk_bytes=min(int(p.chunk_size * (1 << 20)), 32 << 20),
            n_parts=procs)
        log.info("reading data from %s in %s format (%d convert workers)",
                 p.data_in, p.data_format, procs)
        pool = ProcessProducerPool(
            procs, functools.partial(convert_part_iter, spec),
            n_workers=procs, depth=8, slot_bytes=1 << 20)
        nrows = members = out_bytes = 0
        parse_s = write_s = 0.0
        for _, item in pool:
            _, rows, nbytes, p_s, w_s = item
            nrows += rows
            members += 1
            out_bytes += nbytes
            parse_s += p_s
            write_s += w_s
        log.info("done. written %d examples", nrows)
        self.num_rows = nrows
        self.stats.update(procs=procs, members=members,
                          out_bytes=out_bytes, parse_s=round(parse_s, 3),
                          write_s=round(write_s, 3))

    # ------------------------------------------------------------- rec
    def _parsed_blocks(self, threads: int):
        """Parse text chunks on ``threads`` workers, yielding blocks in
        read order (the dmlc ThreadedParser role; native parsers and numpy
        release the GIL, so threads scale)."""
        from collections import deque

        p = self.param
        if p.data_format.lower() == "rec":
            yield from Reader(p.data_in, p.data_format, 0, 1,
                              chunk_bytes=int(p.chunk_size * (1 << 20)))
            return
        from .parsers import get_parser
        from .reader import _byte_ranges, _iter_text_chunks, expand_uri
        parse = get_parser(p.data_format)
        files, sizes = expand_uri(p.data_in, with_sizes=True)
        # read granularity small enough to keep every worker busy
        chunk_bytes = min(int(p.chunk_size * (1 << 20)), 32 << 20)

        def chunks():
            for path, b, e in _byte_ranges(files, sizes, 0, 1):
                yield from _iter_text_chunks(path, b, e, chunk_bytes)

        def timed_parse(ch):
            t0 = time.perf_counter()
            blk = parse(ch)
            self._acc_stage("parse_s", time.perf_counter() - t0)
            return blk

        with ThreadPoolExecutor(max_workers=threads) as ex:
            futs: deque = deque()
            for ch in chunks():
                futs.append(ex.submit(timed_parse, ch))
                while len(futs) >= 2 * threads:
                    blk = futs.popleft().result()
                    if blk.size:
                        yield blk
            while futs:
                blk = futs.popleft().result()
                if blk.size:
                    yield blk

    def _run_rec(self) -> None:
        """Parallel pipeline: threaded parse -> row-aligned member slicing
        -> threaded (localize + compress + write)."""
        import os
        p = self.param
        log.info("reading data from %s in %s format", p.data_in,
                 p.data_format)
        mr = self.member_rows()
        log.info("rec members: %s rows each",
                 mr if mr > 0 else "one read chunk of")
        if p.rec_batch_size == 0 and not p.batch_size and p.rec_localize:
            log.warning(
                "no batch_size given: members default to %d rows; pass "
                "the training batch_size (or rec_batch_size) so members "
                "come out batch-aligned — the cached reader re-compacts "
                "every batch of an unaligned member", DEFAULT_MEMBER_ROWS)
        threads = p.convert_threads or min(6, os.cpu_count() or 1)
        split = p.part_size > 0
        limit = p.part_size * (1 << 20) if split else None

        nrows = 0
        ipart = 0
        nblk = 0
        written = [0]  # compressed bytes in current part (approximate:
        # updated as write futures land; part rollover is checked between
        # member submissions)
        written_lock = mutex()  # += from concurrent workers
        out_dir = self._open_rec_part(ipart, split)

        def write_member(path: str, blk: RowBlock) -> int:
            t0 = time.perf_counter()
            if p.rec_localize:
                cblk, uniq, _ = compact(blk)
                write_rec_block(path, cblk, uniq=uniq,
                                compress=p.rec_compress)
            else:
                write_rec_block(path, blk, compress=p.rec_compress)
            sz = stream.getsize(path)
            self._acc_stage("write_s", time.perf_counter() - t0)
            with written_lock:
                written[0] += sz
            return sz

        def member_blocks(blocks):
            """Re-slice parsed blocks into member-row-count members,
            carrying remainders across blocks (batches never straddle
            members, data/cached.py)."""
            bs = self.member_rows()
            if bs <= 0:  # -1: one member per read chunk
                yield from blocks
                return
            builder = RowBlockBuilder()
            for blk in blocks:
                start = 0
                while start < blk.size:
                    take = min(bs - builder.num_rows, blk.size - start)
                    builder.push(blk.slice(start, start + take))
                    start += take
                    if builder.num_rows >= bs:
                        yield builder.build()
                        builder.clear()
            if builder.num_rows:
                yield builder.build()

        futures = []
        with ThreadPoolExecutor(max_workers=threads) as ex:
            for blk in member_blocks(self._parsed_blocks(threads)):
                if split and written[0] >= limit:
                    for f in futures:  # part boundary: settle sizes
                        f.result()
                    futures.clear()
                    ipart += 1
                    nblk = 0
                    written[0] = 0
                    out_dir = self._open_rec_part(ipart, split)
                path = stream.join(out_dir,
                                   f"part-{nblk:05d}{self.member_suffix()}")
                futures.append(ex.submit(write_member, path, blk))
                nblk += 1
                nrows += blk.size
                if len(futures) >= 2 * threads:
                    futures.pop(0).result()
            for f in futures:
                f.result()
        log.info("done. written %d examples", nrows)
        self.num_rows = nrows

    def _open_rec_part(self, ipart: int, split: bool) -> str:
        path = self.param.data_out + (f"-part_{ipart}" if split else "")
        stream.makedirs(path)
        log.info("writing data to %s in rec format", path)
        return path

    # ------------------------------------------------------------- libsvm
    def _run_libsvm(self) -> None:
        p = self.param
        reader = Reader(p.data_in, p.data_format, 0, 1,
                        chunk_bytes=int(p.chunk_size * (1 << 20)))
        log.info("reading data from %s in %s format", p.data_in, p.data_format)
        split = p.part_size > 0
        limit = p.part_size * (1 << 20) if split else None

        ipart = 0
        nwrite = 0
        nrows = 0
        out = None

        def open_part():
            nonlocal out, nwrite, ipart
            path = p.data_out + (f"-part_{ipart}" if split else "")
            ipart += 1
            nwrite = 0
            out = stream.open_stream(path, "w")
            log.info("writing data to %s in libsvm format", path)
            return out

        out = open_part()
        for blk in reader:
            if split and nwrite >= limit:
                out.close()
                out = open_part()
            nwrite += self._write_text_block(out, blk)
            nrows += blk.size
        if out is not None:
            out.close()
        log.info("done. written %d examples", nrows)
        self.num_rows = nrows

    def _write_text_block(self, out, blk: RowBlock) -> int:
        # vectorised token formatting; only the per-row join is Python
        idx = np.char.mod("%d", blk.index.astype(np.uint64))
        if blk.value is not None:
            feats = np.char.add(np.char.add(idx, ":"),
                                np.char.mod("%g", blk.value))
        else:
            feats = np.char.add(idx, ":1")
        labels = np.char.mod("%g", blk.label)
        off = blk.offset
        lines = [labels[i] + " " + " ".join(feats[off[i]:off[i + 1]])
                 for i in range(blk.size)]
        data = "\n".join(lines) + "\n"
        out.write(data)
        return len(data)
