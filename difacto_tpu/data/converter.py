"""Data converter: any input format -> libsvm text or the binary rec cache.

Equivalent of the reference's ``task=convert`` (src/reader/converter.h:41-124)
with the same parameters: data_in/data_format -> data_out/data_out_format,
``chunk_size`` MB read granularity, optional ``part_size`` MB output splitting
(-1 = single output). The rec output is the npz-shard cache of rec.py — the
fast binary path that keeps TPU chips fed (SURVEY §7 hard part (e)).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

import numpy as np

from ..config import KWArgs, Param
from ..utils import stream
from .reader import Reader
from .rec import write_rec_block
from .rowblock import RowBlock

log = logging.getLogger("difacto_tpu")


@dataclass
class ConverterParam(Param):
    data_in: str = ""
    data_format: str = ""
    data_out: str = ""
    data_out_format: str = ""
    part_size: int = -1      # MB per output part; -1 = one output
    chunk_size: float = 512  # MB per read chunk


class Converter:
    def __init__(self) -> None:
        self.param: ConverterParam | None = None

    def init(self, kwargs: KWArgs) -> KWArgs:
        self.param, remain = ConverterParam.init_allow_unknown(kwargs)
        for req in ("data_in", "data_format", "data_out", "data_out_format"):
            if not getattr(self.param, req):
                raise ValueError(f"converter requires {req}")
        if self.param.data_out_format not in ("libsvm", "rec"):
            raise ValueError(
                f"unknown output format: {self.param.data_out_format}")
        return remain

    def run(self) -> None:
        p = self.param
        reader = Reader(p.data_in, p.data_format, 0, 1,
                        chunk_bytes=int(p.chunk_size * (1 << 20)))
        log.info("reading data from %s in %s format", p.data_in, p.data_format)
        split = p.part_size > 0
        limit = p.part_size * (1 << 20) if split else None

        ipart = 0
        nwrite = 0
        nrows = 0
        out = None

        def open_part():
            nonlocal out, nwrite, ipart
            path = p.data_out + (f"-part_{ipart}" if split else "")
            ipart += 1
            nwrite = 0
            if p.data_out_format == "libsvm":
                out = stream.open_stream(path, "w")
            else:
                stream.makedirs(path)
                out = path  # rec: a directory of npz members
            log.info("writing data to %s in %s format", path,
                     p.data_out_format)
            return out

        out = open_part()
        nblk = 0
        for blk in reader:
            if split and nwrite >= limit:
                if p.data_out_format == "libsvm":
                    out.close()
                out = open_part()
                nblk = 0
            nwrite += self._write_block(out, blk, nblk)
            nblk += 1
            nrows += blk.size
        if p.data_out_format == "libsvm" and out is not None:
            out.close()
        log.info("done. written %d examples", nrows)
        self.num_rows = nrows

    def _write_block(self, out, blk: RowBlock, nblk: int) -> int:
        if self.param.data_out_format == "libsvm":
            # vectorised token formatting; only the per-row join is Python
            idx = np.char.mod("%d", blk.index.astype(np.uint64))
            if blk.value is not None:
                feats = np.char.add(np.char.add(idx, ":"),
                                    np.char.mod("%g", blk.value))
            else:
                feats = np.char.add(idx, ":1")
            labels = np.char.mod("%g", blk.label)
            off = blk.offset
            lines = [labels[i] + " " + " ".join(feats[off[i]:off[i + 1]])
                     for i in range(blk.size)]
            data = "\n".join(lines) + "\n"
            out.write(data)
            return len(data)
        path = stream.join(out, f"part-{nblk:05d}.npz")
        write_rec_block(path, blk)
        return stream.getsize(path)
