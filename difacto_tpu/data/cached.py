"""Pre-localized batch iteration over a `.rec` cache.

The fast-path analog of the reference's CRB flow (src/reader/crb_parser.h:
16-47 + the rec cache produced by task=convert, src/reader/converter.h:41-124):
members store *compacted* CSR (uint32 positions into a sorted reversed-id
``uniq`` vector, rec.py), so per-epoch host work skips parsing and the
O(nnz log nnz) sort/unique of Localizer::Compact entirely — each batch costs
an O(uniq) slot map plus buffer packing.

Batches never span members (each member has its own uniq space); the
converter aligns member row counts to the training batch size so this only
shortens the tail batch — the same behavior as the reference's per-part
batch boundaries (batch_reader.cc:29-69).

Shuffle here is member-order + within-member row permutation (seeded per
epoch), the cache-granular analog of the reference's shuffle buffer
(batch_reader.cc:18-27); negative downsampling keeps the reference's exact
arithmetic (batch_reader.cc:58-64).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Optional, Tuple

import numpy as np

from .rec import read_rec_block_ex, rec_members
from .reader import expand_uri
from .rowblock import RowBlock, RowBlockBuilder


def cache_is_localized(uri: str) -> bool:
    """True if the first member of the cache carries the ``uniq`` array."""
    return cache_probe(uri)[0]


def cache_probe(uri: str) -> Tuple[bool, int]:
    """(is_localized, first_member_rows) in one member read — the learner
    uses the row geometry to warn when members dwarf the training batch
    (the rec_batch_size footgun: oversized members force the per-batch
    re-compaction path on every batch, round-4 verdict weak #5)."""
    files, sizes = expand_uri(uri, with_sizes=True)
    pairs = rec_members(files, sizes)
    if not pairs:
        return False, 0
    blk, uniq = read_rec_block_ex(pairs[0][0])
    return uniq is not None, blk.size


class CachedBatchReader:
    """Yields ``(localized_block, uniq, counts)`` triples per batch.

    ``uniq`` holds the member's sorted reversed feature ids; the block's
    ``index`` is uint32 positions into it. ``counts`` (when requested) are
    per-uniq occurrence counts over the batch's rows — the epoch-0
    kFeaCount payload.
    """

    def __init__(self, uri: str, part_idx: int = 0, num_parts: int = 1,
                 batch_size: int = 100, shuffle: bool = False,
                 neg_sampling: float = 1.0, seed: int = 0,
                 need_counts: bool = False):
        files, sizes = expand_uri(uri, with_sizes=True)
        self._pairs = rec_members(files, sizes)
        if not self._pairs:
            raise FileNotFoundError(f"empty rec cache: {uri!r}")
        # member sharding by cumulative compressed size (rec.py
        # iter_rec_blocks): a member belongs to the part holding its start
        total = sum(sz for _, sz in self._pairs)
        begin = total * part_idx // num_parts
        end = total * (part_idx + 1) // num_parts
        self._members: List[str] = []
        base = 0
        for m, sz in self._pairs:
            if begin <= base < end:
                self._members.append(m)
            base += sz
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.neg_sampling = neg_sampling
        self.seed = seed
        self.need_counts = need_counts

    def __iter__(self) -> Iterator[Tuple[RowBlock, np.ndarray,
                                         Optional[np.ndarray]]]:
        rng = np.random.RandomState(self.seed)
        order = np.arange(len(self._members))
        if self.shuffle:
            rng.shuffle(order)
        for mi in order:
            blk, uniq = read_rec_block_ex(self._members[mi])
            if uniq is None:
                raise ValueError(
                    f"cache member {self._members[mi]!r} is not "
                    "pre-localized; re-convert with rec_localize=1")
            rows = np.arange(blk.size)
            if self.shuffle:
                rng.shuffle(rows)
            if self.neg_sampling < 1.0:
                # keep a negative iff p <= 1 - neg_sampling
                # (batch_reader.cc:58-64)
                keep = (blk.label[rows] > 0) | (
                    rng.random_sample(len(rows)) <= 1.0 - self.neg_sampling)
                rows = rows[keep]
            whole = (len(rows) == blk.size and blk.size <= self.batch_size
                     and not self.shuffle)
            for s in range(0, len(rows), self.batch_size):
                sel = rows[s:s + self.batch_size]
                if whole:
                    sub = blk
                else:
                    b = RowBlockBuilder()
                    b.push_rows(blk, sel)
                    sub = b.build()
                u = uniq
                if len(sel) < blk.size:
                    # the batch covers only part of the member: re-compact
                    # so it ships (and the device step pays u_cap for) only
                    # ITS distinct features, not the whole member
                    # vocabulary — members much larger than the training
                    # batch (the rec_batch_size=0 default) would otherwise
                    # make the "fast path" slower than the non-cached one
                    # (round-3 advisor). O(batch nnz) on uint32 positions;
                    # uniq is sorted, so u stays sorted.
                    loc, inv = np.unique(sub.index, return_inverse=True)
                    sub = dataclasses.replace(
                        sub, index=inv.astype(np.uint32))
                    u = uniq[loc]
                counts = None
                if self.need_counts:
                    counts = np.bincount(
                        sub.index.astype(np.int64),
                        minlength=len(u)).astype(np.float32)
                yield sub, u, counts
