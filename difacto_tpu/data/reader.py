"""Sharded streaming reader.

Equivalent of the reference's ``Reader`` (src/reader/reader.h:18-55), which
wraps ``dmlc::InputSplit`` — *byte-range* file sharding by (part_idx,
num_parts) is how data parallelism partitions the input in the reference; we
keep exactly that contract so the workload-pool/straggler logic (tracker/) can
dispatch file parts to hosts the same way.

Sharding semantics (mirroring dmlc InputSplit for line-based text): the total
byte span of all files is divided evenly into ``num_parts``; a part begins at
the first line start at-or-after its begin offset and ends with the line that
straddles its end offset. Records are yielded in chunks of ``chunk_bytes`` as
:class:`RowBlock`.

URIs: a file path, a directory (all regular files inside, sorted), or a glob
— local, or any fsspec scheme (``gs://``, ``hdfs://``, ``memory://``; the
reference reads hdfs:// via dmlc InputSplit, example/yarn.conf). The binary
`.rec` cache (rec.py) dispatches on format="rec".
"""

from __future__ import annotations

from typing import Iterator, List, Tuple

from ..utils import stream
from .parsers import get_parser
from .rowblock import RowBlock


def expand_uri(uri: str, with_sizes: bool = False):
    """Expand a uri into a sorted list of files. ';' separates multiple uris.

    ``with_sizes`` returns (files, sizes) with sizes batched per directory
    (one remote listing instead of a stat per file)."""
    files: List[str] = []
    sizes: List[int] = []
    for part in uri.split(";"):
        part = part.strip()
        if not part:
            continue
        if stream.isdir(part):
            for f, sz in stream.listdir_files(part):
                files.append(f)
                sizes.append(sz)
        elif stream.isfile(part):
            files.append(part)
            sizes.append(stream.getsize(part) if with_sizes else -1)
        else:
            hits = stream.glob(part)
            if not hits:
                raise FileNotFoundError(f"no files match data uri: {part!r}")
            for h in hits:
                if stream.isfile(h):
                    files.append(h)
                    sizes.append(stream.getsize(h) if with_sizes else -1)
    if with_sizes:
        return files, sizes
    return files


def _byte_ranges(files: List[str], sizes: List[int], part_idx: int,
                 num_parts: int) -> List[Tuple[str, int, int]]:
    """Assign this part's global byte range [begin, end) across files."""
    total = sum(sizes)
    begin = total * part_idx // num_parts
    end = total * (part_idx + 1) // num_parts
    out = []
    base = 0
    for f, sz in zip(files, sizes):
        lo, hi = max(begin, base), min(end, base + sz)
        if lo < hi:
            out.append((f, lo - base, hi - base))
        base += sz
    return out


def _iter_text_chunks(path: str, begin: int, end: int, chunk_bytes: int,
                      ) -> Iterator[bytes]:
    """Yield whole-line chunks covering [begin, end) of path.

    A chunk always ends on a line boundary; the line straddling `end` is
    included (and the line straddling `begin` excluded) so every line belongs
    to exactly one part.
    """
    with stream.open_stream(path, "rb") as f:
        pos = begin
        if begin > 0:
            f.seek(begin - 1)
            head = f.readline()  # finish the straddling line (owned by prev part)
            pos = begin - 1 + len(head)
        else:
            f.seek(0)
        while pos < end:
            data = f.read(max(min(chunk_bytes, end - pos), 1))
            if not data:
                break
            if not data.endswith(b"\n"):
                tail = f.readline()
                data += tail
            yield data
            pos += len(data)


class Reader:
    """Streaming sharded reader producing RowBlocks.

    Iterate, or use the reference-style ``next_block()`` returning None at end.
    """

    def __init__(self, uri: str, data_format: str = "libsvm",
                 part_idx: int = 0, num_parts: int = 1,
                 chunk_bytes: int = 1 << 26):
        if not 0 <= part_idx < num_parts:
            raise ValueError(f"part_idx {part_idx} out of range of {num_parts}")
        self.uri = uri
        self.data_format = data_format.lower()
        self.part_idx = part_idx
        self.num_parts = num_parts
        self.chunk_bytes = chunk_bytes
        self.files, self._sizes = expand_uri(uri, with_sizes=True)
        if not self.files:
            raise FileNotFoundError(f"empty data uri: {uri!r}")
        self._it: Iterator[RowBlock] | None = None

    def __iter__(self) -> Iterator[RowBlock]:
        if self.data_format == "rec":
            from .rec import iter_rec_blocks
            yield from iter_rec_blocks(self.files, self.part_idx,
                                       self.num_parts, sizes=self._sizes)
            return
        parse = get_parser(self.data_format)
        for path, b, e in _byte_ranges(self.files, self._sizes,
                                       self.part_idx, self.num_parts):
            for chunk in _iter_text_chunks(path, b, e, self.chunk_bytes):
                blk = parse(chunk)
                if blk.size:
                    yield blk

    def next_block(self) -> RowBlock | None:
        if self._it is None:
            self._it = iter(self)
        return next(self._it, None)

    def reset(self) -> None:
        self._it = None
