"""Keyed host array store + tile cache for blocked training data.

Equivalents of the reference's src/data layer:

- :class:`DataStore` <- DataStore/DataStoreMemory (src/data/data_store.h:
  24-163): keyed typed arrays with range fetch and a prefetch hint. The
  reference's disk-spill class is an empty stub (DataStoreDisk,
  src/data/data_store_impl.h:77-83); here spilling actually works — set
  ``max_mem_bytes`` and least-recently-used entries are written to
  ``spill_dir`` as .npy files and reloaded on demand.
- :class:`TileCache` <- TileStore (src/data/tile_store.h:32-168): a
  (rowblk, colblk)-keyed cache of *built* tiles (for us: device-resident
  COO slices) with LRU eviction, so feature-blocked learners (BCD, L-BFGS)
  can cap device/host memory on > memory datasets and rebuild evicted
  tiles on demand. ``prefetch`` builds ahead, mirroring
  TileStore::Prefetch's hint semantics.
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import Any, Callable, Dict, Hashable, Optional, Tuple

import numpy as np


class DataStore:
    """Host store of named numpy arrays with optional LRU disk spill."""

    def __init__(self, max_mem_bytes: int = 0,
                 spill_dir: Optional[str] = None):
        self._mem: "OrderedDict[str, np.ndarray]" = OrderedDict()
        self._meta: Dict[str, Tuple[tuple, np.dtype]] = {}
        self._spilled: Dict[str, str] = {}
        self.max_mem_bytes = max_mem_bytes
        self.spill_dir = spill_dir
        if max_mem_bytes and not spill_dir:
            raise ValueError("max_mem_bytes requires spill_dir")

    def store(self, key: str, data: np.ndarray) -> None:
        data = np.asarray(data)
        self._meta[key] = (data.shape, data.dtype)
        self._drop_spill(key)
        self._mem[key] = data
        self._mem.move_to_end(key)
        self._maybe_spill()

    def fetch(self, key: str, begin: int = 0,
              end: Optional[int] = None) -> np.ndarray:
        """The [begin, end) row range of key (Fetch, data_store.h:77-96)."""
        if key not in self._meta:
            raise KeyError(key)
        arr = self._mem.get(key)
        if arr is None:
            arr = np.load(self._spilled[key])
            self._mem[key] = arr
            self._drop_spill(key)  # remove the .npy, not just the entry
            self._maybe_spill()
        self._mem.move_to_end(key)
        return arr[begin:end] if (begin or end is not None) else arr

    def prefetch(self, key: str, begin: int = 0,
                 end: Optional[int] = None) -> None:
        """Hint: pull a spilled entry back into memory."""
        if key in self._spilled:
            self.fetch(key, begin, end)

    def remove(self, key: str) -> None:
        self._meta.pop(key, None)
        self._mem.pop(key, None)
        self._drop_spill(key)

    def size(self, key: str) -> int:
        shape, _ = self._meta[key]
        return int(np.prod(shape)) if shape else 1

    def keys(self):
        return list(self._meta)

    # ------------------------------------------------------------- spill
    def _mem_bytes(self) -> int:
        return sum(a.nbytes for a in self._mem.values())

    def _drop_spill(self, key: str) -> None:
        path = self._spilled.pop(key, None)
        if path:
            try:
                os.unlink(path)
            except OSError:
                pass

    def _maybe_spill(self) -> None:
        if not self.max_mem_bytes:
            return
        os.makedirs(self.spill_dir, exist_ok=True)
        while self._mem_bytes() > self.max_mem_bytes and len(self._mem) > 1:
            key, arr = self._mem.popitem(last=False)  # least recently used
            # unique monotone filename — hash(key) could collide
            self._spill_seq = getattr(self, "_spill_seq", 0) + 1
            path = os.path.join(self.spill_dir,
                                f"spill-{self._spill_seq:08d}.npy")
            np.save(path, arr)
            self._spilled[key] = path


def _leaf_nbytes(tile: Any) -> int:
    """Total bytes of a tile's array leaves (device or host)."""
    if tile is None:
        return 0
    import jax
    return sum(int(getattr(x, "nbytes", 0))
               for x in jax.tree_util.tree_leaves(tile))


class TileCache:
    """LRU cache of built tiles keyed by (rowblk_id, colblk_id).

    ``build(rowblk_id, colblk_id)`` constructs a tile (host or device
    object). Two independent bounds, both 0 = unlimited: ``max_items``
    (count) and ``max_bytes`` (sum of leaf array bytes via ``sizeof``,
    default: jax-tree nbytes) — the byte bound is what caps device
    memory for feature-blocked learners on > HBM datasets (the
    reference's analog: TileStore's cache over DataStore,
    src/data/tile_store.h:32-168). At least one entry always stays
    resident. ``None`` results (empty tiles) are cached too.
    """

    def __init__(self, build: Callable[[Hashable, Hashable], Any],
                 max_items: int = 0, max_bytes: int = 0,
                 sizeof: Optional[Callable[[Any], int]] = None):
        self._build = build
        self._cache: "OrderedDict[Tuple[Hashable, Hashable], " \
            "Tuple[Any, int]]" = OrderedDict()
        self.max_items = max_items
        self.max_bytes = max_bytes
        self._sizeof = sizeof or _leaf_nbytes
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    def fetch(self, rowblk_id: Hashable, colblk_id: Hashable) -> Any:
        key = (rowblk_id, colblk_id)
        if key in self._cache:
            self._cache.move_to_end(key)
            self.hits += 1
            return self._cache[key][0]
        self.misses += 1
        tile = self._build(rowblk_id, colblk_id)
        sz = self._sizeof(tile)
        self._cache[key] = (tile, sz)
        self._bytes += sz
        while len(self._cache) > 1 and (
                (self.max_items and len(self._cache) > self.max_items)
                or (self.max_bytes and self._bytes > self.max_bytes)):
            _, (_, esz) = self._cache.popitem(last=False)
            self._bytes -= esz
        return tile

    def prefetch(self, rowblk_id: Hashable, colblk_id: Hashable) -> None:
        self.fetch(rowblk_id, colblk_id)

    def invalidate(self) -> None:
        self._cache.clear()
        self._bytes = 0

    @property
    def nbytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._cache)
