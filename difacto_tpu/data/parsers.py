"""Text format parsers: libsvm, criteo, adfea.

Parsers take a text chunk (bytes) and produce a :class:`RowBlock` with raw
uint64 feature ids — equivalents of the reference's chunk parsers
(src/reader/reader.h:31-41 libsvm via dmlc; src/reader/criteo_parser.h:25-115;
src/reader/adfea_parser.h:20-91). The hot binary path is the `.rec`-equivalent
npz cache (rec.py); these pure-Python text parsers feed the converter and
small runs only.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..base import FEAID_DTYPE, REAL_DTYPE, encode_fea_grp_id
from .rowblock import RowBlock, empty_block


def parse_libsvm(chunk: bytes) -> RowBlock:
    """Parse a chunk of libsvm text: ``label idx:val idx:val ...`` per line.

    Tokenisation is per line in Python; the index/value string->number
    conversions (the bulk of the work) are batched through numpy.
    """
    lines = chunk.split(b"\n")
    labels = []
    counts = []
    tok_idx: list = []
    tok_val: list = []
    for line in lines:
        toks = line.split()
        if not toks:
            continue
        labels.append(toks[0])
        counts.append(len(toks) - 1)
        for t in toks[1:]:
            i, _, v = t.partition(b":")
            tok_idx.append(i)
            tok_val.append(v)
    if not labels:
        return empty_block()
    offset = np.zeros(len(labels) + 1, dtype=np.int64)
    np.cumsum(counts, out=offset[1:])
    label = np.array(labels, dtype=REAL_DTYPE)
    index = np.array(tok_idx, dtype=FEAID_DTYPE)
    value = np.array(tok_val, dtype=REAL_DTYPE) if tok_idx else np.zeros(0, REAL_DTYPE)
    return RowBlock(offset=offset, label=label, index=index, value=value)


_M64 = 0xC6A4A7935BD1E995
_MASK = (1 << 64) - 1


def _hash64(data: bytes, seed: int = 0) -> int:
    """MurmurHash64A (pure-Python reference implementation).

    The reference uses CityHash64 (criteo_parser.h:96-103); we use
    MurmurHash64A — any stable uniform 64-bit hash preserves the semantics
    (hashed feature space with per-column group ids in the low 12 bits).
    This function and the native one (native/criteo_parser.cc) MUST agree
    bit for bit; tests/test_native.py checks it.
    """
    n = len(data)
    h = (seed ^ (n * _M64)) & _MASK
    nblocks = n // 8
    for i in range(nblocks):
        k = int.from_bytes(data[i * 8:i * 8 + 8], "little")
        k = (k * _M64) & _MASK
        k ^= k >> 47
        k = (k * _M64) & _MASK
        h = ((h ^ k) * _M64) & _MASK
    tail = data[nblocks * 8:]
    if tail:
        h ^= int.from_bytes(tail, "little")
        h = (h * _M64) & _MASK
    h ^= h >> 47
    h = (h * _M64) & _MASK
    h ^= h >> 47
    return h


def parse_criteo(chunk: bytes, is_train: bool = True) -> RowBlock:
    """Parse Criteo CTR tab-separated format.

    ``<label> <int f1..f13> <cat f1..f26>``; each non-empty field is hashed to
    64 bits with its column id packed in the low 12 bits
    (criteo_parser.h:57-86).
    """
    labels = []
    counts = []
    ids: list = []
    for line in chunk.split(b"\n"):
        line = line.strip(b"\r")
        if not line:
            continue
        fields = line.split(b"\t")
        pos = 0
        if is_train:
            labels.append(float(fields[0]))
            pos = 1
        else:
            labels.append(0.0)
        n = 0
        for i in range(13):
            if pos + i < len(fields) and fields[pos + i]:
                ids.append(encode_fea_grp_id(_hash64(fields[pos + i]), i, 12))
                n += 1
        for i in range(26):
            j = pos + 13 + i
            if j < len(fields) and fields[j]:
                ids.append(encode_fea_grp_id(_hash64(fields[j]), i + 13, 12))
                n += 1
        counts.append(n)
    if not labels:
        return empty_block()
    offset = np.zeros(len(labels) + 1, dtype=np.int64)
    np.cumsum(counts, out=offset[1:])
    return RowBlock(
        offset=offset,
        label=np.array(labels, dtype=REAL_DTYPE),
        index=np.array(ids, dtype=FEAID_DTYPE),
        value=None,  # binary features
    )


def parse_adfea(chunk: bytes) -> RowBlock:
    """Parse adfea format: ``lineid count label idx:gid idx:gid ...``.

    Tokens without ``:`` cycle through (lineid, count, label); ``idx:gid``
    tokens become features with the 12-bit group id in the low bits
    (adfea_parser.h:54-77).
    """
    labels = []
    counts = []
    ids: list = []
    i = 0
    cur = -1
    for tok in chunk.split():
        head, sep, tail = tok.partition(b":")
        if sep:
            ids.append(encode_fea_grp_id(int(head), int(tail) % 4096, 12))
            if cur >= 0:
                counts[cur] += 1
        else:
            if i == 2:
                i = 0
                labels.append(1.0 if head.startswith(b"1") else 0.0)
                counts.append(0)
                cur += 1
            else:
                i += 1
    if not labels:
        return empty_block()
    offset = np.zeros(len(labels) + 1, dtype=np.int64)
    np.cumsum(counts, out=offset[1:])
    return RowBlock(
        offset=offset,
        label=np.array(labels, dtype=REAL_DTYPE),
        index=np.array(ids, dtype=FEAID_DTYPE),
        value=None,
    )


def get_parser(fmt: str):
    fmt = fmt.lower()
    if fmt == "libsvm":
        # native C++ fast path with automatic Python fallback
        from .native_parsers import parse_libsvm_native
        return parse_libsvm_native
    if fmt == "criteo":
        from .native_parsers import parse_criteo_native
        return parse_criteo_native
    if fmt == "criteo_test":
        from .native_parsers import parse_criteo_native
        return lambda chunk: parse_criteo_native(chunk, is_train=False)
    if fmt == "adfea":
        from .native_parsers import parse_adfea_native
        return parse_adfea_native
    raise ValueError(f"unknown data format: {fmt}")
