"""Text format parsers: libsvm, criteo, adfea.

Parsers take a text chunk (bytes) and produce a :class:`RowBlock` with raw
uint64 feature ids — equivalents of the reference's chunk parsers
(src/reader/reader.h:31-41 libsvm via dmlc; src/reader/criteo_parser.h:25-115;
src/reader/adfea_parser.h:20-91).

``parse_libsvm`` and ``parse_criteo`` are **bulk numpy** implementations
(ISSUE 7): one ``np.frombuffer`` over the chunk, single-pass delimiter
scans (token/field boundaries via diff-of-masks, line ids via a newline
cumsum), and vectorized number conversion — exact uint64 digit
accumulation for feature ids, a correctly-rounded float path for labels
and values (single multiply/divide by an exact power of ten; anything
exotic falls back to Python ``float`` per token), and a lane-parallel
MurmurHash64A for the criteo categorical hashing. The old per-line loop
implementations survive as ``parse_libsvm_ref``/``parse_criteo_ref`` —
the semantic reference the vectorized and native parsers are tested
against byte for byte.

Implicit-value tokens (``idx`` with no ``:val``) parse as value 1.0 in
every implementation, and a chunk may mix implicit and explicit tokens
freely; the value array is elided (None) when every value is 1.0, the
reference's binary-feature elision (src/reader/batch_reader.cc:71-73).

The hot binary path is the rec cache (rec.py/rec2.py); these parsers
feed the converter, live-text streaming, and the native-parser fallback.
"""

from __future__ import annotations

import numpy as np

from ..base import FEAID_DTYPE, REAL_DTYPE, encode_fea_grp_id
from .rowblock import RowBlock, empty_block

_U64_MAX = (1 << 64) - 1


# ------------------------------------------------------------ bulk lexing
def _token_matrix(buf: np.ndarray, starts: np.ndarray, lens: np.ndarray,
                  pad: int):
    """Gather variable-length byte tokens into a right-padded [L, n]
    uint8 matrix + validity mask (L = longest token, COLUMN-major so the
    per-column loops downstream run over contiguous rows). L is ~20 for
    numbers, so the whole conversion is a handful of numpy passes."""
    L = int(lens.max()) if len(lens) else 0
    # int32 gather indices: half the footprint of the position matrix
    # (chunks are far below 2 GB)
    pos = (starts.astype(np.int32)[None, :]
           + np.arange(L, dtype=np.int32)[:, None])
    np.minimum(pos, np.int32(buf.size - 1), out=pos)
    ch = buf[pos]
    mask = np.arange(L, dtype=np.int32)[:, None] < \
        lens.astype(np.int32)[None, :]
    ch[~mask] = pad
    return ch, mask


def _parse_uint64_tokens(chunk: bytes, buf: np.ndarray, starts: np.ndarray,
                         lens: np.ndarray, what: str) -> np.ndarray:
    """Exact vectorized uint64 parse (digit accumulation — float64 would
    silently round ids past 2^53)."""
    n = len(starts)
    if n == 0:
        return np.zeros(0, FEAID_DTYPE)
    if (lens <= 0).any():
        raise ValueError(f"empty {what}")
    L = int(lens.max())
    if L > 20:
        raise ValueError(f"{what} overflows uint64")
    # RIGHT-aligned gather: digits occupy the trailing columns, leading
    # cells (bytes before the token) zero out in one mask pass — the
    # accumulation then runs unconditionally in place (a leading zero is
    # the identity), no per-column where/temporaries
    pos = ((starts + lens).astype(np.int64)[None, :] - L
           + np.arange(L, dtype=np.int64)[:, None])
    np.clip(pos, 0, buf.size - 1, out=pos)
    ch = buf[pos]
    valid = np.arange(L, dtype=np.int32)[:, None] >= \
        (L - lens.astype(np.int32))[None, :]
    # '0'..'9' minus 48 stays <= 9 in uint8; any other byte wraps past 9
    d = ch - np.uint8(48)
    if ((d > 9) & valid).any():
        raise ValueError(f"malformed {what} (non-digit)")
    d[~valid] = 0
    val = np.zeros(n, np.uint64)
    ten = np.uint64(10)
    for j in range(L):
        np.multiply(val, ten, out=val)
        np.add(val, d[j], out=val, casting="unsafe")
    if (lens == 20).any():
        # the only lengths where uint64 accumulation can wrap: check
        # those few tokens exactly
        for s, ln in zip(starts[lens == 20], lens[lens == 20]):
            if int(chunk[int(s):int(s) + int(ln)]) > _U64_MAX:
                raise ValueError(f"{what} overflows uint64")
    return val.astype(FEAID_DTYPE)


def _parse_float_tokens(chunk: bytes, buf: np.ndarray, starts: np.ndarray,
                        lens: np.ndarray) -> np.ndarray:
    """Vectorized float parse. The dominant token shape —
    ``[sign]digits[.digits]`` — takes a 5-op-per-column fast lane
    (:func:`_float_simple`); tokens carrying an exponent go through the
    general single-sweep parser (:func:`_float_general`); anything
    outside either (inf/nan, > 16 mantissa digits, |exponent| > 22,
    stray characters) falls back to Python ``float`` per token, which
    also supplies the ValueError for genuinely malformed input. Both
    vector lanes accumulate the mantissa exactly in float64 and apply
    the scale as ONE multiply or divide by an exact power of ten, so
    results are correctly rounded — identical to strtod."""
    n = len(starts)
    if n == 0:
        return np.empty(0, np.float64)
    # optimistic tiering: run the fast lane on everything, re-run only
    # its rejects through the general lane, and only ITS rejects through
    # Python float — typical data never leaves tier 1, so no masks or
    # pre-classification costs are paid at all
    out, bad = _float_simple(buf, starts, lens)
    if bad.any():
        idx = np.flatnonzero(bad)
        out[idx], gbad = _float_general(buf, starts[idx], lens[idx])
        for i in idx[gbad]:
            s, ln = int(starts[i]), int(lens[i])
            out[i] = float(chunk[s:s + ln])  # ValueError on real garbage
    return out


def _float_simple(buf: np.ndarray, starts: np.ndarray, lens: np.ndarray):
    """Fast lane: ``[sign]digits[.digits]`` -> (values, bad_mask). The
    dot column comes straight from the gathered byte matrix (the pad
    byte is '0', so pads can never fake a dot), and the accumulation
    runs in place with per-column ``where=`` masks — no temporaries."""
    n = len(starts)
    cap = max(buf.size - 1, 0)
    c0 = buf[np.minimum(starts, cap)]
    neg = c0 == 45
    signed = neg | (c0 == 43)
    s = starts + signed
    ln = lens - signed
    bad = ln <= 0
    ch, mask = _token_matrix(buf, s, np.maximum(ln, 0), ord("0"))
    dotm = ch == 46
    ndot = dotm.sum(axis=0, dtype=np.int16)
    has_dot = ndot == 1
    bad |= ndot > 1
    dcol = np.where(has_dot, dotm.argmax(axis=0), ln)
    d = ch - np.uint8(48)
    use = mask & ~dotm
    bad |= ((d > 9) & use).any(axis=0)
    # uint64 digit accumulation is EXACT up to 19 digits (vs 15 for
    # float64 — ML dumps routinely carry 17-digit fractions); the one
    # uint64->float64 conversion plus one divide by an exact power of
    # ten stays within 1 ulp of strtod, invisible after the float32 cast
    val = np.zeros(n, np.uint64)
    ten = np.uint64(10)
    for j in range(ch.shape[0]):
        np.multiply(val, ten, out=val, where=use[j])
        np.add(val, d[j], out=val, casting="unsafe", where=use[j])
    ndigits = ln - has_dot
    frac = np.where(has_dot, ln - dcol - 1, 0)
    bad |= (ndigits <= 0) | (ndigits > 19) | (frac > 22)
    out = val.astype(np.float64) / np.power(10.0, np.minimum(frac, 22))
    return np.where(neg, -out, out), bad


def _float_general(buf: np.ndarray, starts: np.ndarray, lens: np.ndarray):
    """General lane: ``[sign]digits[.digits][e[sign]digits]`` ->
    (values, bad_mask)."""
    n = len(starts)
    ch, mask = _token_matrix(buf, starts, lens, 32)
    L = ch.shape[0]
    neg = ch[0] == 45

    # ONE left-to-right column sweep over the [L, n] matrix (contiguous
    # rows): digits before the first 'e' accumulate into the mantissa,
    # digits after into the exponent; '.' starts the fraction count.
    # State lives in small per-token vectors — no [n, L] numeric
    # temporaries (those measured slower than the loop reference).
    bad = lens <= 0
    mant = np.zeros(n, np.uint64)
    ev = np.zeros(n)
    n_mant = np.zeros(n, np.int16)
    n_frac = np.zeros(n, np.int16)
    n_exp = np.zeros(n, np.int16)
    seen_e = np.zeros(n, dtype=bool)
    seen_dot = np.zeros(n, dtype=bool)
    prev_e = np.zeros(n, dtype=bool)
    eneg = np.zeros(n, dtype=bool)
    for j in range(L):
        cj = ch[j]
        mj = mask[j]
        dj = (cj >= 48) & (cj <= 57)
        ej = ((cj == 101) | (cj == 69)) & mj
        dotj = (cj == 46) & mj
        signj = ((cj == 43) | (cj == 45)) & mj
        bad |= mj & ~(dj | ej | dotj | signj)
        if j:
            # signs only lead the mantissa (col 0) or the exponent
            bad |= signj & ~prev_e
            eneg |= prev_e & (cj == 45)
        bad |= (ej & seen_e) | (dotj & (seen_dot | seen_e))
        in_mant = dj & ~seen_e
        in_exp = dj & seen_e
        dvalj = (cj - np.uint8(48)).astype(np.uint64)
        mant = np.where(in_mant, mant * np.uint64(10) + dvalj, mant)
        ev = np.where(in_exp, ev * 10.0 + (cj.astype(np.float64) - 48.0),
                      ev)
        n_mant += in_mant
        n_frac += in_mant & seen_dot
        n_exp += in_exp
        seen_e |= ej
        seen_dot |= dotj
        prev_e = ej
    bad |= (n_mant == 0) | (n_mant > 19)  # 19 digits: exact in uint64
    bad |= seen_e & (n_exp == 0)

    exp10 = np.where(eneg, -ev, ev) - n_frac
    # one multiply OR divide by an exact power of ten after the single
    # uint64->float64 conversion: within 1 ulp of strtod for
    # |exp10| <= 22, invisible after the float32 cast
    bad |= np.abs(exp10) > 22
    mantf = mant.astype(np.float64)
    p_pos = np.power(10.0, np.clip(exp10, 0, 22))
    p_neg = np.power(10.0, np.clip(-exp10, 0, 22))
    res = np.where(exp10 >= 0, mantf * p_pos, mantf / p_neg)
    return np.where(neg, -res, res), bad


# non-whitespace lookup table (bytes.split semantics: space \t \n \r \v \f)
_NON_WS_LUT = np.ones(256, dtype=np.int8)
_NON_WS_LUT[[9, 10, 11, 12, 13, 32]] = 0


# ---------------------------------------------------------------- libsvm
def parse_libsvm(chunk: bytes) -> RowBlock:
    """Bulk-numpy parse of libsvm text: ``label idx[:val] idx[:val] ...``
    per line. One pass finds token boundaries and line ids; ids and
    values convert vectorized (see module docstring). Tokens without
    ``:val`` are implicit value 1.0; an all-ones chunk elides the value
    array (binary features)."""
    buf = np.frombuffer(chunk, dtype=np.uint8)
    if buf.size == 0:
        return empty_block()
    tok = _NON_WS_LUT[buf]  # one gather instead of 4 comparison passes
    d = np.diff(tok, prepend=np.int8(0), append=np.int8(0))
    starts = np.flatnonzero(d == 1).astype(np.int64)
    if starts.size == 0:
        return empty_block()
    ends = np.flatnonzero(d == -1).astype(np.int64)

    # line id per token = newlines before its start (positions, not a
    # whole-buffer cumsum: tokens are ~10x sparser than bytes)
    nl_pos = np.flatnonzero(buf == 10).astype(np.int64)
    line_of = np.searchsorted(nl_pos, starts)
    first = np.empty(len(starts), dtype=bool)
    first[0] = True
    np.not_equal(line_of[1:], line_of[:-1], out=first[1:])

    lab_s, lab_e = starts[first], ends[first]
    feat_s, feat_e = starts[~first], ends[~first]
    label = _parse_float_tokens(chunk, buf, lab_s,
                                lab_e - lab_s).astype(REAL_DTYPE)

    # split each feature token at its (single) ':' — one searchsorted
    # finds each token's first colon at-or-after its start; the NEXT
    # colon position rules out a second one inside the same token
    colon_pos = np.flatnonzero(buf == 58).astype(np.int64)
    if colon_pos.size:
        nth = np.searchsorted(colon_pos, feat_s)
        cand = colon_pos[np.minimum(nth, colon_pos.size - 1)]
        has_v = (nth < colon_pos.size) & (cand < feat_e)
        nxt = colon_pos[np.minimum(nth + 1, colon_pos.size - 1)]
        if (has_v & (nth + 1 < colon_pos.size) & (nxt < feat_e)).any():
            raise ValueError("malformed libsvm token (multiple ':')")
        cpos = np.where(has_v, cand, feat_e)
    else:
        has_v = np.zeros(len(feat_s), dtype=bool)
        cpos = feat_e
    index = _parse_uint64_tokens(chunk, buf, feat_s, cpos - feat_s,
                                 "libsvm feature id")
    value64 = np.ones(len(feat_s), np.float64)
    if has_v.any():
        vs = cpos[has_v] + 1
        vl = feat_e[has_v] - vs
        if (vl <= 0).any():
            raise ValueError("empty libsvm value after ':'")
        value64[has_v] = _parse_float_tokens(chunk, buf, vs, vl)

    # row id per feature token = labels seen so far (cumsum beats a
    # searchsorted over the token array)
    row_of = np.cumsum(first)[~first] - 1
    counts = np.bincount(row_of, minlength=len(lab_s))
    offset = np.zeros(len(lab_s) + 1, dtype=np.int64)
    np.cumsum(counts, out=offset[1:])
    value = value64.astype(REAL_DTYPE)
    return RowBlock(
        offset=offset, label=label, index=index,
        value=None if (value == 1.0).all() else value)


def parse_libsvm_ref(chunk: bytes) -> RowBlock:
    """Per-line loop reference implementation (the semantic spec the
    vectorized and native parsers are compared against)."""
    lines = chunk.split(b"\n")
    labels = []
    counts = []
    tok_idx: list = []
    tok_val: list = []
    for line in lines:
        toks = line.split()
        if not toks:
            continue
        labels.append(toks[0])
        counts.append(len(toks) - 1)
        for t in toks[1:]:
            i, sep, v = t.partition(b":")
            tok_idx.append(i)
            # implicit-value token "idx" == "idx:1" — independent of
            # whether any other token in the chunk carries a value
            tok_val.append(v if sep else b"1")
    if not labels:
        return empty_block()
    offset = np.zeros(len(labels) + 1, dtype=np.int64)
    np.cumsum(counts, out=offset[1:])
    label = np.array(labels, dtype=REAL_DTYPE)
    index = np.array(tok_idx, dtype=FEAID_DTYPE)
    value = np.array(tok_val, dtype=REAL_DTYPE) if tok_idx else None
    if value is not None and (value == 1.0).all():
        value = None  # binary elision (batch_reader.cc:71-73)
    return RowBlock(offset=offset, label=label, index=index, value=value)


_M64 = 0xC6A4A7935BD1E995
_MASK = (1 << 64) - 1


def _hash64(data: bytes, seed: int = 0) -> int:
    """MurmurHash64A (pure-Python reference implementation).

    The reference uses CityHash64 (criteo_parser.h:96-103); we use
    MurmurHash64A — any stable uniform 64-bit hash preserves the semantics
    (hashed feature space with per-column group ids in the low 12 bits).
    This function, the bulk one (:func:`_hash64_bulk`) and the native one
    (native/criteo_parser.cc) MUST agree bit for bit; tests/test_native.py
    checks it.
    """
    n = len(data)
    h = (seed ^ (n * _M64)) & _MASK
    nblocks = n // 8
    for i in range(nblocks):
        k = int.from_bytes(data[i * 8:i * 8 + 8], "little")
        k = (k * _M64) & _MASK
        k ^= k >> 47
        k = (k * _M64) & _MASK
        h = ((h ^ k) * _M64) & _MASK
    tail = data[nblocks * 8:]
    if tail:
        h ^= int.from_bytes(tail, "little")
        h = (h * _M64) & _MASK
    h ^= h >> 47
    h = (h * _M64) & _MASK
    h ^= h >> 47
    return h


def _hash64_bulk(buf: np.ndarray, starts: np.ndarray, lens: np.ndarray,
                 seed: int = 0) -> np.ndarray:
    """Lane-parallel MurmurHash64A over variable-length byte spans of
    ``buf`` — bit-identical to :func:`_hash64` per span. The loops run
    over the LONGEST span's 8-byte blocks (criteo fields are short), each
    iteration a masked vector op over every span at once; uint64 numpy
    arithmetic wraps mod 2^64 exactly like the scalar masks."""
    n = len(starts)
    M = np.uint64(_M64)
    h = np.uint64(seed) ^ (lens.astype(np.uint64) * M)
    if n == 0:
        return h
    cap = max(buf.size - 1, 0)

    def byte_at(pos):  # gather n bytes, then widen (never the whole buf)
        return buf[np.minimum(pos, cap)].astype(np.uint64)

    nblocks = lens // 8
    for i in range(int(nblocks.max())):
        base = starts + 8 * i
        k = np.zeros(n, np.uint64)
        for j in range(8):
            k |= byte_at(base + j) << np.uint64(8 * j)
        k *= M
        k ^= k >> np.uint64(47)
        k *= M
        h = np.where(i < nblocks, (h ^ k) * M, h)
    tail_len = lens - nblocks * 8
    tbase = starts + nblocks * 8
    tv = np.zeros(n, np.uint64)
    for j in range(7):
        byte = np.where(j < tail_len, byte_at(tbase + j), np.uint64(0))
        tv |= byte << np.uint64(8 * j)
    h = np.where(tail_len > 0, (h ^ tv) * M, h)
    h ^= h >> np.uint64(47)
    h *= M
    h ^= h >> np.uint64(47)
    return h


# ---------------------------------------------------------------- criteo
def parse_criteo(chunk: bytes, is_train: bool = True) -> RowBlock:
    """Bulk-numpy parse of Criteo CTR tab-separated format.

    ``<label> <int f1..f13> <cat f1..f26>``; each non-empty field is
    hashed to 64 bits (lane-parallel MurmurHash64A) with its column id
    packed in the low 12 bits (criteo_parser.h:57-86). Field boundaries
    come from one tab/newline scan; no per-line Python."""
    buf = np.frombuffer(chunk, dtype=np.uint8)
    if buf.size == 0:
        return empty_block()
    nl = np.flatnonzero(buf == 10).astype(np.int64)
    ls = np.concatenate(([0], nl + 1))
    le = np.concatenate((nl, [buf.size]))
    # strip '\r' at both line ends (the loop reference strips b"\r");
    # a few vector passes cover real data, stragglers finish per line
    for _ in range(4):
        m = (le > ls) & (buf[np.maximum(le - 1, 0)] == 13)
        if not m.any():
            break
        le = le - m
    for _ in range(4):
        m = (le > ls) & (buf[np.minimum(ls, buf.size - 1)] == 13)
        if not m.any():
            break
        ls = ls + m
    dirty = (le > ls) & ((buf[np.maximum(le - 1, 0)] == 13)
                         | (buf[np.minimum(ls, buf.size - 1)] == 13))
    for i in np.flatnonzero(dirty):  # pragma: no cover - exotic input
        while le[i] > ls[i] and buf[le[i] - 1] == 13:
            le[i] -= 1
        while le[i] > ls[i] and buf[ls[i]] == 13:
            ls[i] += 1
    keep = le > ls
    ls, le = ls[keep], le[keep]
    nlines = len(ls)
    if nlines == 0:
        return empty_block()

    tabs = np.flatnonzero(buf == 9).astype(np.int64)
    tl = np.searchsorted(ls, tabs, side="right") - 1
    ok_tab = (tl >= 0)
    safe_tl = np.maximum(tl, 0)
    ok_tab &= (tabs >= ls[safe_tl]) & (tabs < le[safe_tl])
    tabs, tl = tabs[ok_tab], tl[ok_tab]

    nfields = np.bincount(tl, minlength=nlines) + 1
    total = int(nfields.sum())
    firsts_idx = np.concatenate(([0], np.cumsum(nfields)[:-1]))
    first_field = np.zeros(total, dtype=bool)
    first_field[firsts_idx] = True
    last_field = np.zeros(total, dtype=bool)
    last_field[firsts_idx + nfields - 1] = True
    f_start = np.empty(total, np.int64)
    f_end = np.empty(total, np.int64)
    f_start[first_field] = ls
    f_start[~first_field] = tabs + 1
    f_end[last_field] = le
    f_end[~last_field] = tabs
    f_line = np.repeat(np.arange(nlines), nfields)
    col = np.arange(total) - np.repeat(firsts_idx, nfields)

    pos0 = 1 if is_train else 0
    if is_train:
        labels = _parse_float_tokens(
            chunk, buf, f_start[first_field],
            f_end[first_field] - f_start[first_field]).astype(REAL_DTYPE)
    else:
        labels = np.zeros(nlines, dtype=REAL_DTYPE)

    featm = (col >= pos0) & (col < pos0 + 39) & (f_end > f_start)
    fs, flen = f_start[featm], f_end[featm] - f_start[featm]
    h = _hash64_bulk(buf, fs, flen)
    grp = (col[featm] - pos0).astype(np.uint64)
    ids = ((h << np.uint64(12)) | grp).astype(FEAID_DTYPE)

    counts = np.bincount(f_line[featm], minlength=nlines)
    offset = np.zeros(nlines + 1, dtype=np.int64)
    np.cumsum(counts, out=offset[1:])
    return RowBlock(
        offset=offset,
        label=labels,
        index=ids,
        value=None,  # binary features
    )


def parse_criteo_ref(chunk: bytes, is_train: bool = True) -> RowBlock:
    """Per-line loop reference implementation of the criteo parser."""
    labels = []
    counts = []
    ids: list = []
    for line in chunk.split(b"\n"):
        line = line.strip(b"\r")
        if not line:
            continue
        fields = line.split(b"\t")
        pos = 0
        if is_train:
            labels.append(float(fields[0]))
            pos = 1
        else:
            labels.append(0.0)
        n = 0
        for i in range(13):
            if pos + i < len(fields) and fields[pos + i]:
                ids.append(encode_fea_grp_id(_hash64(fields[pos + i]), i, 12))
                n += 1
        for i in range(26):
            j = pos + 13 + i
            if j < len(fields) and fields[j]:
                ids.append(encode_fea_grp_id(_hash64(fields[j]), i + 13, 12))
                n += 1
        counts.append(n)
    if not labels:
        return empty_block()
    offset = np.zeros(len(labels) + 1, dtype=np.int64)
    np.cumsum(counts, out=offset[1:])
    return RowBlock(
        offset=offset,
        label=np.array(labels, dtype=REAL_DTYPE),
        index=np.array(ids, dtype=FEAID_DTYPE),
        value=None,  # binary features
    )


def parse_adfea(chunk: bytes) -> RowBlock:
    """Parse adfea format: ``lineid count label idx:gid idx:gid ...``.

    Tokens without ``:`` cycle through (lineid, count, label); ``idx:gid``
    tokens become features with the 12-bit group id in the low bits
    (adfea_parser.h:54-77).
    """
    labels = []
    counts = []
    ids: list = []
    i = 0
    cur = -1
    for tok in chunk.split():
        head, sep, tail = tok.partition(b":")
        if sep:
            ids.append(encode_fea_grp_id(int(head), int(tail) % 4096, 12))
            if cur >= 0:
                counts[cur] += 1
        else:
            if i == 2:
                i = 0
                labels.append(1.0 if head.startswith(b"1") else 0.0)
                counts.append(0)
                cur += 1
            else:
                i += 1
    if not labels:
        return empty_block()
    offset = np.zeros(len(labels) + 1, dtype=np.int64)
    np.cumsum(counts, out=offset[1:])
    return RowBlock(
        offset=offset,
        label=np.array(labels, dtype=REAL_DTYPE),
        index=np.array(ids, dtype=FEAID_DTYPE),
        value=None,
    )


def get_parser(fmt: str):
    fmt = fmt.lower()
    if fmt == "libsvm":
        # native C++ fast path with automatic Python fallback
        from .native_parsers import parse_libsvm_native
        return parse_libsvm_native
    if fmt == "criteo":
        from .native_parsers import parse_criteo_native
        return parse_criteo_native
    if fmt == "criteo_test":
        from .native_parsers import parse_criteo_native
        return lambda chunk: parse_criteo_native(chunk, is_train=False)
    if fmt == "adfea":
        from .native_parsers import parse_adfea_native
        return parse_adfea_native
    raise ValueError(f"unknown data format: {fmt}")
