"""Binary row-block cache — the `.rec` equivalent.

The reference's fast path is recordio files of LZ4-compressed CSR blocks
(src/reader/crb_parser.h:16-47, src/data/compressed_row_block.h:20-142),
produced by ``task=convert`` (src/reader/converter.h:41-124). Feeding TPU
chips from text on a single-core host is hopeless, so the same design carries
over: parse text once, write compressed binary shards, stream those.

Format: a ``<name>.rec`` directory (or explicit file list) of ``.npz``
members, one compressed CSR block each, arrays: offset/label/index[/value]
[/weight]. Sharding for (part_idx, num_parts) is by whole members, weighted
by compressed size — the unit of work-stealing, like recordio parts.
"""

from __future__ import annotations

from typing import Iterator, List

import numpy as np

from ..utils import stream
from .rowblock import RowBlock


def write_rec_block(path: str, blk: RowBlock, compress: bool = True) -> None:
    arrays = dict(offset=blk.offset, label=blk.label, index=blk.index)
    if blk.value is not None:
        arrays["value"] = blk.value
    if blk.weight is not None:
        arrays["weight"] = blk.weight
    stream.save_npz(path, compress=compress, **arrays)


def read_rec_block(path: str) -> RowBlock:
    with stream.load_npz(path) as z:
        return RowBlock(
            offset=z["offset"],
            label=z["label"],
            index=z["index"],
            value=z["value"] if "value" in z.files else None,
            weight=z["weight"] if "weight" in z.files else None,
        )


def rec_members(files: List[str], sizes=None) -> List[tuple]:
    """Resolve to [(member, size)] .npz members only — stray files (.tmp from
    an interrupted writer, READMEs) in a cache dir must not reach np.load.
    ``sizes`` parallel to ``files`` avoids a remote stat per member."""
    out: List[tuple] = []
    for i, f in enumerate(files):
        if stream.isdir(f):
            out.extend((m, sz) for m, sz in stream.listdir_files(f)
                       if m.endswith(".npz"))
        elif f.endswith(".npz"):
            sz = sizes[i] if sizes is not None and sizes[i] >= 0 \
                else stream.getsize(f)
            out.append((f, sz))
    return out


def iter_rec_blocks(files: List[str], part_idx: int, num_parts: int,
                    sizes=None) -> Iterator[RowBlock]:
    """Yield this part's members, sharded by cumulative compressed size."""
    pairs = rec_members(files, sizes)
    members = [m for m, _ in pairs]
    sizes = [sz for _, sz in pairs]
    total = sum(sizes)
    begin = total * part_idx // num_parts
    end = total * (part_idx + 1) // num_parts
    base = 0
    for m, sz in zip(members, sizes):
        # a member belongs to the part containing its start byte
        if begin <= base < end:
            yield read_rec_block(m)
        base += sz


class RecWriter:
    """Write a stream of RowBlocks into a .rec directory of npz shards."""

    def __init__(self, out_dir: str, compress: bool = True):
        self.out_dir = out_dir
        self.compress = compress
        self._n = 0
        stream.makedirs(out_dir)

    def write(self, blk: RowBlock) -> None:
        path = stream.join(self.out_dir, f"part-{self._n:05d}.npz")
        write_rec_block(path, blk, self.compress)
        self._n += 1

    @property
    def num_blocks(self) -> int:
        return self._n
