"""Binary row-block cache — the `.rec` equivalent.

The reference's fast path is recordio files of LZ4-compressed CSR blocks
(src/reader/crb_parser.h:16-47, src/data/compressed_row_block.h:20-142),
produced by ``task=convert`` (src/reader/converter.h:41-124). Feeding TPU
chips from text on a single-core host is hopeless, so the same design carries
over: parse text once, write compressed binary shards, stream those.

Format: a ``<name>.rec`` directory (or explicit file list) of members,
one CSR block each, arrays: offset/label/index[/value][/weight]. Two
member encodings coexist, dispatched on extension:

- ``.rec2`` (default for new writes) — the raw page-aligned zero-copy
  framing of rec2.py: readers ``mmap`` the member and get
  ``np.frombuffer`` views, no decompress, no archive walk, typed
  :class:`~.rec2.RecCorrupt` on torn/bit-flipped files;
- ``.npz`` (legacy v1) — numpy archives, still read transparently so
  existing caches keep working (``task=convert`` re-encodes them).

Sharding for (part_idx, num_parts) is by whole members, weighted by
on-disk size — the unit of work-stealing, like recordio parts.

**Pre-localized members** additionally carry ``uniq``: the member's sorted
distinct *reversed* feature ids (the Localizer output, data/localizer.py),
with ``index`` already remapped to uint32 positions into it — the same trick
as the reference's CRB storing compacted CSR (crb_parser.h:16-47). Epochs
then skip parse + the O(nnz) sort/unique entirely; the per-batch host work
collapses to an O(uniq) slot map + buffer packing.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..utils import stream
from .rec2 import SUFFIX as REC2_SUFFIX
from .rec2 import read_rec2, write_rec2
from .rowblock import RowBlock

# member extensions the rec cache readers accept, in either encoding
MEMBER_SUFFIXES = (REC2_SUFFIX, ".npz")


def write_rec_block(path: str, blk: RowBlock, compress: bool = True,
                    uniq: Optional[np.ndarray] = None) -> None:
    """``uniq`` marks a pre-localized member: blk.index must be uint32
    positions into uniq (sorted reversed ids). The encoding follows the
    path's extension: ``.rec2`` = the zero-copy framing (rec2.py,
    ``compress`` ignored — raw sections read at page-cache speed),
    ``.npz`` = the legacy archive."""
    arrays = dict(offset=blk.offset, label=blk.label, index=blk.index)
    if uniq is not None:
        arrays["uniq"] = uniq
        arrays["index"] = blk.index.astype(np.uint32)
    if blk.value is not None:
        arrays["value"] = blk.value
    if blk.weight is not None:
        arrays["weight"] = blk.weight
    if path.endswith(REC2_SUFFIX):
        write_rec2(path, arrays)
        return
    stream.save_npz(path, compress=compress, **arrays)


def read_rec_block_ex(path: str) -> Tuple[RowBlock, Optional[np.ndarray]]:
    """(block, uniq-or-None); uniq != None means index is localized.
    Dispatches on the member extension; rec2 members come back as
    zero-copy mmap views."""
    if path.endswith(REC2_SUFFIX):
        z2 = read_rec2(path)
        return RowBlock(
            offset=z2["offset"],
            label=z2["label"],
            index=z2["index"],
            value=z2.get("value"),
            weight=z2.get("weight"),
        ), z2.get("uniq")
    with stream.load_npz(path) as z:
        blk = RowBlock(
            offset=z["offset"],
            label=z["label"],
            index=z["index"],
            value=z["value"] if "value" in z.files else None,
            weight=z["weight"] if "weight" in z.files else None,
        )
        return blk, (z["uniq"] if "uniq" in z.files else None)


def read_rec_block(path: str) -> RowBlock:
    """Legacy view: localized members are de-localized back to the ORIGINAL
    id space (uniq holds reversed ids; un-reverse on expansion) so
    format-agnostic callers see ordinary uint64 CSR blocks."""
    from ..base import reverse_bytes
    blk, uniq = read_rec_block_ex(path)
    if uniq is not None:
        blk.index = reverse_bytes(uniq)[blk.index]
    return blk


def rec_members(files: List[str], sizes=None) -> List[tuple]:
    """Resolve to [(member, size)] known member encodings only — stray
    files (.tmp from an interrupted writer, READMEs) in a cache dir must
    not reach the block readers. ``sizes`` parallel to ``files`` avoids a
    remote stat per member."""
    out: List[tuple] = []
    for i, f in enumerate(files):
        if stream.isdir(f):
            out.extend((m, sz) for m, sz in stream.listdir_files(f)
                       if m.endswith(MEMBER_SUFFIXES))
        elif f.endswith(MEMBER_SUFFIXES):
            sz = sizes[i] if sizes is not None and sizes[i] >= 0 \
                else stream.getsize(f)
            out.append((f, sz))
    return out


def iter_rec_blocks(files: List[str], part_idx: int, num_parts: int,
                    sizes=None) -> Iterator[RowBlock]:
    """Yield this part's members, sharded by cumulative compressed size."""
    pairs = rec_members(files, sizes)
    members = [m for m, _ in pairs]
    sizes = [sz for _, sz in pairs]
    total = sum(sizes)
    begin = total * part_idx // num_parts
    end = total * (part_idx + 1) // num_parts
    base = 0
    for m, sz in zip(members, sizes):
        # a member belongs to the part containing its start byte
        if begin <= base < end:
            yield read_rec_block(m)
        base += sz


class RecWriter:
    """Write a stream of RowBlocks into a .rec directory of member shards
    (rec2 framing by default; ``member_suffix='.npz'`` keeps v1)."""

    def __init__(self, out_dir: str, compress: bool = True,
                 member_suffix: str = REC2_SUFFIX):
        self.out_dir = out_dir
        self.compress = compress
        self.member_suffix = member_suffix
        self._n = 0
        stream.makedirs(out_dir)

    def write(self, blk: RowBlock) -> None:
        path = stream.join(self.out_dir,
                           f"part-{self._n:05d}{self.member_suffix}")
        write_rec_block(path, blk, self.compress)
        self._n += 1

    @property
    def num_blocks(self) -> int:
        return self._n
