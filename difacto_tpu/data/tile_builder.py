"""Shared tile ingestion for the batch learners (L-BFGS, BCD).

The reference's TileBuilder (src/data/tile_builder.h:17-183) ingests raw
row blocks — localize each, store the tile, accumulate the global feature
dictionary via KVUnion — and later BuildColmap matches every tile's local
ids against the (tail-filtered) global dictionary. Both batch learners
here repeated that recipe inline; this is the one shared component:

- :meth:`add` — compact a raw block (Localizer::Compact) and fold its
  (id, count) pairs into the global dictionary (kv_union);
- :meth:`filter_tail` — drop features with count <= threshold
  (RemoveTailFeatures, src/lbfgs/lbfgs_utils.h:104-120 /
  BuildFeatureMap, src/bcd/bcd_learner.cc:141-155);
- :meth:`colmap` — a tile's uniq ids -> positions in the filtered
  dictionary, -1 where filtered (BuildColmap, tile_builder.h:115-183).

Learner-specific layout math (L-BFGS's flat [w, V...] positions, BCD's
per-block column slices) stays with the learner — the reference's
TileBuilder likewise stopped at colmaps.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from ..base import FEAID_DTYPE
from . import compact
from ..ops.kv import find_position, kv_union


class TileBuilder:
    def __init__(self) -> None:
        self.ids = np.empty(0, dtype=FEAID_DTYPE)
        self.cnts = np.empty(0, dtype=np.float32)
        # (compact block, sorted uniq ids, is_train) per ingested tile
        self.tiles: List[Tuple] = []
        self.nrows_train = 0
        self.nrows_val = 0
        self.nnz_train = 0

    def add(self, blk, is_train: bool = True):
        """Ingest one raw row block; returns the compact block."""
        cblk, uniq, cnt = compact(blk, need_counts=is_train)
        self.tiles.append((cblk, uniq, is_train))
        if is_train:
            self.ids, self.cnts = kv_union(self.ids, self.cnts, uniq,
                                           cnt.astype(np.float32))
            self.nrows_train += blk.size
            self.nnz_train += blk.nnz
        else:
            self.nrows_val += blk.size
        return cblk

    def filter_tail(self, threshold: float) -> np.ndarray:
        """Keep features with count > threshold; returns the filtered ids
        (also retained as ``self.ids``/``self.cnts``)."""
        if threshold > 0:
            keep = self.cnts > threshold
            self.ids = self.ids[keep]
            self.cnts = self.cnts[keep]
        return self.ids

    def colmap(self, t: int) -> np.ndarray:
        """Tile t's uniq ids -> positions into the filtered dictionary
        (-1 = filtered away)."""
        return find_position(self.ids, self.tiles[t][1])
