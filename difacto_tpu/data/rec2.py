"""rec2: raw page-aligned CSR block framing — the zero-copy rec format.

The v1 rec cache stored each CSR block as an ``.npz`` member: correct,
but every read pays the zip central-directory walk plus a full memcpy of
each array out of the archive, and the bytes can never be mapped. rec2
replaces that with the layout the reference's recordio/CRB fast path
implies (src/reader/crb_parser.h:16-47, src/data/compressed_row_block.h)
minus the LZ4 (uncompressed members already won the zlib-vs-raw trade,
docs/perf_notes.md "The streamed regime"): a fixed little-endian header,
a section table, and page-aligned raw array sections, so a reader
``mmap``s the file and wraps each section with ``np.frombuffer`` —
**zero copies until the bytes are actually consumed**, and the OS page
cache (not Python) is the read path. A producer worker can memcpy a
mapped section straight into a shm-ring slot, or skip the copy entirely
for same-host consumers.

Layout (all little-endian)::

    [0]   magic  b"DFREC2\\0\\0"                      8 bytes
    [8]   u32 version (=1) | u32 n_sections
    [16]  u32 header_crc32 (over the section table) | u32 pad
    [24]  n_sections x section entry (32 bytes each):
              name   8 bytes (ascii, NUL padded)
              dtype  8 bytes (numpy dtype str, e.g. b"<i8")
              u64    byte offset (page-aligned, from file start)
              u64    nbytes
    [..]  u32 crc32 per section (n_sections x 4, the data checksums)
    [..]  sections, each aligned to PAGE (4096)

Integrity: the header CRC covers the section table, and every section
carries its own CRC32 (zlib.crc32 — C speed, one pass). ``read_rec2``
validates structure on every open and (by default) the section CRCs,
raising a typed :class:`RecCorrupt` on truncation, bit flips, or a bad
magic — never a crash or a silent short read, mirroring the checkpoint
``CheckpointCorrupt`` contract (store/local.py). A torn write cannot be
observed at the final name: writes go through tmp + atomic rename.

Chaos: every read traverses the ``rec.read`` fault-injection point
(utils/faultinject.py): ``err`` raises RecCorrupt (what a failed disk
read becomes), ``truncate`` reads a half-length view (which the CRC then
rejects — the torn-file drill).
"""

from __future__ import annotations

import io
import mmap
import os
import struct
import zlib
from typing import Dict, Optional

import numpy as np

from ..utils import stream

MAGIC = b"DFREC2\0\0"
VERSION = 1
PAGE = 4096
SUFFIX = ".rec2"

_HEAD = struct.Struct("<8sIIII")       # magic, version, n_sections, crc, pad
_SECT = struct.Struct("<8s8sQQ")       # name, dtype, offset, nbytes

# the only arrays a rec2 member may carry (rec.py's block schema); a name
# outside this set fails loudly instead of silently round-tripping junk
SECTION_NAMES = ("offset", "label", "index", "value", "weight", "uniq")


class RecCorrupt(ValueError):
    """A rec2 member failed structural or checksum validation (torn
    write, truncation, bit flip). Typed so callers can walk to the next
    member or re-convert instead of crashing — the data-cache analog of
    store.local.CheckpointCorrupt."""


def _align(n: int) -> int:
    return (n + PAGE - 1) // PAGE * PAGE


def write_rec2(uri: str, arrays: Dict[str, np.ndarray]) -> None:
    """Atomically write ``arrays`` as one rec2 member (tmp + rename
    locally; tmp key + server-side move for remote URIs)."""
    names = list(arrays)
    for n in names:
        if n not in SECTION_NAMES:
            raise ValueError(f"unknown rec2 section {n!r} "
                             f"(one of {SECTION_NAMES})")
    header_len = _HEAD.size + len(names) * _SECT.size + len(names) * 4
    off = _align(header_len)
    entries = []
    crcs = []
    mats = []
    for n in names:
        a = np.ascontiguousarray(arrays[n])
        mats.append(a)
        entries.append((n.encode().ljust(8, b"\0"),
                        a.dtype.str.encode().ljust(8, b"\0"),
                        off, a.nbytes))
        crcs.append(zlib.crc32(a.data))
        off = _align(off + a.nbytes)
    table = b"".join(_SECT.pack(*e) for e in entries) \
        + b"".join(struct.pack("<I", c) for c in crcs)
    head = _HEAD.pack(MAGIC, VERSION, len(names), zlib.crc32(table), 0)

    def emit(f) -> None:
        f.write(head)
        f.write(table)
        pos = len(head) + len(table)
        for (_, _, o, _), a in zip(entries, mats):
            f.write(b"\0" * (o - pos))
            f.write(a.data)
            pos = o + a.nbytes

    if stream.is_remote(uri):
        buf = io.BytesIO()
        emit(buf)
        tmp = uri + ".tmp"
        with stream.open_stream(tmp, "wb") as f:
            f.write(buf.getvalue())
        fs, path = stream._fs(uri)
        _, tmp_path = stream._fs(tmp)
        try:
            fs.mv(tmp_path, path)
        except (AttributeError, NotImplementedError):  # pragma: no cover
            fs.copy(tmp_path, path)
            fs.rm(tmp_path)
        return
    path = stream._strip_file_scheme(uri)
    stream._ensure_parent(path)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        emit(f)
    os.replace(tmp, path)


def _corrupt(uri: str, why: str) -> RecCorrupt:
    return RecCorrupt(f"corrupt rec2 member {uri!r}: {why}")


def read_rec2(uri: str, verify: bool = True,
              use_mmap: bool = True) -> Dict[str, np.ndarray]:
    """Read one rec2 member -> {name: array}. Local reads mmap the file
    and return zero-copy ``np.frombuffer`` views over the mapping (the
    mapping's lifetime rides the arrays' ``base``); remote URIs read the
    bytes once and view those. Structural validation always runs;
    ``verify`` additionally checks every section CRC (one zlib.crc32
    pass per section — C speed, and the pass doubles as page-cache
    warming for the consumer that reads the bytes next)."""
    from ..utils import faultinject
    kind = faultinject.fire("rec.read")
    if kind == "err":  # pragma: no cover - fire() raises for err itself
        raise _corrupt(uri, "injected read error")
    if stream.is_remote(uri) or not use_mmap:
        with stream.open_stream(uri, "rb") as f:
            buf: memoryview = memoryview(f.read())
    else:
        path = stream._strip_file_scheme(uri)
        try:
            with open(path, "rb") as f:
                try:
                    mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
                except ValueError as e:  # zero-length file
                    raise _corrupt(uri, f"unmappable ({e})") from e
        except OSError as e:
            if isinstance(e, FileNotFoundError):
                raise
            raise _corrupt(uri, f"unreadable ({e})") from e
        buf = memoryview(mm)
    if kind == "truncate":
        buf = buf[:max(len(buf) // 2, 1)]
    elif kind is not None:
        faultinject.act_default(kind)
    try:
        return _parse(uri, buf, verify)
    except struct.error as e:
        raise _corrupt(uri, f"short header ({e})") from e


def _parse(uri: str, buf: memoryview, verify: bool) -> Dict[str, np.ndarray]:
    if len(buf) < _HEAD.size:
        raise _corrupt(uri, f"file too short ({len(buf)} bytes)")
    magic, version, n_sections, head_crc, _ = _HEAD.unpack_from(buf, 0)
    if magic != MAGIC:
        raise _corrupt(uri, f"bad magic {magic!r}")
    if version != VERSION:
        raise _corrupt(uri, f"unsupported version {version}")
    if not 0 < n_sections <= len(SECTION_NAMES):
        raise _corrupt(uri, f"implausible section count {n_sections}")
    table_len = n_sections * _SECT.size + n_sections * 4
    if len(buf) < _HEAD.size + table_len:
        raise _corrupt(uri, "truncated section table")
    table = bytes(buf[_HEAD.size:_HEAD.size + table_len])
    if zlib.crc32(table) != head_crc:
        raise _corrupt(uri, "section table checksum mismatch")
    crc_base = _HEAD.size + n_sections * _SECT.size
    out: Dict[str, np.ndarray] = {}
    for i in range(n_sections):
        name_b, dtype_b, off, nbytes = _SECT.unpack_from(
            buf, _HEAD.size + i * _SECT.size)
        name = name_b.rstrip(b"\0").decode("ascii", "replace")
        if name not in SECTION_NAMES:
            raise _corrupt(uri, f"unknown section {name!r}")
        try:
            dt = np.dtype(dtype_b.rstrip(b"\0").decode("ascii", "replace"))
        except TypeError as e:
            raise _corrupt(uri, f"bad dtype for {name!r} ({e})") from e
        if off % PAGE or off + nbytes > len(buf):
            raise _corrupt(
                uri, f"section {name!r} [{off}, {off + nbytes}) outside "
                f"file of {len(buf)} bytes")
        if dt.itemsize == 0 or nbytes % dt.itemsize:
            raise _corrupt(uri, f"section {name!r} nbytes {nbytes} not a "
                           f"multiple of dtype {dt.str}")
        view = buf[off:off + nbytes]
        if verify:
            want, = struct.unpack_from("<I", buf, crc_base + 4 * i)
            if zlib.crc32(view) != want:
                raise _corrupt(uri, f"section {name!r} checksum mismatch")
        out[name] = np.frombuffer(view, dtype=dt)
    return out


def is_rec2(uri: str) -> bool:
    return uri.endswith(SUFFIX)


def probe_rec2(uri: str) -> Optional[Dict[str, np.ndarray]]:
    """read_rec2 that returns None instead of raising on corruption —
    for callers that walk to the next member."""
    try:
        return read_rec2(uri)
    except RecCorrupt:
        return None
