"""Producer-side batch packing, shared by threads AND worker processes.

The hashed-store host pipeline (read -> parse -> localize -> slot-map ->
panel/COO pack) is stateless, so it can run anywhere: on the learner's
producer THREADS (data/producer_pool.OrderedProducerPool) or in spawned
worker PROCESSES (ProcessProducerPool) that ship packed payloads through
the shared-memory ring (data/shm_ring.py). This module is the single
definition of that pipeline — extracted from learners/sgd.py so the two
transports can never diverge on the payload contract (tuple order, shape-
cap keys, counts-section semantics).

Process workers rebuild the pipeline from a picklable :class:`StreamSpec`
(``functools.partial(spec_iter, spec)`` is the pool's ``make_iter``); the
spec carries a snapshot of the consumer's sticky shape caps so workers
start from the same shape schedule and steady-state epochs keep replaying
one compiled step per layout.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from dataclasses import dataclass, field
from typing import Iterator, Optional, Sequence

import numpy as np
from ..utils.locktrace import mutex


class ShapeSchedule:
    """Per-run sticky shape caps: every batch pads to the largest bucket
    seen so far for its (job, dim) key, so steady-state epochs replay ONE
    compiled step instead of re-bucketing per batch (per-batch ``bucket()``
    put every odd-sized tail in a fresh jit cache entry — ~10 s/compile on
    a tunneled chip dominated the whole epoch, round-3 verdict #1). A
    growing batch costs at most log-many recompiles over the run; caps
    never shrink. Thread-safe: producer threads prepare batches
    concurrently. ``snapshot``/``absorb`` ship the caps across the process
    boundary: spawned producer workers seed from the consumer's snapshot,
    and the consumer absorbs the caps each delivered payload was packed at,
    so a cap grown in one worker reaches every later epoch's workers."""

    def __init__(self) -> None:
        self._caps: dict = {}
        self._lock = mutex()

    def cap(self, key: str, n: int, minimum: int = 8,
            exact: bool = False) -> int:
        """``exact`` keeps a plain sticky max instead of bucketing — for
        dims that are naturally constant (panel width: criteo rows are
        always 39 wide; bucketing to 48 would inflate every panel cell
        stream by ~23% and defeat the uniform-reshape fast path)."""
        from ..ops.batch import bucket
        with self._lock:
            c = self._caps.get(key, 0)
            if n > c or c == 0:
                # floor degenerate dims like the bucket() it replaces
                # (bucket(0) == minimum) — empty batches still need
                # non-zero-sized device shapes
                c = max(n, 1) if exact else bucket(n, minimum)
                self._caps[key] = c
            return c

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._caps)

    def absorb(self, caps: dict) -> None:
        """Merge already-resolved cap VALUES (no re-bucketing: the values
        are caps, not raw dims)."""
        with self._lock:
            for k, v in caps.items():
                if v > self._caps.get(k, 0):
                    self._caps[k] = v


@dataclass
class BlkInfo:
    """The slice of a RowBlock the consumer's dispatch still needs after
    the payload is packed (duck-typed for learners' ``blk`` argument):
    shipping the whole block across the process boundary would re-send
    the raw CSR arrays the packed payload already encodes."""
    size: int
    label: Optional[np.ndarray] = None


# ------------------------------------------------------------------ pack
def pack_payload(shapes: ShapeSchedule, cblk, n_lanes: int,
                 padded: np.ndarray, b_cap: int, dim_min: int, job: str,
                 counts=None, stream_chunk: bool = False):
    """Shared pack tail of all batch-preparation paths (prepare_hashed /
    prepare_from_uniq / the learner's consumer-side _pack_mapped): panel
    layout when rows are near-uniform, COO otherwise, shape caps from the
    sticky schedule. One definition, so the payload contract (tuple
    order, cap keys) can never diverge between the producer-side and
    consumer-side packers. ``padded`` is the OOB-padded slot vector (its
    length IS u_cap); ``cblk.index`` must already address its
    sorted-unique lanes (host dedup)."""
    from ..ops.batch import pack_batch, pack_panel, panel_width
    u_cap = len(padded)
    width = panel_width(cblk, b_cap)
    if width is not None:
        width = shapes.cap(job + ".w", width, exact=True)
        i32, f32, binary = pack_panel(
            cblk, n_lanes, padded, b_cap, width, u_cap,
            counts=counts)
        if stream_chunk:
            return ("panel_chunked", i32, f32,
                    chunk_host(i32, f32, b_cap, width, u_cap, binary),
                    binary, b_cap, width, u_cap)
        return ("panel", i32, f32, binary, b_cap, width, u_cap)
    nnz_cap = shapes.cap(job + ".nnz", cblk.nnz, dim_min)
    i32, f32, binary = pack_batch(
        cblk, n_lanes, padded, b_cap, nnz_cap, u_cap,
        counts=counts)
    return ("coo", i32, f32, binary, b_cap, nnz_cap, u_cap)


def chunk_host(i32: np.ndarray, f32: np.ndarray, b_cap: int,
               width: int, u_cap: int, binary: bool):
    """Producer-side chunked-run layout for a packed panel (the host twin
    of the learner's staging-time device chunker): streamed runs then
    dispatch the fast chunked step instead of the unsorted scatter.
    Ragged panels always carry explicit values (zero on pad cells,
    ops/batch._panel_arrays), so pad tokens contribute nothing through
    chunk_vals; uniform binary panels have no pad cells."""
    from ..ops.batch import panel_chunk_tokens_np
    cells = b_cap * width
    fv = None if binary else f32[:cells]
    return panel_chunk_tokens_np(i32[:cells], fv, u_cap, b_cap, width)


def _count_distinct(tok: np.ndarray, hash_capacity: int) -> int:
    """Exact distinct-token count WITHOUT the sort ``np.unique`` pays:
    an O(nnz + capacity) flag pass when the capacity-sized bool array
    is cheap, the sort fallback above that (still skips the inverse
    map + O(nnz) remap, the other half of the host dedup cost). Sizes
    the device-dedup path's sticky u-cap (prepare_hashed)."""
    if hash_capacity <= (1 << 24):
        seen = np.zeros(hash_capacity, dtype=bool)
        seen[tok] = True
        return int(seen.sum())
    return len(np.unique(tok))


def prepare_hashed(shapes: ShapeSchedule, hash_capacity: int, blk,
                   want_counts: bool, fill_counts: bool, dim_min: int,
                   job: str, b_cap: Optional[int] = None,
                   stream_chunk: bool = False,
                   device_dedup: bool = False,
                   admit=None):
    """Producer batch preparation for the hashed store: ONE int32
    np.unique collapses localization (Localizer::Compact), key->slot
    mapping, and collision dedup, then the batch packs into the
    two-buffer transfer — panel layout when rows are near-uniform
    (criteo), COO otherwise. Stateless, so safe off-thread AND
    off-process. ``b_cap`` pins the row cap; the remaining dims ride the
    sticky shape schedule keyed by ``job`` so epochs never recompile.
    ``want_counts`` keeps the packed counts section (and thus the step's
    jit signature) present for the WHOLE run; ``fill_counts`` (epoch 0
    only) computes real occurrence counts — later epochs ship an all-zero
    section, making apply_count a no-op instead of a recompile.

    ``device_dedup`` (ISSUE 13): ship RAW hashed token lanes and let the
    jit step run the sort + run-length dedup on device
    (ops/fused.dedup_tokens) — the host pays only the hash and an
    O(nnz + capacity) distinct-count flag pass (_count_distinct), not
    the O(nnz log nnz) sort + inverse + remap. Engages only on
    panel-shaped TRAINING batches past the count push (fill_counts
    forces the host path: counts need the host inverse) — COO-shaped
    batches fall back to host dedup. The u-cap is sized with a +1
    margin because pad cells introduce the TRASH lane on device.

    ``admit`` (capacity/sketch.AdmissionFilter, ISSUE 19): count-min
    admission over the hashed token stream — unadmitted occurrences
    remap to the OOB sentinel (== hash_capacity) and, being the largest
    "slot", sort LAST among the real slots; the sentinel lane is dropped
    below so the unique declaration stays truthful (cells referencing it
    fall onto the first OOB pad lane: gathers zeros, scatter dropped).
    Admission forces the host-dedup path — the sentinel cannot ride raw
    device lanes (the on-device sorter would give it a real lane)."""
    from ..base import reverse_bytes
    from ..store.local import hash_slots, pad_slots_oob

    tok = hash_slots(reverse_bytes(blk.index), hash_capacity)
    if admit is not None:
        tok = admit.filter(tok)
    if admit is None and device_dedup and not fill_counts:
        from ..ops.batch import pack_panel_raw, panel_width
        b_cap_raw = b_cap or shapes.cap(job + ".b", blk.size, dim_min)
        cblk = dataclasses.replace(blk, index=tok.astype(np.uint32))
        width = panel_width(cblk, b_cap_raw)
        if width is not None:
            n_uniq = _count_distinct(tok, hash_capacity)
            u_cap = shapes.cap(job + ".u", n_uniq + 1)
            width = shapes.cap(job + ".w", width, exact=True)
            i32, f32, binary = pack_panel_raw(cblk, n_uniq, b_cap_raw,
                                              width)
            return ("panel_raw", i32, f32, binary, b_cap_raw, width,
                    u_cap)
    if fill_counts:
        slots, inverse, counts = np.unique(
            tok, return_inverse=True, return_counts=True)
        counts = counts.astype(np.float32)
    else:
        slots, inverse = np.unique(tok, return_inverse=True)
        counts = np.zeros(0, np.float32) if want_counts else None
    if admit is not None and len(slots) and slots[-1] == admit.sentinel:
        # drop the sentinel lane: cells that referenced it now index the
        # first OOB pad position instead (pad value = hash_capacity +
        # position, pad_slots_oob) — still a zero-gather, dropped-scatter
        # lane, and the slots section stays unique
        slots = slots[:-1]
        if fill_counts:
            counts = counts[:-1]
    cblk = dataclasses.replace(blk, index=inverse.astype(np.uint32))
    n_uniq = len(slots)
    # +1 under admission: cells whose token was unadmitted reference
    # position n_uniq, which must exist as an OOB pad lane even when the
    # sticky cap is otherwise exactly full
    u_cap = shapes.cap(job + ".u", n_uniq + (1 if admit is not None else 0))
    b_cap = b_cap or shapes.cap(job + ".b", blk.size, dim_min)
    padded = pad_slots_oob(slots.astype(np.int32), u_cap, hash_capacity)
    return pack_payload(shapes, cblk, n_uniq, padded, b_cap, dim_min,
                        job, counts=counts, stream_chunk=stream_chunk)


def prepare_from_uniq(shapes: ShapeSchedule, hash_capacity: int, cblk,
                      uniq, counts, want_counts: bool, fill_counts: bool,
                      dim_min: int, job: str, b_cap: Optional[int] = None,
                      stream_chunk: bool = False):
    """Cached fast path (data/cached.py): the block arrives already
    localized to ``uniq`` (sorted reversed ids). The slot map + dedup is
    O(uniq); the O(nnz) index gather through the uniq->slot permutation
    runs HERE, once, on the producer. Shape caps come from the sticky
    schedule; the counts section stays present all run (see
    prepare_hashed)."""
    from ..store.local import hash_slots, pad_slots_oob

    raw = hash_slots(uniq, hash_capacity)
    slots, remap = np.unique(raw, return_inverse=True)
    cblk = dataclasses.replace(
        cblk, index=remap[cblk.index].astype(np.uint32))
    n_lanes = len(slots)
    u_cap = shapes.cap(job + ".u", n_lanes)
    b_cap = b_cap or shapes.cap(job + ".b", cblk.size, dim_min)
    scounts = np.zeros(0, np.float32) if want_counts else None
    if fill_counts and counts is not None:
        # counts are per uniq lane; aggregate to slot space (colliding
        # lanes sum, mirroring map_keys_dedup)
        scounts = np.zeros(u_cap, dtype=np.float32)
        scounts[:n_lanes] = np.bincount(
            remap, weights=counts, minlength=n_lanes)
    padded = pad_slots_oob(slots.astype(np.int32), u_cap, hash_capacity)
    return pack_payload(shapes, cblk, n_lanes, padded, b_cap, dim_min,
                        job, counts=scounts, stream_chunk=stream_chunk)


# ------------------------------------------------------------------ spec
@dataclass
class StreamSpec:
    """Everything a spawned producer worker needs to rebuild
    ``make_iter(part)`` for the hashed streamed-training path — plain
    picklable values only (no learner, no store, no device state)."""
    parts: Sequence[int]        # logical pool index -> actual part id
    n_jobs: int
    host_rank: int
    num_hosts: int
    data_in: str
    data_format: str
    cached_uri: Optional[str]
    batch_size: int
    shuffle: int
    neg_sampling: float
    epoch: int
    hash_capacity: int
    want_counts: bool
    fill_counts: bool
    dim_min: int
    job: str
    b_cap: Optional[int]
    stream_chunk: bool
    need_label: bool
    # ship raw hashed token lanes; the jit step dedups on device
    # (prepare_hashed device_dedup — ISSUE 13)
    device_dedup: bool = False
    # count-min admission threshold + sketch seed base (ISSUE 19,
    # capacity/sketch.make_admission): workers rebuild the SAME
    # per-(seed, epoch, part) filter the thread-mode producer builds, so
    # both transports admit identical token sets
    admit_min_count: int = 0
    admit_seed: int = 0
    caps: dict = field(default_factory=dict)
    # the consumer's trace id (obs/trace.py): spawned workers adopt it so
    # their parse/pack spans join the parent's timeline in one trace file
    trace_id: int = 0


def timed_reader(it: Iterator, parse_c, part: int) -> Iterator:
    """Yield from ``it`` accounting each blocking ``next`` to the PARSE
    stage (a counter of seconds + one trace span per batch) — the read +
    parse half of the pipeline, as opposed to the pack half timed at the
    prepare call. One definition for threads and worker processes, so
    bench's stage table means the same thing in both transports."""
    from ..obs import trace
    it = iter(it)
    while True:
        t0 = time.perf_counter()
        with trace.span("producer.parse", part=part):
            item = next(it, None)
        parse_c.inc(time.perf_counter() - t0)
        if item is None:
            return
        yield item


def spec_iter(spec: StreamSpec, part_i: int) -> Iterator:
    """The process-mode ``make_iter``: yields the same ("ready", blk_info,
    payload) items the learner's thread-mode make_iter produces for the
    hashed fast path, deterministically (seeded per (epoch, part) — the
    retry/re-issue contract). Heavy imports happen here, in the worker,
    after its env overrides are applied.

    Instrumented against the worker's process-global obs registry
    (stage_seconds_total{stage=parse|pack}, producer rows/batches); the
    pool ships its snapshot back to the consumer (obs/proc.py), which is
    how the stage decomposition survives the process boundary."""
    from ..obs import REGISTRY, trace
    if spec.trace_id:
        trace.set_trace_id(spec.trace_id)
    stage = REGISTRY.counter(
        "stage_seconds_total",
        "seconds spent per streamed-pipeline stage, summed over threads")
    parse_c, pack_c = stage.labels(stage="parse"), stage.labels(stage="pack")
    rows_c = REGISTRY.counter("producer_rows_total",
                              "rows produced by the streamed pipeline")
    batches_c = REGISTRY.counter("producer_batches_total",
                                 "batches produced by the streamed pipeline")
    shapes = ShapeSchedule()
    shapes.absorb(spec.caps)
    part = spec.parts[part_i]
    g_idx = spec.host_rank * spec.n_jobs + part
    g_num = spec.n_jobs * spec.num_hosts

    def info(blk) -> BlkInfo:
        return BlkInfo(size=blk.size,
                       label=blk.label if spec.need_label else None)

    def packed(fn, *args, **kw):
        t0 = time.perf_counter()
        with trace.span("producer.pack", part=part):
            out = fn(*args, **kw)
        pack_c.inc(time.perf_counter() - t0)
        return out

    if spec.cached_uri is not None:
        from .cached import CachedBatchReader
        rdr = CachedBatchReader(
            spec.cached_uri, g_idx, g_num, spec.batch_size,
            shuffle=spec.shuffle > 0,
            neg_sampling=spec.neg_sampling,
            seed=spec.epoch * max(g_num, 1) + g_idx,
            need_counts=spec.fill_counts)
        for sub, uniq, cnts in timed_reader(rdr, parse_c, part):
            rows_c.inc(sub.size)
            batches_c.inc()
            yield ("ready", info(sub), packed(
                prepare_from_uniq, shapes, spec.hash_capacity, sub, uniq,
                cnts, spec.want_counts, spec.fill_counts, spec.dim_min,
                spec.job, spec.b_cap, stream_chunk=spec.stream_chunk))
        return
    from .batch_reader import BatchReader
    from ..capacity.sketch import make_admission
    admit = make_admission(spec.hash_capacity, spec.admit_min_count,
                           spec.admit_seed, spec.epoch, g_idx)
    reader = BatchReader(spec.data_in, spec.data_format, g_idx, g_num,
                         spec.batch_size, spec.batch_size * spec.shuffle,
                         spec.neg_sampling,
                         seed=spec.epoch * max(g_num, 1) + g_idx)
    for blk in timed_reader(reader, parse_c, part):
        rows_c.inc(blk.size)
        batches_c.inc()
        yield ("ready", info(blk), packed(
            prepare_hashed, shapes, spec.hash_capacity, blk,
            spec.want_counts, spec.fill_counts, spec.dim_min, spec.job,
            spec.b_cap, stream_chunk=spec.stream_chunk,
            device_dedup=spec.device_dedup, admit=admit))
