"""Fixed-capacity ring of shared-memory slots for cross-process batches.

The transport half of the process-based producer pipeline
(data/producer_pool.py ProcessProducerPool): N worker processes run the
host pipeline (read -> parse -> localize -> slot-map -> panel pack) and
hand finished packed batches to the consumer with ZERO consumer-side
copies — a worker writes each payload's numpy arrays directly into a ring
slot of one preallocated ``multiprocessing.shared_memory`` segment, and
the consumer wraps the slot with ``np.frombuffer`` views. Python threads
cannot give this overlap (the round-5 decomposition showed the producer
thread and the dispatch loop serializing on the GIL,
docs/perf_notes.md "The streamed regime"); processes + shared memory can.

Slot layout (one slot = ``slot_bytes`` of the segment)::

    [array 0 bytes | pad to 64 | array 1 bytes | ...]   from offset 0
    [pickled meta][ meta_len u32 | part u32 | seq u32 |
                    gen u32 | span u32 | payload u64 ]  tail header

The tail header carries the item identity (part id, seq no of the FIRST
item, attempt generation), an item COUNT (a producer may coalesce
several small consecutive items of one part into a single slot — the
multi-part-per-slot packing that amortizes slot leases and ring_wait
when payloads run far below slot_bytes; the items then occupy seq ..
seq+count-1), the PRODUCER'S trace span id (``span`` — the obs/trace.py
span that packed this item, so the consumer's unpack/step spans can
point at the exact producer span that built their batch across the
process boundary) and the pickled meta — the item's structure with every array
replaced by a (shape, dtype, offset) placeholder — so a slot is fully
self-describing: the consumer rebuilds the exact item object (for
count > 1: the list of items) from the slot alone.

Lease/release + backpressure: free slot ids travel through per-owner
multiprocessing queues (one queue per worker, slots pre-partitioned), so
a worker blocks when all of ITS slots are leased — bounded memory, and no
cross-part starvation: the worker producing the part the consumer is
draining always has its own slots coming back.

Robust cleanup: the owning (consumer) process registers an ``atexit``
unlink for every live ring, ``unlink`` is idempotent, and attaching
workers unregister the segment from the resource tracker (they never own
it) — no leaked ``/dev/shm`` segments on clean teardown, consumer
early-exit, or a worker raising/dying (tests/test_producer_process.py).
"""

from __future__ import annotations

import atexit
import itertools
import os
import pickle
import struct
import threading
from dataclasses import fields, is_dataclass
from multiprocessing import shared_memory
from typing import Any, List, Optional, Tuple

import numpy as np
from ..utils.locktrace import mutex

# meta_len, part, seq, gen, item count, producer span id, payload_bytes
_HEADER = struct.Struct("<IIIIIIQ")
_ALIGN = 64

# live rings created by THIS process, for the atexit safety net
_live_rings: dict = {}
# segments whose close() found live views: pinned so __del__ never runs
# mid-process (the views' owner may be an in-flight device transfer)
_pinned_maps: list = []
_ring_seq = itertools.count()


class SlotOverflow(Exception):
    """The encoded item does not fit in one slot (caller falls back to a
    plain pickled transport for this item)."""


def _cleanup_live_rings() -> None:  # pragma: no cover - process teardown
    for ring in list(_live_rings.values()):
        ring.unlink()


atexit.register(_cleanup_live_rings)


# ------------------------------------------------------------ encoding
# Item -> (spec tree, [ndarray leaves]). The spec tree mirrors the item's
# structure with arrays replaced by placeholders; everything non-array,
# non-container rides the pickled meta as-is. Dataclasses (RowBlock, the
# learner's _BlkInfo) reconstruct via their field dict.

def encode_item(obj: Any, arrays: List[np.ndarray]):
    if isinstance(obj, np.ndarray):
        a = np.ascontiguousarray(obj)
        arrays.append(a)
        return ("nd", len(arrays) - 1, a.shape, a.dtype.str)
    if isinstance(obj, tuple):
        kids = [encode_item(v, arrays) for v in obj]
        if hasattr(obj, "_fields"):  # NamedTuple
            return ("ntu", type(obj), kids)
        return ("tu", kids)
    if isinstance(obj, list):
        return ("li", [encode_item(v, arrays) for v in obj])
    if isinstance(obj, dict):
        return ("di", [(k, encode_item(v, arrays)) for k, v in obj.items()])
    if is_dataclass(obj) and not isinstance(obj, type):
        return ("dc", type(obj),
                [(f.name, encode_item(getattr(obj, f.name), arrays))
                 for f in fields(obj)])
    return ("py", obj)


def decode_item(spec, arrays: List[np.ndarray]):
    tag = spec[0]
    if tag == "nd":
        return arrays[spec[1]]
    if tag == "tu":
        return tuple(decode_item(s, arrays) for s in spec[1])
    if tag == "ntu":
        return spec[1](*(decode_item(s, arrays) for s in spec[2]))
    if tag == "li":
        return [decode_item(s, arrays) for s in spec[1]]
    if tag == "di":
        return {k: decode_item(s, arrays) for k, s in spec[1]}
    if tag == "dc":
        return spec[1](**{k: decode_item(s, arrays) for k, s in spec[2]})
    return spec[1]


def materialize_item(item: Any) -> Any:
    """Deep-copy an item's arrays out of shared memory (same structure,
    private buffers). The consumer uses this to EVICT buffered items from
    their ring slots when a re-queued part needs slots back but every
    live worker is backpressure-blocked on a future part — the copy costs
    one memcpy, the alternative is a stall."""
    arrays: List[np.ndarray] = []
    spec = encode_item(item, arrays)
    return decode_item(spec, [np.array(a) for a in arrays])


def _align(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


class SlotLease:
    """Consumer-side handle on a leased slot: the reconstructed item's
    arrays VIEW the slot's shared memory, so the slot must not return to
    the ring until the consumer is done with them (for the learner: until
    the device transfer/step consuming the views has completed).
    ``release`` is idempotent.

    A multi-item slot (header count > 1) is shared by every item it
    carries: ``split(k)`` hands out k child handles, each independently
    idempotent, and the slot returns to the ring when the LAST child
    releases."""

    __slots__ = ("_ring", "slot", "_refs", "_mu", "_released")

    def __init__(self, ring: "ShmRing", slot: int):
        self._ring = ring
        self.slot = slot
        self._refs = 1
        self._released = False
        self._mu = mutex()

    def split(self, k: int):
        """k per-item child handles sharing this slot (k >= 1). The
        parent's own reference transfers to the children — callers
        release only the children afterwards."""
        with self._mu:
            self._refs += k - 1
        self._released = True  # the children own the slot now
        return [_LeaseShare(self) for _ in range(k)]

    def _dec(self) -> None:
        with self._mu:
            self._refs -= 1
            last = self._refs == 0
        if last:
            self._ring.release(self.slot)

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._dec()


class _LeaseShare:
    """One item's handle on a shared multi-item slot (idempotent)."""

    __slots__ = ("_parent", "_released")

    def __init__(self, parent: SlotLease):
        self._parent = parent
        self._released = False

    @property
    def slot(self) -> int:
        return self._parent.slot

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._parent._dec()


class ShmRing:
    """One shared-memory segment carved into ``n_slots`` slots of
    ``slot_bytes``, with free-slot queues partitioned over ``n_queues``
    owners (contiguous blocks: slot s belongs to queue s // (n_slots //
    n_queues))."""

    def __init__(self, n_slots: int, slot_bytes: int, n_queues: int = 1,
                 ctx=None):
        if n_slots % max(n_queues, 1):
            raise ValueError(f"n_slots={n_slots} must divide evenly over "
                             f"n_queues={n_queues}")
        import multiprocessing as mp
        ctx = ctx or mp.get_context("spawn")
        self.n_slots = n_slots
        self.slot_bytes = slot_bytes
        self.n_queues = max(n_queues, 1)
        self._per_q = n_slots // self.n_queues
        self.name = f"difacto_ring_{os.getpid()}_{next(_ring_seq)}"
        self._shm = shared_memory.SharedMemory(
            name=self.name, create=True, size=n_slots * slot_bytes)
        self._owner = True
        self._unlinked = False
        self._mu = mutex()
        self.free_qs = [ctx.Queue() for _ in range(self.n_queues)]
        for s in range(n_slots):
            self.free_qs[s // self._per_q].put(s)
        _live_rings[self.name] = self

    # ---------------------------------------------------------- attach
    def descriptor(self) -> Tuple[str, int, int, int]:
        """Picklable handle for workers (queues travel separately through
        the Process args — they are not picklable by value)."""
        return (self.name, self.n_slots, self.slot_bytes, self.n_queues)

    @classmethod
    def attach(cls, desc: Tuple[str, int, int, int]) -> "ShmRing":
        name, n_slots, slot_bytes, n_queues = desc
        ring = cls.__new__(cls)
        ring.n_slots = n_slots
        ring.slot_bytes = slot_bytes
        ring.n_queues = n_queues
        ring._per_q = n_slots // max(n_queues, 1)
        ring.name = name
        ring._shm = shared_memory.SharedMemory(name=name)
        ring._owner = False
        ring._unlinked = False
        ring._mu = mutex()
        # workers lease through the queue handed to them at spawn, not
        # through the ring object (mp queues are not picklable by value)
        ring.free_qs = []
        # NOTE on the resource tracker: spawn children share the parent's
        # tracker process, and its per-type name cache is a SET — the
        # attach-time re-register of the same name is a no-op, and the
        # owner's unlink unregisters it exactly once. (Do NOT unregister
        # here: that would strip the owner's registration and break its
        # unlink bookkeeping.)
        return ring

    # ----------------------------------------------------------- write
    def write(self, slot: int, item: Any, part: int, seq: int,
              gen: int, span: int = 0, count: int = 1) -> None:
        """Encode ``item`` into ``slot``. ``span`` is the producer-side
        trace span id riding the header (0 = tracing off); ``count`` > 1
        marks a multi-item slot (``item`` is then the LIST of coalesced
        items, occupying seq .. seq+count-1). Raises
        :class:`SlotOverflow` (leaving the slot reusable) when it does
        not fit."""
        arrays: List[np.ndarray] = []
        spec = encode_item(item, arrays)
        offs = []
        off = 0
        for a in arrays:
            offs.append(off)
            off = _align(off + a.nbytes)
        meta = pickle.dumps((spec, [(o, a.shape, a.dtype.str)
                                    for o, a in zip(offs, arrays)]),
                            protocol=pickle.HIGHEST_PROTOCOL)
        need = off + len(meta) + _HEADER.size
        if need > self.slot_bytes:
            raise SlotOverflow(
                f"item needs {need} bytes > slot_bytes={self.slot_bytes}")
        base = slot * self.slot_bytes
        buf = self._shm.buf
        for o, a in zip(offs, arrays):
            dst = np.frombuffer(buf, dtype=a.dtype, count=a.size,
                                offset=base + o).reshape(a.shape)
            np.copyto(dst, a)
        end = base + self.slot_bytes
        buf[end - _HEADER.size - len(meta):end - _HEADER.size] = meta
        _HEADER.pack_into(buf, end - _HEADER.size, len(meta), part, seq,
                          gen, count, span & 0xFFFFFFFF, off)

    # ------------------------------------------------------------ read
    def read_header(self, slot: int) -> Tuple[int, int, int, int, int]:
        """(part, seq, gen, producer_span, count) without decoding the
        item — the consumer's cross-process span linkage (obs/trace.py)
        plus the multi-item count."""
        end = (slot + 1) * self.slot_bytes
        _, part, seq, gen, count, span, _ = _HEADER.unpack_from(
            self._shm.buf, end - _HEADER.size)
        return part, seq, gen, span, count

    def read(self, slot: int) -> Tuple[Any, int, int, int]:
        """(item, part, seq, gen) — the item's arrays are zero-copy views
        into the slot; hold the lease until done with them. For a
        multi-item slot (header count > 1) ``item`` is the list of
        items."""
        base = slot * self.slot_bytes
        end = base + self.slot_bytes
        buf = self._shm.buf
        meta_len, part, seq, gen, _count, _span, _ = _HEADER.unpack_from(
            buf, end - _HEADER.size)
        spec, placements = pickle.loads(
            bytes(buf[end - _HEADER.size - meta_len:end - _HEADER.size]))
        arrays = [
            np.frombuffer(buf, dtype=np.dtype(dt),
                          count=int(np.prod(shape)) if shape else 1,
                          offset=base + o).reshape(shape)
            for o, shape, dt in placements
        ]
        return decode_item(spec, arrays), part, seq, gen

    # --------------------------------------------------- lease/release
    def lease(self, qidx: int, timeout: float = 0.1) -> Optional[int]:
        """Take a free slot from queue ``qidx``; None on timeout (callers
        loop, checking their stop flag — this is the backpressure point
        when all of the owner's slots are leased)."""
        import queue as _q
        try:
            return self.free_qs[qidx].get(timeout=timeout)
        except _q.Empty:
            return None

    def release(self, slot: int) -> None:
        """Return a slot to its home queue (consumer side)."""
        if self._unlinked or not self.free_qs:
            return
        try:
            self.free_qs[slot // self._per_q].put_nowait(slot)
        except (ValueError, OSError):  # pragma: no cover - queue closed
            pass

    # --------------------------------------------------------- cleanup
    def close(self) -> None:
        try:
            self._shm.close()
        except BufferError:
            # np.frombuffer views still alive (e.g. the learner's last
            # staged batch): pin the SharedMemory object so a later
            # GC-time __del__ can't re-raise; the mapping frees with the
            # process — what matters for leak-freedom is unlink()
            if self._shm not in _pinned_maps:
                _pinned_maps.append(self._shm)
        except FileNotFoundError:  # pragma: no cover
            pass

    def unlink(self) -> None:
        """Remove the segment name (idempotent; owner only). Safe to call
        with worker processes still attached — their mappings survive
        until they close, but no /dev/shm entry outlives the ring."""
        with self._mu:
            if self._unlinked:
                return
            self._unlinked = True
        _live_rings.pop(self.name, None)
        self.close()
        if self._owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:  # pragma: no cover
                pass
