"""ctypes bridge: native libsvm parsing into RowBlocks.

Falls back to the pure-Python parser (parsers.parse_libsvm) when the native
library is unavailable; both produce identical RowBlocks (tests compare them
byte for byte).
"""

from __future__ import annotations

import ctypes

import numpy as np

from ..base import FEAID_DTYPE, REAL_DTYPE
from ..native import get_lib
from .rowblock import RowBlock, empty_block


def parse_libsvm_native(chunk: bytes) -> RowBlock:
    lib = get_lib()
    if lib is None:
        from .parsers import parse_libsvm
        return parse_libsvm(chunk)

    max_rows = chunk.count(b"\n") + 2
    # implicit-value tokens ("idx" == "idx:1") carry no ':', so budget by
    # token count instead: tokens are separated by >= 1 whitespace char
    # and each row owns one label token, so features <= separators + 1
    max_nnz = (chunk.count(b" ") + chunk.count(b"\t") + chunk.count(b"\n")
               + chunk.count(b"\r") + 2)
    labels = np.empty(max_rows, dtype=REAL_DTYPE)
    offset = np.empty(max_rows + 1, dtype=np.int64)
    index = np.empty(max_nnz, dtype=FEAID_DTYPE)
    value = np.empty(max_nnz, dtype=REAL_DTYPE)
    out_rows = ctypes.c_int64()
    out_nnz = ctypes.c_int64()
    out_has_value = ctypes.c_int()

    rc = lib.difacto_parse_libsvm(
        chunk, len(chunk),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        offset.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        index.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        value.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ctypes.byref(out_rows), ctypes.byref(out_nnz),
        ctypes.byref(out_has_value))
    if rc != 0:
        raise ValueError("malformed libsvm chunk")
    n, nnz = out_rows.value, out_nnz.value
    if n == 0:
        return empty_block()
    return RowBlock(
        offset=offset[:n + 1].copy(),
        label=labels[:n].copy(),
        index=index[:nnz].copy(),
        value=value[:nnz].copy() if out_has_value.value else None,
    )


def parse_criteo_native(chunk: bytes, is_train: bool = True) -> RowBlock:
    lib = get_lib()
    if lib is None:
        from .parsers import parse_criteo
        return parse_criteo(chunk, is_train)

    max_rows = chunk.count(b"\n") + 2
    # every feature field follows a tab in train mode; without a label the
    # first field has no leading tab, so budget one extra feature per row
    max_nnz = chunk.count(b"\t") + (1 if is_train else max_rows) + 1
    labels = np.empty(max_rows, dtype=REAL_DTYPE)
    offset = np.empty(max_rows + 1, dtype=np.int64)
    index = np.empty(max_nnz, dtype=FEAID_DTYPE)
    out_rows = ctypes.c_int64()
    out_nnz = ctypes.c_int64()

    rc = lib.difacto_parse_criteo(
        chunk, len(chunk), int(is_train),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        offset.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        index.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        max_rows, max_nnz,
        ctypes.byref(out_rows), ctypes.byref(out_nnz))
    if rc != 0:
        raise ValueError("malformed criteo chunk" if rc == -1
                         else "criteo parse buffer overflow")
    n, nnz = out_rows.value, out_nnz.value
    if n == 0:
        return empty_block()
    return RowBlock(
        offset=offset[:n + 1].copy(),
        label=labels[:n].copy(),
        index=index[:nnz].copy(),
        value=None,  # binary features
    )


def parse_adfea_native(chunk: bytes) -> RowBlock:
    """Native adfea parser with Python fallback (parsers.py:parse_adfea is
    the semantic reference; src/reader/adfea_parser.h:20-91)."""
    lib = get_lib()
    if lib is None:
        from .parsers import parse_adfea
        return parse_adfea(chunk)

    # every feature token contains ':'; rows are delimited by their 3
    # header tokens, so splitting on whitespace bounds rows loosely
    max_nnz = chunk.count(b":") + 1
    # tokens are separated by >= 1 whitespace char and each row owns 3
    # header tokens, so rows <= (separators + 1) / 3; count every
    # separator class the native tokenizer skips (incl. '\r' — an
    # undercount here overruns the caller-allocated buffers)
    seps = (chunk.count(b"\n") + chunk.count(b" ") + chunk.count(b"\t")
            + chunk.count(b"\r"))
    max_rows = seps // 3 + 2
    labels = np.empty(max_rows, dtype=REAL_DTYPE)
    offset = np.empty(max_rows + 1, dtype=np.int64)
    index = np.empty(max_nnz, dtype=FEAID_DTYPE)
    out_rows = ctypes.c_int64()
    out_nnz = ctypes.c_int64()

    rc = lib.difacto_parse_adfea(
        chunk, len(chunk),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        offset.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        index.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        ctypes.byref(out_rows), ctypes.byref(out_nnz))
    if rc != 0:
        raise ValueError("malformed adfea chunk")
    n, nnz = out_rows.value, out_nnz.value
    if n == 0:
        return empty_block()
    return RowBlock(
        offset=offset[:n + 1].copy(),
        label=labels[:n].copy(),
        index=index[:nnz].copy(),
        value=None,  # binary features
    )
