"""SGD updater: FTRL for w, AdaGrad for V, over a fixed-capacity slot table.

TPU-native re-design of the reference's server-side SGDUpdater
(src/sgd/sgd_updater.{h,cc}). The per-feature hash map of SGDEntry records
(sgd_updater.h:20-69) becomes a struct-of-arrays slot table in device memory;
per-key scalar updates (sgd_updater.cc:105-152) become vectorised gather ->
elementwise -> scatter over the batch's unique slots. **Row 0 is a reserved
trash slot**: padded/invalid entries scatter there, so every kernel runs
unconditionally with static shapes.

Exact semantics preserved:

- FTRL-proximal w update (UpdateW, sgd_updater.cc:105-131): g += l2*w;
  n' = sqrt(n^2 + g^2); z -= g - (n' - n)/lr * w; w = soft-threshold(z, l1)
  scaled by lr/(lr_beta + n').
- AdaGrad V update (UpdateV, sgd_updater.cc:133-142) with V_l2, applied only
  to rows whose embedding was *pulled* this batch (lens[i] > 1 semantics,
  sgd_updater.cc:91-96).
- Lazy V activation (InitV triggers, sgd_updater.cc:71-74,123-127): the union
  of the reference's two trigger sites is exactly
  ``v_live |= (w != 0) & (cnt > V_threshold)`` re-evaluated after every count
  or gradient update. V rows are pre-filled with the uniform init
  ``(u01 - 0.5) * V_init_scale`` (InitV, sgd_updater.cc:144-152) at state
  creation — activation just flips the flag. (Deviation: init values come
  from a counter-based PRNG per slot, not the reference's call-order-dependent
  rand_r stream; distribution is identical.)
- Pull gating (Get, sgd_updater.cc:34-58): the embedding is served only when
  live and not suppressed by ``l1_shrk`` (w == 0).
- Evaluate (sgd_updater.cc:15-32): penalty uses **l2 for the V term as well**
  (a reference quirk — UpdateV regularises with V_l2 but Evaluate charges
  l2); nnz counts V_dim for every live embedding regardless of w.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Param

TRASH_SLOT = 0  # row 0 absorbs padded scatters; never a real feature


@dataclass
class SGDUpdaterParam(Param):
    l1: float = field(default=1.0, metadata=dict(lo=0, hi=1e10))
    l2: float = field(default=0.0, metadata=dict(lo=0, hi=1e10))
    V_l2: float = field(default=0.01, metadata=dict(lo=0, hi=1e10))
    lr: float = field(default=0.01, metadata=dict(lo=0, hi=10))
    lr_beta: float = field(default=1.0, metadata=dict(lo=0, hi=1e10))
    V_lr: float = field(default=0.01, metadata=dict(lo=0, hi=1e10))
    V_lr_beta: float = field(default=1.0, metadata=dict(lo=0, hi=10))
    V_init_scale: float = field(default=0.01, metadata=dict(lo=0, hi=10))
    V_dim: int = field(default=0, metadata=dict(lo=0))
    V_threshold: int = 10
    l1_shrk: bool = True
    seed: int = 0
    # > 0 switches the store to a fixed-capacity hashed table: slot =
    # reversed_id mod (capacity-1) + 1, no host dictionary. Deterministic
    # across hosts (multi-controller requirement, parallel/multihost.py);
    # collisions alias features, the standard hashing-trick tradeoff.
    hash_capacity: int = 0
    # dictionary store only: initial slot-table rows (grows by doubling,
    # store/local.py). Lower it to bound the first HBM allocation on
    # small models — or, in tests, to force growth events.
    init_capacity: int = field(default=1 << 14, metadata=dict(lo=2))
    # storage dtype of the fused [V | Vg] embedding rows. bfloat16 halves
    # the dominant HBM traffic of the fused step (the [U, 2k] row
    # gather/scatter); compute stays float32. FTRL scalars (w/z/sqrt_g)
    # always stay float32 — z accumulates and must not round.
    V_dtype: str = field(default="float32",
                         metadata=dict(enum=["float32", "bfloat16"]))
    # pad each VVg half to a multiple of 64 elements so the fused row is a
    # multiple of the 128-lane TPU tile width. Sub-lane-width rows make the
    # per-row table scatter a misaligned read-modify-write: at V_dim=16
    # over a 4.2M-row table, the [196k, 32] scatter measured 33 ms vs
    # 15 ms for the padded [196k, 128] row — MORE bytes, half the time
    # (docs/perf_notes.md). The pad costs up to 4x VVg HBM at V_dim<=32,
    # so it auto-disables when the padded table would exceed
    # ``pad_v_rows_max_mb`` (the donated-state double plus the batch
    # cache must still fit; an 8.4M-row V16 bf16 table OOMed a 16 GB
    # chip padded but trains unpadded). Set pad_v_rows=False to force
    # the compact layout.
    pad_v_rows: bool = True
    pad_v_rows_max_mb: int = 1536
    # table-kernel backend of the fused SGD hot path (ops/fused.py):
    # "off" = the composed gather/scatter ops (pull and push gathers
    # merged only by XLA CSE); "jnp" = the fused single-program path
    # (the step threads the gathered rows from pull to push and the
    # FTRL/AdaGrad epilogue scatters once — byte-identical
    # trajectories, guaranteed single gather); "pallas" = the same
    # dataflow as pl.pallas_call DMA kernels with the row update folded
    # into the scatter's epilogue (TPU backends; interpret-mode parity
    # elsewhere; unsharded tables only); "auto" = jnp until a driver
    # bench shows the pallas kernels ahead (docs/perf_notes.md "Fused
    # FM kernel").
    fused_kernel: str = field(default="auto",
                              metadata=dict(enum=["auto", "pallas",
                                                  "jnp", "off"]))
    # ---- table-capacity levers (difacto_tpu/capacity/; docs/perf_notes
    # "Table capacity"). All default OFF: fp32 + admit-all + no tier is
    # byte-identical to the pre-capacity trajectory.
    # Storage dtype of the fused slot rows. "fp32" = full precision (the
    # container still follows the legacy V_dtype knob, so existing bf16
    # configs are untouched); "bf16" forces the bfloat16 container;
    # "int8"/"fp8" store BOTH embedding halves as 8-bit codes in an int8
    # container with per-row f32 scale factors riding the spare scalar
    # lanes — 4x (2x vs bf16) more rows per HBM byte, with dequant/
    # requant folded into the fused row epilogue so the hot path stays
    # one gather + one scatter (ops/fused.quant_half). V_dim > 0 only
    # (the flat layout has no fused row to quantize).
    slot_dtype: str = field(default="fp32",
                            metadata=dict(enum=["fp32", "bf16",
                                                "int8", "fp8"]))
    # Frequency-adaptive admission (capacity/sketch.py): a hashed token
    # must reach this count-min-sketch estimate in the producer's ingest
    # stream before it is admitted to the table; rarer tokens route to
    # an OOB lane (gathers zeros, scatter dropped). 0 = admit all. The
    # TPU-side analog of the reference's frequency filter: rare features
    # never cost a slot.
    admit_min_count: int = field(default=0, metadata=dict(lo=0))
    # Occupancy-pressure eviction (SlotStore.maybe_evict, cold path):
    # when the occupied fraction of table rows exceeds this threshold,
    # the lowest-count rows are evicted (demoted to the cold tier when
    # it is on, else their FTRL/AdaGrad scalars reset to virgin) until
    # occupancy drops to 0.9x the threshold. 0 = off.
    evict_occupancy: float = field(default=0.0, metadata=dict(lo=0, hi=1))
    # Host-RAM cold tier (capacity/tier.py): the device table holds
    # hash_capacity - cold_tier_rows HOT rows; the zipf tail lives in
    # host RAM and rows promote/demote in batches on the dispatch
    # thread. 0 = off. Hashed stores with V_dim > 0 only.
    cold_tier_rows: int = field(default=0, metadata=dict(lo=0))


class SGDState(NamedTuple):
    """Slot-table model state; all arrays have capacity+1 rows (row 0 trash).

    TWO layouts, keyed on V_dim:

    - ``V_dim == 0`` (linear models): flat f32 FTRL arrays w/z/sqrt_g/cnt
      (+ v_live, vestigial), ``VVg`` is [C, 0]. The flat T(1024) scalar
      layout is the fast form when there is no embedding row to ride.
    - ``V_dim > 0``: EVERYTHING lives in ``VVg`` [C, Wx] and the five
      flat fields are empty [0] placeholders (pytree/donation still sees
      six leaves). The row is [V | pad | Vg | pad | scal]: V in [:, :k],
      Vg in [:, h:h+k] with h = v_half(param) >= k, and the last SCAL_W
      lanes carry the FTRL scalars (w, z, sqrt_g, cnt as f32 bit-split
      into storage-dtype lane pairs — see pack_scal) plus the v_live
      flag. One fused row means the step runs ONE gather + ONE scatter
      instead of ~10 per-slot table ops; each op costs ~10-19 ns per
      ROW regardless of width, so merging ops is the lever (measured
      52.4 -> 37.4 ms for the u=262k V64 table-op train, 31.0 -> 21.0 ms
      for u=196k V16 where the scalars ride the EXISTING pad lanes —
      docs/perf_notes.md round-5 "fused scalar lanes").

    Reference analog: the SGDEntry record (src/sgd/sgd_updater.h:20-69)
    keeps w, z, sqrt_g and V[] contiguous per feature for the same
    reason — one cache line per key.
    """
    w: jnp.ndarray        # f32[C] (V_dim=0) | f32[0]
    z: jnp.ndarray        # f32[C] FTRL dual  | f32[0]
    sqrt_g: jnp.ndarray   # f32[C] FTRL accumulated grad norm | f32[0]
    cnt: jnp.ndarray      # f32[C] feature occurrence counts  | f32[0]
    VVg: jnp.ndarray      # [C, Wx] fused rows (V_dim>0) | [C, 0]
    v_live: jnp.ndarray   # bool[C] (V_dim=0, vestigial) | bool[0]

    @property
    def capacity(self) -> int:
        return self.VVg.shape[0]


def quantized(param: SGDUpdaterParam) -> bool:
    """True when the fused rows store 8-bit codes with per-row scales
    (slot_dtype int8/fp8) — the layout where the embedding halves need a
    dequant before use and a requant on write-back."""
    return param.slot_dtype in ("int8", "fp8") and param.V_dim > 0


def v_dtype(param: SGDUpdaterParam):
    """Container dtype of the fused rows. slot_dtype=fp32 means "full
    precision" and defers to the legacy V_dtype knob (so existing bf16
    configs keep their exact layout); int8 AND fp8 share the int8
    container (fp8 bit patterns bitcast in, ops/fused.quant_half)."""
    if param.V_dim > 0:
        if param.slot_dtype in ("int8", "fp8"):
            return jnp.int8
        if param.slot_dtype == "bf16":
            return jnp.bfloat16
    return jnp.bfloat16 if param.V_dtype == "bfloat16" else jnp.float32


def v_half(param: SGDUpdaterParam, capacity: int) -> int:
    """Stored width of each VVg half at this table capacity: V_dim
    rounded up to a multiple of 64 (so the fused [V | Vg] row is a
    multiple of the 128-lane tile) when pad_v_rows and the padded table
    fits pad_v_rows_max_mb, else exactly V_dim. The full row adds the
    scalar lanes behind the halves — row_layout is the single source for
    the complete geometry."""
    k = param.V_dim
    if k == 0 or not param.pad_v_rows:
        return k
    h = -(-k // 64) * 64
    bytes_per_el = np.dtype(v_dtype(param)).itemsize
    if capacity * 2 * h * bytes_per_el > param.pad_v_rows_max_mb << 20:
        return k
    return h


def fuse_vvg(V, Vg, h: int):
    """The padded embedding halves: [V | pad | Vg | pad] with each half
    zero-padded from k columns to h. Accepts jnp or numpy halves. The
    fused-row builders below append the scalar lanes behind this."""
    k = V.shape[1]
    if h == k:
        return jnp.concatenate([V, Vg], axis=1)
    pad = jnp.zeros((V.shape[0], h - k), dtype=jnp.asarray(V).dtype)
    return jnp.concatenate([V, pad, Vg, pad], axis=1)


# fused-row scalar section: the BYTES of f32[8] = (w, z, sqrt_g, cnt,
# v_live-as-1.0/0.0, scale_V, scale_Vg, 1 spare) reinterpreted in the
# row's storage dtype — 8 f32 lanes, 16 bfloat16 lanes, or 32 int8 lanes
# (quantized slots). One contiguous minor-dim slice plus a bulk
# bitcast_convert_type reads/writes the whole section (bit-exact: each
# f32 spans 4/itemsize adjacent lanes, low bits first), which keeps XLA
# on the row-major layout — per-lane extraction with uint shifts made
# layout assignment prefer a TRANSPOSED gather and insert a full-table
# copy of the donated state every step (docs/perf_notes.md). Lanes 5/6
# carry the per-row quantization scales of the V/Vg halves when
# slot_dtype is int8/fp8 (ops/fused.quant_half); exact 0.0 otherwise —
# bit-identical to the old spare-lane zeros.
SCAL_F32S = 8


def scal_lanes(dtype) -> int:
    return SCAL_F32S * (4 // np.dtype(dtype).itemsize)


def row_layout(param: SGDUpdaterParam, capacity: int
               ) -> Tuple[int, int, int, int]:
    """(k, h, Wx, off) of the fused row at this capacity: half width h
    from v_half (budget-gated lane padding), total row width Wx, and the
    scalar-section offset off = Wx - scal_lanes. The scalars ride INSIDE
    the Vg-half pad when it is wide enough (V_dim <= 48 padded: zero
    extra bytes); otherwise the row is extended to the next multiple of
    the 128-lane tile (V_dim=64 bf16: 128 -> 256). The multiple is
    load-bearing: a 192-lane row made XLA's entry-layout pass choose a
    TRANSPOSED {0,1} table layout (it avoids the 192->256 tile padding),
    which inserted two full-table transpose copies around every step's
    gather/scatter — ~5.7 ms/step of pure copy at 2M rows
    (docs/perf_notes.md round-5 "fused scalar lanes"). A tile-aligned
    width costs the same HBM as the padded 192 and keeps {1,0}."""
    k = param.V_dim
    assert k > 0, "flat layout has no fused row"
    h = v_half(param, capacity)
    ns = scal_lanes(v_dtype(param))
    Wx = 2 * h if h - k >= ns else -(-(2 * h + ns) // 128) * 128
    return k, h, Wx, Wx - ns


def pack_scal(w, z, sqrt_g, cnt, live, dtype, scale_V=None, scale_Vg=None):
    """f32 scalar columns + bool live -> [n, scal_lanes] of ``dtype``.
    ``scale_V``/``scale_Vg`` fill the quantization-scale lanes 5/6
    (quantized slots); omitted they stay exact 0.0 — byte-identical to
    the historical spare-lane zeros."""
    wf = jnp.asarray(w, jnp.float32)
    f = jnp.stack([wf, jnp.asarray(z, jnp.float32),
                   jnp.asarray(sqrt_g, jnp.float32),
                   jnp.asarray(cnt, jnp.float32),
                   jnp.asarray(live, jnp.float32),
                   jnp.zeros_like(wf) if scale_V is None
                   else jnp.asarray(scale_V, jnp.float32),
                   jnp.zeros_like(wf) if scale_Vg is None
                   else jnp.asarray(scale_Vg, jnp.float32),
                   jnp.zeros_like(wf)],
                  axis=1)
    if dtype == jnp.float32:
        return f
    n_per = 4 // np.dtype(dtype).itemsize
    return jax.lax.bitcast_convert_type(f, dtype).reshape(
        f.shape[0], n_per * SCAL_F32S)


def scal_f32(lanes):
    """[n, scal_lanes] scalar section (any container dtype) -> the
    underlying f32[n, SCAL_F32S] matrix — columns (w, z, sqrt_g, cnt,
    live, scale_V, scale_Vg, spare)."""
    if lanes.dtype == jnp.float32:
        return lanes
    n_per = 4 // np.dtype(lanes.dtype).itemsize
    return jax.lax.bitcast_convert_type(
        lanes.reshape(lanes.shape[0], SCAL_F32S, n_per), jnp.float32)


def unpack_scal(lanes):
    """[n, scal_lanes] scalar section -> (w, z, sqrt_g, cnt, live)."""
    f = scal_f32(lanes)
    return f[:, 0], f[:, 1], f[:, 2], f[:, 3], f[:, 4] > 0


def scal_cols(param: SGDUpdaterParam, state: SGDState):
    """(w, z, sqrt_g, cnt, v_live) as full-table columns — the host /
    eval / checkpoint view, layout-independent. Column slices of the
    fused rows read whole tiles, so this is a full-table pass: fine once
    per epoch or task, never per step."""
    if param.V_dim == 0:
        return state.w, state.z, state.sqrt_g, state.cnt, state.v_live
    _, _, _, off = row_layout(param, state.capacity)
    return unpack_scal(state.VVg[:, off:])


def col_w(param: SGDUpdaterParam, state: SGDState) -> jnp.ndarray:
    return scal_cols(param, state)[0]


def col_V(param: SGDUpdaterParam, state: SGDState) -> jnp.ndarray:
    """Full-table V columns (storage dtype), pad/scal lanes stripped."""
    if param.V_dim == 0:
        return state.VVg
    k, _, _, _ = row_layout(param, state.capacity)
    return state.VVg[:, :k]


def col_Vg(param: SGDUpdaterParam, state: SGDState) -> jnp.ndarray:
    if param.V_dim == 0:
        return state.VVg
    k, h, _, _ = row_layout(param, state.capacity)
    return state.VVg[:, h:h + k]


def emb_cols_f32(param: SGDUpdaterParam, state: SGDState):
    """Full-table LOGICAL f32 (V, Vg) columns — dequantized when the
    rows store 8-bit codes (the per-row scales come from the scalar
    lanes). The layout-independent view checkpoints, eval and growth
    re-layout read; full-table pass, cold paths only."""
    k, h, _, off = row_layout(param, state.capacity)
    V, Vg = state.VVg[:, :k], state.VVg[:, h:h + k]
    if not quantized(param):
        return V.astype(jnp.float32), Vg.astype(jnp.float32)
    from ..ops import fused
    f = scal_f32(state.VVg[:, off:])
    return (fused.dequant_half(V, f[:, 5], param.slot_dtype),
            fused.dequant_half(Vg, f[:, 6], param.slot_dtype))


def state_bytes(param: SGDUpdaterParam, capacity: int) -> int:
    """HBM bytes of the slot table at ``capacity`` rows — the number the
    fs-sharding capacity story is about: per-device residency is
    ``state_bytes / fs`` (parallel/mesh.py fs_shard_bounds), so an
    fs-way mesh holds an fs-times-larger table in the same per-chip
    HBM. One definition shared by bench.py's multichip capacity legs
    and the store's shard stats."""
    if param.V_dim == 0:
        # four f32 columns (w, z, sqrt_g, cnt) + bool v_live
        return capacity * (4 * 4 + 1)
    _, _, Wx, _ = row_layout(param, capacity)
    return capacity * Wx * np.dtype(v_dtype(param)).itemsize


def gather_bytes(param: SGDUpdaterParam, capacity: int, u_cap: int) -> int:
    """HBM bytes ONE direction of a fused row gather (or scatter) of
    ``u_cap`` unique rows moves at this table capacity's row layout —
    the per-dispatch unit of the ``store_gather_bytes_total`` counter
    (docs/observability.md): serve counts it once per dispatch (pull
    only), train twice (pull + push), so cross-shard row traffic is
    observable per path."""
    if param.V_dim == 0:
        return u_cap * 3 * 4
    _, _, Wx, _ = row_layout(param, capacity)
    return u_cap * Wx * np.dtype(v_dtype(param)).itemsize


def set_all_live(param: SGDUpdaterParam, state: SGDState) -> SGDState:
    """Bench/entry helper: activate every embedding row."""
    if param.V_dim == 0:
        return state._replace(v_live=jnp.ones_like(state.v_live))
    _, _, _, off = row_layout(param, state.capacity)
    f = scal_f32(state.VVg[:, off:])
    scal = pack_scal(f[:, 0], f[:, 1], f[:, 2], f[:, 3],
                     jnp.ones_like(f[:, 0], bool), state.VVg.dtype,
                     scale_V=f[:, 5], scale_Vg=f[:, 6])
    return state._replace(
        VVg=jnp.concatenate([state.VVg[:, :off], scal], axis=1))


def build_rows(param: SGDUpdaterParam, capacity: int, V, Vg,
               w, z, sqrt_g, cnt, live) -> jnp.ndarray:
    """Assemble full fused rows [V | pad | Vg | pad | scal] at this
    capacity's layout from f32 parts. Every builder (init, growth
    re-layout, checkpoint assembly) goes through here so the layout
    cannot drift between sites."""
    _, h, Wx, off = row_layout(param, capacity)
    dt = v_dtype(param)
    if quantized(param):
        from ..ops import fused
        Vc, sV = fused.quant_half(jnp.asarray(V, jnp.float32),
                                  param.slot_dtype)
        Vgc, sVg = fused.quant_half(jnp.asarray(Vg, jnp.float32),
                                    param.slot_dtype)
        halves = fuse_vvg(Vc, Vgc, h)
    else:
        sV = sVg = None
        halves = fuse_vvg(jnp.asarray(V, jnp.float32),
                          jnp.asarray(Vg, jnp.float32), h).astype(dt)
    scal = pack_scal(jnp.asarray(w, jnp.float32), jnp.asarray(z, jnp.float32),
                     jnp.asarray(sqrt_g, jnp.float32),
                     jnp.asarray(cnt, jnp.float32),
                     jnp.asarray(live), dt, scale_V=sV, scale_Vg=sVg)
    # in-pad layout (off < 2h): the scal section replaces the tail of the
    # Vg-half pad; appended layout: zero gap lanes between halves and scal
    if off <= 2 * h:
        return jnp.concatenate([halves[:, :off], scal], axis=1)
    gap = jnp.zeros((halves.shape[0], off - 2 * h), dt)
    return jnp.concatenate([halves, gap, scal], axis=1)


def init_state(param: SGDUpdaterParam, capacity: int) -> SGDState:
    k = param.V_dim
    if k == 0:
        def zeros():
            # distinct buffers — donate_argnums forbids aliased leaves
            return jnp.zeros(capacity, dtype=jnp.float32)
        return SGDState(
            w=zeros(), z=zeros(), sqrt_g=zeros(), cnt=zeros(),
            VVg=jnp.zeros((capacity, 0), jnp.float32),
            v_live=jnp.zeros(capacity, dtype=bool))
    key = jax.random.PRNGKey(param.seed)
    V = (jax.random.uniform(key, (capacity, k), dtype=jnp.float32) - 0.5) \
        * param.V_init_scale
    _, _, Wx, _ = row_layout(param, capacity)
    if quantized(param):
        # quantized rows need their per-row V scale in the scalar lanes
        # (a zero scale would dequantize the init values to 0), so init
        # routes through the full row builder
        zcol = jnp.zeros(capacity, jnp.float32)
        T = build_rows(param, capacity, V,
                       jnp.zeros((capacity, k), jnp.float32),
                       zcol, zcol, zcol, zcol,
                       jnp.zeros(capacity, dtype=bool))
    else:
        # all-zero scalar lanes already encode (w,z,sqrt_g,cnt,live) =
        # (0,0,0,0,False) in both dtypes, so only the V block needs
        # writing
        T = jnp.zeros((capacity, Wx), v_dtype(param)
                      ).at[:, :k].set(V.astype(v_dtype(param)))
    empty = jnp.zeros(0, jnp.float32)
    return SGDState(w=empty, z=empty + 0, sqrt_g=empty + 0, cnt=empty + 0,
                    VVg=T, v_live=jnp.zeros(0, dtype=bool))


def grow_state(param: SGDUpdaterParam, state: SGDState, new_capacity: int
               ) -> SGDState:
    """Double-and-copy growth; new V rows get fresh init values. Growth
    can cross the pad_v_rows_max_mb threshold, shrinking v_half back to
    V_dim — old rows are re-laid-out to the new row width (their scalar
    lanes move with the scal offset)."""
    old = state.capacity
    if new_capacity <= old:
        return state
    ext = init_state(param, new_capacity)
    # compare the FULL geometry, not the width: crossing the
    # pad_v_rows_max_mb gate at V_dim<=48 keeps Wx=128 while h moves
    # (64 -> k), so a width-equality guard would silently leave Vg at
    # the old offset (advisor round-5 finding, reproduced: grown rows
    # read Vg=0 from the old V-pad lanes)
    if param.V_dim and row_layout(param, old) != row_layout(param,
                                                            new_capacity):
        k, h, _, off = row_layout(param, old)
        w, z, sg, cnt, live = unpack_scal(state.VVg[:, off:])
        Vf, Vgf = emb_cols_f32(param, state)
        state = state._replace(VVg=build_rows(
            param, new_capacity, Vf, Vgf, w, z, sg, cnt, live))
    return SGDState(*(jnp.concatenate([a, jnp.asarray(b)[old:]], axis=0)
                      for a, b in zip(state, ext)))


def ftrl_w(w, z, sg, gw, l1: float, l2: float, lr: float, lr_beta: float):
    """The FTRL-proximal w update (UpdateW, sgd_updater.cc:105-131),
    identical math in both layouts. Module-level so every fused_kernel
    backend traces the SAME op sequence (ops/fused.py)."""
    g = gw + l2 * w
    sg_new = jnp.sqrt(sg * sg + g * g)
    z_new = z - (g - (sg_new - sg) / lr * w)
    eta = (lr_beta + sg_new) / lr
    w_new = jnp.where(
        jnp.abs(z_new) <= l1, 0.0,
        (z_new - jnp.sign(z_new) * l1) / eta)
    return w_new, z_new, sg_new


def row_epilogue(param: SGDUpdaterParam, capacity: int, rows: jnp.ndarray,
                 gw: jnp.ndarray, gV: Optional[jnp.ndarray],
                 pull_vmask: Optional[jnp.ndarray]) -> jnp.ndarray:
    """The per-row FTRL(w) + AdaGrad(V) update on gathered fused rows
    [n, Wx] -> new rows, WITHOUT the surrounding gather/scatter: the
    single source of the push math for every fused_kernel backend —
    the off/jnp paths scatter its result, the pallas kernel traces it
    per R-row VMEM tile as the scatter's epilogue (ops/fused.py
    fm_update_rows). ``pull_vmask`` gates AdaGrad to rows whose
    embedding was PULLED this batch (lens[i] > 1 semantics,
    sgd_updater.cc:91-96); padded OOB lanes compute garbage that the
    scatter drops."""
    k, h, _, off = row_layout(param, capacity)
    thr = float(param.V_threshold)
    q = quantized(param)
    f = scal_f32(rows[:, off:])
    w, z, sg, cnt, live = f[:, 0], f[:, 1], f[:, 2], f[:, 3], f[:, 4] > 0
    # per-row quantization scales ride lanes 5/6 (exact 0.0 when the
    # rows are not quantized — carried through bit-identically)
    sV, sVg = f[:, 5], f[:, 6]
    w_new, z_new, sg_new = ftrl_w(w, z, sg, gw, param.l1, param.l2,
                                  param.lr, param.lr_beta)
    # lazy-V activation on the touched rows (the union of the
    # reference's two trigger sites re-evaluated after the update)
    live_new = live | ((w_new != 0) & (cnt > thr))

    if gV is not None:
        if q:
            from ..ops import fused
            V = fused.dequant_half(rows[:, :k], sV, param.slot_dtype)
            Vg = fused.dequant_half(rows[:, h:h + k], sVg, param.slot_dtype)
        else:
            V = rows[:, :k].astype(jnp.float32)
            Vg = rows[:, h:h + k].astype(jnp.float32)
        gv = gV + param.V_l2 * V
        Vg_new = jnp.sqrt(Vg * Vg + gv * gv)
        V_new = V - param.V_lr / (Vg_new + param.V_lr_beta) * gv
        # AdaGrad only touches rows whose embedding was PULLED this
        # batch (lens[i] > 1 semantics, sgd_updater.cc:91-96)
        upd = pull_vmask[:, None] > 0
        if q:
            # requant with FRESH per-row scales; both the codes and the
            # scales are gated on pull_vmask so an untouched row keeps a
            # consistent (codes, scale) pair
            Vc, sV_new = fused.quant_half(V_new, param.slot_dtype)
            Vgc, sVg_new = fused.quant_half(Vg_new, param.slot_dtype)
            emb = jnp.where(upd, fuse_vvg(Vc, Vgc, h), rows[:, :2 * h])
            um = pull_vmask > 0
            sV = jnp.where(um, sV_new, sV)
            sVg = jnp.where(um, sVg_new, sVg)
        else:
            emb = jnp.where(upd, fuse_vvg(V_new, Vg_new, h),
                            rows[:, :2 * h].astype(jnp.float32)
                            ).astype(rows.dtype)
    else:
        emb = rows[:, :2 * h]
    scal = pack_scal(w_new, z_new, sg_new, cnt, live_new, rows.dtype,
                     scale_V=sV, scale_Vg=sVg)
    # in-pad layout: scal replaces the tail of emb's own pad lanes;
    # appended layout: the gap lanes between are carried through
    if off <= 2 * h:
        return jnp.concatenate([emb[:, :off], scal], axis=1)
    return jnp.concatenate([emb, rows[:, 2 * h:off], scal], axis=1)


def make_fns(param: SGDUpdaterParam, mesh=None):
    """Build the pure update/get functions with hyperparameters baked in
    as compile-time constants. Returns a namespace of jit-ready callables
    (not yet jit-wrapped; the store/learner composes and jits them).

    ``mesh`` (the store's SPMD mesh, or None) gates the fused_kernel
    backend resolution: the pallas kernels require an unsharded table
    (ops/fused.py resolve_backend)."""

    from ..ops import fused

    l1, l2 = param.l1, param.l2
    lr, lr_beta = param.lr, param.lr_beta
    has_V = param.V_dim > 0
    # table-kernel backend of the V>0 hot path ("off" on flat tables —
    # there is no fused row to kernel over); see SGDUpdaterParam.
    # V_l2 / V_lr / V_lr_beta are read by row_epilogue from ``param``.
    backend = fused.resolve_backend(param.fused_kernel, mesh=mesh,
                                    V_dim=param.V_dim)

    def _gather(arr, slots):
        # the store guarantees sorted unique slots (map_keys_dedup) with
        # out-of-bounds ASCENDING padding (pad_slots) — the gather-flag
        # contract lives in ops/fused.gather_rows (measured ~20% off
        # the fused step); padded lanes read as zeros (mode=fill)
        return fused.gather_rows(arr, slots, "jnp")

    def _scatter(arr, slots, rows):
        # padded (out-of-bounds) entries are dropped, real rows are unique
        return fused.scatter_rows(arr, slots, rows, "jnp")

    thr = float(param.V_threshold)

    def _layout(state):
        return row_layout(param, state.capacity)

    def _ftrl(w, z, sg, gw):
        return ftrl_w(w, z, sg, gw, l1, l2, lr, lr_beta)

    def pull_rows(state: SGDState, slots: jnp.ndarray) -> jnp.ndarray:
        """ONE full fused-row gather of the batch's unique slots,
        backend-dispatched (ops/fused.py). The fused train step
        (step.py) threads the result from pull to push so the push
        never re-gathers — the "off" path instead relies on XLA CSE
        merging its two gathers. A partial-row gather (VVg[slots, :k])
        would lower to a strided gather ~8x slower. V keeps its
        STORAGE dtype (param.V_dtype) so the loss's per-token gather
        can ride bf16."""
        return fused.gather_rows(state.VVg, slots, backend)

    def rows_to_params(state: SGDState, rows: jnp.ndarray):
        """(w, V, v_mask) views of gathered fused rows (Get,
        sgd_updater.cc:34-58): the embedding is served only when live
        and not suppressed by ``l1_shrk`` (w == 0)."""
        _, _, _, off = _layout(state)
        f = scal_f32(rows[:, off:])
        w, live = f[:, 0], f[:, 4] > 0
        vmask = live
        if param.l1_shrk:
            vmask = vmask & (w != 0)
        if quantized(param):
            # loss-side V must be real values, not codes: dequantize
            # with the per-row scale riding lane 5 (f32 compute)
            V = fused.dequant_half(rows[:, :param.V_dim], f[:, 5],
                                   param.slot_dtype)
        else:
            V = rows[:, :param.V_dim]
        return w, V, vmask.astype(jnp.float32)

    def get_rows(state: SGDState, slots: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray],
                            Optional[jnp.ndarray]]:
        """Pull [w, V, v_mask] rows for the batch's unique slots (Get)."""
        if not has_V:
            return _gather(state.w, slots), None, None
        return rows_to_params(state, pull_rows(state, slots))

    def apply_count(state: SGDState, slots: jnp.ndarray, counts: jnp.ndarray
                    ) -> SGDState:
        """kFeaCount push (Update, sgd_updater.cc:64-75). Sorted unique
        slots with out-of-bounds padding (dropped). Touched rows also
        re-evaluate their lazy-V activation (InitV trigger,
        sgd_updater.cc:71-74) — untouched rows cannot flip, their (w,
        cnt) did not change."""
        if not has_V:
            cnt = state.cnt.at[slots].add(counts, indices_are_sorted=True,
                                          unique_indices=True, mode="drop")
            return state._replace(cnt=cnt)
        _, _, _, off = _layout(state)
        rows = _gather(state.VVg, slots)
        f = scal_f32(rows[:, off:])
        w, z, sg, cnt, live = f[:, 0], f[:, 1], f[:, 2], f[:, 3], f[:, 4] > 0
        cnt_new = cnt + counts
        live_new = live | ((w != 0) & (cnt_new > thr))
        # scale lanes 5/6 carried through — a count push must not zero a
        # quantized row's dequant scales
        scal = pack_scal(w, z, sg, cnt_new, live_new, state.VVg.dtype,
                         scale_V=f[:, 5], scale_Vg=f[:, 6])
        out = jnp.concatenate([rows[:, :off], scal], axis=1)
        return state._replace(VVg=_scatter(state.VVg, slots, out))

    def apply_grad_rows(state: SGDState, slots: jnp.ndarray,
                        rows: jnp.ndarray, gw: jnp.ndarray,
                        gV: Optional[jnp.ndarray],
                        pull_vmask: Optional[jnp.ndarray]) -> SGDState:
        """Fused kGradient push over rows the step ALREADY gathered
        (pull_rows): the per-row FTRL/AdaGrad epilogue (row_epilogue)
        plus ONE scatter. The pallas backend folds the epilogue into
        the scatter kernel itself (ops/fused.fm_update_rows), so the
        table row moves through HBM exactly once on the push."""
        cap = state.capacity

        def epi(r, g, gv, vm):
            return row_epilogue(param, cap, r, g, gv, vm)

        if backend == "pallas" and gV is not None \
                and pull_vmask is not None:
            VVg = fused.fm_update_rows(state.VVg, slots, rows, gw, gV,
                                       pull_vmask, epi, backend="pallas")
        else:
            VVg = _scatter(state.VVg, slots,
                           epi(rows, gw, gV, pull_vmask))
        return state._replace(VVg=VVg)

    def apply_grad(state: SGDState, slots: jnp.ndarray,
                   gw: jnp.ndarray, gV: Optional[jnp.ndarray],
                   pull_vmask: Optional[jnp.ndarray]) -> SGDState:
        """kGradient push: FTRL(w) + AdaGrad(V). ``slots`` are sorted unique
        (padding -> TRASH_SLOT, whose gw must be 0). Gathers the fused
        rows itself (the "off" path's second gather, CSE'd with
        get_rows' in the composed train step) and delegates the update
        to apply_grad_rows — one definition of the push math."""
        if not has_V:
            w = _gather(state.w, slots)
            sg = _gather(state.sqrt_g, slots)
            z = _gather(state.z, slots)
            w_new, z_new, sg_new = _ftrl(w, z, sg, gw)
            return state._replace(
                w=_scatter(state.w, slots, w_new),
                sqrt_g=_scatter(state.sqrt_g, slots, sg_new),
                z=_scatter(state.z, slots, z_new))
        rows = pull_rows(state, slots)
        return apply_grad_rows(state, slots, rows, gw, gV, pull_vmask)

    def evaluate(state: SGDState) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(penalty, nnz) over real rows (Evaluate, sgd_updater.cc:15-32).
        Full-table column reads of the fused rows — once per epoch."""
        w, _, _, _, live = scal_cols(param, state)
        w = w.at[TRASH_SLOT].set(0.0)
        penalty = jnp.sum(l1 * jnp.abs(w) + 0.5 * l2 * w * w)
        nnz = jnp.sum((w != 0).astype(jnp.float32))
        if has_V:
            live = live.at[TRASH_SLOT].set(False)
            Vcol = (emb_cols_f32(param, state)[0] if quantized(param)
                    else col_V(param, state).astype(jnp.float32))
            Vm = Vcol * live[:, None]
            # quirk preserved: Evaluate charges l2 (not V_l2) on V
            penalty = penalty + jnp.sum(0.5 * l2 * Vm * Vm)
            nnz = nnz + jnp.sum(live) * param.V_dim
        return penalty, nnz

    class _NS:
        pass

    ns = _NS()
    ns.get_rows = get_rows
    ns.apply_count = apply_count
    ns.apply_grad = apply_grad
    ns.evaluate = evaluate
    ns.param = param
    # fused-kernel surface (ops/fused.py; step.py threads rows through
    # when ``fused`` is set): pull once, update the threaded rows
    ns.backend = backend
    ns.fused = backend != "off"
    ns.pull_rows = pull_rows
    ns.rows_to_params = rows_to_params
    ns.apply_grad_rows = apply_grad_rows
    return ns
