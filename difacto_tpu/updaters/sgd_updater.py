"""SGD updater: FTRL for w, AdaGrad for V, over a fixed-capacity slot table.

TPU-native re-design of the reference's server-side SGDUpdater
(src/sgd/sgd_updater.{h,cc}). The per-feature hash map of SGDEntry records
(sgd_updater.h:20-69) becomes a struct-of-arrays slot table in device memory;
per-key scalar updates (sgd_updater.cc:105-152) become vectorised gather ->
elementwise -> scatter over the batch's unique slots. **Row 0 is a reserved
trash slot**: padded/invalid entries scatter there, so every kernel runs
unconditionally with static shapes.

Exact semantics preserved:

- FTRL-proximal w update (UpdateW, sgd_updater.cc:105-131): g += l2*w;
  n' = sqrt(n^2 + g^2); z -= g - (n' - n)/lr * w; w = soft-threshold(z, l1)
  scaled by lr/(lr_beta + n').
- AdaGrad V update (UpdateV, sgd_updater.cc:133-142) with V_l2, applied only
  to rows whose embedding was *pulled* this batch (lens[i] > 1 semantics,
  sgd_updater.cc:91-96).
- Lazy V activation (InitV triggers, sgd_updater.cc:71-74,123-127): the union
  of the reference's two trigger sites is exactly
  ``v_live |= (w != 0) & (cnt > V_threshold)`` re-evaluated after every count
  or gradient update. V rows are pre-filled with the uniform init
  ``(u01 - 0.5) * V_init_scale`` (InitV, sgd_updater.cc:144-152) at state
  creation — activation just flips the flag. (Deviation: init values come
  from a counter-based PRNG per slot, not the reference's call-order-dependent
  rand_r stream; distribution is identical.)
- Pull gating (Get, sgd_updater.cc:34-58): the embedding is served only when
  live and not suppressed by ``l1_shrk`` (w == 0).
- Evaluate (sgd_updater.cc:15-32): penalty uses **l2 for the V term as well**
  (a reference quirk — UpdateV regularises with V_l2 but Evaluate charges
  l2); nnz counts V_dim for every live embedding regardless of w.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Param

TRASH_SLOT = 0  # row 0 absorbs padded scatters; never a real feature


@dataclass
class SGDUpdaterParam(Param):
    l1: float = field(default=1.0, metadata=dict(lo=0, hi=1e10))
    l2: float = field(default=0.0, metadata=dict(lo=0, hi=1e10))
    V_l2: float = field(default=0.01, metadata=dict(lo=0, hi=1e10))
    lr: float = field(default=0.01, metadata=dict(lo=0, hi=10))
    lr_beta: float = field(default=1.0, metadata=dict(lo=0, hi=1e10))
    V_lr: float = field(default=0.01, metadata=dict(lo=0, hi=1e10))
    V_lr_beta: float = field(default=1.0, metadata=dict(lo=0, hi=10))
    V_init_scale: float = field(default=0.01, metadata=dict(lo=0, hi=10))
    V_dim: int = field(default=0, metadata=dict(lo=0))
    V_threshold: int = 10
    l1_shrk: bool = True
    seed: int = 0
    # > 0 switches the store to a fixed-capacity hashed table: slot =
    # reversed_id mod (capacity-1) + 1, no host dictionary. Deterministic
    # across hosts (multi-controller requirement, parallel/multihost.py);
    # collisions alias features, the standard hashing-trick tradeoff.
    hash_capacity: int = 0
    # dictionary store only: initial slot-table rows (grows by doubling,
    # store/local.py). Lower it to bound the first HBM allocation on
    # small models — or, in tests, to force growth events.
    init_capacity: int = field(default=1 << 14, metadata=dict(lo=2))
    # storage dtype of the fused [V | Vg] embedding rows. bfloat16 halves
    # the dominant HBM traffic of the fused step (the [U, 2k] row
    # gather/scatter); compute stays float32. FTRL scalars (w/z/sqrt_g)
    # always stay float32 — z accumulates and must not round.
    V_dtype: str = field(default="float32",
                         metadata=dict(enum=["float32", "bfloat16"]))
    # pad each VVg half to a multiple of 64 elements so the fused row is a
    # multiple of the 128-lane TPU tile width. Sub-lane-width rows make the
    # per-row table scatter a misaligned read-modify-write: at V_dim=16
    # over a 4.2M-row table, the [196k, 32] scatter measured 33 ms vs
    # 15 ms for the padded [196k, 128] row — MORE bytes, half the time
    # (docs/perf_notes.md). The pad costs up to 4x VVg HBM at V_dim<=32,
    # so it auto-disables when the padded table would exceed
    # ``pad_v_rows_max_mb`` (the donated-state double plus the batch
    # cache must still fit; an 8.4M-row V16 bf16 table OOMed a 16 GB
    # chip padded but trains unpadded). Set pad_v_rows=False to force
    # the compact layout.
    pad_v_rows: bool = True
    pad_v_rows_max_mb: int = 1536


class SGDState(NamedTuple):
    """Slot-table model state; all arrays have capacity+1 rows (row 0 trash).

    The embedding values and their AdaGrad accumulators live in ONE array
    ``VVg`` (f32[C, 2h]: V in [:, :k], Vg in [:, h:h+k], with h =
    v_half(param) >= k) so the per-step gather/scatter touches a single
    wide row per feature — TPU scatter cost scales with the number of
    scattered rows, so one wide scatter beats two narrow ones (measured
    ~22 ms vs ~44 ms for 131k rows, k=64). Each half is zero-padded from
    k to h so the row is a multiple of the 128-lane tile width
    (pad_v_rows; see SGDUpdaterParam).
    """
    w: jnp.ndarray        # f32[C]
    z: jnp.ndarray        # f32[C] FTRL dual
    sqrt_g: jnp.ndarray   # f32[C] FTRL accumulated grad norm
    cnt: jnp.ndarray      # f32[C] feature occurrence counts
    VVg: jnp.ndarray      # f32[C, 2h] embeddings + AdaGrad accumulators
    v_live: jnp.ndarray   # bool[C] embedding activated

    @property
    def capacity(self) -> int:
        return self.w.shape[0]

    @property
    def V(self) -> jnp.ndarray:
        return self.VVg[:, :self.VVg.shape[1] // 2]

    @property
    def Vg(self) -> jnp.ndarray:
        return self.VVg[:, self.VVg.shape[1] // 2:]


def v_dtype(param: SGDUpdaterParam):
    return jnp.bfloat16 if param.V_dtype == "bfloat16" else jnp.float32


def v_half(param: SGDUpdaterParam, capacity: int) -> int:
    """Stored width of each VVg half at this table capacity: V_dim
    rounded up to a multiple of 64 (so the fused [V | Vg] row is a
    multiple of the 128-lane tile) when pad_v_rows and the padded table
    fits pad_v_rows_max_mb, else exactly V_dim. Kernels never call this —
    they read the layout off ``VVg.shape[1] // 2``."""
    k = param.V_dim
    if k == 0 or not param.pad_v_rows:
        return k
    h = -(-k // 64) * 64
    bytes_per_el = 2 if param.V_dtype == "bfloat16" else 4
    if capacity * 2 * h * bytes_per_el > param.pad_v_rows_max_mb << 20:
        return k
    return h


def fuse_vvg(V, Vg, h: int):
    """THE padded-row layout, in one place: [V | pad | Vg | pad] with each
    half zero-padded from k columns to h. Accepts jnp or numpy halves;
    every builder of a VVg array (init, growth re-layout, the update
    write-back, checkpoint assembly) goes through here so the layout
    cannot drift between sites."""
    k = V.shape[1]
    if h == k:
        return jnp.concatenate([V, Vg], axis=1)
    pad = jnp.zeros((V.shape[0], h - k), dtype=jnp.asarray(V).dtype)
    return jnp.concatenate([V, pad, Vg, pad], axis=1)


def init_state(param: SGDUpdaterParam, capacity: int) -> SGDState:
    k, h = param.V_dim, v_half(param, capacity)
    key = jax.random.PRNGKey(param.seed)
    V = (jax.random.uniform(key, (capacity, k), dtype=jnp.float32) - 0.5) \
        * param.V_init_scale
    def zeros():
        # distinct buffers — donate_argnums forbids aliased leaves
        return jnp.zeros(capacity, dtype=jnp.float32)
    return SGDState(
        w=zeros(), z=zeros(), sqrt_g=zeros(), cnt=zeros(),
        VVg=fuse_vvg(V, jnp.zeros((capacity, k), jnp.float32),
                     h).astype(v_dtype(param)),
        v_live=jnp.zeros(capacity, dtype=bool),
    )


def grow_state(param: SGDUpdaterParam, state: SGDState, new_capacity: int
               ) -> SGDState:
    """Double-and-copy growth; new V rows get fresh init values. Growth
    can cross the pad_v_rows_max_mb threshold, shrinking v_half back to
    V_dim — old rows are re-laid-out to the new half width."""
    old = state.capacity
    if new_capacity <= old:
        return state
    ext = init_state(param, new_capacity)
    if param.V_dim and ext.VVg.shape[1] != state.VVg.shape[1]:
        k = param.V_dim
        oh, nh = state.VVg.shape[1] // 2, ext.VVg.shape[1] // 2
        state = state._replace(VVg=fuse_vvg(
            state.VVg[:, :k], state.VVg[:, oh:oh + k], nh))
    return SGDState(*(jnp.concatenate([a, jnp.asarray(b)[old:]], axis=0)
                      for a, b in zip(state, ext)))


def _refresh_v_live(param: SGDUpdaterParam, state: SGDState) -> jnp.ndarray:
    if param.V_dim == 0:
        return state.v_live
    return state.v_live | ((state.w != 0)
                           & (state.cnt > float(param.V_threshold)))


def make_fns(param: SGDUpdaterParam):
    """Build the pure update/get functions with hyperparameters baked in
    as compile-time constants. Returns a namespace of jit-ready callables
    (not yet jit-wrapped; the store/learner composes and jits them)."""

    l1, l2 = param.l1, param.l2
    lr, lr_beta = param.lr, param.lr_beta
    V_l2, V_lr, V_lr_beta = param.V_l2, param.V_lr, param.V_lr_beta
    has_V = param.V_dim > 0

    def _gather(arr, slots):
        # the store guarantees sorted unique slots (map_keys_dedup) with
        # out-of-bounds ASCENDING padding (pad_slots) — the flags let XLA
        # skip duplicate handling in the TPU lowering (measured ~20% off
        # the fused step); padded lanes read as zeros (mode=fill)
        return arr.at[slots].get(indices_are_sorted=True,
                                 unique_indices=True,
                                 mode="fill", fill_value=0)

    def _scatter(arr, slots, rows):
        # padded (out-of-bounds) entries are dropped, real rows are unique
        return arr.at[slots].set(rows, indices_are_sorted=True,
                                 unique_indices=True, mode="drop")

    def get_rows(state: SGDState, slots: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray],
                            Optional[jnp.ndarray]]:
        """Pull [w, V, v_mask] rows for the batch's unique slots (Get)."""
        w = _gather(state.w, slots)
        if not has_V:
            return w, None, None
        vmask = _gather(state.v_live, slots)
        if param.l1_shrk:
            vmask = vmask & (w != 0)
        # gather FULL [V|Vg] rows then slice: a partial-row gather
        # (VVg[slots, :k]) lowers to a strided gather that is ~8x slower;
        # the full-row gather is CSE'd with apply_grad's in the fused step.
        # V keeps its STORAGE dtype (param.V_dtype) so the loss's per-token
        # gather can ride bf16 — the update math casts to f32 itself.
        V = _gather(state.VVg, slots)[:, :param.V_dim]
        return w, V, vmask.astype(jnp.float32)

    def apply_count(state: SGDState, slots: jnp.ndarray, counts: jnp.ndarray
                    ) -> SGDState:
        """kFeaCount push (Update, sgd_updater.cc:64-75). Sorted unique
        slots with out-of-bounds padding (dropped)."""
        cnt = state.cnt.at[slots].add(counts, indices_are_sorted=True,
                                      unique_indices=True, mode="drop")
        state = state._replace(cnt=cnt)
        return state._replace(v_live=_refresh_v_live(param, state))

    def apply_grad(state: SGDState, slots: jnp.ndarray,
                   gw: jnp.ndarray, gV: Optional[jnp.ndarray],
                   pull_vmask: Optional[jnp.ndarray]) -> SGDState:
        """kGradient push: FTRL(w) + AdaGrad(V). ``slots`` are sorted unique
        (padding -> TRASH_SLOT, whose gw must be 0)."""
        w = _gather(state.w, slots)
        sg = _gather(state.sqrt_g, slots)
        z = _gather(state.z, slots)

        g = gw + l2 * w
        sg_new = jnp.sqrt(sg * sg + g * g)
        z_new = z - (g - (sg_new - sg) / lr * w)
        eta = (lr_beta + sg_new) / lr
        w_new = jnp.where(
            jnp.abs(z_new) <= l1, 0.0,
            (z_new - jnp.sign(z_new) * l1) / eta)

        state = state._replace(
            w=_scatter(state.w, slots, w_new),
            sqrt_g=_scatter(state.sqrt_g, slots, sg_new),
            z=_scatter(state.z, slots, z_new),
        )

        if has_V and gV is not None:
            # ONE gather + ONE scatter over the fused [V | pad | Vg | pad]
            # rows; the half width rides the array shape (v_half)
            h = state.VVg.shape[1] // 2
            VVg = _gather(state.VVg, slots).astype(jnp.float32)
            V = VVg[:, :param.V_dim]
            Vg = VVg[:, h:h + param.V_dim]
            gv = gV + V_l2 * V
            Vg_new = jnp.sqrt(Vg * Vg + gv * gv)
            V_new = V - V_lr / (Vg_new + V_lr_beta) * gv
            upd = pull_vmask[:, None] > 0
            new_rows = jnp.where(upd, fuse_vvg(V_new, Vg_new, h), VVg)
            state = state._replace(
                VVg=_scatter(state.VVg, slots,
                             new_rows.astype(state.VVg.dtype)))

        return state._replace(v_live=_refresh_v_live(param, state))

    def evaluate(state: SGDState) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(penalty, nnz) over real rows (Evaluate, sgd_updater.cc:15-32)."""
        w = state.w.at[TRASH_SLOT].set(0.0)
        penalty = jnp.sum(l1 * jnp.abs(w) + 0.5 * l2 * w * w)
        nnz = jnp.sum((w != 0).astype(jnp.float32))
        if has_V:
            live = state.v_live.at[TRASH_SLOT].set(False)
            Vm = state.V * live[:, None]
            # quirk preserved: Evaluate charges l2 (not V_l2) on V
            penalty = penalty + jnp.sum(0.5 * l2 * Vm * Vm)
            nnz = nnz + jnp.sum(live) * param.V_dim
        return penalty, nnz

    class _NS:
        pass

    ns = _NS()
    ns.get_rows = get_rows
    ns.apply_count = apply_count
    ns.apply_grad = apply_grad
    ns.evaluate = evaluate
    ns.param = param
    return ns
