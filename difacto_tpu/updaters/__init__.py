from .sgd_updater import (SGDState, SGDUpdaterParam, init_state, make_fns,
                          TRASH_SLOT)

__all__ = ["SGDState", "SGDUpdaterParam", "init_state", "make_fns",
           "TRASH_SLOT"]
