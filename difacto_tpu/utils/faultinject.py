"""Fault-injection registry: named failure points, armed by environment.

The resilience layer (verified checkpoints, serve drain/hot-reload, the
retrying client) is only as good as the failure paths that exercise it —
and none of those paths occur naturally in CI. This module gives every
interesting IO boundary a *named injection point* that tests (or a chaos
run of a real cluster) arm through one environment variable:

    DIFACTO_FAULTS="point:kind@prob[:after_n][,point:kind@prob...]"

- ``point`` — a dotted site name. Current points: ``ckpt.write``,
  ``ckpt.read`` (utils/stream.py), ``serve.sock.read``,
  ``serve.sock.write`` (serve/server.py), ``batcher.enqueue``
  (serve/batcher.py), ``producer.part`` (data/producer_pool.py),
  ``step.device`` (the host-side dispatch of a fused device step,
  step.py fire_step_fault — a poisoned program / device loss stand-in),
  ``dcn.collective`` (the cross-host control-plane exchange,
  parallel/multihost.py — a dead-coordinator / partition stand-in),
  ``serve.handoff`` (the #handoff takeover control line, serve/
  server.py — a botched replica rotation stand-in), ``reload.warm``
  (each bucket of a blue/green warm loop, serve/reload.py — ``err``
  aborts the swap with the old model still serving, ``delay_ms``
  stretches the warm window for drain-race tests), ``router.forward``
  (the routing tier's backend forward path, serve/router.py — ``err``/
  ``close`` model a backend dying mid-chunk and must surface as a peer
  retry, never a client error), ``fleet.handoff`` (each replica's
  handoff step of a rolling restart, serve/fleet.py — ``err`` models a
  botched rotation and must abort the rollout with the incumbent still
  serving), ``rec.read`` (every rec2 data-cache member open,
  data/rec2.py — ``err`` is a failed disk read, ``truncate`` reads a
  half-length view which the per-section CRCs must reject as a typed
  ``RecCorrupt``, never a crash or silent short read), ``push.stale``
  (a bounded-delay host posting its per-step clock after a windowed
  push, parallel/multihost.py post_clock — ``err`` models a host
  failing mid-τ-window while peers may be staged ahead against its
  clock; the typed failure must surface through the windowed exchange
  pipeline, not wedge it), ``online.log.append`` (the serve path
  appending a served row to the online training log, online/log.py —
  ``err`` must drop only the log entry, counted in
  ``online_log_drops_total``, while the row is still answered),
  ``online.label_join`` (the delayed-label feedback join — ``err``
  surfaces as a typed ``!err`` reply to the reporting client, the
  connection stays up), ``online.seal`` (committing a full segment —
  ``err`` keeps the resolved buffer in memory and retries on the next
  advance, so a transient seal failure never loses rows),
  ``router.takeover`` (the router's ``#handoff`` roll-out-of-the-group
  path, serve/router.py — ``err`` refuses the roll before any state
  changes, the incumbent keeps routing and the group keeps serving),
  ``autoscale.spawn`` (the autoscaler's scale-up decision,
  serve/autoscale.py — ``err`` models the spawn path failing: no
  binary, no free port, quota; the decision is refused and counted in
  ``autoscale_aborts_total`` while the control loop keeps measuring),
  ``store.demote`` (a cold-tier demotion batch, capacity/tier.py —
  fired BEFORE the device fetch, so ``err`` leaves every victim row
  resident and serving; the move is fetch-then-forget and a refused
  demote loses nothing), ``store.promote`` (a cold-tier promotion
  batch — fired before the device scatter; ``err`` keeps the missing
  slots cold for this batch only, which reads zeros through the OOB
  lanes, and the next touch retries the promote), ``wal.append``
  (sealing one write-ahead delta window as a CRC'd segment,
  durability/wal.py — ``err`` fails the write and the learner RETAINS
  the window for the next flush (counted in
  ``wal_append_failures_total``; the log stores values, so a late
  segment stays correct), ``truncate`` lands a torn segment at its
  final name which replay's CRCs must reject as a typed ``WalCorrupt``,
  ``kill`` dies before any bytes land — the honest mid-window crash
  the chaos RPO leg arms), ``wal.replay`` (reading one WAL segment at
  recovery, durability/wal.py — ``err`` is a failed disk read,
  ``truncate`` reads a half-length view; both must stop replay TYPED
  at the verified prefix, a consistent earlier batch boundary, never a
  crash or silently-wrong rows), ``replica.push`` (one file copy of an
  async peer replication, durability/replicate.py — ``err`` fails the
  copy, counted in ``replica_push_failures_total``, and the
  anti-entropy scrub re-pushes it later; ``truncate`` lands a torn
  file at the peer which the scrub's verification must catch),
  ``replica.fetch`` (one file copy of a disk-loss recovery fetch —
  ``err`` is a dead/unreachable peer and must surface typed so the
  recovery ladder tries the next peer, counted in
  ``replica_fetch_failures_total``).
- ``kind`` — what happens when the fault fires:
    - ``err``      raise :class:`FaultInjected` (an OSError, so IO call
                   sites treat it exactly like a real IO failure);
    - ``truncate`` the call site tears its artifact (a checkpoint is
                   written half-length with no manifest — the torn-write
                   shape a crash mid-upload produces);
    - ``close``    the call site drops its connection mid-stream;
    - ``delay_ms`` sleep; the value rides on the kind: ``delay_ms=20``;
    - ``kill``     SIGKILL the current process — the honest crash.
- ``prob`` — firing probability in (0, 1] once armed (seeded RNG:
  deterministic per-process sequence).
- ``after_n`` — skip the first N traversals of the point, fire on the
  (N+1)-th, then re-arm (counter resets): ``serve.sock.write:close@1:30``
  closes every 31st response write. Omitted = eligible immediately.

``fire(point)`` is the single call sites make. When nothing is armed it
is one truthiness check on an empty dict — cheap enough for per-line
socket loops. When armed it handles ``err``/``delay_ms`` itself and
returns the kind for kinds the call site must sequence (``truncate``/
``close``/``kill`` — a checkpoint writer tears its artifact *before*
dying, exactly like a real SIGKILL mid-write); sites with no special
handling pass the returned kind to :func:`act_default`.

In-process tests arm/disarm with :func:`configure` (the env var is read
once at import, which is how armed subprocesses inherit the faults).
"""

from __future__ import annotations

import os
import random
import signal
import threading
import time
from typing import Dict, List, Optional
from .locktrace import mutex

KINDS = ("err", "truncate", "close", "delay_ms", "kill")


class FaultInjected(OSError):
    """An injected failure. Derives OSError so IO call sites handle it
    through the same paths a real disk/socket failure takes."""


class _Fault:
    __slots__ = ("kind", "arg", "prob", "after", "hits", "fired")

    def __init__(self, kind: str, arg: float, prob: float, after: int):
        self.kind = kind
        self.arg = arg
        self.prob = prob
        self.after = after
        self.hits = 0
        self.fired = 0


_armed: Dict[str, List[_Fault]] = {}
_mu = mutex()
_rng = random.Random()


def parse(spec: str) -> Dict[str, List[_Fault]]:
    """Parse a DIFACTO_FAULTS spec; raises ValueError on a malformed
    entry (a chaos run with a typo'd spec must fail loudly, not silently
    run fault-free)."""
    out: Dict[str, List[_Fault]] = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        try:
            point, rest = entry.split(":", 1)
            if "@" not in rest:
                raise ValueError("missing @prob")
            kindspec, probspec = rest.split("@", 1)
            after = 0
            if ":" in probspec:
                probspec, afterspec = probspec.split(":", 1)
                after = int(afterspec)
            prob = float(probspec)
            kind, arg = kindspec, 0.0
            if "=" in kindspec:
                kind, argspec = kindspec.split("=", 1)
                arg = float(argspec)
            if kind not in KINDS:
                raise ValueError(f"unknown kind {kind!r} (one of {KINDS})")
            if not (0.0 < prob <= 1.0):
                raise ValueError(f"prob {prob} outside (0, 1]")
        except ValueError as e:
            raise ValueError(
                f"bad DIFACTO_FAULTS entry {entry!r} "
                f"(want point:kind@prob[:after_n]): {e}") from e
        out.setdefault(point, []).append(_Fault(kind, arg, prob, after))
    return out


def configure(spec: Optional[str] = None, seed: int = 0xD1FAC70) -> None:
    """(Re)arm the registry. ``spec=None`` reads DIFACTO_FAULTS from the
    environment; ``spec=""`` disarms everything."""
    global _armed
    if spec is None:
        spec = os.environ.get("DIFACTO_FAULTS", "")
    _rng.seed(seed)
    # lint: ok(data-race) armed at process/test setup before traffic;
    # steady-state readers take the unarmed fast path
    _armed = parse(spec)


def armed() -> bool:
    return bool(_armed)


def fire(point: str) -> Optional[str]:
    """Traverse injection point ``point``. Returns None (no fault), or
    the kind the call site must sequence (``truncate``/``close``/
    ``kill``). ``err`` raises FaultInjected, ``delay_ms`` sleeps."""
    if not _armed:  # the unarmed fast path: one dict truthiness check
        return None
    faults = _armed.get(point)
    if not faults:
        return None
    for f in faults:
        with _mu:
            f.hits += 1
            if f.hits <= f.after:
                continue
            if f.prob < 1.0 and _rng.random() >= f.prob:
                continue
            f.fired += 1
            f.hits = 0  # re-arm: after_n skips apply to the next cycle too
        # every armed fire is observable: chaos runs watch
        # faults_fired_total{point,kind} alongside the failure it causes
        # (import deferred — this branch only runs when a fault fires)
        from ..obs import REGISTRY
        REGISTRY.counter(
            "faults_fired_total",
            "injected faults that actually fired, per point and kind"
        ).labels(point=point, kind=f.kind).inc()
        if f.kind == "delay_ms":
            time.sleep(f.arg / 1e3)
            continue
        if f.kind == "err":
            raise FaultInjected(f"injected fault at {point}")
        return f.kind  # truncate / close / kill: the call site sequences
    return None


def act_default(kind: Optional[str]) -> None:
    """Fallback for call sites without site-specific handling of a
    returned kind: ``kill`` dies here; tear/drop kinds degrade to an
    injected error (never silently ignored)."""
    if kind is None:
        return
    if kind == "kill":  # pragma: no cover - the process dies
        os.kill(os.getpid(), signal.SIGKILL)
    raise FaultInjected(f"injected fault kind {kind!r} (unhandled here)")


def stats() -> Dict[str, int]:
    """Fired counts per point — chaos tests assert the fault actually
    triggered (a test that passes because nothing fired proves nothing)."""
    with _mu:
        return {p: sum(f.fired for f in fs) for p, fs in _armed.items()}


# arm from the environment at import: subprocess chaos tests set
# DIFACTO_FAULTS before exec and need no in-process hook
configure()
