"""Runtime shared-state access tracing: the dynamic half of the race
analyzer (analysis/races.py is the static half), mirroring how
locktrace.py complements the lock-order model.

Classes whose fields the static model marks ``GuardedBy`` (or
deliberately suppresses) opt in by declaring the field through
:func:`attr` in the class body::

    class MicroBatcher:
        _rows_queued = shared.attr()
        _alive = shared.attr()

Disabled (the default), :func:`attr` returns ``None`` — the class
attribute is an inert placeholder, ``self._rows_queued = 0`` in
``__init__`` shadows it with a plain instance attribute, and steady
state pays nothing. With ``DIFACTO_RACETRACE=1`` (read at class
definition, i.e. import time) it returns a data descriptor that stores
the value under a private slot and runs Eraser's per-field state
machine on every traced get/set:

- **exclusive** — only the first-accessing thread has touched the
  field (construction; the dynamic init-before-publish hatch: these
  accesses never constrain the lockset);
- **shared** — a second thread has read it; from here the field's
  *candidate lockset* is intersected with the locks held at every
  access (locktrace's per-thread held stack — RACETRACE implies lock
  tracing);
- **shared-modified** — a write after sharing began. A
  shared-modified field whose candidate lockset is EMPTY is a dynamic
  race alarm.

``DIFACTO_RACETRACE_SAMPLE=n`` processes every n-th access per field
(cheaper for long soaks; the default 1 is already cheap — the state
machine is a dict lookup and a set intersection).

Field identity is ``relpath::Class.attr`` — byte-identical to the
static shared-state index — so the tier-1 gate (tests/test_lint.py)
can assert: every field observed in a shared state is statically
**known-safe** (consistently locked, read-only after publish, or
suppressed with a rationale), and every dynamic ALARM is a suppressed
field — anything else is a thread-root or index blind spot to fix.

``DIFACTO_RACETRACE_OUT=<path>`` dumps the field states as JSON at
process exit (same contract as DIFACTO_LOCKTRACE_OUT).
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
from pathlib import Path
from typing import Dict, FrozenSet, Optional, Set

from . import locktrace

_ROOT = Path(__file__).resolve().parents[2]

EXCLUSIVE = "exclusive"
SHARED = "shared"
SHARED_MODIFIED = "shared-modified"


class FieldState:
    """One field's Eraser state (see module docstring)."""

    __slots__ = ("first_tid", "state", "lockset", "tids", "accesses")

    def __init__(self, tid: int):
        self.first_tid = tid
        self.state = EXCLUSIVE
        self.lockset: Optional[FrozenSet[str]] = None  # None until shared
        self.tids: Set[int] = {tid}
        self.accesses = 0


_reg_mu = threading.Lock()        # guards _fields (raw on purpose)
# field -> instance id -> state. Eraser's machine runs per OBJECT: two
# MicroBatcher instances each have their own exclusive/shared life, so
# instance B's construction (another thread, no lock) must not empty
# instance A's candidate lockset. Reporting aggregates per field.
# (Instance identity is id(obj): entries outlive their objects, and an
# id reused after GC merges histories — fine for a test sentinel.)
_fields: Dict[str, Dict[int, FieldState]] = {}


def enabled() -> bool:
    return os.environ.get("DIFACTO_RACETRACE", "") not in ("", "0")


def _sample_every() -> int:
    try:
        return max(1, int(os.environ.get("DIFACTO_RACETRACE_SAMPLE",
                                         "1") or 1))
    except ValueError:
        return 1


def _note(fid: str, oid: int, write: bool) -> None:
    tid = threading.get_ident()
    held = frozenset(locktrace._held())
    n = _sample_every()
    with _reg_mu:
        insts = _fields.setdefault(fid, {})
        st = insts.get(oid)
        if st is None:
            st = insts[oid] = FieldState(tid)
        st.accesses += 1
        if n > 1 and (st.accesses - 1) % n:
            return
        st.tids.add(tid)
        if st.state == EXCLUSIVE:
            if tid == st.first_tid:
                return          # construction: unconstrained
            st.state = SHARED
        st.lockset = held if st.lockset is None else (st.lockset & held)
        if write:
            st.state = SHARED_MODIFIED


class _TracedAttr:
    """Data descriptor recording every get/set of one opted-in field.
    Takes precedence over the instance ``__dict__`` (that is what makes
    it a data descriptor), so the value lives under a private slot."""

    __slots__ = ("name", "slot", "field")

    def __set_name__(self, owner, name: str) -> None:
        self.name = name
        self.slot = f"_shared${name}"
        mod = sys.modules.get(owner.__module__)
        fn = getattr(mod, "__file__", "") or ""
        try:
            rel = Path(fn).resolve().relative_to(_ROOT).as_posix()
        except ValueError:
            rel = fn
        self.field = f"{rel}::{owner.__qualname__}.{name}"

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        _note(self.field, id(obj), False)
        try:
            return obj.__dict__[self.slot]
        except KeyError:
            raise AttributeError(self.name) from None

    def __set__(self, obj, value) -> None:
        _note(self.field, id(obj), True)
        obj.__dict__[self.slot] = value

    def __delete__(self, obj) -> None:
        _note(self.field, id(obj), True)
        try:
            del obj.__dict__[self.slot]
        except KeyError:
            raise AttributeError(self.name) from None


def attr():
    """Class-body field declaration (see module docstring). ``None``
    placeholder when disabled; a traced descriptor when
    DIFACTO_RACETRACE=1 was set before the class was defined."""
    if not enabled():
        return None
    return _TracedAttr()


# ------------------------------------------------------------------ data


_RANK = {EXCLUSIVE: 0, SHARED: 1, SHARED_MODIFIED: 2}


def fields() -> Dict[str, dict]:
    """Snapshot: field -> aggregated state record over its instances.
    ``state`` is the worst instance's; ``lockset`` is [] if ANY
    shared(-modified) instance emptied its candidate set (the alarm
    condition), else the intersection over shared instances; ``threads``
    is the busiest instance's count; ``instances`` rides along."""
    with _reg_mu:
        out: Dict[str, dict] = {}
        for f, insts in _fields.items():
            worst = max(insts.values(), key=lambda s: _RANK[s.state])
            lockset = None
            for st in insts.values():
                if st.state == EXCLUSIVE or st.lockset is None:
                    continue
                lockset = st.lockset if lockset is None \
                    else (lockset & st.lockset)
            out[f] = {
                "state": worst.state,
                "threads": max(len(s.tids) for s in insts.values()),
                "accesses": sum(s.accesses for s in insts.values()),
                "instances": len(insts),
                "lockset": (sorted(lockset)
                            if lockset is not None else None),
            }
        return out


def shared_fields() -> Dict[str, dict]:
    """Fields observed from >= 2 threads (state left ``exclusive``) —
    what the tier-1 gate checks against the static model."""
    return {f: rec for f, rec in fields().items()
            if rec["state"] != EXCLUSIVE}


def alarms() -> Dict[str, dict]:
    """Dynamic race alarms: shared-modified fields whose candidate
    lockset emptied — Eraser's report condition."""
    return {f: rec for f, rec in fields().items()
            if rec["state"] == SHARED_MODIFIED and rec["lockset"] == []}


def reset() -> None:
    with _reg_mu:
        _fields.clear()


def dump(path) -> str:
    """Write the field states as JSON; returns the path."""
    payload = {"version": 1, "fields": dict(sorted(fields().items()))}
    p = Path(path)
    if p.parent and str(p.parent) not in (".", ""):
        p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(json.dumps(payload, indent=1) + "\n", encoding="utf-8")
    return str(p)


def load(path) -> Dict[str, dict]:
    """Read a dump() file back into the fields() shape."""
    data = json.loads(Path(path).read_text(encoding="utf-8"))
    if data.get("version") != 1:
        raise ValueError(f"racetrace dump {path}: unsupported version "
                         f"{data.get('version')!r}")
    return dict(data.get("fields", {}))


def _atexit_dump() -> None:  # pragma: no cover - process teardown
    out = os.environ.get("DIFACTO_RACETRACE_OUT", "")
    if out and enabled():
        try:
            dump(out)
        except OSError as e:
            print(f"racetrace: dump to {out} failed: {e}",
                  file=sys.stderr)


atexit.register(_atexit_dump)
