"""Platform-override helper for product entry points.

The environment can pin the device platform at interpreter startup
(JAX_PLATFORMS is read once and not re-read), so an explicit
``JAX_PLATFORMS=cpu`` — the documented virtual-mesh usage, e.g. an
8-device CPU mesh via ``--xla_force_host_platform_device_count=8`` —
needs ``jax.config.update`` to take effect. Entry points call
:func:`apply_env_platform` before their first backend touch (importing
jax is fine; only device binding fixes the platform).

The multi-process test workers and tests/conftest.py keep their own
unconditional two-line preamble instead of importing this: their env
setup must run before ANY difacto_tpu import, so a helper import there
would reintroduce the ordering bug it avoids.
"""

from __future__ import annotations

import os


def apply_env_platform() -> None:
    """Honor an explicit ``JAX_PLATFORMS`` value from the environment.

    ANY non-empty value passes through to ``jax.config.update`` — not just
    ``cpu`` (the round-5 ADVICE finding: the old cpu-only check silently
    ignored e.g. ``JAX_PLATFORMS=tpu,cpu`` or a vendor platform set after
    interpreter startup, leaving the process on whatever the env pinned
    at import time). An empty/unset variable changes nothing: JAX keeps
    its own default platform selection."""
    val = os.environ.get("JAX_PLATFORMS", "").strip()
    if val:
        import jax
        jax.config.update("jax_platforms", val.lower())
