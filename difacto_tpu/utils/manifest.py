"""Checkpoint manifests: sidecar verification + generation bookkeeping.

The whole recovery story (launch.py eviction-restart, SGD auto_resume,
serve model loading) pivots on one artifact — the saved ``.npz`` — and
before this module nothing checked that the artifact was intact: a
truncated upload or a bit-flipped array crashed ``auto_resume`` deep in
numpy with no fallback. Every checkpoint writer now leaves a sidecar

    <path>.manifest.json
      {"format": 1, "generation": 7, "rows": 12345, "learner": "sgd",
       "epoch": 3, "arrays": {"w": {"sha256": ..., "dtype": "<f4",
                                    "shape": [12345]}, ...}}

written strictly AFTER the npz finalizes, so the manifest doubles as the
commit marker: a torn write (crash/SIGKILL mid-upload) leaves either no
manifest or digests that don't match, and both read as "this generation
is incomplete" instead of a crash. ``generation`` increases monotonically
across every save of the same checkpoint *family* (the prefix with
``_iter-k`` / ``_part-r`` / ``.npz`` suffixes stripped), which is what
lets loaders walk back to the newest generation that verifies and lets
``prune_checkpoints`` retire the oldest interval checkpoints.

``verify`` is the single gate: SGD ``auto_resume`` requires a manifest
(this codebase always writes one, so a missing sidecar there means a torn
save); ``task=pred``/``task=serve`` accept legacy manifest-less files but
still fail typed — :class:`CheckpointCorrupt` names the bad file and the
reason — instead of surfacing ``zipfile.BadZipFile`` from numpy's guts.
"""

from __future__ import annotations

import hashlib
import json
import logging
import re
import zipfile
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from . import stream

log = logging.getLogger("difacto_tpu")

MANIFEST_SUFFIX = ".manifest.json"
FORMAT = 1

# the per-rank / per-epoch / per-fs-shard decorations learners append to
# a model prefix (learners/sgd.py _model_name, lbfgs/bcd _ckpt_path,
# store/local.py fs_shard_path)
_DECOR_RE = re.compile(
    r"(?:_iter-\d+)?(?:_part-\d+)?(?:_fs-\d+-of-\d+)?(?:\.npz)?$")
_ITER_RE = re.compile(r"_iter-(\d+)")


class CheckpointCorrupt(RuntimeError):
    """A checkpoint failed verification: truncated/torn npz, digest
    mismatch (bit flip), or a missing/incomplete manifest where one is
    required. Carries the path and reason so the error is actionable."""

    def __init__(self, path: str, reason: str):
        self.path = path
        self.reason = reason
        super().__init__(
            f"corrupt checkpoint {path!r}: {reason}. Delete or replace "
            "the file, or point at an older generation (auto_resume and "
            "task=serve fall back to the newest verified one "
            "automatically).")


def manifest_path(uri: str) -> str:
    return uri + MANIFEST_SUFFIX


def family_prefix(uri: str) -> str:
    """The checkpoint family a file belongs to: its path with the
    ``_iter-k`` / ``_part-r`` / ``.npz`` decorations stripped. One family
    = one trained model's saves, across epochs and ranks."""
    return _DECOR_RE.sub("", uri)


def _digest(arr: np.ndarray) -> str:
    a = np.ascontiguousarray(arr)
    h = hashlib.sha256()
    h.update(str(a.dtype.str).encode())
    h.update(str(a.shape).encode())
    h.update(a.tobytes())
    return h.hexdigest()


def build(arrays: Dict[str, np.ndarray], **extra) -> dict:
    """Manifest dict for a set of named arrays. ``extra`` carries the
    writer's metadata (learner, epoch, rows, generation)."""
    man = {"format": FORMAT}
    man.update(extra)
    man["arrays"] = {
        name: {"sha256": _digest(np.asarray(a)),
               "dtype": str(np.asarray(a).dtype.str),
               "shape": list(np.asarray(a).shape)}
        for name, a in arrays.items()}
    return man


def write(uri: str, man: dict) -> None:
    with stream.open_stream(manifest_path(uri), "w") as f:
        f.write(json.dumps(man, sort_keys=True))


def read(uri: str) -> Optional[dict]:
    """The manifest for ``uri``, or None when the sidecar is absent.
    An unreadable/garbled sidecar counts as corrupt, not absent — it
    means the save tore mid-manifest."""
    mp = manifest_path(uri)
    if not stream.exists(mp):
        return None
    try:
        with stream.open_stream(mp, "r") as f:
            man = json.loads(f.read())
        if not isinstance(man, dict) or "arrays" not in man:
            raise ValueError("manifest missing 'arrays'")
        return man
    except (ValueError, OSError) as e:
        raise CheckpointCorrupt(uri, f"unreadable manifest: {e}") from e


def verify(uri: str, require_manifest: bool = False) -> Optional[dict]:
    """Verify checkpoint ``uri`` against its manifest.

    Returns the manifest dict (None for an accepted legacy manifest-less
    file). Raises FileNotFoundError when the npz itself is missing (so
    existence probes keep their semantics) and CheckpointCorrupt on any
    verification failure. With ``require_manifest`` a missing sidecar is
    itself corruption — the right contract for files this codebase wrote
    (save always leaves a manifest, so its absence means a torn save).
    """
    if not stream.isfile(uri):
        raise FileNotFoundError(uri)
    man = read(uri)
    if man is None:
        if require_manifest:
            raise CheckpointCorrupt(
                uri, "manifest missing — incomplete (torn) checkpoint, "
                     "or a file not written by a difacto save")
        # legacy file: no digests to check, but at least require a
        # readable zip so numpy's BadZipFile never escapes untyped
        try:
            with stream.load_npz(uri) as z:
                z.files
        except FileNotFoundError:
            raise
        except Exception as e:
            raise CheckpointCorrupt(uri, f"unreadable npz: {e}") from e
        return None
    try:
        with stream.load_npz(uri) as z:
            names = set(z.files)
            for name, info in man["arrays"].items():
                if name not in names:
                    raise CheckpointCorrupt(
                        uri, f"array {name!r} listed in manifest but "
                             "missing from npz (truncated write)")
                a = z[name]
                if _digest(a) != info["sha256"]:
                    raise CheckpointCorrupt(
                        uri, f"array {name!r} sha256 mismatch (bit flip "
                             "or partial write)")
    except CheckpointCorrupt:
        raise
    except FileNotFoundError:
        raise
    except (zipfile.BadZipFile, ValueError, KeyError, OSError,
            EOFError) as e:
        raise CheckpointCorrupt(uri, f"unreadable npz: {e}") from e
    return man


class VerifiedNpz:
    """Single-pass verifying npz reader: digests members AS the caller
    loads them instead of a separate verify pass over the whole file.

    ``verify`` + ``load`` used to read every array twice (the manifest
    verify pass, then the real load) — ~2x the checkpoint read IO, which
    on a multi-hundred-MB model over a remote filesystem is the dominant
    startup cost. Here ``__getitem__`` hashes each manifest-listed array
    the moment it is decompressed for the load and compares digests in
    place; :meth:`finish` then hashes only the members the load never
    touched (e.g. optimizer state skipped by a weights-only load), so
    every byte is read exactly once and the CheckpointCorrupt contract
    is IDENTICAL to verify(): truncation, digest mismatch and a missing
    required manifest all raise the same typed error.

    Callers use it as a context manager; a clean ``with`` exit runs
    ``finish()`` implicitly (an exceptional exit does not — the caller's
    error wins). Call ``finish()`` explicitly BEFORE committing loaded
    state when corruption must not leave partial mutations behind.
    """

    def __init__(self, uri: str, require_manifest: bool = False,
                 fault_point: str = ""):
        if not stream.isfile(uri):
            raise FileNotFoundError(uri)
        self.uri = uri
        self.manifest = read(uri)  # raises on a garbled sidecar
        if self.manifest is None and require_manifest:
            raise CheckpointCorrupt(
                uri, "manifest missing — incomplete (torn) checkpoint, "
                     "or a file not written by a difacto save")
        try:
            self._npz = stream.load_npz(uri, fault_point=fault_point)
            self._names = set(self._npz.files)
        except (FileNotFoundError, CheckpointCorrupt):
            raise
        except Exception as e:
            from . import faultinject
            if isinstance(e, faultinject.FaultInjected):
                raise  # chaos-injected IO failures keep their type
            raise CheckpointCorrupt(uri, f"unreadable npz: {e}") from e
        self._checked: set = set()
        self._finished = False

    @property
    def files(self):
        return self._npz.files

    def __contains__(self, name: str) -> bool:
        return name in self._names

    def __getitem__(self, name: str):
        try:
            a = self._npz[name]
        except KeyError:
            raise
        except Exception as e:
            raise CheckpointCorrupt(
                self.uri, f"array {name!r} unreadable: {e}") from e
        man = self.manifest
        if man is not None and name not in self._checked:
            info = man["arrays"].get(name)
            if info is not None and _digest(np.asarray(a)) != info["sha256"]:
                raise CheckpointCorrupt(
                    self.uri, f"array {name!r} sha256 mismatch (bit flip "
                              "or partial write)")
            self._checked.add(name)
        return a

    def finish(self) -> Optional[dict]:
        """Digest every manifest-listed member the caller did not load
        (their bytes are read once, here). Idempotent; returns the
        manifest (None for an accepted legacy file)."""
        if self._finished:
            return self.manifest
        self._finished = True
        if self.manifest is None:
            return None
        for name in self.manifest["arrays"]:
            if name in self._checked:
                continue
            if name not in self._names:
                raise CheckpointCorrupt(
                    self.uri, f"array {name!r} listed in manifest but "
                              "missing from npz (truncated write)")
            self[name]
        return self.manifest

    def close(self) -> None:
        try:
            self._npz.close()
        except Exception as e:  # pragma: no cover - np.load handles vary
            log.debug("npz close failed for %s: %s", self.uri, e)

    def __enter__(self) -> "VerifiedNpz":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        try:
            if exc_type is None:
                self.finish()
        finally:
            self.close()


def open_verified(uri: str, require_manifest: bool = False,
                  fault_point: str = "") -> VerifiedNpz:
    """Open ``uri`` for a hash-while-loading verified read (see
    :class:`VerifiedNpz`) — the one-IO-pass replacement for the
    ``verify(uri)`` + ``load_npz(uri)`` pair."""
    return VerifiedNpz(uri, require_manifest=require_manifest,
                       fault_point=fault_point)


# ------------------------------------------------------- generations

def _family_manifests(uri: str) -> List[Tuple[int, str]]:
    """[(generation, npz_path)] for every manifest in ``uri``'s family,
    newest generation first. Unreadable sidecars are skipped (they will
    fail verify later anyway)."""
    fam = family_prefix(uri)
    out = []
    for mp in stream.glob(fam + "*" + MANIFEST_SUFFIX):
        base = mp[:-len(MANIFEST_SUFFIX)]
        if family_prefix(base) != fam:
            continue  # a longer sibling prefix globbed in
        try:
            with stream.open_stream(mp, "r") as f:
                man = json.loads(f.read())
            gen = int(man.get("generation", 0))
        except (ValueError, OSError, KeyError):
            continue
        if man.get("fs_shard") is not None:
            # per-key-range shard members (store/local.py fs_shard_path)
            # are not load entry points: their generation's walk-back
            # candidate is the undecorated stub, whose own load verifies
            # every shard member
            continue
        out.append((gen, base))
    out.sort(key=lambda t: (-t[0], t[1]))
    return out


def next_generation(uri: str) -> int:
    """The monotonically-increasing generation number the next save of
    this family should stamp (max existing + 1; first save = 1)."""
    gens = _family_manifests(uri)
    return (gens[0][0] + 1) if gens else 1


def generation_paths(uri: str) -> List[str]:
    """Checkpoint paths of ``uri``'s family, newest generation first —
    the walk-back order for loaders recovering from a corrupt file."""
    return [p for _, p in _family_manifests(uri)]


def prune_checkpoints(model_prefix: str, keep: int,
                      rank: Optional[int] = None,
                      protect: Optional[Iterable[int]] = None
                      ) -> List[str]:
    """Retire interval checkpoints older than the newest ``keep`` epochs
    of ``model_prefix``'s family. Only ``_iter-k`` files are candidates —
    the final (undecorated) model is never pruned. With ``rank`` set only
    that rank's ``_part-<rank>`` files are removed (each host prunes what
    it wrote; no cross-host delete races). Returns the removed paths.

    ``protect`` exempts specific epochs from retirement regardless of
    age: the durability layer passes the epoch a live WAL chain is
    rooted at and any epoch an in-flight replica push still references
    (durability/wal.py, durability/replicate.py) — pruning either would
    orphan the delta chain (replay has no base to apply onto) or tear
    the copy a peer is mid-receive on. The retention-count semantics
    are otherwise unchanged: protected epochs don't consume ``keep``
    slots, they are simply skipped until their chain rebase / push
    completion releases them (the next prune retires them normally)."""
    if keep <= 0:
        return []
    protected = frozenset(int(e) for e in (protect or ()))
    fam = family_prefix(model_prefix)
    by_epoch: Dict[int, List[str]] = {}
    for path in stream.glob(fam + "_iter-*"):
        if path.endswith(MANIFEST_SUFFIX):
            continue
        m = _ITER_RE.search(path)
        if m is None:
            continue
        if rank is not None and f"_part-{rank}" not in path[m.end():]:
            continue
        by_epoch.setdefault(int(m.group(1)), []).append(path)
    removed = []
    for epoch in sorted(by_epoch)[:-keep]:
        if epoch in protected:
            continue
        for path in by_epoch[epoch]:
            for p in (path, manifest_path(path)):
                try:
                    stream.remove(p)
                    if p == path:
                        removed.append(p)
                except (FileNotFoundError, OSError):
                    pass
    return removed
