"""Compiled-HLO collective/memory scan: the runtime half of the
sharding-flow analyzer (analysis/shardflow.py is the static half),
mirroring how jaxtrace.py complements jaxflow, locktrace the lock-order
model and shared.py the race model.

The static pass proves the *source* threads the fs layout (pins,
no axis-breakers, no replication) — but GSPMD partitioning happens at
compile time, and the compiled HLO is the only artifact that cannot
lie: if output-layout inference decided to re-gather the key-range-
sharded table, there is an ``all-gather`` (or ``all-to-all``) with the
table's full row count in its shape sitting in ``compiled.as_text()``,
and ``compiled.memory_analysis()`` shows the blown temp arena.

With ``DIFACTO_HLOSCAN=1`` every jit program created through
``utils/jaxtrace.jit``/``pjit`` (the tracer is implied on — jaxtrace
``enabled()`` honors this knob too) is lowered and compiled ONCE per
new argument signature BEFORE the real call (lowering only reads
avals, so donation is unaffected), and the scan records, per jit-site
identity (the same ``relpath:lineno`` jaxtrace and jaxflow use):

- every collective in the optimized HLO (kind + the shape dims on its
  line), with ``all-gather``/``all-to-all`` carrying the table's row
  count (``DIFACTO_HLOSCAN_ROWS``) classified **table-axis** — the
  sharded capacity axis moved whole across the mesh;
- ``memory_analysis()`` byte counts, checked against the per-program
  peak-temp budget ``DIFACTO_HLOSCAN_BUDGET`` (bytes; 0 = no budget).

``DIFACTO_HLOSCAN_OUT=<path>`` dumps the scan as JSON at process exit
(same contract as DIFACTO_JAXTRACE_OUT). ``tools/hlomap.py`` merges
the dump with the static shardflow model — ``--check`` fails CI on any
table-axis collective, budget breach, or dynamic site outside the
static model; the tier-1 gate (tests/test_hloscan.py) drives the fs=4
train step and serve executor through it on the CPU virtual mesh.

Scan mode compiles each new signature twice (the scan's
``lower().compile()`` plus the real dispatch) — a diagnostic-mode cost,
never paid when disabled (the default: everything here short-circuits
on one env read).
"""

from __future__ import annotations

import atexit
import json
import os
import re
import sys
import threading
from pathlib import Path
from typing import Dict, List, Optional

_mu = threading.Lock()
_programs: Dict[str, dict] = {}     # site -> scan record
_seen: Dict[str, set] = {}          # site -> arg signatures scanned

# one optimized-HLO line, e.g.
#   %all-gather = f32[512,4]{1,0} all-gather(f32[128,4]{1,0} %p), ...
_COLLECTIVE_RE = re.compile(
    r"\b(all-gather|all-to-all|all-reduce|reduce-scatter|"
    r"collective-permute)[\w.-]*\(")
_SHAPE_RE = re.compile(r"\[([0-9][0-9,]*)\]")

# only these move an axis whole across the mesh; all-reduce /
# reduce-scatter combine VALUES and are expected (gradient combines)
_TABLE_AXIS_KINDS = ("all-gather", "all-to-all")


def enabled() -> bool:
    return os.environ.get("DIFACTO_HLOSCAN", "") not in ("", "0")


def table_rows() -> int:
    """The full (unsharded) table row count whose appearance in an
    all-gather/all-to-all shape marks a table-axis collective; 0 (the
    default) disables the classification."""
    try:
        return int(os.environ.get("DIFACTO_HLOSCAN_ROWS", "0"))
    except ValueError:
        return 0


def temp_budget() -> int:
    """Per-program peak temp-arena budget in bytes; 0 = no budget."""
    try:
        return int(os.environ.get("DIFACTO_HLOSCAN_BUDGET", "0"))
    except ValueError:
        return 0


def scan_text(text: str, rows: int = 0) -> List[dict]:
    """All collectives in an (optimized) HLO dump: ``{kind, dims,
    table_axis, line}`` per occurrence. ``table_axis`` is True for an
    all-gather/all-to-all whose line carries a shape dimension equal to
    ``rows`` — the sharded capacity axis re-materialized whole."""
    out = []
    for line in text.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if not m:
            continue
        kind = m.group(1)
        dims = sorted({int(d) for g in _SHAPE_RE.findall(line)
                       for d in g.split(",") if d})
        out.append({
            "kind": kind,
            "dims": dims,
            "table_axis": bool(rows) and kind in _TABLE_AXIS_KINDS
            and rows in dims,
            "line": line.strip()[:200],
        })
    return out


def _memory(compiled) -> dict:
    try:
        ma = compiled.memory_analysis()
    except Exception as e:                           # pragma: no cover
        # some backends ship executables without memory stats; the
        # collective scan must still run, so note it and move on
        print(f"hloscan: memory_analysis unavailable: {e}",
              file=sys.stderr)
        return {}
    out = {}
    for k in ("temp_size_in_bytes", "argument_size_in_bytes",
              "output_size_in_bytes", "alias_size_in_bytes"):
        v = getattr(ma, k, None)
        if v is not None:
            out[k] = int(v)
    return out


def scan_compiled(compiled, rows: Optional[int] = None,
                  budget: Optional[int] = None, label: str = "") -> dict:
    """Scan ONE compiled executable (no registry side effect):
    collectives + memory_analysis + the table-axis/budget verdicts.
    ``rows``/``budget`` default to the env knobs — callers that know
    their own table geometry (parallel/capacity.py legs) pass them."""
    rows = table_rows() if rows is None else rows
    budget = temp_budget() if budget is None else budget
    colls = scan_text(compiled.as_text(), rows)
    mem = _memory(compiled)
    temp = mem.get("temp_size_in_bytes", 0)
    return {
        "label": label,
        "collectives": colls,
        "table_collectives": sum(1 for c in colls if c["table_axis"]),
        "memory": mem,
        "peak_temp_bytes": temp,
        "over_budget": bool(budget) and temp > budget,
        "signatures": 1,
    }


def record(site: str, compiled, label: str = "",
           rows: Optional[int] = None,
           budget: Optional[int] = None) -> dict:
    """Scan one compiled executable under the jit-site identity
    ``site`` and remember the worst view per site (collectives union,
    max temp bytes across signatures)."""
    rec = scan_compiled(compiled, rows=rows, budget=budget, label=label)
    colls = rec["collectives"]
    temp = rec["peak_temp_bytes"]
    with _mu:
        prev = _programs.get(site)
        if prev is not None:
            rec["collectives"] = prev["collectives"] + colls
            rec["table_collectives"] += prev["table_collectives"]
            rec["peak_temp_bytes"] = max(temp, prev["peak_temp_bytes"])
            rec["over_budget"] = rec["over_budget"] or prev["over_budget"]
            rec["signatures"] = prev["signatures"] + 1
            if not rec["label"]:
                rec["label"] = prev["label"]
        _programs[site] = rec
    return rec


def scan_fn(site: str, fn, args: tuple, kwargs: Optional[dict] = None,
            label: str = "", rows: Optional[int] = None,
            budget: Optional[int] = None) -> Optional[dict]:
    """Lower+compile ``fn`` on ``args`` and :func:`record` it — the
    explicit entry capacity.py and the tests use. Returns the record,
    or None when ``fn`` cannot lower (pallas inner callables)."""
    if not hasattr(fn, "lower"):
        return None
    compiled = fn.lower(*args, **(kwargs or {})).compile()
    return record(site, compiled,
                  label or getattr(fn, "__name__", ""),
                  rows=rows, budget=budget)


def _sig(args: tuple, kwargs: dict) -> tuple:
    def leaf(a):
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is not None and dtype is not None:
            return ("a", tuple(shape), str(dtype))
        if isinstance(a, (tuple, list)):
            return ("t", tuple(leaf(x) for x in a))
        return ("o", type(a).__name__)
    return tuple(leaf(a) for a in args) + tuple(
        (k, leaf(kwargs[k])) for k in sorted(kwargs))


def maybe_scan(site: str, fn, args: tuple, kwargs: dict) -> None:
    """The jaxtrace ``_TracedJit.__call__`` pre-call hook: scan once
    per (site, argument signature), and never let a scan failure break
    the run it is observing."""
    if not enabled():
        return
    try:
        sig = _sig(args, kwargs)
        with _mu:
            seen = _seen.setdefault(site, set())
            if sig in seen:
                return
            seen.add(sig)
        scan_fn(site, fn, args, kwargs)
    except Exception as e:                           # pragma: no cover
        print(f"hloscan: scan of {site} failed: {e}", file=sys.stderr)


# ----------------------------------------------------------------- data


def programs() -> Dict[str, dict]:
    """Snapshot: jit site -> scan record."""
    with _mu:
        return {s: dict(rec) for s, rec in _programs.items()}


def violations(progs: Optional[Dict[str, dict]] = None) -> List[dict]:
    """Gate view: one entry per table-axis collective or budget breach
    in ``progs`` (default: the live snapshot)."""
    progs = programs() if progs is None else progs
    out = []
    for site, rec in sorted(progs.items()):
        for c in rec.get("collectives", []):
            if c.get("table_axis"):
                out.append({"site": site, "kind": "table-collective",
                            "detail": f"{c['kind']} {c['dims']}"})
        if rec.get("over_budget"):
            out.append({"site": site, "kind": "temp-budget",
                        "detail": f"peak_temp_bytes="
                                  f"{rec.get('peak_temp_bytes')}"})
    return out


def reset() -> None:
    with _mu:
        _programs.clear()
        _seen.clear()


def dump(path) -> str:
    """Write the scan as JSON (stamped with the knobs that shaped it);
    returns the path."""
    payload = {
        "version": 1,
        "rows": table_rows(),
        "budget": temp_budget(),
        "programs": dict(sorted(programs().items())),
    }
    p = Path(path)
    if p.parent and str(p.parent) not in (".", ""):
        p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(json.dumps(payload, indent=1) + "\n", encoding="utf-8")
    return str(p)


def load(path) -> dict:
    """Read a dump() back: {'rows', 'budget', 'programs'}."""
    data = json.loads(Path(path).read_text(encoding="utf-8"))
    if data.get("version") != 1:
        raise ValueError(f"hloscan dump {path}: unsupported version "
                         f"{data.get('version')!r}")
    return {"rows": int(data.get("rows", 0)),
            "budget": int(data.get("budget", 0)),
            "programs": dict(data.get("programs", {}))}


def _atexit_dump() -> None:  # pragma: no cover - process teardown
    out = os.environ.get("DIFACTO_HLOSCAN_OUT", "")
    if out and enabled():
        try:
            dump(out)
        except OSError as e:
            print(f"hloscan: dump to {out} failed: {e}", file=sys.stderr)


atexit.register(_atexit_dump)
