"""Training progress records.

Equivalent of sgd::Progress (src/sgd/sgd_utils.h:52-110): raw sums of
{nrows, loss, auc, penalty, nnz_w} merged by elementwise add; the printer
divides by nrows. Also the throttled live progress row
(Report_prog::PrintStr, sgd_utils.h:97-110).
"""

from __future__ import annotations

from dataclasses import dataclass, fields


@dataclass
class Progress:
    nrows: float = 0.0
    loss: float = 0.0
    auc: float = 0.0
    penalty: float = 0.0
    nnz_w: float = 0.0

    def merge(self, other: "Progress") -> "Progress":
        for f in fields(self):
            setattr(self, f.name,
                    getattr(self, f.name) + getattr(other, f.name))
        return self

    def reset(self) -> None:
        for f in fields(self):
            setattr(self, f.name, 0.0)

    def text(self) -> str:
        n = max(self.nrows, 1.0)
        s = (f"Rows = {self.nrows:g}, loss = {self.loss / n:.6f}, "
             f"AUC = {self.auc / n:.6f}")
        return s


class ReportProg:
    """Accumulating live progress printer (sgd_utils.h:97-110)."""

    def __init__(self) -> None:
        self.prog = Progress()
        self.total_rows = 0.0
        self.total_nnz = 0.0

    def print_str(self) -> str:
        self.total_rows += self.prog.nrows
        self.total_nnz += self.prog.nnz_w
        n = max(self.prog.nrows, 1.0)
        s = (f"{self.total_rows:9.4g}  {self.prog.nrows:7.2g} | "
             f"{self.total_nnz:9.4g} | {self.prog.loss / n:6.4f}  "
             f"{self.prog.auc / n:7.5f} ")
        self.prog.reset()
        return s
