"""Profiling: wall-clock section timers + JAX device profiler hooks.

The reference only had ``dmlc::GetTime`` wall-clock spans (epoch timer
sgd_learner.cc:55,145; per-part times in WorkloadPool) and the spmv_perf
harness. Here:

- :class:`Timer` — named cumulative wall-clock sections (host side);
- :func:`device_trace` — context manager around ``jax.profiler.trace``
  producing a TensorBoard/XProf trace of the XLA execution (the TPU-native
  answer to "where did the step time go").
"""

from __future__ import annotations

import contextlib
import logging
import time
from collections import defaultdict
from typing import Dict, Iterator

log = logging.getLogger("difacto_tpu")


class Timer:
    """Cumulative named sections: ``with timer("pull"): ...``; report()."""

    def __init__(self) -> None:
        self.total: Dict[str, float] = defaultdict(float)
        self.count: Dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def __call__(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.total[name] += time.perf_counter() - t0
            self.count[name] += 1

    def report(self) -> str:
        rows = sorted(self.total.items(), key=lambda kv: -kv[1])
        return "\n".join(
            f"{name:24s} {tot:8.3f}s  x{self.count[name]}"
            for name, tot in rows)


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture a device profile into ``log_dir`` (view with xprof/
    TensorBoard). No-op shield: profiling failures never break training."""
    import jax
    started = False
    try:
        jax.profiler.start_trace(log_dir)
        started = True
    except Exception as e:  # pragma: no cover - backend-dependent
        log.debug("device trace unavailable: %s", e)
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # pragma: no cover
                log.debug("stop_trace failed: %s", e)
