"""Progress reporter: the out-of-band node -> scheduler channel.

Equivalent of the reference's Reporter (include/difacto/reporter.h:14-56;
LocalReporter src/reporter/local_reporter.h). In the single-controller design
the "channel" is a callback, but the contract is kept — components call
``report(payload)``, whoever set the monitor receives it — so learners and
stores stay decoupled from the progress consumer, and a multi-host build can
swap in a DCN-backed implementation without touching them. The reference's
servers auto-report every 50 pushes (include/difacto/store.h:118-123);
``every`` reproduces that throttle.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional
from .locktrace import mutex


class Reporter:
    def __init__(self, every: int = 1):
        self._monitor: Optional[Callable[[int, Any], None]] = None
        self._mu = mutex()
        self._count = 0
        self._every = max(every, 1)

    def set_monitor(self, fn: Callable[[int, Any], None]) -> None:
        """fn(node_id, payload)."""
        self._monitor = fn

    def report(self, payload: Any, node_id: int = 0) -> int:
        """Deliver payload to the monitor (throttled); returns a sequence
        number like the reference's report timestamp."""
        with self._mu:
            self._count += 1
            seq = self._count
        if self._monitor is not None and seq % self._every == 0:
            self._monitor(node_id, payload)
        return seq
