"""URI streams: local files + fsspec-backed remote filesystems.

The TPU-native equivalent of dmlc-core's ``Stream``/``InputSplit`` IO layer
(SURVEY §2.9): the reference reads training data and writes models over
``hdfs://`` URIs through dmlc Streams (example/yarn.conf, run_yarn.sh); here
any ``scheme://`` URI routes through fsspec (``gs://``, ``s3://``,
``hdfs://``, ``memory://`` for tests, ...), while plain paths use the
standard library so local behavior is byte-identical and dependency-free.

All helpers accept either form. fsspec is only imported when a remote URI is
actually used, so environments without it keep working for local paths.
"""

from __future__ import annotations

import glob as _glob
import io
import os
from typing import IO, List, Optional

import numpy as np


def is_remote(uri: str) -> bool:
    """True for scheme://-style URIs (except file://, which is local)."""
    if "://" not in uri:
        return False
    return not uri.startswith("file://")


def _strip_file_scheme(uri: str) -> str:
    return uri[len("file://"):] if uri.startswith("file://") else uri


def _fs(uri: str):
    """(fsspec filesystem, path) for a remote URI."""
    try:
        import fsspec
    except ImportError as e:  # pragma: no cover - fsspec is in the image
        raise ImportError(
            f"remote URI {uri!r} requires fsspec (pip install fsspec)") from e
    return fsspec.core.url_to_fs(uri)


def _scheme(uri: str) -> str:
    return uri.split("://", 1)[0] + "://"


def _ensure_parent(path: str) -> None:
    """Create a local write target's missing parent directories
    (model_out/pred_out prefixes point into run directories that may not
    exist yet; fsspec remote writes already auto-mkdir)."""
    if os.path.dirname(path):
        os.makedirs(os.path.dirname(path), exist_ok=True)


def open_stream(uri: str, mode: str = "rb") -> IO:
    """Open a local path or remote URI for reading/writing. Local writes
    create missing parent directories."""
    if is_remote(uri):
        fs, path = _fs(uri)
        return fs.open(path, mode)
    path = _strip_file_scheme(uri)
    if "w" in mode or "a" in mode:
        _ensure_parent(path)
    return open(path, mode)


def exists(uri: str) -> bool:
    if is_remote(uri):
        fs, path = _fs(uri)
        return fs.exists(path)
    return os.path.exists(_strip_file_scheme(uri))


def isdir(uri: str) -> bool:
    if is_remote(uri):
        fs, path = _fs(uri)
        return fs.isdir(path)
    return os.path.isdir(_strip_file_scheme(uri))


def isfile(uri: str) -> bool:
    if is_remote(uri):
        fs, path = _fs(uri)
        return fs.isfile(path)
    return os.path.isfile(_strip_file_scheme(uri))


def listdir(uri: str) -> List[str]:
    """Sorted full paths (URIs stay URIs) of entries in a directory."""
    if is_remote(uri):
        fs, path = _fs(uri)
        sch = _scheme(uri)
        return sorted(sch + p.lstrip("/") if not p.startswith(sch) else p
                      for p in fs.ls(path, detail=False))
    path = _strip_file_scheme(uri)
    return sorted(os.path.join(path, f) for f in os.listdir(path))


def listdir_files(uri: str) -> List[tuple]:
    """Sorted [(path, size)] for regular files in a directory — ONE remote
    listing call (fs.ls detail=True), vs a stat per file; a gs:// dir of
    thousands of parts would otherwise pay serial round-trips for isfile +
    getsize each."""
    if is_remote(uri):
        fs, path = _fs(uri)
        sch = _scheme(uri)
        out = []
        for e in fs.ls(path, detail=True):
            if e.get("type") == "file":
                name = e["name"]
                if not name.startswith(sch):
                    name = sch + name.lstrip("/")
                out.append((name, int(e.get("size") or 0)))
        return sorted(out)
    path = _strip_file_scheme(uri)
    return sorted((e.path, e.stat().st_size) for e in os.scandir(path)
                  if e.is_file())


def glob(uri: str) -> List[str]:
    if is_remote(uri):
        fs, path = _fs(uri)
        sch = _scheme(uri)
        return sorted(sch + p.lstrip("/") for p in fs.glob(path))
    return sorted(_glob.glob(_strip_file_scheme(uri)))


def getsize(uri: str) -> int:
    if is_remote(uri):
        fs, path = _fs(uri)
        return fs.size(path)
    return os.path.getsize(_strip_file_scheme(uri))


def makedirs(uri: str) -> None:
    if is_remote(uri):
        fs, path = _fs(uri)
        fs.makedirs(path, exist_ok=True)
        return
    os.makedirs(_strip_file_scheme(uri), exist_ok=True)


def remove(uri: str) -> None:
    """Delete a file (checkpoint pruning, tmp-key cleanup)."""
    if is_remote(uri):
        fs, path = _fs(uri)
        fs.rm(path)
        return
    os.remove(_strip_file_scheme(uri))


def getmtime(uri: str) -> float:
    """Last-modified time (seconds); 0.0 when the backend can't say —
    the serve hot-reload watcher treats mtime as a hint and falls back
    to manifest generations."""
    if is_remote(uri):
        fs, path = _fs(uri)
        try:
            return fs.modified(path).timestamp()
        except (NotImplementedError, AttributeError, OSError):
            return 0.0
    return os.path.getmtime(_strip_file_scheme(uri))


def join(uri: str, *parts: str) -> str:
    if is_remote(uri):
        return "/".join([uri.rstrip("/"), *parts])
    return os.path.join(_strip_file_scheme(uri), *parts)


def save_npz(uri: str, compress: bool = True, manifest: Optional[dict] = None,
             fault_point: str = "", **arrays) -> None:
    """Atomic npz write: local goes through tmp+rename; remote uploads to
    a ``<path>.tmp`` key then finalizes with a server-side move, so a
    reader can never observe a half-uploaded object under the real key
    (the old single-put left exactly that window).

    ``manifest`` (extra metadata: learner/epoch/rows/generation) turns on
    the checkpoint-verification sidecar: ``<path>.manifest.json`` with
    per-array sha256 digests is written strictly AFTER the npz finalizes,
    so it doubles as the commit marker — a crash between the two leaves a
    checkpoint that loaders treat as incomplete (utils/manifest.py).

    ``fault_point`` names the chaos-harness injection point to traverse
    (utils/faultinject.py): ``truncate`` tears the artifact (half-length
    final bytes, no manifest — the shape a crash mid-upload produces) and
    ``kill`` tears it then SIGKILLs, which is what the mid-checkpoint
    crash test arms.
    """
    from . import faultinject
    kind = faultinject.fire(fault_point) if fault_point else None
    save = np.savez_compressed if compress else np.savez
    if is_remote(uri):
        buf = io.BytesIO()
        save(buf, **arrays)
        data = buf.getvalue()
        if kind in ("truncate", "kill"):
            _torn_write(uri, data, kind)
            return
        tmp = uri + ".tmp"
        with open_stream(tmp, "wb") as f:
            f.write(data)
        fs, path = _fs(uri)
        _, tmp_path = _fs(tmp)
        try:
            fs.mv(tmp_path, path)
        except (AttributeError, NotImplementedError):  # pragma: no cover
            fs.copy(tmp_path, path)
            fs.rm(tmp_path)
    else:
        path = _strip_file_scheme(uri)
        _ensure_parent(path)
        tmp = path + ".tmp.npz"  # .npz suffix stops savez appending its own
        save(tmp, **arrays)
        if kind in ("truncate", "kill"):
            with open(tmp, "rb") as f:
                data = f.read()
            os.remove(tmp)
            _torn_write(path, data, kind)
            return
        os.replace(tmp, path)
    if manifest is not None:
        from . import manifest as _mft
        _mft.write(uri, _mft.build(
            {k: np.asarray(v) for k, v in arrays.items()}, **manifest))


def _torn_write(uri: str, data: bytes, kind: str) -> None:
    """Injected torn write: half the bytes land under the FINAL name
    (bypassing the tmp+rename discipline — this is the failure that
    discipline exists to prevent), no manifest follows, and ``kill``
    then takes the process down like a real SIGKILL mid-checkpoint."""
    with open_stream(uri, "wb") as f:
        f.write(data[:max(len(data) // 2, 1)])
    if kind == "kill":  # pragma: no cover - the process dies here
        import signal
        os.kill(os.getpid(), signal.SIGKILL)


def load_npz(uri: str, fault_point: str = ""):
    """np.load over a stream; caller uses it as a context manager. Remote
    files are fetched into memory first (np.load needs a seekable file and
    npz member access does many small reads). ``fault_point`` traverses a
    chaos-harness injection point (``err`` surfaces as the same OSError a
    failing disk/network read raises)."""
    if fault_point:
        from . import faultinject
        faultinject.act_default(faultinject.fire(fault_point))
    if is_remote(uri):
        with open_stream(uri, "rb") as f:
            return np.load(io.BytesIO(f.read()))
    return np.load(_strip_file_scheme(uri))
