"""Runtime jit-compile and device->host transfer tracing: the dynamic
half of the JAX flow analyzer (analysis/jaxflow.py is the static half),
mirroring how locktrace.py complements the lock-order model and
shared.py the race model.

Every steady-state-relevant jit program in the tree is created through
:func:`jit` instead of bare ``jax.jit``, and every *sanctioned*
device->host sync goes through :func:`fetch` instead of bare
``np.asarray``. Disabled (the default), both are pass-throughs —
``jit`` returns the raw ``jax.jit`` wrapper, ``fetch`` is one extra
function call around ``np.asarray`` — zero steady-state overhead.

With ``DIFACTO_JAXTRACE=1``:

- ``jit`` wraps the compiled function and records, per **creation
  site** (``relpath:lineno`` of the ``jit(...)`` call — byte-identical
  to the static analyzer's jit-site identity), the call count, the
  authoritative compile count (the wrapper's own jit cache size, so
  weak-typed scalar arguments never over-count), and the set of
  observed *compile keys*: static-argnum values by value, traced
  arrays by ``(shape, dtype)``, Python scalars by type (weak-typed —
  a new float value is NOT a new compile);
- ``fetch`` records each device->host transfer per call site. A
  transfer at a site the static model does not list as a declared sync
  point — or any implicit coercion that never went through ``fetch``
  and therefore shows up as compile-cache-stable wall time instead —
  is what the jax-host-sync rule exists to catch.

That shared identity is the point: the tier-1 gate (tests/
test_jaxflow.py) drives the serve path under ``DIFACTO_JAXTRACE=1``
and asserts (a) every observed jit site is a site the static model
knows and declares warm-bounded, (b) compiles STOP GROWING once the
bucket caps are warm — the "zero steady-state recompiles" claim,
previously only bench-measured — and (c) every observed transfer in
the dispatch loop is a declared fetch point. ``tools/jitmap.py``
merges both views for humans (``make jitmap``).

``DIFACTO_JAXTRACE_OUT=<path>`` dumps the observed sites as JSON at
process exit (same contract as DIFACTO_LOCKTRACE_OUT /
DIFACTO_RACETRACE_OUT).
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
from pathlib import Path
from typing import Dict, Optional, Tuple

import numpy as np

# repo root: difacto_tpu/utils/jaxtrace.py -> two parents up from the
# package directory; sites are stored relative to it so they match the
# static analyzer's repo-relative paths (same convention as locktrace)
_ROOT = Path(__file__).resolve().parents[2]

_reg_mu = threading.Lock()          # guards _sites/_fetches (raw on purpose)
_sites: Dict[str, "_SiteStats"] = {}
_fetches: Dict[str, Dict[str, int]] = {}   # site -> {point, count}


class _SiteStats:
    __slots__ = ("calls", "compiles", "keys", "label")

    def __init__(self, label: str):
        self.calls = 0
        self.compiles = 0
        self.keys: set = set()
        self.label = label


def enabled() -> bool:
    # DIFACTO_HLOSCAN implies tracing: the HLO scan (utils/hloscan.py)
    # rides the same _TracedJit wrappers and jit-site identities, so
    # turning it on must install them even without DIFACTO_JAXTRACE
    return os.environ.get("DIFACTO_JAXTRACE", "") not in ("", "0") \
        or os.environ.get("DIFACTO_HLOSCAN", "") not in ("", "0")


def _site(depth: int = 2) -> str:
    fr = sys._getframe(depth)
    fn = fr.f_code.co_filename
    try:
        rel = Path(fn).resolve().relative_to(_ROOT).as_posix()
    except ValueError:
        rel = fn
    return f"{rel}:{fr.f_lineno}"


def _arg_key(args: tuple, kwargs: dict, statics: frozenset) -> tuple:
    """Approximate jit cache key: statics by VALUE, arrays by aval
    signature, Python scalars by TYPE (weak-typed: a new float value is
    not a new compile). Only used for the jitmap key display — the
    compile count itself comes from the jit cache size, which is
    authoritative."""
    out = []
    for i, a in enumerate(args):
        if i in statics:
            try:
                hash(a)
                out.append(("s", a))
            except TypeError:
                out.append(("s!", type(a).__name__))
        else:
            out.append(_leaf_key(a))
    for k in sorted(kwargs):
        out.append((k, _leaf_key(kwargs[k])))
    return tuple(out)


def _leaf_key(a):
    if a is None or isinstance(a, (bool,)):
        return ("c", a)
    if isinstance(a, (int, float, complex, str, bytes)):
        return ("py", type(a).__name__)        # weak-typed scalar
    shape = getattr(a, "shape", None)
    dtype = getattr(a, "dtype", None)
    if shape is not None and dtype is not None:
        return ("a", tuple(shape), str(dtype))
    if isinstance(a, (tuple, list)):
        return ("t", tuple(_leaf_key(x) for x in a))
    # pytrees (namedtuples land in the tuple branch above; dataclass
    # pytrees summarize by type — shapes inside don't vary in this tree)
    return ("o", type(a).__name__)


class _TracedJit:
    """Callable wrapper stamping per-site call/compile counts. Forwards
    attribute access to the underlying jit wrapper so callers can still
    reach lower()/clear_cache()/etc."""

    __slots__ = ("_fn", "site", "_statics")

    def __init__(self, fn, site: str, statics: frozenset):
        self._fn = fn
        self.site = site
        self._statics = statics

    def __call__(self, *args, **kwargs):
        # hloscan first: lowering only reads avals, so scanning BEFORE
        # the real dispatch keeps donated buffers untouched
        from . import hloscan
        if hloscan.enabled():
            hloscan.maybe_scan(self.site, self._fn, args, kwargs)
        out = self._fn(*args, **kwargs)
        key = _arg_key(args, kwargs, self._statics)
        try:
            compiled = int(self._fn._cache_size())
        except (AttributeError, TypeError):
            compiled = -1              # fall back to key-set cardinality
        with _reg_mu:
            st = _sites[self.site]
            st.calls += 1
            st.keys.add(key)
            st.compiles = compiled if compiled >= 0 else len(st.keys)
        return out

    def __getattr__(self, name):
        return getattr(self._fn, name)


def _wrap(fun, jit_kwargs: dict, site: str):
    """Shared jit/pjit body: build the jax.jit wrapper and, when
    tracing, stamp it with the CALLER's creation-site identity."""
    import jax

    wrapped = jax.jit(fun, **jit_kwargs)
    if not enabled():
        return wrapped
    statics = jit_kwargs.get("static_argnums", ())
    if isinstance(statics, int):
        statics = (statics,)
    label = getattr(fun, "__name__", type(fun).__name__)
    with _reg_mu:
        _sites.setdefault(site, _SiteStats(label))
    return _TracedJit(wrapped, site, frozenset(statics))


def jit(fun, **jit_kwargs):
    """``jax.jit(fun, **jit_kwargs)``, traced when DIFACTO_JAXTRACE=1.

    The jit-site identity is the creation site of THIS call
    (``relpath:lineno``), byte-identical to the static jaxflow model's
    site ids — that is what lets the tier-1 gate compare observed
    compiles against the statically declared warm set."""
    return _wrap(fun, jit_kwargs, _site())


def pjit(fun, **jit_kwargs):
    """Sharded-jit creation with the SAME site identity contract as
    :func:`jit`: ``jax.jit`` has absorbed pjit, so this forwards
    ``in_shardings``/``out_shardings``/statics/donation to jax.jit —
    but the call is *named* pjit so the static analyzer's jit-site
    discovery (analysis/jaxflow.py ``_is_jit_name`` matches ``pjit`` /
    ``*.pjit``) and this tracer agree on one ``relpath:lineno``
    identity for the program. Mesh-sharded train/serve programs created
    through here stay inside the jax-recompile / donation / host-sync
    gates instead of dodging them behind a differently-named wrapper."""
    return _wrap(fun, jit_kwargs, _site())


def pallas_call(kernel, **kw):
    """``pl.pallas_call(kernel, **kw)`` with the SAME creation-site
    identity contract as :func:`jit`/:func:`pjit`: the ``relpath:lineno``
    of THIS call is the site id, byte-identical to the static analyzer's
    pallas-site discovery (analysis/jaxflow.py ``_is_pallas_name``), so
    the fused-kernel programs (ops/fused.py) stay inside the
    recompile/donation/host-sync gates and ``make jitmap`` shows them.

    Unlike a jit wrapper, the returned callable runs at TRACE time of
    its enclosing jit program — so its per-site call count approximates
    the number of enclosing-program compiles that baked this kernel in
    (steady state: the count stops growing with the bucket caps, same
    acceptance as the jit sites)."""
    from jax.experimental import pallas as pl

    inner = pl.pallas_call(kernel, **kw)
    if not enabled():
        return inner
    site = _site()
    label = getattr(kernel, "__name__", type(kernel).__name__)
    with _reg_mu:
        _sites.setdefault(site, _SiteStats(label))
    return _TracedJit(inner, site, frozenset())


def fetch(x, point: str = "") -> np.ndarray:
    """A DECLARED device->host sync: ``np.asarray(x)``, counted per
    call site when DIFACTO_JAXTRACE=1. The static analyzer treats
    ``jaxtrace.fetch(...)`` as the sanctioned coercion of device values
    on the hot path (analysis/jaxflow.py jax-host-sync) — implicit
    ``float()``/``np.asarray`` syncs there are findings; this is how a
    deliberate one is written down and audited at runtime."""
    if not enabled():
        return np.asarray(x)
    site = _site()
    with _reg_mu:
        per = _fetches.setdefault(site, {"point": point, "count": 0})
        per["count"] += 1
    return np.asarray(x)


# ----------------------------------------------------------------- data


def sites() -> Dict[str, dict]:
    """Snapshot: jit site -> {label, calls, compiles, keys}."""
    with _reg_mu:
        return {
            s: {"label": st.label, "calls": st.calls,
                "compiles": st.compiles,
                "keys": sorted(repr(k) for k in st.keys)}
            for s, st in _sites.items()
        }


def fetches() -> Dict[str, dict]:
    """Snapshot: fetch site -> {point, count}."""
    with _reg_mu:
        return {s: dict(rec) for s, rec in _fetches.items()}


def reset() -> None:
    with _reg_mu:
        _sites.clear()
        _fetches.clear()


def dump(path) -> str:
    """Write the observed jit/transfer sites as JSON; returns the path."""
    payload = {
        "version": 1,
        "sites": dict(sorted(sites().items())),
        "fetches": dict(sorted(fetches().items())),
    }
    p = Path(path)
    if p.parent and str(p.parent) not in (".", ""):
        p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(json.dumps(payload, indent=1) + "\n", encoding="utf-8")
    return str(p)


def load(path) -> dict:
    """Read a dump() file back: {'sites': {...}, 'fetches': {...}}."""
    data = json.loads(Path(path).read_text(encoding="utf-8"))
    if data.get("version") != 1:
        raise ValueError(f"jaxtrace dump {path}: unsupported version "
                         f"{data.get('version')!r}")
    return {"sites": dict(data.get("sites", {})),
            "fetches": dict(data.get("fetches", {}))}


def _atexit_dump() -> None:  # pragma: no cover - process teardown
    out = os.environ.get("DIFACTO_JAXTRACE_OUT", "")
    if out and enabled():
        try:
            dump(out)
        except OSError as e:
            print(f"jaxtrace: dump to {out} failed: {e}", file=sys.stderr)


atexit.register(_atexit_dump)
