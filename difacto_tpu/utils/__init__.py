from .progress import Progress, ReportProg

__all__ = ["Progress", "ReportProg"]
