"""Runtime lock-order tracing: the dynamic half of the concurrency
analyzer (analysis/concurrency.py is the static half).

Every lock in the tree is created through :func:`mutex` /
:func:`rmutex` / :func:`condition` instead of bare ``threading.Lock()``.
Disabled (the default), the factories return the raw ``threading``
primitive — zero steady-state overhead, one extra function call at
construction. With ``DIFACTO_LOCKTRACE=1`` they return a traced wrapper
that records, per thread, the stack of currently-held locks and — on
every successful acquire — one *acquisition-order edge* per already-held
lock: ``(held creation site) -> (acquired creation site)``.

Lock identity is the **creation site** (``relpath:lineno`` of the
``mutex()`` call), which is byte-identical to the static analyzer's
declaration-site identity: all instances of ``self._mu = mutex()``
collapse onto one node in both graphs, so the two can be compared
edge-for-edge. That comparison is the point:

- the tier-1 gate (tests/test_lint.py) asserts every OBSERVED edge is a
  subgraph of the static lock-order graph — a dynamic edge the static
  model missed means a callgraph blind spot to fix, never to ignore;
- ``tools/lockmap.py`` merges both graphs into DOT/JSON so a human can
  see which static edges real executions confirm.

The edge store is process-global and thread-safe (its own raw lock —
never traced, it would recurse). ``dump``/``load`` round-trip the edges
as JSON; ``DIFACTO_LOCKTRACE_OUT=<path>`` dumps automatically at
process exit, so a whole pytest run can feed lockmap.
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
from pathlib import Path
from typing import Dict, List, Optional, Tuple

# repo root: difacto_tpu/utils/locktrace.py -> two parents up from the
# package directory; creation sites are stored relative to it so they
# match the static analyzer's repo-relative paths
_ROOT = Path(__file__).resolve().parents[2]

_reg_mu = threading.Lock()          # guards _edges/_sites (raw on purpose)
_edges: Dict[Tuple[str, str], int] = {}
_sites: Dict[str, str] = {}         # site -> kind (Lock/RLock/Condition)
_tls = threading.local()


def enabled() -> bool:
    # DIFACTO_RACETRACE implies lock tracing: the shared-state access
    # tracer (utils/shared.py) records each access's held-lock stack,
    # which only exists while the factories hand out traced wrappers
    return (os.environ.get("DIFACTO_LOCKTRACE", "") not in ("", "0")
            or os.environ.get("DIFACTO_RACETRACE", "") not in ("", "0"))


def _site(depth: int = 2) -> str:
    fr = sys._getframe(depth)
    fn = fr.f_code.co_filename
    try:
        rel = Path(fn).resolve().relative_to(_ROOT).as_posix()
    except ValueError:
        rel = fn
    return f"{rel}:{fr.f_lineno}"


def _held() -> List[str]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _note_acquire(site: str) -> None:
    held = _held()
    new = []
    for h in held:
        if h != site and (h, site) not in new:
            new.append((h, site))
    if new:
        with _reg_mu:
            for e in new:
                _edges[e] = _edges.get(e, 0) + 1
    held.append(site)


def _note_release(site: str) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] == site:
            del held[i]
            return


class _Traced:
    """Context-manager lock wrapper stamping acquisition-order edges.
    Forwards the full Lock/RLock protocol; ``Condition(lock)`` works
    because it only needs acquire/release (the _is_owned fallback probes
    with a zero-timeout acquire)."""

    __slots__ = ("_lk", "site")

    def __init__(self, lk, site: str):
        self._lk = lk
        self.site = site

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # the wrapper forwards the primitive's own acquire; acquire/
        # release pairing is the CALLER'S contract, checked at their site
        # lint: ok(lock-release) forwarding wrapper, pairing checked at callers
        ok = self._lk.acquire(blocking, timeout)
        if ok:
            _note_acquire(self.site)
        return ok

    def release(self) -> None:
        self._lk.release()
        _note_release(self.site)

    def locked(self) -> bool:
        return self._lk.locked()

    def __enter__(self) -> bool:
        # lint: ok(lock-release) __enter__ half of the context protocol
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


def _register(site: str, kind: str) -> str:
    with _reg_mu:
        _sites.setdefault(site, kind)
    return site


def mutex():
    """``threading.Lock()``, traced when DIFACTO_LOCKTRACE=1."""
    if not enabled():
        return threading.Lock()
    return _Traced(threading.Lock(), _register(_site(), "Lock"))


def rmutex():
    """``threading.RLock()``, traced when DIFACTO_LOCKTRACE=1 (repeat
    acquisitions of one site record no self edges)."""
    if not enabled():
        return threading.RLock()
    return _Traced(threading.RLock(), _register(_site(), "RLock"))


def condition():
    """``threading.Condition`` over a (possibly traced) fresh lock."""
    if not enabled():
        return threading.Condition()
    return threading.Condition(
        _Traced(threading.Lock(), _register(_site(), "Condition")))


# ----------------------------------------------------------------- data


def edges() -> Dict[Tuple[str, str], int]:
    """Snapshot of the observed acquisition-order edges -> count."""
    with _reg_mu:
        return dict(_edges)


def sites() -> Dict[str, str]:
    with _reg_mu:
        return dict(_sites)


def reset() -> None:
    with _reg_mu:
        _edges.clear()
        _sites.clear()


def dump(path) -> str:
    """Write the observed graph as JSON; returns the path."""
    with _reg_mu:
        payload = {
            "version": 1,
            "sites": dict(sorted(_sites.items())),
            "edges": [{"src": a, "dst": b, "count": c}
                      for (a, b), c in sorted(_edges.items())],
        }
    p = Path(path)
    if p.parent and str(p.parent) not in (".", ""):
        p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(json.dumps(payload, indent=1) + "\n", encoding="utf-8")
    return str(p)


def load(path) -> dict:
    """Read a dump() file back: {'sites': {...}, 'edges': {(a,b): n}}."""
    data = json.loads(Path(path).read_text(encoding="utf-8"))
    if data.get("version") != 1:
        raise ValueError(f"locktrace dump {path}: unsupported version "
                         f"{data.get('version')!r}")
    return {"sites": dict(data.get("sites", {})),
            "edges": {(e["src"], e["dst"]): int(e.get("count", 1))
                      for e in data.get("edges", [])}}


def _atexit_dump() -> None:  # pragma: no cover - process teardown
    out = os.environ.get("DIFACTO_LOCKTRACE_OUT", "")
    if out and enabled():
        try:
            dump(out)
        except OSError as e:
            print(f"locktrace: dump to {out} failed: {e}",
                  file=sys.stderr)


atexit.register(_atexit_dump)
