"""Distributed vector-free L-BFGS learner.

TPU-native re-design of the reference's src/lbfgs/ (lbfgs_learner.{h,cc},
lbfgs_updater.h). The reference splits state between workers (data tiles,
loss grad) and servers (weights, s/y history, two-loop); here everything is
dense device arrays under one controller:

- the model is ONE flat vector ``weights[N]`` in the reference's exact
  variable-length layout ``[w_i, V_i...]`` per kept feature
  (lbfgs_updater.h:45-56) plus a trailing trash/pad region (zeros), so the
  two-loop inner products are plain dots over the same coordinates;
- the training data is cached as device tiles (COO chunks + per-tile
  position arrays ``w_pos``/``V_pos`` into the flat vector — the analog of
  TileStore colmaps + GetPos, lbfgs_learner.cc:293-313);
- f/∇f = a jit pass over tiles accumulating a dense gradient via
  scatter-add (CalcGrad's two-level thread pool, lbfgs_learner.cc:237-291);
- the Gram matrix B of [s, y, g] is one einsum; the two-loop coefficients
  are solved in float64 on host (learners/twoloop.py) — the 6m+1 inner
  products the reference allreduced across servers become XLA reductions.

The scheduler state machine (RunScheduler, lbfgs_learner.cc:14-108) is kept
step for step: PrepareData -> InitServer -> InitWorker -> per epoch
{PushGradient, PrepareCalcDirection, CalcDirection, Wolfe line search with
backtracking rho, Evaluate}, with identical stop criteria and the same
epoch-0 alpha heuristic ntrain/nnz.
"""

from __future__ import annotations

import dataclasses
import logging
from dataclasses import dataclass, field
from typing import Callable, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..base import FEAID_DTYPE
from ..config import KWArgs, Param
from ..data import Reader
from ..losses import FMParams, fm_grad, fm_predict, logit_objv
from ..losses.metrics import auc_times_n_jnp
from ..ops.batch import DeviceBatch, bucket, pad_batch
from ..ops.kv import expand_ranges, find_position
from ..utils import jaxtrace
from .base import Learner, register

log = logging.getLogger("difacto_tpu")


@dataclass
class LBFGSLearnerParam(Param):
    """src/lbfgs/lbfgs_param.h:10-77."""
    data_in: str = ""
    data_val: str = ""
    data_format: str = "libsvm"
    data_cache: str = ""
    model_out: str = ""
    model_in: str = ""
    loss: str = "fm"
    max_num_epochs: int = 100
    min_num_epochs: int = 10
    data_chunk_size: float = 256  # MB
    stop_rel_objv: float = 1e-5
    stop_val_auc: float = 1e-5
    load_epoch: int = 0
    init_alpha: float = 0.0  # 0 = ntrain/nnz heuristic (lbfgs_learner.cc:49)
    alpha: float = 1.0
    c1: float = 1e-4
    c2: float = 0.9
    rho: float = 0.5
    gamma: float = 1.0
    max_num_linesearchs: int = 5
    num_threads: int = 0  # accepted for config parity; XLA owns threading
    # shard the flat [w, V...] vector (and every grad/direction/s/y vector)
    # over an fs-axis device mesh — the TPU analog of the reference's
    # key-range server sharding for L-BFGS (lbfgs_updater.h:45-56): the
    # 6m+1 Gram inner products the reference allreduces across servers
    # (SendJobAndWait vector-add, src/common/learner_utils.h:21-51) become
    # XLA psums over the sharded axis. 1 = single device.
    mesh_fs: int = 1
    # cap on HBM held by device tiles (0 = keep every tile resident, the
    # round-3 behavior); evicted tiles rebuild on demand from the host
    # blocks (the reference streams tiles from TileStore/DataStore,
    # src/lbfgs/lbfgs_learner.cc:237-291; round-3 verdict #7)
    tile_cache_mb: int = 1024


@dataclass
class LBFGSUpdaterParam(Param):
    """src/lbfgs/lbfgs_param.h:79-104. V_dim is required (no dmlc default)."""
    V_dim: int = -1
    V_threshold: int = 0
    V_init_scale: float = 0.01
    tail_feature_filter: int = 4
    l2: float = 0.1
    V_l2: float = 0.01
    m: int = 10
    seed: int = 0


class LBFGSProgress(NamedTuple):
    """lbfgs::Progress (src/lbfgs/lbfgs_utils.h:45-63)."""
    objv: float = 0.0
    auc: float = 0.0
    val_auc: float = 0.0
    nnz_w: float = 0.0


class Tile(NamedTuple):
    """A cached device chunk: COO batch + positions into the flat vector."""
    batch: DeviceBatch
    w_pos: jnp.ndarray   # i32[U_cap] position of w (trash slot if filtered)
    v_pos: jnp.ndarray   # i32[U_cap] position of V start (safe if masked)
    v_mask: jnp.ndarray  # f32[U_cap] 1 where the feature has an embedding


@register("lbfgs")
class LBFGSLearner(Learner):
    def __init__(self) -> None:
        super().__init__()
        self.param: Optional[LBFGSLearnerParam] = None
        self.weight_initializer: Optional[Callable] = None
        self.epoch_end_callbacks: List[Callable[[int, LBFGSProgress], None]] \
            = []

    # ----------------------------------------------------------- init
    def init(self, kwargs: KWArgs) -> KWArgs:
        self.param, remain = LBFGSLearnerParam.init_allow_unknown(kwargs)
        self.uparam, remain = LBFGSUpdaterParam.init_allow_unknown(remain)
        if self.uparam.V_dim < 0:
            raise ValueError("V_dim is required for the lbfgs learner")
        if self.param.loss == "logit":
            self.uparam = dataclasses.replace(self.uparam, V_dim=0)
        self.k = self.uparam.V_dim
        # multi-host: each host reads its byte range and accumulates
        # partial (objv, auc, grad) over its local tiles; the raw sums
        # meet in a DCN allreduce — the reference's workers pushing
        # partial gradients that the servers sum
        # (src/lbfgs/lbfgs_learner.cc:121-125). All hosts then run the
        # identical two-loop/Wolfe math on identical inputs.
        self._num_hosts = jax.process_count()
        self._host_rank = jax.process_index()
        # dead-host detection for the DCN reductions (parallel/fault.py)
        from ..parallel import fault
        self.monitor = fault.from_env(self._host_rank, self._num_hosts)
        self.mesh = None
        if self.param.mesh_fs > 1 and self._num_hosts > 1:
            raise ValueError(
                "lbfgs multi-host runs shard DATA across hosts; in-host "
                "vector sharding (mesh_fs > 1) is single-host only — "
                "set mesh_fs=1 under launch.py")
        if self.param.mesh_fs > 1:
            from jax.sharding import NamedSharding, PartitionSpec
            from ..parallel import make_mesh
            from ..parallel.mesh import FS_AXIS
            self.mesh = make_mesh(dp=1, fs=self.param.mesh_fs)
            self._vec_shard = NamedSharding(self.mesh,
                                            PartitionSpec(FS_AXIS))
            from ..parallel import replicated
            self._repl = replicated(self.mesh)
        self._build_steps()
        return remain

    def _put_vec(self, arr) -> jnp.ndarray:
        """Place a flat-layout vector: fs-sharded under a mesh, else local."""
        if self.mesh is None:
            return jnp.asarray(arr)
        return jax.device_put(jnp.asarray(arr), self._vec_shard)

    def set_weight_initializer(self, fn: Callable) -> None:
        """fn(lens: int32[n_feat], weights: f32[N]) -> f32[N] — the
        deterministic-init hook (SetWeightInitializer, lbfgs_updater.h:27-32),
        used by the golden tests in place of the C rand_r stream."""
        self.weight_initializer = fn

    # ----------------------------------------------------------- data prep
    def _prepare_data(self) -> None:
        """PrepareData (lbfgs_learner.cc:146-194): read once through the
        shared TileBuilder — localize each chunk, keep the compact blocks,
        accumulate the global (id, count) dictionary."""
        from ..data.tile_builder import TileBuilder
        p = self.param
        chunk = int(p.data_chunk_size * (1 << 20))
        part_idx, num_parts = 0, 1
        if self._num_hosts > 1:
            from ..parallel.multihost import host_part
            part_idx, num_parts = host_part()
        tb = TileBuilder()
        for blk in Reader(p.data_in, p.data_format, part_idx, num_parts,
                          chunk_bytes=chunk):
            tb.add(blk, is_train=True)
        if p.data_val:
            for blk in Reader(p.data_val, p.data_format, part_idx,
                              num_parts, chunk_bytes=chunk):
                tb.add(blk, is_train=False)
        self._builder = tb
        self._raw_train = [(cb, u) for cb, u, t in tb.tiles if t]
        self._raw_val = [(cb, u) for cb, u, t in tb.tiles if not t]
        self.ntrain, self.nval = tb.nrows_train, tb.nrows_val
        self.train_nnz = tb.nnz_train
        if self._num_hosts > 1:
            self._merge_global_dict(tb)
        self.feaids, self.feacnts = tb.ids, tb.cnts
        log.info("found %d training examples, %d features",
                 self.ntrain, len(tb.ids))

    def _merge_global_dict(self, tb) -> None:
        """Union the per-host dictionaries so every host lays out the
        IDENTICAL global [w, V...] vector (the reference's servers own a
        global key space; InitServer, lbfgs_updater.h:35-56); row/nnz
        totals sum (int64-safe: criteo-scale nnz exceeds int32)."""
        from ..parallel.multihost import allreduce_np, global_kv_union
        tb.ids, tb.cnts = global_kv_union(tb.ids, tb.cnts)
        tot = allreduce_np(np.array(
            [self.ntrain, self.nval, self.train_nnz], dtype=np.int64),
            self.monitor)
        self.ntrain, self.nval, self.train_nnz = (int(t) for t in tot)

    def _init_model(self) -> float:
        """InitServer + InitWorker (lbfgs_updater.h:35-77,
        lbfgs_learner.cc:196-219): tail filter, [w, V...] layout, V init.
        Returns r(w0); also builds tiles and the regularizer vector."""
        up = self.uparam
        self.feaids = self._builder.filter_tail(up.tail_feature_filter)
        self.feacnts = self._builder.cnts
        nf = len(self.feaids)
        if up.V_dim > 0:
            lens = 1 + np.where(self.feacnts > up.V_threshold, up.V_dim, 0)
        else:
            lens = np.ones(nf, dtype=np.int64)
        self.lens = lens.astype(np.int32)
        offsets = np.zeros(nf + 1, dtype=np.int64)
        np.cumsum(lens, out=offsets[1:])
        self.offsets = offsets
        self.N = int(offsets[-1])
        # trailing trash/pad region; last V_dim+1 slots reserved so trash
        # V rows stay in bounds
        self.N_pad = bucket(self.N + up.V_dim + 1, self._dim_min())
        self.trash_w = self.N_pad - 1
        self.trash_v = self.N_pad - 1 - up.V_dim

        w = np.zeros(self.N_pad, dtype=np.float32)
        if self.weight_initializer is not None:
            w[:self.N] = self.weight_initializer(
                self.lens, w[:self.N].copy())
        elif up.V_dim > 0:
            # uniform V init (InitWeight, lbfgs_updater.h:60-70); counter
            # PRNG instead of the reference's call-order rand_r stream
            rng = np.random.RandomState(up.seed)
            vals = (rng.rand(self.N) - 0.5) * (2 * up.V_init_scale)
            is_w = np.zeros(self.N, dtype=bool)
            is_w[offsets[:-1]] = True
            w[:self.N] = np.where(is_w, 0.0, vals)

        self._refresh_layout_constants()
        self.weights = self._put_vec(w)

        self._n_tiles = {"train": len(self._raw_train),
                         "val": len(self._raw_val)}
        if self.param.tile_cache_mb > 0:
            # bounded HBM: device tiles live in a byte-budgeted LRU and
            # rebuild from the kept host blocks on miss
            from ..data.tile_store import TileCache
            self._tile_cache = TileCache(
                lambda which, i: self._build_tile(
                    *(self._raw_train if which == "train"
                      else self._raw_val)[i]),
                max_bytes=self.param.tile_cache_mb << 20)
        else:
            self._tile_cache = None
            self._res_tiles = {
                "train": [self._build_tile(cb, u)
                          for cb, u in self._raw_train],
                "val": [self._build_tile(cb, u) for cb, u in self._raw_val],
            }
            del self._raw_train, self._raw_val

    def _iter_tiles(self, which: str):
        if self._tile_cache is None:
            yield from self._res_tiles[which]
            return
        for i in range(self._n_tiles[which]):
            yield self._tile_cache.fetch(which, i)

    def _refresh_layout_constants(self) -> None:
        """(Re)derive the device constants tied to the flat layout: the
        per-coordinate regularizer (l2 on w positions, V_l2 on V) and the
        real-parameter count. Every path that changes N/N_pad/offsets must
        call this — these ride as runtime jit arguments precisely so a
        layout change can never leave stale trace-time copies behind."""
        c = np.zeros(self.N_pad, dtype=np.float32)
        c[:self.N] = self.uparam.V_l2
        c[self.offsets[:-1]] = self.uparam.l2
        self.reg_c = self._put_vec(c)
        self._n_real = jnp.asarray(self.N, dtype=jnp.int32)

    def _dim_min(self) -> int:
        """Bucket floor for the flat vector: divisible by the fs axis."""
        if self.mesh is None:
            return 8
        from ..ops.batch import mesh_dim_min
        return mesh_dim_min(self.param.mesh_fs)

    def _warm_start(self, path: str) -> int:
        """Copy checkpoint weights into the current layout (model_in warm
        start, lbfgs_param.h model_in). Features present in both with the
        same row length take the saved values; the rest keep their init."""
        from ..utils import stream
        with stream.load_npz(self._ckpt_path(path)) as z:
            if int(z["V_dim"]) != self.k:
                raise ValueError("checkpoint V_dim mismatch")
            ck_ids, ck_lens, ck_w = z["feaids"], z["lens"], z["weights"]
        ck_off = np.zeros(len(ck_ids) + 1, dtype=np.int64)
        np.cumsum(ck_lens, out=ck_off[1:])
        pos = find_position(ck_ids.astype(FEAID_DTYPE), self.feaids)
        ok = (pos >= 0) & (ck_lens[np.maximum(pos, 0)] == self.lens)
        if not ok.any():
            return 0
        src_rows = pos[ok].astype(np.int64)
        lens = self.lens[ok].astype(np.int64)
        src_idx = expand_ranges(ck_off[src_rows], lens)
        dst_idx = expand_ranges(self.offsets[:-1][ok], lens)
        w = np.asarray(self.weights).copy()
        w[dst_idx] = ck_w[src_idx]
        self.weights = self._put_vec(w)
        return int(ok.sum())

    def _build_tile(self, cblk, uniq: np.ndarray) -> Tile:
        """BuildColmap + GetPos (tile_builder.h:115-183,
        lbfgs_learner.cc:293-313): map tile features to flat positions."""
        colmap = find_position(self.feaids, uniq)
        hit = colmap >= 0
        w_pos = np.full(len(uniq), self.trash_w, dtype=np.int64)
        w_pos[hit] = self.offsets[colmap[hit]]
        has_v = hit & (self.lens[np.maximum(colmap, 0)] > 1)
        v_pos = np.full(len(uniq), self.trash_v, dtype=np.int64)
        v_pos[has_v] = w_pos[has_v] + 1
        u_cap = bucket(len(uniq))
        batch = pad_batch(cblk, num_uniq=len(uniq),
                          batch_cap=bucket(cblk.size),
                          nnz_cap=bucket(cblk.nnz))

        def pad(a, fill):
            out = np.full(u_cap, fill, dtype=a.dtype)
            out[:len(a)] = a
            return out

        tile = Tile(
            batch=batch,
            w_pos=jnp.asarray(pad(w_pos.astype(np.int32),
                                  np.int32(self.trash_w))),
            v_pos=jnp.asarray(pad(v_pos.astype(np.int32),
                                  np.int32(self.trash_v))),
            v_mask=jnp.asarray(pad(has_v.astype(np.float32), np.float32(0))),
        )
        if self.mesh is not None:
            # tiles ride replicated over the mesh; only the flat vector is
            # fs-sharded, so the tile gathers/scatters become the XLA
            # collectives of the Push/Pull (SURVEY §7 step 7)
            tile = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, self._repl), tile)
        return tile

    # ----------------------------------------------------------- jit steps
    def _build_steps(self) -> None:
        k = self.k
        gamma = self.param.gamma

        def gather_params(weights, tile: Tile) -> FMParams:
            w = weights[tile.w_pos]
            V = None
            if k > 0:
                V = (weights[tile.v_pos[:, None]
                             + jnp.arange(k)[None, :]]
                     * tile.v_mask[:, None])
            return FMParams(w=w, V=V, v_mask=tile.v_mask if k else None)

        def tile_grad(weights, grad, tile: Tile):
            """objv/auc on this tile; scatter loss grad into the flat vec."""
            params = gather_params(weights, tile)
            pred = fm_predict(params, tile.batch)
            objv = logit_objv(pred, tile.batch)
            auc = auc_times_n_jnp(tile.batch.labels, pred,
                                  tile.batch.row_mask)
            gw, gV = fm_grad(params, tile.batch, pred)
            grad = grad.at[tile.w_pos].add(gw)
            if gV is not None:
                grad = grad.at[tile.v_pos[:, None]
                               + jnp.arange(k)[None, :]].add(
                    gV * tile.v_mask[:, None])
            return objv, auc, grad

        def tile_pred_auc(weights, tile: Tile):
            params = gather_params(weights, tile)
            pred = fm_predict(params, tile.batch)
            return auc_times_n_jnp(tile.batch.labels, pred,
                                   tile.batch.row_mask)

        def finish_grad(grad, n):
            """gamma transform (CalcGrad, lbfgs_learner.cc:283-286) +
            clear the trash region so dots/axpys see zeros there. ``n`` (the
            real-parameter count) rides as a runtime argument — baking self.N
            in at trace time goes stale if run()/load() re-initializes the
            model on the same learner instance."""
            if gamma != 1:
                grad = jnp.sign(grad) * jnp.abs(grad) ** gamma
            return jnp.where(jnp.arange(grad.shape[0]) < n, grad, 0.0)

        def reg_objv(weights, reg_c):
            return 0.5 * jnp.sum(reg_c * weights * weights)

        def reg_grad(weights, reg_c):
            return reg_c * weights

        self._tile_grad = jaxtrace.jit(tile_grad, donate_argnums=1)
        self._tile_pred_auc = jaxtrace.jit(tile_pred_auc)
        self._finish_grad = jaxtrace.jit(finish_grad)
        self._reg_objv = jaxtrace.jit(reg_objv)
        self._reg_grad = jaxtrace.jit(reg_grad)
        self._axpy = jaxtrace.jit(lambda a, x, y: y + a * x)
        self._dot = jaxtrace.jit(lambda a, b: jnp.dot(a, b))
        self._nnz = jaxtrace.jit(lambda w: jnp.sum(w != 0))

    def _calc_grad(self, weights):
        """f(w), train auc, loss gradient — one pass over the LOCAL train
        tiles; multi-host sums the raw partials over DCN before
        finish_grad (the gamma transform is nonlinear, so the reduction
        must precede it)."""
        grad = self._put_vec(jnp.zeros(self.N_pad, dtype=jnp.float32))
        objv = 0.0
        auc = 0.0
        # obs: per-tile device step time into the shared histogram type
        # (one quantile definition across sgd/bcd/lbfgs/serve)
        import time as _time

        from ..obs import REGISTRY, trace
        step_h = REGISTRY.histogram(
            "train_step_seconds",
            "host-side dispatch+wait time of one fused device step"
        ).labels(learner="lbfgs")
        for tile in self._iter_tiles("train"):
            t0 = _time.perf_counter()
            with trace.span("lbfgs.tile_grad"):
                o, a, grad = self._tile_grad(weights, grad, tile)
                # ONE stacked transfer per tile for both metric scalars
                # (the separate float(o)/float(a) pair paid two blocking
                # RTTs; found by jax-host-sync, difacto-lint v4) — the
                # host-float64 accumulation order is unchanged, so
                # trajectories stay byte-identical
                oa = jaxtrace.fetch(jnp.stack([o, a]),
                                    point="lbfgs.tile_metrics")
                objv += float(oa[0])
                auc += float(oa[1])
            step_h.observe(_time.perf_counter() - t0)
        if self._num_hosts > 1:
            from ..parallel.multihost import allreduce_np
            # scalars ride a float64-safe wire; the gradient gathers as
            # float32 (half the wire bytes) and sums in float64
            scal = allreduce_np(np.array([objv, auc], dtype=np.float64),
                                self.monitor)
            objv, auc = float(scal[0]), float(scal[1])
            g = allreduce_np(jaxtrace.fetch(grad, point="lbfgs.grad"),
                             self.monitor,
                             sum_dtype=np.float64)
            grad = self._put_vec(g.astype(np.float32))
        return objv, auc, self._finish_grad(grad, self._n_real)

    # ----------------------------------------------------------- driver
    def run(self) -> None:
        """RunScheduler (lbfgs_learner.cc:14-108)."""
        p, up = self.param, self.uparam
        self._prepare_data()
        self._init_model()
        log.info("inited model with %d parameters", self.N)
        if p.model_in:
            n = self._warm_start(p.model_in)
            log.info("warm start from %s: %d features matched", p.model_in, n)
        r0 = float(jaxtrace.fetch(self._reg_objv(self.weights, self.reg_c),
                                  point="lbfgs.linesearch"))
        f0, auc, g_loss = self._calc_grad(self.weights)
        objv = r0 + f0

        s_hist: List[jnp.ndarray] = []
        y_hist: List[jnp.ndarray] = []
        grads = None          # g at accepted w, incl. regularizer
        alpha = 0.0           # server/worker alpha bookkeeping (unified)
        val_auc_prev = 0.0
        new_objv = objv

        k = p.load_epoch if p.load_epoch >= 0 else 0
        for epoch in range(k, p.max_num_epochs):
            log.info("epoch %d:", epoch)
            # kPushGradient + kPrepareCalcDirection (lbfgs_updater.h:84-99)
            new_grads = self._axpy(1.0, self._reg_grad(self.weights, self.reg_c),
                                   g_loss)
            if grads is None:
                grads = new_grads
            else:
                if len(y_hist) == up.m:
                    y_hist.pop(0)
                y_hist.append(self._axpy(-1.0, grads, new_grads))
                grads = new_grads
                # s_last was stored unscaled; scale by the accepted alpha
                # (PrepareCalcDirection, lbfgs_updater.h:95-97)
                s_hist[-1] = alpha * s_hist[-1]
            alpha = 0.0

            # kCalcDirection (lbfgs_updater.h:105-121): two-loop or -g
            if y_hist:
                basis = jnp.stack([*s_hist, *y_hist, grads])
                B = np.asarray(jnp.einsum("in,jn->ij", basis, basis),
                               dtype=np.float64)
                from .twoloop import calc_delta
                delta = calc_delta(B)
                direction = jnp.asarray(delta, dtype=jnp.float32) @ basis
            else:
                direction = -grads
            direction = jnp.clip(direction, -5.0, 5.0)
            if len(s_hist) == up.m:
                s_hist.pop(0)
            s_hist.append(direction)
            # declared sync: the line search needs <p,g> on host to
            # branch — one scalar, one deliberate fetch
            p_gf = float(jaxtrace.fetch(self._dot(grads, direction),
                                        point="lbfgs.linesearch"))

            # line search (lbfgs_learner.cc:46-71)
            log.info(" - start linesearch with objv = %g, <p,g> = %g",
                     objv, p_gf)
            if epoch != 0:
                trial = p.alpha
            else:
                trial = p.init_alpha if p.init_alpha > 0 \
                    else self.ntrain / self.train_nnz
            for i in range(p.max_num_linesearchs):
                self.weights = self._axpy(trial - alpha, direction,
                                          self.weights)
                alpha = trial
                f_new, auc, g_loss = self._calc_grad(self.weights)
                # the Wolfe test needs three scalars on host — ONE
                # stacked transfer instead of three (same values, same
                # float32->float64 conversions; jax-host-sync scrub)
                ls = jaxtrace.fetch(jnp.stack([
                    self._reg_objv(self.weights, self.reg_c),
                    self._dot(g_loss, direction),
                    self._dot(self._reg_grad(self.weights, self.reg_c),
                              direction)]), point="lbfgs.linesearch")
                new_objv = f_new + float(ls[0])
                pg_new = float(ls[1]) + float(ls[2])
                log.info(" - alpha = %g, objv = %g, <p,g> = %g",
                         trial, new_objv, pg_new)
                if (new_objv <= objv + p.c1 * trial * p_gf
                        and pg_new >= p.c2 * p_gf):
                    log.info(" - wolfe condition is satisfied")
                    break
                if i + 1 == p.max_num_linesearchs:
                    log.info(" - reached max linesearch steps [%d]", i + 1)
                trial *= p.rho

            # kEvaluate (lbfgs_learner.cc:72-84)
            val_auc = 0.0
            for tile in self._iter_tiles("val"):
                val_auc += float(jaxtrace.fetch(
                    self._tile_pred_auc(self.weights, tile),
                    point="lbfgs.val_auc"))
            if self._num_hosts > 1 and self.nval:
                from ..parallel.multihost import allreduce_np
                val_auc = float(allreduce_np(
                    np.array([val_auc], dtype=np.float64), self.monitor)[0])
            prog = LBFGSProgress(
                objv=new_objv,
                auc=auc / max(self.ntrain, 1),
                val_auc=val_auc / self.nval if self.nval else 0.0,
                nnz_w=float(jaxtrace.fetch(self._nnz(self.weights),
                                           point="lbfgs.nnz")),
            )
            if self.nval:
                log.info(" - training AUC = %g, validation AUC = %g",
                         prog.auc, prog.val_auc)
            else:
                log.info(" - training AUC = %g", prog.auc)
            for cb in self.epoch_end_callbacks:
                cb(epoch, prog)

            # stop criteria (lbfgs_learner.cc:86-103)
            if epoch > p.min_num_epochs:
                eps = abs(new_objv - objv) / objv
                if eps < p.stop_rel_objv:
                    log.info("change of objv [%g] < stop_rel_objv", eps)
                    break
                if self.nval:
                    eps = prog.val_auc - val_auc_prev
                    if eps < p.stop_val_auc:
                        log.info("change of val auc [%g] < stop_val_auc", eps)
                        break
            objv = new_objv
            val_auc_prev = prog.val_auc

        if p.model_out:
            self.save(p.model_out)
        log.info("training is done")

    # ----------------------------------------------------------- ckpt
    @staticmethod
    def _ckpt_path(path: str) -> str:
        # savez appends .npz; normalize so save(p) and load(p) round-trip
        return path if path.endswith(".npz") else path + ".npz"

    def save(self, path: str) -> None:
        """Flat-model checkpoint (the reference LBFGSUpdater's Save/Load are
        empty stubs, lbfgs_updater.h:22-24; we persist anyway)."""
        from ..utils import manifest as mft
        from ..utils import stream
        p = self._ckpt_path(path)
        stream.save_npz(p, feaids=self.feaids,
                        lens=self.lens,
                        weights=np.asarray(self.weights)[:self.N],
                        V_dim=np.array(self.k),
                        learner=np.array("lbfgs"),
                        manifest={"learner": "lbfgs",
                                  "rows": int(len(self.feaids)),
                                  "generation": mft.next_generation(p)},
                        fault_point="ckpt.write")

    def load(self, path: str) -> None:
        from ..utils import stream
        with stream.load_npz(self._ckpt_path(path)) as z:
            if int(z["V_dim"]) != self.k:
                raise ValueError("checkpoint V_dim mismatch")
            self.feaids = z["feaids"]
            self.lens = z["lens"]
            w = z["weights"]
        offsets = np.zeros(len(self.feaids) + 1, dtype=np.int64)
        np.cumsum(self.lens, out=offsets[1:])
        self.offsets = offsets
        self.N = int(offsets[-1])
        self.N_pad = bucket(self.N + self.k + 1, self._dim_min())
        self.trash_w = self.N_pad - 1
        self.trash_v = self.N_pad - 1 - self.k
        buf = np.zeros(self.N_pad, dtype=np.float32)
        buf[:self.N] = w
        self.weights = self._put_vec(buf)
        self._refresh_layout_constants()
