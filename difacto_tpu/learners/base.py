"""Learner base + factory.

Equivalent of include/difacto/learner.h / src/learner.cc. The reference's
``Run()`` dispatches on DMLC_ROLE (scheduler drives, workers/servers block in
tracker Wait); in the SPMD design there is one controller, so ``run()`` just
drives the epoch loop — the "roles" are the host pipeline (worker), the
device slot table (server), and this loop (scheduler).
"""

from __future__ import annotations

from typing import Callable, Dict, List

from ..config import KWArgs
from ..utils.progress import Progress

EpochCallback = Callable[[int, Progress, Progress], None]

_REGISTRY: Dict[str, type] = {}


def register(name: str):
    def deco(cls):
        _REGISTRY[name] = cls
        return cls
    return deco


class Learner:
    """Base learner: init(kwargs) -> run() -> stop()."""

    def __init__(self) -> None:
        self.epoch_end_callbacks: List[EpochCallback] = []

    @staticmethod
    def create(name: str) -> "Learner":
        # the reference factory registers only "sgd" (src/learner.cc:11-18);
        # we register every learner we implement
        try:
            cls = _REGISTRY[name.lower()]
        except KeyError:
            raise ValueError(
                f"unknown learner {name!r}; have {sorted(_REGISTRY)}")
        return cls()

    def init(self, kwargs: KWArgs) -> KWArgs:
        raise NotImplementedError

    def run(self) -> None:
        raise NotImplementedError

    def stop(self) -> None:
        pass

    def add_epoch_end_callback(self, cb: EpochCallback) -> None:
        self.epoch_end_callbacks.append(cb)
