"""Block coordinate descent learner (ℓ1 logistic regression).

TPU-native re-design of the reference's src/bcd/ (bcd_learner.{h,cc},
bcd_updater.h, bcd_utils.h). The feature axis is partitioned into blocks by
feature-group bits + sampled occurrence stats; each epoch sweeps the blocks
(shuffled), and per block computes the first-order gradient and diagonal
Hessian on *feature-major* ("transposed") data, applies a diagonal-Newton
proximal ℓ1 step with a per-coordinate trust region, and updates the cached
predictions with X·Δw.

Mapping to the reference:
- transposed tiles (TileBuilder with transpose=true, bcd_learner.cc:100-105)
  -> per (row-tile, feature-block) COO slices on device, cols = block-local
  feature index; the g/h contraction and the pred update are segment-sums
  (losses/logit_delta.py <- src/loss/logit_loss_delta.h);
- BCDUpdater::UpdateWeight diag-Newton + bcd::Delta trust region
  (bcd_updater.h:139-159, bcd_utils.h:146-163) -> one vectorised update over
  the block's weight slice (host numpy — O(block) elementwise);
- FeaGroupStats 10%-row sampling (bcd_utils.h:92-120) and PartitionFeature's
  reversed-keyspace range math (bcd_utils.h:65-87) are kept bit-exact;
- the per-epoch progress [count, objv, auc, acc] is evaluated after the last
  block's update over ALL cached tiles incl. validation, like UpdtPred's
  accumulation (bcd_learner.cc:265-313).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Callable, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..base import FEAID_DTYPE, encode_fea_grp_id, decode_fea_grp_id, \
    reverse_bytes
from ..config import KWArgs, Param
from ..data import Reader
from ..losses.logit_delta import BlockSlice as _BlockSlice
from ..losses.metrics import accuracy_times_n, auc_times_n, logit_objv_np
from ..ops.batch import bucket
from ..ops.kv import expand_ranges, find_position
from ..utils import jaxtrace
from .base import Learner, register

log = logging.getLogger("difacto_tpu")

UINT64_MAX = np.uint64(0xFFFFFFFFFFFFFFFF)


@dataclass
class BCDLearnerParam(Param):
    """src/bcd/bcd_param.h:10-51."""
    data_in: str = ""
    data_val: str = ""
    data_format: str = "libsvm"
    data_cache: str = ""
    model_out: str = ""
    model_in: str = ""
    loss: str = "fm"  # accepted for parity; BCD always uses logit_delta
    max_num_epochs: int = 20
    block_ratio: float = 4.0
    random_block: int = 1
    num_feature_group_bits: int = 0
    neg_sampling: float = 1.0  # declared but unused in the reference too
    data_chunk_size: int = 1 << 28  # bytes
    seed: int = 0
    # device-tile cache bounds; evicted (tile, block) slices rebuild on
    # demand from the host arrays. tile_cache_mb bounds DEVICE bytes and
    # defaults ON so criteo-scale runs cannot exhaust HBM (round-3 verdict
    # #7); tile_cache_items adds a count bound (0 = none). The reference's
    # analog is TileStore's cache over DataStore.
    tile_cache_items: int = 0
    tile_cache_mb: int = 1024
    # shard the ROW axis over a dp device mesh: each device holds its row
    # slice of every tile (pred/labels/mask + the per-block COO entries
    # whose rows land in it) and the per-block (g, h) contraction becomes
    # per-device segment-sums + a psum — the TPU analog of the reference's
    # workers computing partial block gradients that the servers sum
    # (bcd_learner.cc:236-263, bcd_updater.h:139-159). The diag-Newton
    # update stays replicated (O(block) elementwise). 1 = single device.
    mesh_dp: int = 1


@dataclass
class BCDUpdaterParam(Param):
    """src/bcd/bcd_updater.h:20-38."""
    V_dim: int = 0  # BCD supports linear only (InitWeights CHECK_EQ)
    tail_feature_filter: int = 4
    l1: float = 1.0
    l2: float = 0.01
    lr: float = 0.9


class BCDProgress(NamedTuple):
    """The reference's progress vector [count, objv, auc, acc]
    (bcd_learner.cc:296-311) + nnz_w; raw sums, not divided."""
    count: float = 0.0
    objv: float = 0.0
    auc: float = 0.0
    acc: float = 0.0
    nnz_w: float = 0.0


def fea_group_stats(blocks, nbits: int, skip: int = 10) -> np.ndarray:
    """Sampled per-group nnz counts (FeaGroupStats, bcd_utils.h:92-120):
    every ``skip``-th row contributes; layout [cnt_0..cnt_{2^b-1},
    sampled_rows, total_rows]. Streaming: call add_group_stats per block."""
    ngrp = 1 << nbits
    value = np.zeros(ngrp + 2, dtype=np.float64)
    for blk in blocks:
        add_group_stats(value, blk, nbits, skip)
    return value


def add_group_stats(value: np.ndarray, blk, nbits: int,
                    skip: int = 10) -> None:
    """Accumulate one block's sampled stats into ``value`` in place."""
    ngrp = 1 << nbits
    rows = np.arange(0, blk.size, skip)
    counts = np.diff(blk.offset)[rows]
    nnz_idx = expand_ranges(np.asarray(blk.offset[rows]), counts)
    gids = decode_fea_grp_id(blk.index[nnz_idx], nbits)
    np.add.at(value, gids.astype(np.int64), 1)
    value[ngrp] += len(rows)
    value[ngrp + 1] += blk.size


def partition_feature(nbits: int, feagrps: List[Tuple[int, int]]
                      ) -> List[Tuple[int, int]]:
    """PartitionFeature (bcd_utils.h:65-87): per (group, nblk) split the
    group's reversed-keyspace range into nblk even segments."""
    if nbits % 4 != 0:
        raise ValueError("num_feature_group_bits must be 0, 4, 8, ...")
    ranges: List[List[int]] = []
    for gid, nblk in feagrps:
        lo = int(reverse_bytes(encode_fea_grp_id(0, gid, nbits)))
        hi = int(reverse_bytes(encode_fea_grp_id(int(UINT64_MAX) >> nbits,
                                                 gid, nbits)))
        span = hi - lo
        for i in range(nblk):
            b = lo + span * i // nblk
            e = lo + span * (i + 1) // nblk
            if e > b:
                ranges.append([b, e])
    ranges.sort(key=lambda r: r[0])
    for i in range(1, len(ranges)):
        if ranges[i - 1][1] < ranges[i][0]:
            ranges[i - 1][1] += 1  # close 1-gaps (bcd_utils.h:83-86)
    return [(b, e) for b, e in ranges]




@register("bcd")
class BCDLearner(Learner):
    def __init__(self) -> None:
        super().__init__()
        self.param: Optional[BCDLearnerParam] = None
        self.epoch_end_callbacks: List[Callable[[int, BCDProgress], None]] \
            = []

    # ----------------------------------------------------------- init
    def init(self, kwargs: KWArgs) -> KWArgs:
        self.param, remain = BCDLearnerParam.init_allow_unknown(kwargs)
        self.uparam, remain = BCDUpdaterParam.init_allow_unknown(remain)
        if self.uparam.V_dim != 0:
            raise ValueError("bcd supports V_dim=0 only (linear model), like "
                             "the reference (bcd_updater.h InitWeights)")
        # multi-host: each host holds its byte range's row tiles; per-block
        # (g, h) partials meet in a DCN allreduce and every host applies
        # the identical diag-Newton update — the reference's workers
        # pushing partial block gradients that the servers sum
        # (src/bcd/bcd_learner.cc:236-263)
        self._num_hosts = jax.process_count()
        self._host_rank = jax.process_index()
        from ..parallel import fault
        self.monitor = fault.from_env(self._host_rank, self._num_hosts)
        if self._num_hosts > 1 and self.param.mesh_dp > 1:
            raise ValueError(
                "bcd multi-host runs shard rows across hosts; in-host row "
                "sharding (mesh_dp > 1) is single-host only — set "
                "mesh_dp=1 under launch.py")
        self._build_steps()
        return remain

    def _allreduce_np(self, buf: np.ndarray, sum_dtype=None) -> np.ndarray:
        from ..parallel.multihost import allreduce_np
        return allreduce_np(buf, self.monitor, sum_dtype=sum_dtype)

    def _build_steps(self) -> None:
        from ..losses.logit_delta import delta_grad, delta_pred_update
        self.mesh = None
        if self.param.mesh_dp > 1:
            from functools import partial

            from jax.sharding import NamedSharding, PartitionSpec as P
            shard_map = jax.shard_map

            from ..parallel import DP_AXIS, make_mesh
            self.mesh = make_mesh(dp=self.param.mesh_dp, fs=1)
            self._row_shard = NamedSharding(self.mesh, P(DP_AXIS))
            self._coo_shard = NamedSharding(self.mesh, P(DP_AXIS, None))
            mesh, dp_axis = self.mesh, DP_AXIS

            @partial(jaxtrace.jit, static_argnums=6)
            def grad_gh(pred, labels, mask, rows, cols, vals, nf_cap):
                def body(pred, labels, mask, rows, cols, vals):
                    blk = _BlockSlice(rows=rows[0], cols=cols[0],
                                      vals=vals[0])
                    g, h = delta_grad(pred, labels, mask, blk, nf_cap)
                    return (jax.lax.psum(g, dp_axis),
                            jax.lax.psum(h, dp_axis))
                return shard_map(
                    body, mesh=mesh,
                    in_specs=(P(dp_axis), P(dp_axis), P(dp_axis),
                              P(dp_axis, None), P(dp_axis, None),
                              P(dp_axis, None)),
                    out_specs=(P(), P()))(pred, labels, mask, rows, cols,
                                          vals)

            @partial(jaxtrace.jit, donate_argnums=0)
            def pred_add(pred, rows, cols, vals, d):
                def body(pred, rows, cols, vals, d):
                    blk = _BlockSlice(rows=rows[0], cols=cols[0],
                                      vals=vals[0])
                    return delta_pred_update(pred, blk, d)
                return shard_map(
                    body, mesh=mesh,
                    in_specs=(P(dp_axis), P(dp_axis, None),
                              P(dp_axis, None), P(dp_axis, None), P()),
                    out_specs=P(dp_axis))(pred, rows, cols, vals, d)

            self._grad_gh_sharded = grad_gh
            self._pred_add_sharded = pred_add
        self._grad_gh = jaxtrace.jit(delta_grad, static_argnums=4)
        self._pred_add = jaxtrace.jit(delta_pred_update, donate_argnums=0)

    def _place_rows(self, arr: np.ndarray) -> jnp.ndarray:
        if self.mesh is None:
            return jnp.asarray(arr)
        return jax.device_put(arr, self._row_shard)

    # ----------------------------------------------------------- data prep
    def _prepare(self) -> None:
        from ..data.tile_builder import TileBuilder
        p, up = self.param, self.uparam
        # read + localize all tiles through the shared TileBuilder
        # (PrepareData, bcd_learner.cc:96-132)
        part_idx, num_parts = 0, 1
        if self._num_hosts > 1:
            from ..parallel.multihost import host_part
            part_idx, num_parts = host_part()
        tb = TileBuilder()
        # stats accumulate per block so raw text blocks are dropped as we go
        # (the reference streams via TileBuilder the same way)
        stats = np.zeros((1 << p.num_feature_group_bits) + 2,
                         dtype=np.float64)
        for blk in Reader(p.data_in, p.data_format, part_idx, num_parts,
                          chunk_bytes=p.data_chunk_size):
            add_group_stats(stats, blk, p.num_feature_group_bits)
            tb.add(blk, is_train=True)
        if p.data_val:
            for blk in Reader(p.data_val, p.data_format, part_idx,
                              num_parts, chunk_bytes=p.data_chunk_size):
                tb.add(blk, is_train=False)
        self.ntrain, self.nval = tb.nrows_train, tb.nrows_val
        if self._num_hosts > 1:
            # global dictionary + group stats + row totals: the feature
            # partition and the tail filter must be identical on every
            # host (BuildFeatureMap, bcd_learner.cc:141-155)
            from ..parallel.multihost import global_kv_union
            tb.ids, tb.cnts = global_kv_union(tb.ids, tb.cnts)
            stats = self._allreduce_np(stats)
            tot = self._allreduce_np(np.array([self.ntrain, self.nval],
                                              dtype=np.int64))
            self.ntrain, self.nval = int(tot[0]), int(tot[1])

        # tail filter (BuildFeatureMap, bcd_learner.cc:141-155); the
        # reference filters with cnt > threshold via the builder
        self.feaids = tb.filter_tail(up.tail_feature_filter)
        nf = len(self.feaids)

        # partition feature blocks (RunScheduler, bcd_learner.cc:60-72)
        ngrp = 1 << p.num_feature_group_bits
        feagrp = []
        for g in range(ngrp):
            nblk = int(np.ceil(stats[g] / max(stats[ngrp], 1)
                               * p.block_ratio))
            if nblk > 0:
                feagrp.append((g, nblk))
        ranges = partition_feature(p.num_feature_group_bits, feagrp)
        # block f owns filtered features in [begin, end) of the reversed space
        begins = np.searchsorted(self.feaids,
                                 np.array([r[0] for r in ranges],
                                          dtype=FEAID_DTYPE))
        ends = np.searchsorted(self.feaids,
                               np.array([r[1] for r in ranges],
                                        dtype=FEAID_DTYPE))
        self.blocks = [(int(b), int(e)) for b, e in zip(begins, ends)
                       if e > b]
        log.info("loaded %d examples; %d features in %d blocks",
                 self.ntrain, nf, len(self.blocks))

        # model state (host: O(nf) elementwise)
        self.w = np.zeros(nf, dtype=np.float32)
        self.delta = np.ones(nf, dtype=np.float32)  # bcd::Delta init 1.0

        # device tiles: labels/mask/pred per row tile; per (tile, block)
        # COO slices built lazily and cached
        from ..ops.batch import mesh_dim_min
        dim_min = 8 if self.mesh is None else mesh_dim_min(p.mesh_dp)
        self.tiles = []
        for t, (cblk, uniq, is_train) in enumerate(tb.tiles):
            colmap = tb.colmap(t)
            col_global = colmap[cblk.index]  # -1 where filtered
            b_cap = bucket(cblk.size, dim_min)
            labels = np.zeros(b_cap, dtype=np.float32)
            labels[:cblk.size] = cblk.label
            mask = np.zeros(b_cap, dtype=np.float32)
            mask[:cblk.size] = 1.0
            self.tiles.append(dict(
                size=cblk.size,
                b_cap=b_cap,
                is_train=is_train,
                rows=cblk.row_ids(),
                col_global=col_global,
                vals=cblk.values_or_ones(),
                label_np=cblk.label,
                labels=self._place_rows(labels),
                mask=self._place_rows(mask),
                pred=self._place_rows(np.zeros(b_cap, dtype=np.float32)),
            ))
        from ..data.tile_store import TileCache
        self._tile_cache = TileCache(self._build_slice,
                                     max_items=p.tile_cache_items,
                                     max_bytes=p.tile_cache_mb << 20)

    def _build_slice(self, t: int, f: int) -> Optional[_BlockSlice]:
        """Device COO of tile t's columns in block f (block-local ids).
        Under a mesh the arrays are [dp, cap] with device-LOCAL row ids:
        entry (r, c, v) lands on the device whose row shard holds r."""
        tile = self.tiles[t]
        b_lo, b_hi = self.blocks[f]
        m = (tile["col_global"] >= b_lo) & (tile["col_global"] < b_hi)
        nnz = int(m.sum())
        if nnz == 0:
            return None
        rows_g = tile["rows"][m].astype(np.int64)
        cols_g = (tile["col_global"][m] - b_lo).astype(np.int32)
        vals_g = tile["vals"][m].astype(np.float32)
        if self.mesh is None:
            cap = bucket(nnz)
            rows = np.zeros(cap, dtype=np.int32)
            rows[:nnz] = rows_g
            cols = np.zeros(cap, dtype=np.int32)
            cols[:nnz] = cols_g
            vals = np.zeros(cap, dtype=np.float32)
            vals[:nnz] = vals_g
            return _BlockSlice(rows=jnp.asarray(rows),
                               cols=jnp.asarray(cols),
                               vals=jnp.asarray(vals))
        dp = self.param.mesh_dp
        shard = tile["b_cap"] // dp
        dev = rows_g // shard
        cap = bucket(max(int(np.bincount(dev, minlength=dp).max()), 1))
        rows = np.zeros((dp, cap), dtype=np.int32)
        cols = np.zeros((dp, cap), dtype=np.int32)
        vals = np.zeros((dp, cap), dtype=np.float32)
        for d in range(dp):
            sel = dev == d
            k = int(sel.sum())
            rows[d, :k] = rows_g[sel] - d * shard
            cols[d, :k] = cols_g[sel]
            vals[d, :k] = vals_g[sel]
        return _BlockSlice(
            rows=jax.device_put(rows, self._coo_shard),
            cols=jax.device_put(cols, self._coo_shard),
            vals=jax.device_put(vals, self._coo_shard))

    def _block_slice(self, t: int, f: int) -> Optional[_BlockSlice]:
        return self._tile_cache.fetch(t, f)

    # ----------------------------------------------------------- epoch
    def _iterate_block(self, f: int) -> None:
        """IterateFeablk (bcd_learner.cc:196-233): grad -> update -> pred."""
        up = self.uparam
        b_lo, b_hi = self.blocks[f]
        nf_blk = b_hi - b_lo
        nf_cap = bucket(nf_blk)

        g = jnp.zeros(nf_cap, dtype=jnp.float32)
        h = jnp.zeros(nf_cap, dtype=jnp.float32)
        for t, tile in enumerate(self.tiles):
            if not tile["is_train"]:
                continue
            s = self._block_slice(t, f)
            if s is None:
                continue
            if self.mesh is not None:
                dg, dh = self._grad_gh_sharded(
                    tile["pred"], tile["labels"], tile["mask"],
                    s.rows, s.cols, s.vals, nf_cap)
            else:
                dg, dh = self._grad_gh(tile["pred"], tile["labels"],
                                       tile["mask"], s, nf_cap)
            g = g + dg
            h = h + dh

        # (g, h) leave the device as ONE concatenated transfer — the
        # separate np.asarray(g)/np.asarray(h) pair paid two blocking
        # RTTs per block (jax-host-sync scrub, difacto-lint v4); the
        # [:nf_cap]/[nf_cap:] split is the same layout the DCN wire
        # already used
        gh = jaxtrace.fetch(jnp.concatenate([g, h]), point="bcd.grad_gh")
        if self._num_hosts > 1:
            # per-block partial (g, h) -> global sums over DCN (float32
            # wire, float64 accumulation); all hosts then apply the
            # identical update
            s = self._allreduce_np(gh, sum_dtype=np.float64)
            g_np = s[:nf_blk]
            h_np = s[nf_cap:nf_cap + nf_blk]
        else:
            g_np = gh[:nf_blk].astype(np.float64)
            h_np = gh[nf_cap:nf_cap + nf_blk].astype(np.float64)

        # diag-Newton + trust region (UpdateWeight, bcd_updater.h:139-159)
        w = self.w[b_lo:b_hi].astype(np.float64)
        dlt = self.delta[b_lo:b_hi]
        g_pos, g_neg = g_np + up.l1, g_np - up.l1
        u = h_np / up.lr + 1e-10
        d = np.where(g_pos <= u * w, -g_pos / u,
                     np.where(g_neg >= u * w, -g_neg / u, -w))
        d = np.clip(d, -dlt, dlt).astype(np.float32)
        self.delta[b_lo:b_hi] = np.minimum(5.0, np.abs(d) * 2 + 0.1)
        self.w[b_lo:b_hi] += d

        d_cap = np.zeros(nf_cap, dtype=np.float32)
        d_cap[:nf_blk] = d
        d_dev = jnp.asarray(d_cap)
        for t, tile in enumerate(self.tiles):  # train AND val (UpdtPred)
            s = self._block_slice(t, f)
            if s is None:
                continue
            if self.mesh is not None:
                tile["pred"] = self._pred_add_sharded(
                    tile["pred"], s.rows, s.cols, s.vals, d_dev)
            else:
                tile["pred"] = self._pred_add(tile["pred"], s, d_dev)

    def _progress(self) -> BCDProgress:
        count = objv = auc = acc = 0.0
        for tile in self.tiles:
            pred = np.asarray(tile["pred"])[:tile["size"]]
            lab = tile["label_np"]
            count += tile["size"]
            objv += logit_objv_np(lab, pred)
            auc += auc_times_n(lab, pred)
            acc += accuracy_times_n(lab, pred, 0.5)
        if self._num_hosts > 1:
            count, objv, auc, acc = (float(v) for v in self._allreduce_np(
                np.array([count, objv, auc, acc], dtype=np.float64)))
        return BCDProgress(count=count, objv=objv, auc=auc, acc=acc,
                           nnz_w=float(np.sum(self.w != 0)))

    # ----------------------------------------------------------- driver
    def run(self) -> None:
        """RunScheduler (bcd_learner.cc:51-93)."""
        p = self.param
        self._prepare()
        if p.model_in:
            self.load(p.model_in)
        order = np.arange(len(self.blocks))
        rng = np.random.RandomState(p.seed)
        import time as _time

        from ..obs import REGISTRY, trace
        step_h = REGISTRY.histogram(
            "train_step_seconds",
            "host-side dispatch+wait time of one fused device step"
        ).labels(learner="bcd")
        for epoch in range(p.max_num_epochs):
            if p.random_block:
                rng.shuffle(order)
            with trace.span("epoch", epoch=epoch, learner="bcd"):
                for f in order:
                    t0 = _time.perf_counter()
                    with trace.span("bcd.block", block=int(f)):
                        self._iterate_block(int(f))
                    step_h.observe(_time.perf_counter() - t0)
            prog = self._progress()
            log.info("epoch: %d, objv: %g, auc: %g, acc: %g, nnz(w): %d",
                     epoch, prog.objv / max(prog.count, 1),
                     prog.auc / max(prog.count, 1),
                     prog.acc / max(prog.count, 1), int(prog.nnz_w))
            for cb in self.epoch_end_callbacks:
                cb(epoch, prog)
        if p.model_out:
            self.save(p.model_out)

    # ----------------------------------------------------------- ckpt
    @staticmethod
    def _ckpt_path(path: str) -> str:
        return path if path.endswith(".npz") else path + ".npz"

    def save(self, path: str) -> None:
        """(reference BCDUpdater Save/Load are stubs; we persist anyway)"""
        from ..utils import manifest as mft
        from ..utils import stream
        p = self._ckpt_path(path)
        stream.save_npz(p, feaids=self.feaids, w=self.w,
                        learner=np.array("bcd"),
                        manifest={"learner": "bcd",
                                  "rows": int(len(self.feaids)),
                                  "generation": mft.next_generation(p)},
                        fault_point="ckpt.write")

    def load(self, path: str) -> None:
        from ..utils import stream
        with stream.load_npz(self._ckpt_path(path)) as z:
            pos = find_position(z["feaids"].astype(FEAID_DTYPE), self.feaids)
            ok = pos >= 0
            self.w[ok] = z["w"][pos[ok]]
        # loaded weights change predictions: rebuild pred = X w per tile
        for tile in self.tiles:
            pred = np.zeros(tile["pred"].shape[0], dtype=np.float32)
            valid = tile["col_global"] >= 0
            np.add.at(pred, tile["rows"][valid],
                      tile["vals"][valid] * self.w[tile["col_global"][valid]])
            tile["pred"] = self._place_rows(pred)
