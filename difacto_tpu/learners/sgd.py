"""SGD learner: the async-minibatch FM/LR trainer.

TPU-native re-design of the reference SGDLearner (src/sgd/sgd_learner.{h,cc}).
The reference's 3-thread pipeline per batch — read+localize / pull weights /
compute+push gradients (sgd_learner.h:85-102) — collapses into

    host: read + localize + slot-map  ->  device: ONE fused jit step
          (gather rows -> FM forward -> metrics -> backward -> FTRL/AdaGrad
           scatter update)

with pipelining supplied by JAX's async dispatch: the host prepares batch
k+1 while the device runs batch k; metric scalars are fetched only at epoch
end (the analog of the <=2 in-flight bounded-delay backpressure,
sgd_learner.cc:310-312 — here depth is bounded by dispatch depth).

Scheduler logic preserved exactly (RunScheduler, sgd_learner.cc:52-122):
epoch loop with train/val jobs, relative-objective and validation-AUC early
stopping, model load/save, epoch-end callbacks, progress rows.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..config import KWArgs, Param
from ..data import BatchReader, Reader, compact
from ..losses import create as create_loss
from ..ops.batch import bucket, pad_batch
from ..store.local import SlotStore
from ..updaters.sgd_updater import SGDUpdaterParam
from ..utils.progress import Progress, ReportProg
from .base import Learner, register

log = logging.getLogger("difacto_tpu")

# job types (sgd::Job, src/sgd/sgd_utils.h:16-21)
K_LOAD_MODEL, K_SAVE_MODEL, K_TRAINING, K_VALIDATION, K_PREDICTION, \
    K_EVALUATION = 1, 2, 3, 4, 5, 6


@dataclass
class SGDLearnerParam(Param):
    data_in: str = ""
    data_val: str = ""
    data_format: str = "libsvm"
    model_out: str = ""
    model_in: str = ""
    loss: str = "fm"
    max_num_epochs: int = 20
    load_epoch: int = -1
    batch_size: int = 100
    shuffle: int = 10
    neg_sampling: float = 1.0
    pred_out: str = ""
    pred_prob: bool = True
    num_jobs_per_epoch: int = 10
    report_interval: int = 1
    stop_rel_objv: float = 1e-5
    stop_val_auc: float = 1e-5
    has_aux: bool = False
    task: int = 0  # 0 = train, 2 = predict (main.cc task names train/predict)
    # SPMD mesh (parallel/mesh.py): feature shards ("servers") × data
    # parallelism ("workers"); 1×1 = single device. The reference analog is
    # launch.py's -s/-n server/worker counts.
    mesh_fs: int = 1
    mesh_dp: int = 1


@register("sgd")
class SGDLearner(Learner):
    def __init__(self) -> None:
        super().__init__()
        self.param: Optional[SGDLearnerParam] = None
        self.store: Optional[SlotStore] = None
        self._fo_pred = None

    # ----------------------------------------------------------- init
    def init(self, kwargs: KWArgs) -> KWArgs:
        self.param, remain = SGDLearnerParam.init_allow_unknown(kwargs)
        uparam, remain = SGDUpdaterParam.init_allow_unknown(remain)
        # the resolved loss owns the effective V_dim (loss=logit forces 0,
        # like the reference's linear path); thread it back so the store
        # never allocates or computes dead embedding state
        self.loss = create_loss(self.param.loss, uparam.V_dim)
        self.V_dim = self.loss.V_dim
        if uparam.V_dim != self.V_dim:
            uparam = dataclasses.replace(uparam, V_dim=self.V_dim)
        self.mesh = None
        if self.param.mesh_fs * self.param.mesh_dp > 1:
            from ..parallel import make_mesh
            self.mesh = make_mesh(dp=self.param.mesh_dp,
                                  fs=self.param.mesh_fs)
        self.store = SlotStore(uparam, mesh=self.mesh)
        self.do_embedding = self.V_dim > 0
        # multi-controller: this host owns a contiguous slice of the global
        # file parts (parallel/multihost.py; the reference's Rank()/
        # NumWorkers() reader sharding)
        from ..parallel.multihost import host_part
        self._host_rank, self._num_hosts = host_part()
        if self._num_hosts > 1:
            if self.mesh is not None:
                # a global mesh requires every host to issue the same
                # sequence of collective-bearing steps; per-host readers
                # produce differing batch counts/bucket shapes, which would
                # deadlock SPMD. Synchronized-step multihost is future work.
                raise ValueError(
                    "mesh_dp/mesh_fs > 1 is not supported with multiple "
                    "hosts yet; run single-host meshes, or multi-host "
                    "without a mesh (independent per-host replicas)")
            if not self.store.hashed:
                # per-host slot assignment would silently train independent
                # replicas that never communicate — a correctness footgun,
                # not a mode (round-1 verdict item 7)
                raise ValueError(
                    "multi-host runs require the hashed store "
                    "(set hash_capacity > 0): the dictionary store assigns "
                    "slots per-host, so hosts would train independent "
                    "models that never synchronize")
        self._build_steps()
        return remain

    def _build_steps(self) -> None:
        from ..ops.batch import unpack_batch
        from ..step import make_step_fns
        fns = self.store.fns
        _, train_step, eval_step = make_step_fns(fns, self.loss)
        self._train_step = jax.jit(train_step, donate_argnums=0)
        self._eval_step = jax.jit(eval_step)
        self._apply_count = jax.jit(fns.apply_count, donate_argnums=0)

        # packed single-transfer variants (ops/batch.py pack_batch): the
        # whole batch rides in one i32 + one f32 buffer — on tunneled or
        # remote devices per-transfer latency dominates the host->device
        # path, so 2 transfers/batch instead of 8
        def packed_train(state, i32, f32, b_cap, nnz_cap, u_cap, has_cnt,
                         binary):
            batch, slots, counts = unpack_batch(i32, f32, b_cap, nnz_cap,
                                                u_cap, has_cnt, binary)
            if counts is not None:
                state = fns.apply_count(state, slots, counts)
            return train_step(state, batch, slots)

        def packed_eval(state, i32, f32, b_cap, nnz_cap, u_cap, binary):
            batch, slots, _ = unpack_batch(i32, f32, b_cap, nnz_cap, u_cap,
                                           binary=binary)
            return eval_step(state, batch, slots)

        self._packed_train = jax.jit(packed_train, donate_argnums=0,
                                     static_argnums=(3, 4, 5, 6, 7))
        self._packed_eval = jax.jit(packed_eval,
                                    static_argnums=(3, 4, 5, 6))

    # ----------------------------------------------------------- driver
    def run(self) -> None:
        """RunScheduler (sgd_learner.cc:52-122)."""
        p = self.param
        self._start_time = time.time()
        self._report = ReportProg()
        pre_loss, pre_val_auc = 0.0, 0.0
        k = 0

        if p.model_in:
            if p.load_epoch >= 0:
                log.info("loading model from epoch %d", p.load_epoch)
                self.store.load(self._model_name(p.model_in, p.load_epoch))
                k = p.load_epoch + 1
            else:
                log.info("loading latest model...")
                self.store.load(self._model_name(p.model_in, -1))

        if p.task == 2:
            if not p.model_in:
                raise ValueError("prediction needs model_in")
            prog = Progress()
            self._run_epoch(k, K_PREDICTION, prog)
            log.info("prediction: %s", prog.text())
            self.stop()
            return

        while k < p.max_num_epochs:
            train_prog = Progress()
            self._run_epoch(k, K_TRAINING, train_prog)
            log.info("epoch[%d] training: %s", k, train_prog.text())

            val_prog = Progress()
            if p.data_val:
                self._run_epoch(k, K_VALIDATION, val_prog)
                log.info("epoch[%d] validation: %s", k, val_prog.text())

            for cb in self.epoch_end_callbacks:
                cb(k, train_prog, val_prog)

            # stop criteria (sgd_learner.cc:92-110): the reference divides by
            # pre_loss with no zero guard — first epoch never triggers
            eps = abs(train_prog.loss - pre_loss) / pre_loss \
                if pre_loss else float("inf")
            if eps < p.stop_rel_objv:
                log.info("change of loss [%g] < stop_rel_objv [%g]",
                         eps, p.stop_rel_objv)
                break
            if val_prog.auc > 0:
                eps = (val_prog.auc - pre_val_auc) / val_prog.nrows
                if eps < p.stop_val_auc:
                    log.info("change of val AUC [%g] < stop_val_auc [%g]",
                             eps, p.stop_val_auc)
                    break
            k += 1
            if k >= p.max_num_epochs:
                log.info("reached max_num_epochs %d", p.max_num_epochs)
                break
            pre_loss, pre_val_auc = train_prog.loss, val_prog.auc

        if p.model_out:
            log.info("saving final model...")
            self.store.save(self._model_name(p.model_out, -1), p.has_aux)
        self.stop()

    def stop(self) -> None:
        if self._fo_pred is not None:
            self._fo_pred.close()
            self._fo_pred = None

    # ----------------------------------------------------------- epochs
    def _model_name(self, prefix: str, it: int) -> str:
        # per-rank files like the reference's "<prefix>[_iter-k]_part-<rank>"
        # (ModelName, sgd_learner.h:65-69) — no cross-host write races
        name = prefix
        if it >= 0:
            name += f"_iter-{it}"
        return name + f"_part-{self._host_rank}"

    def _run_epoch(self, epoch: int, job_type: int, prog: Progress) -> None:
        p = self.param
        n_jobs = p.num_jobs_per_epoch if job_type == K_TRAINING else 1
        for part in range(n_jobs):
            before = Progress(nrows=prog.nrows, loss=prog.loss, auc=prog.auc)
            self._iterate_data(job_type, epoch, part, n_jobs, prog)
            if job_type == K_TRAINING and p.report_interval > 0:
                # report only this part's delta, like the reference's
                # per-batch reporter messages (sgd_learner.cc:242-247)
                elapsed = time.time() - self._start_time
                self._report.prog.merge(Progress(
                    nrows=prog.nrows - before.nrows,
                    loss=prog.loss - before.loss,
                    auc=prog.auc - before.auc))
                print(f"{elapsed:5.0f}  {self._report.print_str()}",
                      flush=True)

    def _iterate_data(self, job_type: int, epoch: int, part_idx: int,
                      num_parts: int, prog: Progress) -> None:
        """IterateData (sgd_learner.cc:201-317) — fused-step version."""
        p = self.param
        push_cnt = (job_type == K_TRAINING and epoch == 0
                    and self.do_embedding)
        # this host's slice of the global part space
        g_idx = self._host_rank * num_parts + part_idx
        g_num = num_parts * self._num_hosts
        if job_type == K_TRAINING:
            # vary the shuffle/sampling stream across epochs and parts (the
            # reference's std::random_shuffle advances global state per epoch)
            reader = BatchReader(p.data_in, p.data_format, g_idx,
                                 g_num, p.batch_size,
                                 p.batch_size * p.shuffle, p.neg_sampling,
                                 seed=epoch * max(g_num, 1) + g_idx)
        else:
            reader = Reader(p.data_val or p.data_in, p.data_format, g_idx,
                            g_num, chunk_bytes=256 << 20)

        def produce():
            # parsing + localization on the producer thread; store access
            # (key mapping, state) stays on the consumer side
            for blk in reader:
                yield blk, compact(blk, need_counts=push_cnt)

        from ..data.prefetch import prefetch
        from ..ops.batch import pack_batch
        pending: list = []  # device scalars fetched lazily at the end
        for blk, (cblk, uniq, cnts) in prefetch(produce(), depth=2):
            slots_np, remap, cnts = self.store.map_keys_dedup(uniq, cnts)
            if remap is not None:
                # hashed-mode in-batch collisions: point the COO entries at
                # the deduped slot rows so colliding features alias (their
                # gradients segment-sum together on device)
                cblk = dataclasses.replace(
                    cblk, index=remap[cblk.index].astype(np.uint32))
            n_uniq = len(slots_np)
            u_cap = bucket(n_uniq)
            b_cap, nnz_cap = bucket(blk.size), bucket(blk.nnz)
            if self.mesh is None:
                # packed path: 2 host->device transfers per batch
                i32, f32, binary = pack_batch(
                    cblk, n_uniq, slots_np, b_cap, nnz_cap, u_cap,
                    counts=cnts if push_cnt else None)
                i32, f32 = jnp.asarray(i32), jnp.asarray(f32)
                if job_type == K_TRAINING:
                    self.store.state, objv, auc = self._packed_train(
                        self.store.state, i32, f32, b_cap, nnz_cap, u_cap,
                        push_cnt, binary)
                else:
                    pred, objv, auc = self._packed_eval(
                        self.store.state, i32, f32, b_cap, nnz_cap, u_cap,
                        binary)
            else:
                slots = self.store.pad_slots(slots_np, u_cap)
                dev = pad_batch(cblk, num_uniq=n_uniq,
                                batch_cap=b_cap, nnz_cap=nnz_cap)
                from ..parallel import batch_sharding, shard_pytree
                dev = shard_pytree(dev, batch_sharding(self.mesh))
                if push_cnt:
                    c = np.zeros(u_cap, dtype=np.float32)
                    c[:len(cnts)] = cnts
                    self.store.state = self._apply_count(
                        self.store.state, slots, jnp.asarray(c))
                if job_type == K_TRAINING:
                    self.store.state, objv, auc = self._train_step(
                        self.store.state, dev, slots)
                else:
                    pred, objv, auc = self._eval_step(self.store.state, dev,
                                                      slots)
            if job_type == K_PREDICTION and p.pred_out:
                # stream predictions per batch (SavePred,
                # sgd_learner.cc:231-238) — don't buffer the dataset
                self._save_pred(np.asarray(pred)[:blk.size], blk.label)
            pending.append((blk.size, objv, auc))

        # metric scalars are fetched in ONE transfer after all batches are
        # dispatched — JAX async dispatch supplies the pipeline overlap
        if pending:
            flat = jnp.stack([s for _, o, a in pending for s in (o, a)])
            vals = np.asarray(flat)
            for i, (nrows, _, _) in enumerate(pending):
                prog.merge(Progress(nrows=nrows, loss=float(vals[2 * i]),
                                    auc=float(vals[2 * i + 1])))

    def _save_pred(self, pred: np.ndarray, label) -> None:
        """SavePred (sgd_learner.h:72-83); per-rank output file."""
        if self._fo_pred is None:
            from ..utils import stream
            self._fo_pred = stream.open_stream(
                f"{self.param.pred_out}_part-{self._host_rank}", "w")
        out = 1.0 / (1.0 + np.exp(-pred)) if self.param.pred_prob else pred
        for i, v in enumerate(out):
            if label is not None:
                self._fo_pred.write(f"{label[i]:g}\t")
            self._fo_pred.write(f"{v:g}\n")
