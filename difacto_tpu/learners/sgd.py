"""SGD learner: the async-minibatch FM/LR trainer.

TPU-native re-design of the reference SGDLearner (src/sgd/sgd_learner.{h,cc}).
The reference's 3-thread pipeline per batch — read+localize / pull weights /
compute+push gradients (sgd_learner.h:85-102) — collapses into

    host: read + localize + slot-map  ->  device: ONE fused jit step
          (gather rows -> FM forward -> metrics -> backward -> FTRL/AdaGrad
           scatter update)

with pipelining supplied by JAX's async dispatch: the host prepares batch
k+1 while the device runs batch k; metric scalars are fetched only at epoch
end (the analog of the <=2 in-flight bounded-delay backpressure,
sgd_learner.cc:310-312 — here depth is bounded by dispatch depth).

Scheduler logic preserved exactly (RunScheduler, sgd_learner.cc:52-122):
epoch loop with train/val jobs, relative-objective and validation-AUC early
stopping, model load/save, epoch-end callbacks, progress rows.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import KWArgs, Param
from ..data import BatchReader, Reader, compact
from ..losses import create as create_loss
from ..ops.batch import bucket, pad_batch
from ..store.local import SlotStore
from ..updaters.sgd_updater import SGDUpdaterParam
from ..utils import jaxtrace
from ..utils.progress import Progress, ReportProg
from .base import Learner, register

log = logging.getLogger("difacto_tpu")

# job types (sgd::Job, src/sgd/sgd_utils.h:16-21)
K_LOAD_MODEL, K_SAVE_MODEL, K_TRAINING, K_VALIDATION, K_PREDICTION, \
    K_EVALUATION = 1, 2, 3, 4, 5, 6


class _DeviceBatchCache:
    """Device-resident replay cache for staged batches (all store modes).

    Host->device transfer through a tunneled/remote chip measures ~5-10 MB/s
    while the fused step consumes packed batches far faster — steady-state
    epochs were transfer-bound (round-4 probe: 4 MB/batch at ~5 MB/s vs a
    ~30 ms device step). The first pass over a part stages each packed batch
    once and keeps the device buffers; later epochs replay them straight
    from HBM with ZERO host->device traffic. The TPU-native analog of the
    reference caching training data in memory between passes
    (src/data/tile_store.h:32-168) — here the cached unit is the packed,
    already-localized device batch.

    The hashed store stages on its FIRST pass: its capacity is fixed, so
    cached slot vectors (including their out-of-bounds padding) stay
    truthful forever. The dictionary store can GROW, which would pull
    padded indices back in bounds — but slot assignment itself is
    insertion-stable, so on a single host it ALSO stages on pass one
    and the replay entry rewrites each staged pad tail to the live
    capacity (``repadable`` / learner._repad_cache; round-5 — the old
    second-pass staging paid a whole extra streamed epoch). The MESH
    dictionary keeps ``stage_after_pass=1`` (its payloads are sharded
    global pairs) and any capacity change after staging invalidates the
    cache back to streaming. Shuffle degrades to
    a per-epoch permutation of cached batches within each part
    (row->batch assignment is frozen at staging time); neg_sampling != 1
    disables the cache (each epoch must resample).

    A dataset larger than the budget keeps the staged part PREFIX: the
    budget-filling part is dropped (a half-cached part can't replay) and
    staging freezes; later epochs replay the prefix from HBM and stream
    only the remaining parts, so a dataset 1.1x the budget pays the
    streaming cost for 0.1x of it, not all of it.

    Mesh and multi-host runs cache their staged global (DeviceBatch,
    slots) pairs ("devbatch" payloads): the epoch-seeded permutation is
    identical on every host, so replayed epochs rerun the same
    synchronized collective schedule with zero host->device transfers
    AND zero DCN control-plane handshakes.
    """

    def __init__(self, budget_mb: int, shared: Optional[dict] = None,
                 stage_after_pass: int = 0, repadable: bool = False) -> None:
        """``shared`` is a mutable ``{"used": bytes}`` pool: all caches of
        one learner (training + validation) draw from the SAME
        device_cache_mb budget, so actual HBM held never exceeds the
        configured cap however many job types cache.

        ``repadable``: staged payloads' OOB slot padding can be rewritten
        for a grown table (the single-host dictionary path — slot
        assignment is insertion-stable, only the padding aliases), so
        capacity growth marks the pads stale instead of invalidating."""
        self.budget = budget_mb << 20
        self.shared = shared if shared is not None else {"used": 0}
        self.used = 0
        self.entries: dict = {}   # part -> list of payload tuples
        self.part_bytes: dict = {}
        self.ready = False        # True once a staging pass completed
        self.alive = True
        self.frozen = False       # True once the budget filled mid-pass
        self.stage_after_pass = stage_after_pass
        self.repadable = repadable
        self.stale_pads = False   # some payloads padded at an older capacity
        self.passes = 0
        self.capacity: Optional[int] = None  # store capacity at staging

    @property
    def staging(self) -> bool:
        """True while the CURRENT pass should stage payloads."""
        return (self.alive and not self.frozen
                and self.passes == self.stage_after_pass)

    @property
    def partial(self) -> bool:
        """True when the cache holds a proper prefix of the parts: replay
        it, stream the rest (round-4 verdict weak #3 — a dataset 1.1x
        the budget used to lose the WHOLE cache and train ~6x slower
        than one 0.9x it)."""
        return self.frozen and bool(self.entries)

    def parts(self) -> set:
        return set(self.entries)

    def invalidate(self, reason: str) -> None:
        self.alive = False
        self.ready = False
        self.entries.clear()
        self.part_bytes.clear()
        self.shared["used"] -= self.used
        self.used = 0
        log.info("device batch cache invalidated (%s) — streaming", reason)

    def _freeze(self, drop_part: int, reason: str) -> None:
        """Budget filled: keep the fully-staged part prefix, drop the
        partially-staged part (a half-cached part can't replay — its
        remaining batches would be lost), stream everything else. Parts
        stage in canonical order, so the kept set is a prefix and
        replay-then-stream preserves the canonical part order."""
        self.frozen = True
        dropped = self.part_bytes.pop(drop_part, 0)
        self.entries.pop(drop_part, None)
        self.used -= dropped
        self.shared["used"] -= dropped
        log.info("device batch cache frozen (%s): keeping %d staged "
                 "part(s), streaming the rest", reason, len(self.entries))

    def add(self, part: int, payload, nbytes: int,
            capacity: Optional[int] = None) -> None:
        if not self.staging:
            return
        if capacity is not None:
            if self.capacity is None:
                self.capacity = capacity
            elif self.capacity != capacity:
                if self.repadable:
                    # dictionary growth mid-staging: earlier payloads'
                    # OOB padding is now stale; the replay entry repads
                    # them (learner._repad_cache) instead of refetching
                    self.capacity = capacity
                    self.stale_pads = True
                else:
                    self.invalidate("store capacity grew during staging")
                    return
        if self.shared["used"] + nbytes > self.budget:
            self._freeze(part, f"budget {self.budget >> 20} MB filled")
            return
        self.used += nbytes
        self.shared["used"] += nbytes
        self.entries.setdefault(part, []).append(payload)
        self.part_bytes[part] = self.part_bytes.get(part, 0) + nbytes

    def finish_pass(self) -> None:
        if self.alive and self.passes == self.stage_after_pass:
            self.ready = bool(self.entries)
            if self.frozen and not self.entries:
                # nothing fit — permanent streaming, stop probing
                self.alive = False
        self.passes += 1

    def iter_parts(self, shuffle: bool, seed: int):
        rng = np.random.RandomState(seed)
        for part in sorted(self.entries):
            items = self.entries[part]
            order = rng.permutation(len(items)) if shuffle \
                else range(len(items))
            for i in order:
                yield part, items[i]


# the sticky shape-cap schedule lives in data/pack_stream.py now: the
# process-based producer pipeline snapshots/absorbs it across the spawn
# boundary, and the packing helpers it governs are shared between the
# learner's threads and the worker processes
from ..data.pack_stream import ShapeSchedule as _ShapeSchedule  # noqa: E402


@dataclass
class SGDLearnerParam(Param):
    data_in: str = ""
    data_val: str = ""
    data_format: str = "libsvm"
    model_out: str = ""
    model_in: str = ""
    loss: str = "fm"
    max_num_epochs: int = 20
    load_epoch: int = -1
    batch_size: int = 100
    shuffle: int = 10
    neg_sampling: float = 1.0
    pred_out: str = ""
    pred_prob: bool = True
    num_jobs_per_epoch: int = 10
    report_interval: int = 1
    stop_rel_objv: float = 1e-5
    stop_val_auc: float = 1e-5
    has_aux: bool = False
    task: int = 0  # 0 = train, 2 = predict (main.cc task names train/predict)
    # host pipeline: producer threads preparing batches ahead of the device
    # (the reference's ThreadedParser + 3-thread worker pipeline,
    # sgd_learner.h:85-102); 0 = auto. Parts are dispatched to producers
    # through the WorkloadPool (pull-based self-scheduling,
    # dist_tracker.h:136-156) and consumed in canonical order, so
    # trajectories stay deterministic.
    num_producers: int = 0
    producer_depth: int = 3
    # re-issue a part stuck on a producer for > max(10 x mean part time,
    # this many seconds); 0 disables (straggler_timeout,
    # src/reader/workload_pool.h:155-176). Safe: generation-guarded
    # delivery keeps items exactly-once even if the original attempt wakes
    # up later (data/producer_pool.py).
    straggler_timeout: float = 0.0
    # per-step training metric: "binned" = O(B) histogram AUC (default),
    # "exact" = argsort AUC, "none". Validation is always exact (step.py).
    train_auc: str = "binned"
    # streamed-path producer transport: "thread" = in-process producer
    # threads (OrderedProducerPool), "process" = spawn worker processes
    # shipping packed batches through a shared-memory ring
    # (ProcessProducerPool + data/shm_ring.py) so host pack work truly
    # overlaps the dispatch loop instead of GIL-slicing against it;
    # "auto" picks process on hosts with >= 4 cores, thread below (the
    # spawn + ring overhead only pays when cores can actually overlap).
    # Process mode engages on the hashed-store streamed TRAINING path
    # while no device cache is staging (staged payloads pin device
    # buffers; ring slots must recycle) — other paths fall back to
    # threads. The ring holds num_producers x producer_depth slots.
    producer_mode: str = "auto"
    # bytes per ring slot, MB; 0 = auto-size from the packed-batch byte
    # budget (~batch_size * 320 B, floored at 1 MB). A batch that outgrows
    # its slot falls back to pickled transport — slower, never wrong.
    ring_slot_mb: int = 0
    # STREAMED panel training (no replay cache): build the chunked-run
    # backward layout on the producer threads so streamed steps take the
    # fast chunked step instead of the unsorted scatter (39 vs 73 ms at
    # bench shapes). OFF by default: the host sort measures ~9 us/example
    # /core against ~0.5 us/example of device time saved (an 18x core-
    # to-chip ratio), so it only pays on hosts with abundant spare cores
    # per chip AND num_producers raised to match. Ignored while a device
    # cache is staging (the staging-time device chunker derives the same
    # layout from buffers already on the chip — shipping host-built
    # chunks would double the staged bytes on the slow link). Chunking
    # ON DEVICE per step was also measured out (221 ms/step). Numbers:
    # docs/perf_notes.md "streamed chunking".
    stream_chunks: bool = False
    # STREAMED hashed training: ship RAW hashed token lanes and run the
    # unique-key dedup ON DEVICE (sort + run-length segment ids inside
    # the jit step, ops/fused.dedup_tokens) instead of the producer's
    # np.unique — the host pays only the hash plus an O(nnz+capacity)
    # distinct-count flag pass, shrinking the pack stage further
    # (ISSUE 13). Engages on panel-shaped training batches past the
    # epoch-0 count push while no replay cache may stage (the cache's
    # target regime replays from HBM anyway) and stream_chunks is off
    # (the chunked layout needs the host inverse). OFF by default: it
    # trades device sort time for host pack time, which only pays when
    # the producer cores are the bottleneck (the >HBM streamed regime).
    device_dedup: bool = False
    # HBM budget for the device-resident batch replay cache (0 disables).
    # Single-host hashed-store runs stage each packed batch once and replay
    # it from device memory every later epoch — essential when the
    # host<->device link is slow (tunneled chips measure ~5-10 MB/s).
    device_cache_mb: int = 2048
    # fault tolerance (parallel/fault.py): checkpoint every k epochs to
    # model_out WITH optimizer state (0 = only the final save), and resume
    # automatically from the newest such checkpoint at startup — the
    # recovery half of the dead-host protocol (the reference reloads a
    # saved model after a server loss, SURVEY §5.3).
    ckpt_interval: int = 0
    auto_resume: bool = False
    # retention for interval checkpoints: keep the newest k generations
    # (``_iter-*`` files + manifests), prune older ones after each save;
    # 0 = keep everything. Keep >= 2 so a torn newest generation still
    # leaves a verified one for auto_resume to walk back to.
    ckpt_keep: int = 0
    # SPMD mesh (parallel/mesh.py): feature shards ("servers") × data
    # parallelism ("workers"); 1×1 = single device. The reference analog is
    # launch.py's -s/-n server/worker counts.
    mesh_fs: int = 1
    mesh_dp: int = 1
    # instantiate the mesh even at 1x1 (normally 1x1 = no mesh): the
    # degenerate-mesh parity leg — the sharded program path must be
    # byte-identical to the flat path at fs=1 (tests/test_fs_sharding.py)
    mesh_force: bool = False
    # multi-host SPMD caps: every host must jit the same batch shapes, so
    # the per-host nnz / distinct-feature buckets are pinned up front
    # (0 = auto: bucket(batch_size * 64)). Single-host runs ignore these
    # and bucket per batch.
    nnz_cap: int = 0
    uniq_cap: int = 0
    # bounded-delay asynchronous training (the reference's max_delay τ,
    # SURVEY §5.7/§5.8): the control-plane exchange pipeline may run up
    # to τ steps AHEAD of the slowest peer's dispatched step before
    # blocking on its clock (multihost.post_clock/wait_clock). τ=0 is
    # the fully synchronous schedule — BYTE-IDENTICAL to the pre-window
    # code path (prefetch depth 2, no clock traffic); τ>0 deepens the
    # exchange window to 2+τ staged steps so a fast host overlaps its
    # pull->step->push pipeline with slow hosts' DCN exchanges. The
    # trajectory itself is τ-invariant: device steps stay collective-
    # synchronous on the global mesh (XLA collectives cannot lose a
    # member), so τ buys throughput, not a quality delta
    # (docs/perf_notes.md "Bounded-delay training"). -1 (default)
    # inherits DIFACTO_BOUNDED_DELAY from the launcher env (launch.py
    # --bounded-delay), else 0. τ>0 with a mesh also engages the
    # windowed SPMD schedule on a single host (its fast path).
    bounded_delay: int = -1
    # observability (difacto_tpu/obs): append a JSONL snapshot of the
    # run's metric registry to this path every metrics_interval_s (plus a
    # final flush at run end); "" disables. tools/obs_report.py renders
    # the log; DIFACTO_TRACE=<path> additionally captures span timelines.
    metrics_path: str = ""
    metrics_interval_s: float = 30.0
    # roll metrics_path to <path>.1 when it would exceed this many MB
    # (0 = unbounded) — long-running processes cap their event log
    metrics_max_mb: float = dataclasses.field(default=0.0,
                                              metadata=dict(lo=0))
    # durability (difacto_tpu/durability, ISSUE 20) — all OFF by
    # default; the defaults-off build is byte-identical to the
    # pre-durability path. wal_flush_batches > 0 turns on the
    # write-ahead delta log: every k dispatched training batches the
    # touched fused rows are appended as one CRC'd segment
    # (durability/wal.py), shrinking the recovery point objective from
    # ckpt_interval epochs to k batches. Single-host hashed-store
    # streamed training only (init() raises typed errors for
    # incompatible knobs); forces device_cache_mb=0 (replayed cached
    # batches bypass the dispatch path the WAL observes).
    wal_flush_batches: int = 0
    # comma-separated peer DIRECTORIES (a shared filesystem path or
    # per-peer mounts) that receive an async copy of each committed
    # checkpoint family + the live WAL chain (durability/replicate.py).
    # "" disables. With auto_resume, a host that lost its local dir
    # recovers by fetching the newest verifying peer replica
    # (durability/recover.py ladder).
    replica_peers: str = ""
    # how many of replica_peers each commit is pushed to (clamped to
    # the peer count); k >= 2 survives a peer loss concurrent with the
    # host loss
    replica_k: int = 1


@register("sgd")
class SGDLearner(Learner):
    def __init__(self) -> None:
        super().__init__()
        self.param: Optional[SGDLearnerParam] = None
        self.store: Optional[SlotStore] = None
        self._fo_pred = None

    # ----------------------------------------------------------- init
    def init(self, kwargs: KWArgs) -> KWArgs:
        self.param, remain = SGDLearnerParam.init_allow_unknown(kwargs)
        uparam, remain = SGDUpdaterParam.init_allow_unknown(remain)
        # the resolved loss owns the effective V_dim (loss=logit forces 0,
        # like the reference's linear path); thread it back so the store
        # never allocates or computes dead embedding state
        self.loss = create_loss(self.param.loss, uparam.V_dim)
        self.V_dim = self.loss.V_dim
        if uparam.V_dim != self.V_dim:
            uparam = dataclasses.replace(uparam, V_dim=self.V_dim)
        self.mesh = None
        if self.param.mesh_fs * self.param.mesh_dp > 1 \
                or self.param.mesh_force:
            from ..parallel import make_mesh
            self.mesh = make_mesh(dp=self.param.mesh_dp,
                                  fs=self.param.mesh_fs)
            if self.param.mesh_dp > 1:
                # dp-sharded chunk_lane blocks are sorted per shard but
                # not globally — the chunked backward must not promise
                # sorted indices to XLA (losses/__init__.py chunks_sorted)
                self.loss = dataclasses.replace(self.loss,
                                                chunks_sorted=False)
        self.store = SlotStore(uparam, mesh=self.mesh)
        self.do_embedding = self.V_dim > 0
        if self.param.train_auc not in ("binned", "exact", "none"):
            raise ValueError(
                f"unknown train_auc {self.param.train_auc!r} "
                "(expected binned|exact|none)")
        if self.param.producer_mode not in ("auto", "thread", "process"):
            raise ValueError(
                f"unknown producer_mode {self.param.producer_mode!r} "
                "(expected auto|thread|process)")
        # observability (difacto_tpu/obs): each learner instance keeps its
        # OWN registry so stage totals are attributable to this run (two
        # learners in one process — bench's replay + streamed windows —
        # must not blur together); producer worker processes report into
        # it through the pool's snapshot channel (obs/proc.py). The
        # streamed-epoch stage decomposition lives in
        # stage_seconds_total{stage}:
        #   parse    = read+parse half of the producer pipeline
        #   pack     = localize/slot-map/pack half
        #   ring_wait= producer blocked on a free shm-ring slot
        #   transfer = host->device staging of packed buffers
        #   step     = step dispatch + the metric-fetch waits where
        #              device time surfaces
        # bench.py's e2e.streamed.stages is stage_stats() over this
        # registry — no private timers.
        from ..obs import Registry
        self.obs = Registry()
        stage_c = self.obs.counter(
            "stage_seconds_total",
            "seconds spent per streamed-pipeline stage, summed over "
            "threads")
        self._stage_c = {k: stage_c.labels(stage=k)
                         for k in ("parse", "pack", "ring_wait",
                                   "transfer", "step")}
        self._step_h = self.obs.histogram(
            "train_step_seconds",
            "host-side dispatch+wait time of one fused device step")
        self._rows_c = self.obs.counter(
            "train_rows_total", "examples consumed by dispatched steps")
        self._gather_c = self.obs.counter(
            "store_gather_bytes_total",
            "slot-table row bytes gathered+scattered per dispatched "
            "device program").labels(path="train")
        self._last_producer_mode = "thread"
        self._flusher = None
        self._shapes = _ShapeSchedule()
        # job types whose data THIS process has fully passed over once —
        # after that the SPMD dictionary exchange ships slots instead of
        # ids (every id is known; a resumed process starts empty because
        # checkpoints drop all-zero entries, so its first pass re-inserts)
        self._dict_ids_done: set = set()
        # multi-controller: this host owns a contiguous slice of the global
        # file parts (parallel/multihost.py; the reference's Rank()/
        # NumWorkers() reader sharding)
        from ..parallel.multihost import host_part
        self._host_rank, self._num_hosts = host_part()
        # dead-host detection: UDP heartbeat mesh + blocked-collective
        # watchdog (parallel/fault.py; the reference's GetDeadNodes poll,
        # dist_tracker.h:164-186). Enabled by launch.py via DIFACTO_HB_*.
        from ..parallel import fault
        self.monitor = fault.from_env(self._host_rank, self._num_hosts)
        # bounded-delay window: explicit knob wins, else the launcher's
        # cluster-wide env (launch.py --bounded-delay), else synchronous
        self._tau = (self.param.bounded_delay
                     if self.param.bounded_delay >= 0
                     else int(os.environ.get("DIFACTO_BOUNDED_DELAY",
                                             "0")))
        # the synchronized/windowed SPMD schedule engages for any
        # multi-host mesh run, and on a single host when a τ>0 window is
        # requested (the windowed fast path: same schedule, clock posts
        # take their single-process early returns)
        self._spmd_schedule = self.mesh is not None and (
            self._num_hosts > 1 or self._tau > 0)
        if self._num_hosts > 1:
            if self.mesh is not None and self.param.mesh_dp \
                    % self._num_hosts:
                raise ValueError(
                    f"mesh_dp={self.param.mesh_dp} must be a multiple "
                    f"of the host count {self._num_hosts}")
        if self._spmd_schedule:
            # synchronized-step SPMD over a global mesh: every host
            # executes the same jitted step each iteration with a
            # pre-agreed shape schedule (_iterate_data_spmd); per-host
            # batch-count divergence is absorbed by empty padded
            # batches, uniq divergence by a slot-union allgather.
            # dp-sharded dims must divide the dp axis (see dim_min in
            # _iterate_data)
            from ..ops.batch import mesh_dim_min
            dmin = mesh_dim_min(self.param.mesh_dp)
            auto = bucket(self.param.batch_size * 64, dmin)
            self._spmd_b_cap = bucket(self.param.batch_size, dmin)
            self._spmd_nnz_cap = self.param.nnz_cap or auto
            self._spmd_u_cap = self.param.uniq_cap or auto
        if self._num_hosts > 1:
            # Both store modes work over a multi-host MESH. Hashed: slot
            # assignment is stateless modular hashing, identical on every
            # host for free. Dictionary (exact 64-bit ids, the reference's
            # server design — src/sgd/sgd_updater.h:141-176 grows
            # unordered_maps keyed by feature id, so no two features ever
            # alias): the synchronized schedule's control plane ships raw
            # uint64 ids instead of slots, and every host inserts the SAME
            # sorted id union into its dictionary in the same order, so
            # the replica id->slot maps stay bit-identical with no extra
            # communication rounds (_iterate_data_spmd exchange()).
            if self.mesh is None and not self.store.hashed:
                # without the mesh schedule there is no per-step exchange:
                # per-host slot assignment would silently train
                # independent replicas that never communicate — a
                # correctness footgun, not a mode (round-1 verdict item 7)
                raise ValueError(
                    "multi-host runs without a mesh require the hashed "
                    "store (set hash_capacity > 0, or set mesh_dp/mesh_fs "
                    "for the synchronized-step schedule): the dictionary "
                    "store assigns slots per-host outside the mesh "
                    "schedule, so hosts would train independent models "
                    "that never synchronize")
        self._init_durability()
        self._build_steps()
        return remain

    def _init_durability(self) -> None:
        """Durability legs (ISSUE 20, difacto_tpu/durability): the
        write-ahead delta log and the async peer replicator. Both
        default OFF; the WAL's compatibility gates raise TYPED errors
        (the SlotStore cold-tier precedent) because every listed knob
        changes rows outside the dispatch path the WAL observes —
        silently missing those writes would make replay silently
        wrong, the one failure mode this subsystem exists to exclude."""
        p = self.param
        self._wal = None
        self._replica = None
        self._wal_touched: list = []
        self._wal_step = 0
        self._wal_lo = 0
        self._wal_epoch = 0
        # batches of the re-entered epoch whose effects a WAL replay
        # already applied — the recovery ladder arms this and the
        # dispatch path fast-forwards past them (durability/recover.py)
        self._wal_skip = 0
        if p.wal_flush_batches > 0:
            if not p.model_out:
                raise ValueError(
                    "wal_flush_batches requires model_out: the delta "
                    "log lives in <model_out>.wal/")
            if not self.store.hashed:
                raise ValueError(
                    "wal_flush_batches requires the hashed store "
                    "(hash_capacity > 0): dictionary slots are assigned "
                    "at consume time, so a replayed delta has no stable "
                    "row space to land in")
            if self.mesh is not None or self._num_hosts > 1:
                raise ValueError(
                    "wal_flush_batches is single-host/flat-device only: "
                    "mesh and multi-host runs mutate rows through the "
                    "SPMD exchange, outside the dispatch path the WAL "
                    "observes")
            if self.store.tier is not None:
                raise ValueError(
                    "wal_flush_batches is incompatible with "
                    "cold_tier_rows: tier promotes/demotes rewrite rows "
                    "off the dispatch path, so replay would miss them")
            if self.store.param.evict_occupancy > 0:
                raise ValueError(
                    "wal_flush_batches is incompatible with "
                    "evict_occupancy: epoch-boundary eviction resets "
                    "rows outside the dispatch path the WAL observes")
            if p.device_dedup:
                raise ValueError(
                    "wal_flush_batches is incompatible with "
                    "device_dedup: panel_raw payloads derive slots "
                    "in-step and carry no host slots section to log")
            if p.device_cache_mb:
                # not an error — 2048 is the default: cached batches
                # replay from HBM through _replay_cached, bypassing the
                # dispatch path the WAL observes, so the cache is
                # forced off while the delta log runs
                log.info("wal_flush_batches: forcing device_cache_mb=0 "
                         "(HBM-replayed batches bypass the WAL's "
                         "dispatch hook)")
                self.param = dataclasses.replace(self.param,
                                                 device_cache_mb=0)
                p = self.param
            from ..durability.wal import WalWriter, wal_dir
            from ..obs import counter as _gcounter
            self._wal = WalWriter(wal_dir(p.model_out), self._host_rank,
                                  self.store.wal_geometry())
            self._wal_fail_c = _gcounter(
                "wal_append_failures_total",
                "WAL segment appends that failed (window retained and "
                "retried at the next flush boundary)")
        if p.replica_peers and p.model_out:
            from ..durability.replicate import Replicator, parse_peers
            self._replica = Replicator(
                parse_peers(p.replica_peers), p.replica_k,
                self._host_rank,
                root=os.path.dirname(p.model_out) or ".")

    def _build_steps(self) -> None:
        from ..ops.batch import unpack_batch
        from ..step import make_step_fns, state_constrainer
        fns = self.store.fns
        # mesh runs pin the table's fs key-range layout INSIDE every
        # program that returns state (step.state_constrainer): the
        # donated update stays in place across shards instead of
        # round-tripping through whatever layout GSPMD inference picks
        state_shardings = None
        if self.mesh is not None:
            from ..parallel import sharding_tree, state_sharding
            state_shardings = sharding_tree(self.store.state,
                                            state_sharding(self.mesh))
        constrain = state_constrainer(state_shardings)
        _, train_step, eval_step = make_step_fns(
            fns, self.loss, train_auc=self.param.train_auc,
            state_shardings=state_shardings)
        # every step program routes through jaxtrace.jit — identical to
        # jax.jit unless DIFACTO_JAXTRACE=1, in which case per-site
        # compile counts feed the jitmap/gate (analysis/jaxflow.py)
        self._train_step = jaxtrace.jit(train_step, donate_argnums=0)
        self._eval_step = jaxtrace.jit(eval_step)
        self._apply_count = jaxtrace.jit(
            lambda state, slots, counts: constrain(
                fns.apply_count(state, slots, counts)),
            donate_argnums=0)

        # packed single-transfer variants (ops/batch.py pack_batch): the
        # whole batch rides in one i32 + one f32 buffer — on tunneled or
        # remote devices per-transfer latency dominates the host->device
        # path, so 2 transfers/batch instead of 8
        def packed_train(state, i32, f32, b_cap, nnz_cap, u_cap, has_cnt,
                         binary):
            batch, slots, counts = unpack_batch(i32, f32, b_cap, nnz_cap,
                                                u_cap, has_cnt, binary)
            if counts is not None:
                state = fns.apply_count(state, slots, counts)
            return train_step(state, batch, slots)

        def packed_eval(state, i32, f32, b_cap, nnz_cap, u_cap, binary):
            batch, slots, _ = unpack_batch(i32, f32, b_cap, nnz_cap, u_cap,
                                           binary=binary)
            return eval_step(state, batch, slots)

        self._packed_train = jaxtrace.jit(packed_train, donate_argnums=0,
                                          static_argnums=(3, 4, 5, 6, 7))
        self._packed_eval = jaxtrace.jit(packed_eval,
                                         static_argnums=(3, 4, 5, 6))

        from ..ops.batch import unpack_panel

        def packed_panel_train(state, i32, f32, b_cap, width, u_cap,
                               has_cnt, binary):
            pb, slots, counts = unpack_panel(i32, f32, b_cap, width, u_cap,
                                             has_cnt, binary)
            if counts is not None:
                state = fns.apply_count(state, slots, counts)
            return train_step(state, pb, slots)

        def packed_panel_eval(state, i32, f32, b_cap, width, u_cap, binary):
            pb, slots, _ = unpack_panel(i32, f32, b_cap, width, u_cap,
                                        binary=binary)
            return eval_step(state, pb, slots)

        self._packed_panel_train = jaxtrace.jit(
            packed_panel_train, donate_argnums=0,
            static_argnums=(3, 4, 5, 6, 7))
        self._packed_panel_eval = jaxtrace.jit(packed_panel_eval,
                                               static_argnums=(3, 4, 5, 6))

        # chunked-run variant for cached replays: the backward's per-token
        # scatter becomes a dense chunk gather+reduce plus a ~U + B*F/L row
        # scatter (1.35x over the sorted path, 2.0x over unsorted at bench
        # shapes, docs/perf_notes.md). The layout is computed on device
        # ONCE at staging time (_panel_chunk_packed) and replayed with the
        # cached buffers — streaming epoch 0 keeps the unsorted step, so
        # this adds exactly one extra compile per run.
        def panel_chunk_packed(i32, f32, b_cap, width, u_cap, binary):
            # the chunk arrays are staged PRECOMPUTED (ci+cl+cv): like the
            # earlier sorted order, deriving them inside every replayed
            # step would break XLA's fusion around the reduction and pay
            # the argsort per step. Footprint: ~2x the packed i32 per
            # cached train batch; a budget overflow degrades gracefully
            # to streaming (cache.add kills the cache), so tight
            # device_cache_mb budgets lose the replay, not correctness.
            from ..ops.batch import panel_chunk_tokens_flat
            cells = b_cap * width
            flat = i32[:cells]
            vals = None if binary else f32[:cells]
            return panel_chunk_tokens_flat(flat, vals, u_cap, b_cap, width)

        self._panel_chunk_packed = jaxtrace.jit(panel_chunk_packed,
                                                static_argnums=(2, 3, 4, 5))

        def packed_panel_train_chunked(state, i32, f32, ci, cl, cv, b_cap,
                                       width, u_cap, has_cnt, binary):
            pb, slots, counts = unpack_panel(i32, f32, b_cap, width, u_cap,
                                             has_cnt, binary)
            if counts is not None:
                state = fns.apply_count(state, slots, counts)
            pb = pb._replace(chunk_idx=ci, chunk_lane=cl, chunk_vals=cv)
            return train_step(state, pb, slots)

        self._packed_panel_train_chunked = jaxtrace.jit(
            packed_panel_train_chunked, donate_argnums=0,
            static_argnums=(6, 7, 8, 9, 10))

        def packed_panel_train_raw(state, i32, f32, b_cap, width, u_cap,
                                   binary):
            # device-dedup streamed path (ISSUE 13): the payload's idx
            # cells are RAW hashed tokens; the sorted-unique slot
            # vector (OOB-padded, the kernel contract) and the inverse
            # index map are derived here, on device, per step. No
            # counts section — the raw path only engages past the
            # epoch-0 count push, where the zero-count apply_count is a
            # bit-level no-op (the pair-replay program omits it on the
            # same argument, _warm_pair_exec).
            from ..ops.batch import unpack_panel_raw
            from ..ops.fused import dedup_tokens
            pb = unpack_panel_raw(i32, f32, b_cap, width, binary)
            cells = b_cap * width
            slots, inverse, n = dedup_tokens(i32[:cells], u_cap,
                                             state.capacity)
            pb = pb._replace(idx=inverse.reshape(b_cap, width),
                             num_uniq=n)
            return train_step(state, pb, slots)

        self._packed_panel_train_raw = jaxtrace.jit(
            packed_panel_train_raw, donate_argnums=0,
            static_argnums=(3, 4, 5, 6))

        def packed_panel_train_chunked2(state, pa, pb, b_cap, width,
                                        u_cap, has_cnt, binary):
            # TWO cached batches in ONE dispatch (replay epochs only):
            # on tunneled/remote devices each program invocation costs
            # ~10 ms of host marshalling that a ~30-step replay epoch
            # pays in full; pairing halves the invocation count.
            # Straight-line composition, NOT lax.scan — the scan's
            # loop-carry copies on the gather-then-scatter table were
            # measured 55% slower at V64 (docs/perf_notes.md "scan
            # replay"); unrolling keeps the donated in-place update.
            state, o1, a1 = packed_panel_train_chunked(
                state, *pa, b_cap, width, u_cap, has_cnt, binary)
            state, o2, a2 = packed_panel_train_chunked(
                state, *pb, b_cap, width, u_cap, has_cnt, binary)
            return state, o1, a1, o2, a2

        # lint: ok(data-race) written once in _build_steps before any
        # warm-pool thread exists; workers only read the jitted fn
        self._packed_panel_train_chunked2 = jaxtrace.jit(
            packed_panel_train_chunked2, donate_argnums=0,
            static_argnums=(3, 4, 5, 6, 7))
        # statics-key -> compiled pair executable (or None while the
        # background compile runs / if it failed). Replay pairs ONLY
        # when the executable is ready, so the ~18 s pair compile never
        # lands on an epoch's critical path (_warm_pair_exec).
        # lint: ok(data-race) dict binding set before the first warm
        # thread spawns; workers mutate items, never rebind
        self._pair_execs: dict = {}
        # device-side zeroing of the packed f32 counts tail: replayed cache
        # entries must not re-push epoch-0 feature counts
        self._zero_counts = jaxtrace.jit(
            lambda f32, u_cap: f32.at[f32.shape[0] - u_cap:].set(0.0),
            static_argnums=1)

    # ----------------------------------------------------------- driver
    def _init_run_state(self) -> None:
        """Per-run state the epoch loop depends on: flusher, report
        accumulator, reporter monitor. Shared by run() and the online
        trainer (online/trainer.py), which drives _run_epoch directly
        per sealed log segment."""
        p = self.param
        self._start_time = time.monotonic()
        if p.metrics_path and self._flusher is None:
            # periodic JSONL export of this run's registry + the
            # process-global one (faults, DCN counters); final flush +
            # trace save happen in stop()
            from ..obs import REGISTRY, MetricsFlusher
            self._flusher = MetricsFlusher(
                p.metrics_path, p.metrics_interval_s,
                registries=[self.obs, REGISTRY],
                max_mb=p.metrics_max_mb).start()
        self._report = ReportProg()
        # live nnz(w)/penalty flow through the Reporter contract
        # (include/difacto/reporter.h:14-56): the part cadence reports a
        # Progress delta, the monitor folds in the store's nnz delta (the
        # reference's servers auto-report new_w, store.h:118-123,
        # sgd_updater.h:141-147) and prints the throttled row
        from ..utils.reporter import Reporter
        self._last_nnz = 0.0
        self._last_row_t = time.monotonic()
        self.reporter = Reporter(every=1)
        self.reporter.set_monitor(self._on_report)

    def run(self) -> None:
        """RunScheduler (sgd_learner.cc:52-122)."""
        p = self.param
        self._init_run_state()
        pre_loss, pre_val_auc = 0.0, 0.0
        k = 0

        if p.auto_resume and p.model_out:
            resumed = self._try_resume()
            if resumed is not None:
                k = resumed + 1
                log.info("auto-resumed from epoch %d checkpoint", resumed)
        if k == 0 and p.model_in:
            # prediction never updates the model: load weights-only so a
            # checkpoint's optimizer state (aux) is skipped entirely
            # (store/local.py load)
            wo = p.task == 2
            if p.load_epoch >= 0:
                log.info("loading model from epoch %d", p.load_epoch)
                self.store.load(self._model_name(p.model_in, p.load_epoch),
                                weights_only=wo)
                k = p.load_epoch + 1
            else:
                log.info("loading latest model...")
                self.store.load(self._model_name(p.model_in, -1),
                                weights_only=wo)

        if p.task == 2:
            if not p.model_in:
                raise ValueError("prediction needs model_in")
            prog = Progress()
            if self.mesh is None and self._num_hosts == 1:
                # single-controller batch prediction rides the SAME
                # bucketed predict executor as task=serve (serve/
                # executor.py), so offline pred files and online serve
                # responses are bit-identical for the same rows
                self._run_pred_executor(prog)
            else:
                self._run_epoch(k, K_PREDICTION, prog)
            log.info("prediction: %s", prog.text())
            self.stop()
            return

        while k < p.max_num_epochs:
            train_prog = Progress()
            self._run_epoch(k, K_TRAINING, train_prog)
            # epoch-end model stats: regularization penalty + nnz(w)
            # (the reference merges these from server Evaluate reports,
            # sgd_updater.cc:15-32); printed here, unconditionally, so an
            # all-zero model (nnz 0) is visible rather than suppressed
            train_prog.penalty, train_prog.nnz_w = self._take_eval_scalars()
            log.info("epoch[%d] training: %s, nnz(w) = %g, penalty = %g",
                     k, train_prog.text(), train_prog.nnz_w,
                     train_prog.penalty)

            # occupancy-pressure eviction (ISSUE 19, evict_occupancy):
            # epoch boundary only — one full-table column read, and the
            # dispatch queue is drained so demotes cannot race a step
            n_evicted = self.store.maybe_evict()
            if n_evicted:
                log.info("epoch[%d] evicted %d rows under occupancy "
                         "pressure", k, n_evicted)

            val_prog = Progress()
            if p.data_val:
                self._run_epoch(k, K_VALIDATION, val_prog)
                log.info("epoch[%d] validation: %s", k, val_prog.text())

            for cb in self.epoch_end_callbacks:
                cb(k, train_prog, val_prog)

            if p.ckpt_interval > 0 and p.model_out \
                    and (k + 1) % p.ckpt_interval == 0:
                self._save_checkpoint(k)

            # stop criteria (sgd_learner.cc:92-110): the reference divides by
            # pre_loss with no zero guard — first epoch never triggers
            eps = abs(train_prog.loss - pre_loss) / pre_loss \
                if pre_loss else float("inf")
            if eps < p.stop_rel_objv:
                log.info("change of loss [%g] < stop_rel_objv [%g]",
                         eps, p.stop_rel_objv)
                break
            if val_prog.auc > 0:
                eps = (val_prog.auc - pre_val_auc) / val_prog.nrows
                if eps < p.stop_val_auc:
                    log.info("change of val AUC [%g] < stop_val_auc [%g]",
                             eps, p.stop_val_auc)
                    break
            k += 1
            if k >= p.max_num_epochs:
                log.info("reached max_num_epochs %d", p.max_num_epochs)
                break
            pre_loss, pre_val_auc = train_prog.loss, val_prog.auc

        if p.model_out:
            log.info("saving final model...")
            final = self._model_name(p.model_out, -1)
            self.store.save(final, p.has_aux)
            if self._replica is not None:
                # the final model replicates too (stop() drains the
                # queue, so exit implies the peers hold it)
                import glob as _glob
                self._replica.push(sorted(_glob.glob(final + "*")))
        if self.store.fs_count > 1 or self.store.hashed:
            # per-shard occupancy gauges (docs/observability.md): one
            # full-table host read at run end, never per step. Hashed
            # stores publish even unsharded — the capacity levers'
            # occupancy/tier digest (tools/obs_report.py) reads these
            self.store.publish_shard_stats()
        self.stop()

    def stop(self) -> None:
        if self._fo_pred is not None:
            self._fo_pred.close()
            self._fo_pred = None
        if getattr(self, "_replica", None) is not None:
            # drain the push queue before exit: the last commit's
            # replica is the one a disk-loss recovery will need
            self._replica.close()
            self._replica = None
        if self._flusher is not None:
            self._flusher.close()
            self._flusher = None

    def _save_checkpoint(self, epoch: int) -> None:
        """Commit one resumable generation: a checkpoint WITH optimizer
        state so a restarted run continues the exact trajectory; the
        meta marker is written last (by host 0) so a crash mid-save
        resumes from the previous complete epoch. Shared by the
        epoch-cadence path (run) and the wall-clock cadence of the
        online trainer (online/trainer.py)."""
        p = self.param
        if self._wal is not None:
            # seal the open delta window first: the checkpoint then
            # supersedes every segment of the outgoing chain, and
            # rebase below roots a fresh chain at the new generation
            self._wal_flush()
        path = self._model_name(p.model_out, epoch)
        self.store.save(path, save_aux=True, epoch=epoch)
        if self._host_rank == 0:
            self._write_ckpt_meta(epoch)
            if p.ckpt_keep > 0:
                # rank 0 prunes the WHOLE generation family (every
                # rank's _iter-* parts via the meta+glob scan) —
                # per-rank pruning left an evicted rank's stale parts
                # behind forever, since the rank that wrote them is
                # gone (ROADMAP leftover from PR 3). Safe concurrently
                # with peers still writing: only epochs older than the
                # newest ckpt_keep are removed, and no rank rewrites an
                # old generation. ``protect`` (computed BEFORE the WAL
                # rebase below) pins the base epoch a live delta chain
                # or an in-flight replica push still references —
                # retiring either would orphan the chain / tear the
                # peer's copy (ISSUE 20 bugfix).
                from ..utils import manifest as mft
                mft.prune_checkpoints(
                    p.model_out, p.ckpt_keep,
                    protect=self._durability_protected_epochs())
        if self._wal is not None or self._replica is not None:
            from ..utils import manifest as mft
            man = mft.read(path) or {}
            gen = int(man.get("generation", 0))
            if self._wal is not None:
                self._wal.rebase(gen, epoch)
            if self._replica is not None:
                import glob as _glob
                files = sorted(_glob.glob(path + "*"))
                if self._host_rank == 0:
                    files.append(self._meta_path())
                self._replica.push(files, generation=gen, epoch=epoch)

    def _durability_protected_epochs(self) -> set:
        """Epochs ``ckpt_keep`` pruning must not retire right now: the
        base generation the live WAL chain is rooted at, plus any epoch
        an in-flight replica push still references. Released naturally
        — the next rebase / drained push stops reporting them."""
        prot: set = set()
        if self._wal is not None and self._wal.base_epoch is not None:
            prot.add(self._wal.base_epoch)
        if self._replica is not None:
            prot |= self._replica.protected_epochs()
        return prot

    # ----------------------------------------------------------- epochs
    def _model_name(self, prefix: str, it: int) -> str:
        # per-rank files like the reference's "<prefix>[_iter-k]_part-<rank>"
        # (ModelName, sgd_learner.h:65-69) — no cross-host write races
        name = prefix
        if it >= 0:
            name += f"_iter-{it}"
        return name + f"_part-{self._host_rank}"

    def _meta_path(self) -> str:
        return self.param.model_out + ".meta"

    def _write_ckpt_meta(self, epoch: int) -> None:
        import json

        from ..utils import stream
        with stream.open_stream(self._meta_path(), "w") as f:
            f.write(json.dumps({"last_epoch": epoch}))

    def _try_resume(self) -> Optional[int]:
        """auto_resume entry point. With the durability legs OFF this
        is exactly the classic local generation walk-back
        (:meth:`_try_resume_base` — the defaults-off build stays
        byte-identical to the pre-durability path). With
        ``wal_flush_batches`` / ``replica_peers`` on, resume climbs the
        recovery ladder instead: local walk-back -> peer replica fetch
        -> WAL replay to head (durability/recover.py), arming
        ``_wal_skip`` when the replayed head sits mid-epoch. Returns
        the last completed epoch (may be -1: WAL-only progress on a
        virgin base) or None."""
        if getattr(self, "_wal", None) is None \
                and not self.param.replica_peers:
            got = self._try_resume_base()
            return got[0] if got is not None else None
        from ..durability import recover
        return recover.run_ladder(self)

    def _try_resume_base(self) -> Optional[Tuple[int, str]]:
        """Load the newest interval checkpoint THAT VERIFIES
        (ckpt_interval/auto_resume; the recovery leg of parallel/fault.py).
        Returns (completed epoch, loaded checkpoint path) or None — the
        path lets the recovery ladder read the base generation its WAL
        replay chains onto.

        Candidates come from the meta marker AND a direct ``_iter-*``
        scan — a crash mid-checkpoint can leave a torn part behind the
        meta epoch (meta written last) or a meta pointing at bytes that
        never finished. Each candidate is manifest-verified
        (require_manifest: every checkpoint this code writes has one, so
        a missing sidecar means a torn save); corrupt generations are
        logged and skipped, walking back to the newest good one instead
        of crashing. A host joining after an eviction may not have
        written the part file itself — any rank's part works, because
        the store state is host-complete in both modes (table replicated
        over dp; the dictionary replicas are bit-identical by
        construction, multihost.py)."""
        import json
        import re

        from ..store.local import CheckpointCorrupt
        from ..utils import manifest as mft
        from ..utils import stream
        epochs = set()
        try:
            with stream.open_stream(self._meta_path(), "r") as f:
                epochs.add(int(json.loads(f.read())["last_epoch"]))
        except (FileNotFoundError, OSError, ValueError, KeyError):
            pass
        for path in stream.glob(self.param.model_out + "_iter-*_part-*"):
            if path.endswith(mft.MANIFEST_SUFFIX):
                continue
            m = re.search(r"_iter-(\d+)_part-", path)
            if m:
                epochs.add(int(m.group(1)))
        for epoch in sorted(epochs, reverse=True):
            base = self.param.model_out + f"_iter-{epoch}_part-"
            for rank in [self._host_rank] + list(range(self._num_hosts + 8)):
                try:
                    self.store.load(base + str(rank),
                                    require_manifest=True)
                    return epoch, base + str(rank)
                except (FileNotFoundError, OSError):
                    continue
                except CheckpointCorrupt as e:
                    log.warning("auto_resume: %s; walking back", e)
                    continue
        if epochs:
            log.warning("checkpoint meta/parts found but no generation "
                        "verified; starting fresh")
        return None

    def _run_epoch(self, epoch: int, job_type: int, prog: Progress) -> None:
        from ..obs import trace
        with trace.span("epoch", epoch=epoch, job=job_type):
            self._run_epoch_body(epoch, job_type, prog)

    def _run_epoch_body(self, epoch: int, job_type: int,
                        prog: Progress) -> None:
        p = self.param
        n_jobs = p.num_jobs_per_epoch if job_type == K_TRAINING else 1
        if self._spmd_schedule:
            cache = self._get_cache(job_type)
            cached_parts: set = set()
            if cache is not None and cache.ready:
                if (cache.capacity is not None
                        and cache.capacity != self.store.state.capacity):
                    # staged slot padding is only truthful at the staging
                    # capacity (pad_slots_oob); the dictionary store can
                    # grow if genuinely-new ids arrive after staging
                    cache.invalidate("store capacity changed since staging")
                else:
                    # replay the staged prefix; a partial cache streams the
                    # remaining parts below (same canonical part order: the
                    # cached set is a prefix, _DeviceBatchCache._freeze)
                    self._replay_cached(job_type, epoch, cache, prog)
                    if not cache.partial:
                        return
                    cached_parts = cache.parts()
            before = Progress(nrows=prog.nrows, loss=prog.loss,
                              auc=prog.auc)
            for part in range(n_jobs):
                if part in cached_parts:
                    continue
                self._iterate_data_spmd(job_type, epoch, part, n_jobs, prog)
                if self._row_due(job_type):
                    self._report_part(job_type, before, prog)
                    before = Progress(nrows=prog.nrows, loss=prog.loss,
                                      auc=prog.auc)
            if prog.nrows > before.nrows:
                self._report_part(job_type, before, prog)
            # a full pass completed: the dictionary now holds every id of
            # this job's data, so later streamed passes exchange slots
            self._dict_ids_done.add(job_type)
            if cache is not None and not cache.ready:
                cache.finish_pass()
            return
        self._iterate_parts(job_type, epoch, n_jobs, prog)

    def _part_reports(self, job_type: int) -> bool:
        """Whether per-part progress rows are live for this job. When they
        are not, the part loops skip the per-part metric merge entirely:
        each merge is a SYNCHRONOUS device fetch (~an RTT on a tunneled
        chip), and a many-part epoch otherwise stalls once per part for a
        row nobody prints (measured ~3.5 s of a 7.5 s replay epoch on 62
        rec members). Pending still merges every _MERGE_CAP batches so
        the epoch-final stack stays bounded."""
        return job_type == K_TRAINING and self.param.report_interval > 0

    def _row_due(self, job_type: int) -> bool:
        """TIME-throttled part-boundary rows: a part boundary emits a row
        only when ``report_interval`` seconds have elapsed since the last
        one (the reference prints on a time interval too,
        sgd_learner.cc:242-247; here boundaries are the only candidate
        sites, so the cadence floor is one row per part). The throttle
        matters because a part-boundary row costs a SYNCHRONOUS device
        fetch (the pending metric merge plus the monitor's nnz(w)
        evaluate) and, on the replay path, flushes the held pair — at the
        default interval the §4 replay epoch measured 5.25 s with a row
        per part vs 2.12 s with rows only when due (docs/perf_notes.md
        round-5)."""
        return (self._part_reports(job_type)
                and time.monotonic() - self._last_row_t
                >= self.param.report_interval)

    # max dispatched-batch metrics held before a merge when per-part
    # reporting is off: bounds the epoch-final jnp.stack operand count
    # (and the live tiny device buffers) while amortizing the fetch RTT
    # over ~256 steps
    _MERGE_CAP = 256

    def _report_part(self, job_type: int, before: Progress, prog: Progress
                     ) -> None:
        """Throttled progress row after a part, like the reference's
        per-batch reporter messages (sgd_learner.cc:242-247)."""
        if not self._part_reports(job_type):
            return
        self._last_row_t = time.monotonic()
        self.reporter.report(Progress(
            nrows=prog.nrows - before.nrows,
            loss=prog.loss - before.loss,
            auc=prog.auc - before.auc))

    def _on_report(self, node_id: int, delta: Progress) -> None:
        """Reporter monitor: fold the store's nnz(w) DELTA into the row
        (the reference accumulates per-report new_w into the live total,
        sgd_utils.h:97-110) and print. The penalty half of evaluate() is
        surfaced on the epoch line instead (_run_epoch), not here — the
        live row format has no penalty column."""
        _, nnz = self.store.evaluate()
        delta.nnz_w = nnz - self._last_nnz
        self._last_nnz = nnz
        elapsed = time.monotonic() - self._start_time
        self._report.prog.merge(delta)
        print(f"{elapsed:5.0f}  {self._report.print_str()}", flush=True)

    def _make_reader(self, job_type: int, epoch: int, g_idx: int,
                     g_num: int):
        p = self.param
        if job_type == K_TRAINING:
            # vary the shuffle/sampling stream across epochs and parts (the
            # reference's std::random_shuffle advances global state per epoch)
            return BatchReader(p.data_in, p.data_format, g_idx, g_num,
                               p.batch_size, p.batch_size * p.shuffle,
                               p.neg_sampling,
                               seed=epoch * max(g_num, 1) + g_idx)
        return Reader(p.data_val or p.data_in, p.data_format, g_idx, g_num,
                      chunk_bytes=256 << 20)

    def _iterate_data_spmd(self, job_type: int, epoch: int, part_idx: int,
                           num_parts: int, prog: Progress) -> None:
        """Synchronized-step multi-host epoch (verdict item 4; reference
        analog: ps-lite's rendezvous + barrier schedule,
        src/store/kvstore_dist.h:61-70).

        Protocol per step, identical on every host:
        1. read the next LOCAL batch (or none — this host is out of data);
        2. allgather [local key list | local counts | nu | fmax | rows |
           has-data] over DCN (parallel/multihost.py) — keys are int32
           slots in hashed mode, raw uint64 feature ids in dictionary mode
           (see exchange());
        3. every host deterministically computes the key UNION -> the
           replicated scatter/gather index vector, and remaps its local COO
           columns into union positions;
        4. run the SAME jitted train/eval step over the global mesh: batch
           arrays dp-sharded from per-host blocks, slot union replicated.
        The epoch ends when no host has data, so all hosts issue the same
        number of collective-bearing programs (no SPMD deadlock).

        **Bounded delay** (τ = ``bounded_delay``, the reference's
        ``max_delay``): with τ=0 this function IS the synchronous
        schedule above — no clock machinery runs and the trajectory is
        byte-identical to the pre-τ code path. With τ>0 the exchange
        pipeline below runs up to ``2+τ`` steps ahead of the device
        dispatch, and a clock-vector barrier bounds the skew: each host
        posts a clock key after dispatching step t (post_clock) and the
        exchange thread, before staging step s, blocks until every peer
        has dispatched step ``s-τ-1`` (wait_clock). Fast hosts overlap
        their pull→step→push pipeline with slow hosts' DCN exchanges up
        to the window; because waits are on strictly earlier peer steps
        the protocol is deadlock-free, and because device steps remain
        collective-synchronous on the global mesh the MODEL trajectory
        is τ-invariant — τ only moves wait time off the critical path.
        """
        from ..parallel import put_dp_local, put_global, replicated
        from ..parallel.multihost import clock_open, control_allgather_np, \
            control_cleanup, post_clock, wait_clock

        p = self.param
        cache = self._get_cache(job_type)
        push_cnt = (job_type == K_TRAINING and epoch == 0
                    and self.do_embedding)
        g_idx = self._host_rank * num_parts + part_idx
        g_num = num_parts * self._num_hosts
        reader = self._make_reader(job_type, epoch, g_idx, g_num)
        b_cap, nnz_cap = self._spmd_b_cap, self._spmd_nnz_cap
        u_cap = self._spmd_u_cap
        tau = self._tau
        # windowed-mode state (all untouched when τ=0, keeping that path
        # byte-identical): a fresh clock generation per part — every host
        # opens generations in the same deterministic order, so the ids
        # agree with no communication — and shared step counters between
        # the exchange thread (sent) and the dispatch loop (done).
        # list-cell counters: int append/item assignment is atomic under
        # the GIL, and each cell has a single writer.
        clock_gen = clock_open() if tau > 0 else -1
        sent = [0]   # steps the exchange thread has staged (yielded)
        done = [0]   # steps the dispatch loop has issued to the device
        if tau > 0:
            from ..obs import counter, gauge, histogram
            stale_g = gauge(
                "train_staleness_batches",
                "bounded-delay pipeline skew: staged-ahead batches not "
                "yet dispatched on this host").labels(
                    rank=str(self._host_rank))
            wait_c = counter(
                "exchange_wait_seconds_total",
                "seconds the windowed exchange thread spent blocked on "
                "peer clocks (τ-window full)")
            delay_h = histogram(
                "push_delay_batches",
                "batches of delay between staging a step and posting "
                "its clock (bounded above by τ + pipeline depth)",
                bounds=(0, 1, 2, 4, 8, 16, 32))

        def produce():
            for blk in reader:
                if job_type == K_TRAINING:
                    yield blk, compact(blk, need_counts=push_cnt)
                    continue
                # eval/pred reads arrive as 256MB Reader chunks; the SPMD
                # shape schedule pins b_cap to bucket(batch_size), so slice
                # into row windows that fit BOTH the row and nnz caps
                # before the synchronized steps (uniq <= nnz <= nnz_cap)
                s = 0
                while s < blk.size:
                    e = min(s + p.batch_size, blk.size)
                    lim = blk.offset[s] + min(nnz_cap, u_cap)
                    e_nnz = int(np.searchsorted(blk.offset, lim,
                                                side="right")) - 1
                    e = max(min(e, e_nnz), s + 1)
                    sub = blk.slice(s, e)
                    s = e
                    yield sub, compact(sub, need_counts=False)

        from ..data.prefetch import prefetch

        def exchange():
            """Control-plane + staging pipeline stage, run ``depth`` steps
            ahead of the device dispatch on a prefetch thread (round-4
            verdict weak #6: the synchronous per-step DCN allgather used
            to sit between device steps; now it overlaps them). Yields
            fully staged (batch, slots_dev, counts_dev, nrows, cblk,
            grow) tuples; the main thread only applies deferred
            dictionary growth and counts (store-state order) and
            dispatches steps. Every host runs this stage in
            the same step order, so the cross-host collective sequence
            is unchanged — just earlier.

            produce() is consumed INLINE here (not through a second
            prefetch thread): this whole generator already runs ahead of
            the main loop, and a third Python thread measurably starves
            the dispatch loop on single-CPU hosts (GIL churn against the
            collective's busy-wait)."""
            it = iter(produce())
            hashed = self.store.hashed
            # dictionary mode defers device-state growth to the dispatch
            # thread (map_keys(grow=False) + grow markers in the yielded
            # tuples): growing here would swap the table buffers under an
            # in-flight step. cap_logical tracks the capacity the dispatch
            # thread WILL have when each batch steps, so the OOB slot
            # padding below is computed against the right table size.
            cap_logical = self.store.state.capacity
            # id-exchange is only needed while the dictionary can still
            # gain entries: the first full pass over this job's data (or
            # every pass when training resamples rows). Afterwards every
            # id is known on every host, so streamed passes ship int32
            # slots — half the DCN control bytes, no union re-insert.
            # This is the regime the >HBM (1TB) config lives in: replay
            # epochs skip DCN entirely, but a dataset that cannot replay
            # pays the exchange every step of every epoch.
            use_ids = (not hashed
                       and (job_type not in self._dict_ids_done
                            or (job_type == K_TRAINING
                                and p.neg_sampling != 1)))
            while True:
                if tau > 0:
                    # τ-window barrier: before staging step s, every peer
                    # must have DISPATCHED step s-τ-1 (its clock key is
                    # posted after dispatch, see the main loop below).
                    # Each wait targets a strictly earlier peer step, so
                    # the pairwise blocking can never cycle (deadlock-
                    # free); within the window the waits return
                    # instantly and the DCN exchange overlaps the peers'
                    # device steps.
                    need = sent[0] - tau - 1
                    if need >= 0:
                        waited = 0.0
                        for r in range(self._num_hosts):
                            if r == self._host_rank:
                                continue
                            if self.monitor is not None:
                                waited += self.monitor.guarded(
                                    wait_clock, clock_gen, r, need)
                            else:
                                waited += wait_clock(clock_gen, r, need)
                        if waited:
                            wait_c.inc(waited)
                item = next(it, None)
                # [keys(u) | counts(u) if push_cnt | nu | fmax | nrows |
                # has] — the counts half is only shipped on the epoch-0
                # count push; fmax (this host's max row nnz) lets every
                # host agree on the panel-vs-COO layout for the step.
                # Hashed store: keys are int32 slots (stateless modular
                # hashing is host-consistent for free). Dictionary store,
                # first pass (use_ids): keys are the raw uint64 feature
                # ids — every host inserts the identical sorted id UNION
                # into its dictionary in the same order each step, so the
                # replica id->slot maps stay bit-identical (the
                # reference's exact-id server design,
                # src/sgd/sgd_updater.h:141-176, at 2x the control
                # bytes). Dictionary, later passes: int32 slots like the
                # hashed store — the dictionary is complete, so lookups
                # suffice and the payload halves.
                payload = np.zeros((2 * u_cap if push_cnt else u_cap) + 4,
                                   dtype=np.uint64 if use_ids else np.int32)
                cblk = slots_np = None
                uniq = None
                if item is not None:
                    blk, (cblk, uniq, cnts) = item
                    if use_ids:
                        # sorted unique byte-reversed ids from compact();
                        # mapping to slots happens after the union below
                        local_keys = uniq
                    elif hashed:
                        slots_np, remap, cnts = self.store.map_keys_dedup(
                            uniq, cnts)
                        if remap is not None:
                            cblk = dataclasses.replace(
                                cblk,
                                index=remap[cblk.index].astype(np.uint32))
                        local_keys = slots_np
                    else:
                        # dictionary slot mode (every pass after the
                        # first): all ids are known, ship their slots
                        slots_l = self.store.lookup(uniq)
                        from ..updaters.sgd_updater import TRASH_SLOT
                        if (slots_l == TRASH_SLOT).any():
                            raise RuntimeError(
                                "dictionary slot-exchange saw an unknown "
                                "feature id after the first pass — the "
                                "input data changed between epochs "
                                "(fixed data inserts every id on pass 0)")
                        # dictionary slots are insertion-ordered; the
                        # schedule needs them sorted with the COO columns
                        # remapped to match
                        slots_np, remap = np.unique(slots_l,
                                                    return_inverse=True)
                        slots_np = slots_np.astype(np.int32)
                        cblk = dataclasses.replace(
                            cblk, index=remap[cblk.index].astype(np.uint32))
                        # counts never reach this branch: push_cnt is
                        # epoch-0-only and epoch 0 always runs in id mode
                        local_keys = slots_np
                        self._spmd_slot_steps = getattr(
                            self, "_spmd_slot_steps", 0) + 1
                    nu = len(local_keys)
                    if nu > u_cap or blk.nnz > nnz_cap or blk.size > b_cap:
                        raise ValueError(
                            f"batch (rows={blk.size}, nnz={blk.nnz}, "
                            f"uniq={nu}) exceeds the multi-host shape "
                            f"schedule (b_cap={b_cap}, nnz_cap={nnz_cap}, "
                            f"uniq_cap={u_cap}); raise nnz_cap/uniq_cap in "
                            "the config (b_cap follows batch_size — raise "
                            "batch_size if rows exceed it)")
                    payload[:nu] = local_keys
                    if push_cnt and cnts is not None:
                        payload[u_cap:u_cap + nu] = cnts.astype(
                            payload.dtype)
                    counts_r = np.diff(cblk.offset)
                    payload[-4] = nu
                    payload[-3] = int(counts_r.max()) if len(counts_r) else 0
                    payload[-2] = blk.size
                    payload[-1] = 1
                # DCN control-plane exchange over the deviceless KV
                # channel (multihost.control_allgather_np — a
                # device-collective gather here would interleave with the
                # step stream in host-dependent order and deadlock),
                # guarded by the dead-host monitor: a dead peer raises
                # HostFailure before entry (or aborts via the watchdog if
                # it dies mid-collective) instead of hanging the
                # surviving hosts forever
                if self.monitor is not None:
                    g = self.monitor.guarded(control_allgather_np, payload)
                else:
                    g = control_allgather_np(payload)  # [n_hosts, (2u|u)+4]
                if g[:, -1].max() == 0:
                    return
                nus = g[:, -4].astype(np.int64)
                spans = [g[h, :nus[h]] for h in range(g.shape[0]) if nus[h]]
                union = (np.unique(np.concatenate(spans)) if spans
                         else np.empty(0, payload.dtype))
                grow = None
                if not use_ids:
                    # union is already the sorted unique global slot list
                    slots_sorted = union.astype(np.int32)
                    rank = None
                else:
                    # deterministic replica insert: identical union array +
                    # identical prior dictionary => identical new-slot
                    # assignment on every host (induction from empty)
                    slots_u = self.store.map_keys(union, grow=False)
                    new_cap = self.store.capacity_for(
                        self.store.next_slot, current=cap_logical)
                    if new_cap != cap_logical:
                        cap_logical = grow = new_cap
                    # dictionary slots are insertion-ordered, the device
                    # kernels need them sorted ascending — sort, and keep
                    # the rank permutation to translate union positions
                    order = np.argsort(slots_u)
                    slots_sorted = slots_u[order].astype(np.int32)
                    rank = np.empty(len(order), dtype=np.int64)
                    rank[order] = np.arange(len(order))
                gu = len(slots_sorted)
                gu_cap = bucket(gu)
                from ..store.local import pad_slots_oob
                slots_g = pad_slots_oob(slots_sorted, gu_cap, cap_logical)
                slots_dev = put_global(slots_g, replicated(self.mesh))
                cts_dev = None
                if push_cnt:
                    cts = np.zeros(gu_cap, dtype=np.float64)
                    for h in range(g.shape[0]):
                        k = int(nus[h])
                        hs, hc = g[h, :k], g[h, u_cap:u_cap + k]
                        pos = np.searchsorted(union, hs)
                        if rank is not None:
                            pos = rank[pos]
                        np.add.at(cts, pos, hc.astype(np.float64))
                    cts_dev = put_global(cts.astype(np.float32),
                                         replicated(self.mesh))
                # this host's localized column ids -> positions in the
                # sorted global slot list (shared by the panel + COO
                # layouts below)
                pos_local = None
                if cblk is not None:
                    if use_ids:
                        pos_local = rank[np.searchsorted(union, uniq)]
                    else:
                        pos_local = np.searchsorted(union, slots_np)
                    pos_local = pos_local.astype(np.int64)

                nrows_g = int(g[:, -2].sum())
                fmax_g = int(g[:, -3].max())
                # global panel decision (every host computes it from the
                # same allgathered metadata, so the jitted program
                # agrees): the fixed-width panel + chunked-run backward is
                # the fast step (docs/perf_notes.md); COO remains for
                # heavily skewed rows and for eval/pred (whose Reader
                # windows are ragged)
                use_panel = (job_type == K_TRAINING and fmax_g > 0
                             and b_cap * fmax_g <= 1.5 * nnz_cap)
                if use_panel:
                    width_cap = self._shapes.cap("spmd.w", fmax_g,
                                                 exact=True)
                    cblk2 = None
                    if cblk is not None:
                        cblk2 = dataclasses.replace(
                            cblk,
                            index=pos_local[cblk.index].astype(np.uint32))
                    pb = self._panel_host_batch(
                        cblk2, gu, b_cap, width_cap, gu_cap,
                        dp_div=max(1, p.mesh_dp // self._num_hosts),
                        row_base=self._host_rank * b_cap,
                        b_fill=b_cap * self._num_hosts,
                        force_vals=True)
                    from ..ops.batch import PanelBatch
                    batch = PanelBatch(
                        idx=put_dp_local(pb.idx, self.mesh),
                        vals=put_dp_local(pb.vals, self.mesh),
                        labels=put_dp_local(pb.labels, self.mesh),
                        rweight=put_dp_local(pb.rweight, self.mesh),
                        row_mask=put_dp_local(pb.row_mask, self.mesh),
                        num_rows=put_global(np.int32(nrows_g),
                                            replicated(self.mesh)),
                        num_uniq=put_global(np.int32(gu),
                                            replicated(self.mesh)),
                        chunk_idx=put_dp_local(pb.chunk_idx, self.mesh),
                        chunk_lane=put_dp_local(pb.chunk_lane, self.mesh),
                        chunk_vals=put_dp_local(pb.chunk_vals, self.mesh),
                    )
                    self._spmd_panel_steps = getattr(
                        self, "_spmd_panel_steps", 0) + 1
                else:
                    # local block at the pinned caps (zeros = inert
                    # padding)
                    rows = np.zeros(nnz_cap, dtype=np.int32)
                    cols = np.zeros(nnz_cap, dtype=np.int32)
                    vals = np.zeros(nnz_cap, dtype=np.float32)
                    labels = np.zeros(b_cap, dtype=np.float32)
                    rweight = np.zeros(b_cap, dtype=np.float32)
                    row_mask = np.zeros(b_cap, dtype=np.float32)
                    if cblk is not None:
                        b, nnz = cblk.size, cblk.nnz
                        # row ids address the GLOBAL label space: this
                        # host's rows live at [rank*b_cap, rank*b_cap + b)
                        # of the concatenated dp batch
                        base = self._host_rank * b_cap
                        rows[:nnz] = cblk.row_ids() + base
                        rows[nnz:] = base + max(b - 1, 0)
                        cols[:nnz] = pos_local[cblk.index]
                        vals[:nnz] = cblk.values_or_ones()
                        labels[:b] = cblk.label
                        rweight[:b] = (cblk.weight
                                       if cblk.weight is not None else 1.0)
                        row_mask[:b] = 1.0

                    from ..ops.batch import DeviceBatch
                    batch = DeviceBatch(
                        rows=put_dp_local(rows, self.mesh),
                        cols=put_dp_local(cols, self.mesh),
                        vals=put_dp_local(vals, self.mesh),
                        labels=put_dp_local(labels, self.mesh),
                        rweight=put_dp_local(rweight, self.mesh),
                        row_mask=put_dp_local(row_mask, self.mesh),
                        num_rows=put_global(np.int32(nrows_g),
                                            replicated(self.mesh)),
                        num_uniq=put_global(np.int32(gu),
                                            replicated(self.mesh)),
                    )
                sent[0] += 1
                yield batch, slots_dev, cts_dev, nrows_g, cblk, grow

        pending: list = []
        # τ deepens the staging pipeline: the exchange thread may run up
        # to 2+τ steps ahead of the dispatch loop (τ=0 keeps the historic
        # depth-2 double-buffer, so that path is untouched)
        for batch, slots_dev, cts_dev, nrows_g, cblk, grow in prefetch(
                exchange(), depth=2 + tau):
            if grow is not None:
                # deferred dictionary growth (see exchange()): applied in
                # step order on this thread, BEFORE the first step whose
                # slots address the grown table
                self.store.grow_to(grow)
            if cts_dev is not None:
                # epoch-0 feature-count push; applied on the main thread
                # so store-state mutations stay ordered with the steps
                self.store.state = self._apply_count(
                    self.store.state, slots_dev, cts_dev)
            from ..step import fire_step_fault
            fire_step_fault()
            # table row traffic of this synchronized step (PR 12
            # leftover: the SPMD drain path never counted it): the
            # replicated global slot union is pulled once — and pushed
            # once when training — at the fused-row width
            # (updaters.gather_bytes; docs/observability.md)
            from ..updaters.sgd_updater import gather_bytes
            per_dir = gather_bytes(self.store.param,
                                   self.store.state.capacity,
                                   slots_dev.shape[0])
            self._gather_c.inc(
                per_dir * (2 if job_type == K_TRAINING else 1))
            if job_type == K_TRAINING:
                self.store.state, objv, auc = self._train_step(
                    self.store.state, batch, slots_dev)
            else:
                pred, objv, auc = self._eval_step(self.store.state, batch,
                                                  slots_dev)
                if job_type == K_PREDICTION and p.pred_out and \
                        cblk is not None:
                    # pred is dp-sharded; this host's rows are its own block
                    from ..parallel.multihost import local_rows
                    lo = self._host_rank * b_cap
                    self._save_pred(
                        local_rows(pred, lo, lo + cblk.size), cblk.label)
            if cache is not None and cache.staging:
                # stage the global (batch, slots) pair: replayed epochs
                # rerun the identical synchronized step schedule on every
                # host with NO DCN handshakes (counts were applied during
                # this streaming pass, so replays never re-count).
                # NOTE the budget charges per-HOST resident bytes; the
                # add() SEQUENCE is still identical across hosts (same
                # global payloads, same device counts per host on a
                # uniform mesh), so alive flips in lockstep
                cache.add(part_idx,
                          ("devbatch", batch, slots_dev, nrows_g),
                          self._payload_nbytes((batch, slots_dev)),
                          capacity=self.store.state.capacity)
            pending.append((nrows_g, objv, auc))
            if tau > 0:
                # step done[0] is now in flight on the device — publish
                # this host's clock so peers' windows can advance, and
                # account the pipeline skew (staged-ahead minus
                # dispatched = how many batches of delay the window is
                # currently absorbing)
                done[0] += 1
                post_clock(clock_gen, done[0] - 1)
                ahead = sent[0] - done[0]
                stale_g.set(float(ahead))
                delay_h.observe(float(ahead))

        # draining the pending step results blocks on device programs that
        # contain cross-host collectives — keep the dead-host watchdog armed
        # (a peer dying after the final allgather but before its queued
        # steps complete would otherwise hang this fetch forever)
        import contextlib
        drain_guard = (self.monitor.collective() if self.monitor is not None
                       else contextlib.nullcontext())
        with drain_guard:
            # ONE stacked transfer for the whole part's metric scalars —
            # the per-step float(np.asarray(objv))/float(np.asarray(auc))
            # pair this replaces paid TWO blocking device->host RTTs per
            # step (the single-host path batched this in _merge_pending
            # since round 5; the SPMD drain predates it and never did —
            # found by the jax-host-sync pass, difacto-lint v4)
            if pending:
                vals = jaxtrace.fetch(
                    jnp.stack([s for _, o, a in pending
                               for s in (o, a)]),
                    point="sgd.spmd_metrics")
                for i, (nrows, _o, _a) in enumerate(pending):
                    prog.merge(Progress(nrows=nrows,
                                        loss=float(vals[2 * i]),
                                        auc=float(vals[2 * i + 1])))
            # every host has now fetched all of this part's step results,
            # so every control payload has been consumed — reclaim the
            # coordinator's KV memory (barrier + delete own keys)
            control_cleanup()

    def _prepare_hashed(self, blk, want_counts: bool, fill_counts: bool,
                        dim_min: int, job: str,
                        b_cap: Optional[int] = None,
                        stream_chunk: bool = False,
                        device_dedup: bool = False,
                        admit=None):
        """Producer batch preparation for the hashed store — delegates to
        the shared pipeline definition (data/pack_stream.prepare_hashed)
        so the thread and process transports pack identically."""
        from ..data.pack_stream import prepare_hashed
        return prepare_hashed(self._shapes, self.store.param.hash_capacity,
                              blk, want_counts, fill_counts, dim_min, job,
                              b_cap, stream_chunk=stream_chunk,
                              device_dedup=device_dedup, admit=admit)

    def _pack_payload(self, cblk, n_lanes, padded, b_cap, dim_min: int,
                      job: str, counts=None,
                      stream_chunk: bool = False):
        """Shared pack tail (data/pack_stream.pack_payload): one payload
        contract for producer-side (thread or process) and consumer-side
        (_pack_mapped) packers."""
        from ..data.pack_stream import pack_payload
        return pack_payload(self._shapes, cblk, n_lanes, padded, b_cap,
                            dim_min, job, counts=counts,
                            stream_chunk=stream_chunk)

    def _prepare_from_uniq(self, cblk, uniq, counts, want_counts: bool,
                           fill_counts: bool, dim_min: int, job: str,
                           b_cap: Optional[int] = None,
                           stream_chunk: bool = False):
        """Cached fast path (data/cached.py): the block arrives already
        localized to ``uniq`` (sorted reversed ids). The slot map + dedup
        is O(uniq); the O(nnz) index gather through the uniq->slot
        permutation runs HERE, once, on the producer thread. The payload
        used to ship that permutation to the device instead ("the index
        array ships untouched") — but resolving it per step cost an
        unsorted u_cap-row permute on pull plus a scatter-add on push,
        measured as the whole gap between hashed and dictionary replay
        (2.57 vs 2.18 s steady epochs on the same data,
        docs/perf_notes.md round-5 "host dedup"); a staged batch pays the
        host gather once and replays the clean layout every epoch.
        Delegates to data/pack_stream.prepare_from_uniq (shared with the
        process workers)."""
        from ..data.pack_stream import prepare_from_uniq
        return prepare_from_uniq(self._shapes,
                                 self.store.param.hash_capacity, cblk,
                                 uniq, counts, want_counts, fill_counts,
                                 dim_min, job, b_cap,
                                 stream_chunk=stream_chunk)

    def _cached_uri(self, job_type: int) -> Optional[str]:
        """The pre-localized rec cache uri for this job, or None."""
        p = self.param
        if p.data_format.lower() != "rec":
            return None
        uri = p.data_in if job_type == K_TRAINING \
            else (p.data_val or p.data_in)
        if not hasattr(self, "_cache_probe"):
            self._cache_probe = {}
        if uri not in self._cache_probe:
            from ..data.cached import cache_probe
            try:
                ok, member_rows = cache_probe(uri)
            except FileNotFoundError:
                ok, member_rows = False, 0
            if ok and member_rows > 4 * p.batch_size:
                # oversized members force the per-batch re-compaction path
                # (data/cached.py) on EVERY batch — correct, but the
                # "fast path" label stops being true (round-4 verdict
                # weak #5: the degenerate rec_batch_size=-1 layout)
                log.warning(
                    "rec cache %s has %d-row members but batch_size=%d: "
                    "every batch pays an O(nnz) re-compaction; re-convert "
                    "with batch_size=%d (or rec_batch_size=%d) for "
                    "batch-aligned members", uri, member_rows,
                    p.batch_size, p.batch_size, p.batch_size)
            self._cache_probe[uri] = ok
        return uri if self._cache_probe[uri] else None

    def _merge_pending(self, pending: list, prog: Progress,
                       extra=()) -> list:
        """Fetch all dispatched metric scalars in ONE transfer and merge —
        JAX async dispatch supplies the pipeline overlap. ``extra`` device
        scalars ride the same fetch (their values are returned): one RTT
        instead of two for the epoch-end store.evaluate()."""
        extra = list(extra)
        if not pending and not extra:
            return []
        flat = jnp.stack([s for _, o, a in pending for s in (o, a)]
                         + extra)
        t0 = time.perf_counter()
        # the declared sync point where device time lands (jaxtrace
        # counts it under DIFACTO_JAXTRACE)
        vals = jaxtrace.fetch(flat, point="sgd.metrics")
        self._add_stage("step_s", time.perf_counter() - t0)
        for i, (nrows, _, _) in enumerate(pending):
            self._rows_c.inc(nrows)
            prog.merge(Progress(nrows=nrows, loss=float(vals[2 * i]),
                                auc=float(vals[2 * i + 1])))
        return [float(v) for v in vals[2 * len(pending):]]

    @staticmethod
    def _payload_nbytes(tree) -> int:
        """ACTUAL per-host HBM held by a (possibly sharded/replicated)
        payload: replicated leaves cost one copy per addressable device,
        so mesh cache entries charge what they really pin — global
        logical nbytes would under-count fs-replicated batch arrays by
        up to mesh_fs x and blow the device_cache_mb promise."""
        total = 0
        for x in jax.tree_util.tree_leaves(tree):
            shards = getattr(x, "addressable_shards", None)
            if shards:
                total += sum(s.data.nbytes for s in shards)
            else:
                total += x.nbytes
        return total

    def _get_cache(self, job_type: int) -> Optional[_DeviceBatchCache]:
        """The device replay cache for this job, or None when ineligible
        (see _DeviceBatchCache docstring for the constraints). Mesh and
        multi-host runs cache their staged global (batch, slots) pairs —
        replayed steps rerun the SAME synchronized schedule on every
        host (identical payload counts and epoch-seeded permutations),
        so the DCN handshakes of the streaming pass disappear too."""
        p = self.param
        if (p.device_cache_mb <= 0
                or job_type not in (K_TRAINING, K_VALIDATION)
                or (job_type == K_TRAINING and p.neg_sampling != 1.0)
                # a staged replay would freeze batch->device-row routes
                # that later promotes/demotes invalidate — tiered runs
                # re-route every batch at staging time instead
                or self.store.tier is not None):
            return None
        if not hasattr(self, "_dev_caches"):
            self._dev_caches = {}
            self._dev_cache_pool = {"used": 0}  # one budget across jobs
        if job_type not in self._dev_caches:
            # single-host dictionary stores stage on their FIRST pass and
            # repad the staged OOB slot tails once the dictionary freezes
            # (slot assignment is insertion-stable, so growth only stales
            # the padding — _repad_cache). The MESH dictionary keeps
            # second-pass staging: its staged payloads are sharded global
            # (batch, slots) pairs whose repad would have to run
            # identically on every host.
            dict_single = not self.store.hashed and self.mesh is None
            self._dev_caches[job_type] = _DeviceBatchCache(
                p.device_cache_mb, shared=self._dev_cache_pool,
                stage_after_pass=0 if (self.store.hashed or dict_single)
                else 1,
                repadable=dict_single)
        return self._dev_caches[job_type]

    def device_cache_info(self) -> dict:
        """Replay-cache coverage after a run, per job type: ``complete``
        means steady epochs replay entirely from HBM; ``frozen`` means the
        budget filled mid-staging and steady epochs are a MIXED regime
        (the staged part prefix replays, the tail streams). Lets callers
        (bench.py e2e) label a "replay" rate honestly instead of assuming
        full coverage."""
        out = {}
        for jt, c in getattr(self, "_dev_caches", {}).items():
            out[jt] = {
                "complete": bool(c.ready and c.alive and not c.frozen),
                # an invalidated cache keeps its frozen flag but holds no
                # entries — that run is fully streaming, not mixed
                "frozen": bool(c.frozen and c.entries),
                "staged_parts": len(c.entries),
                "staged_mb": round(c.used / (1 << 20), 1),
            }
        return out

    # ------------------------------------------------ streamed pipeline
    _STAGE_KEYS = ("parse_s", "pack_s", "ring_wait_s", "transfer_s",
                   "step_s")

    def _add_stage(self, key: str, dt: float) -> None:
        # key is the legacy "<stage>_s" form; the value lands in the
        # registry counter stage_seconds_total{stage} (per-thread cells,
        # so producer threads report without contention)
        self._stage_c[key[:-2]].inc(dt)

    def stage_stats(self) -> dict:
        """Streamed-epoch stage decomposition accumulated over the run —
        read from THE OBS REGISTRY (stage_seconds_total{stage}), so the
        numbers include what producer worker processes reported across
        the process boundary (obs/proc.py) — plus the producer transport
        that ran. bench.py emits this as ``e2e.streamed.stages`` so a
        streamed regression localizes to a stage instead of hiding in
        the headline rate."""
        snap = self.obs.snapshot()
        series = snap.get("counters", {}).get("stage_seconds_total", {})
        vals = {dict(k).get("stage", ""): v for k, v in series.items()}
        out = {k: round(vals.get(k[:-2], 0.0), 3) for k in self._STAGE_KEYS}
        out["producer_mode"] = self._last_producer_mode
        return out

    def _resolve_producer_mode(self) -> str:
        """auto -> process once the host has cores to overlap (>= 4);
        below that the spawn + ring overhead buys nothing a thread
        doesn't (the 1-CPU measurement in docs/perf_notes.md)."""
        import os
        mode = self.param.producer_mode
        if mode == "auto":
            mode = "process" if (os.cpu_count() or 1) >= 4 else "thread"
        return mode

    def _absorb_payload_caps(self, job: str, item) -> None:
        """Fold the caps a worker-process payload was packed at back into
        the consumer's sticky schedule, so later epochs' worker snapshots
        (and any thread-mode fallback) keep the same jit signatures."""
        if item[0] != "ready":
            return
        payload = item[2]
        if payload[0] == "panel_chunked":
            b_cap, d2, u_cap = payload[5], payload[6], payload[7]
            wkey = job + ".w"
        else:
            b_cap, d2, u_cap = payload[4], payload[5], payload[6]
            wkey = job + (".w" if payload[0] in ("panel", "panel_raw")
                          else ".nnz")
        self._shapes.absorb({job + ".b": b_cap, wkey: d2,
                             job + ".u": u_cap})

    def _repad_cache(self, cache: _DeviceBatchCache) -> None:
        """Rewrite every staged payload's OOB slot padding for the LIVE
        table capacity. Dictionary slot assignment is insertion-stable
        (growth never moves a slot), so only the ascending pad tail —
        pad_slots_oob wrote ``capacity-at-pack-time + i`` — goes stale:
        after growth those ids fall IN bounds, alias real rows, and can
        duplicate real slots in the same vector (the kernels declare
        unique indices). ``nu`` rides the payload meta, so the rewrite
        is one tiny jitted op per staged batch; buffers stay on device
        and the cache accounting is unchanged (same sizes)."""
        if not hasattr(self, "_repad_i32"):
            def repad_i32(i32, off, u_cap, cap):
                nu = i32[off + u_cap + 1]
                j = jnp.arange(u_cap, dtype=jnp.int32)
                slots = i32[off:off + u_cap]
                fresh = jnp.where(j < nu, slots, cap + j - nu)
                return i32.at[off:off + u_cap].set(fresh)
            self._repad_i32 = jaxtrace.jit(repad_i32,
                                           static_argnums=(1, 2, 3),
                                           donate_argnums=0)
        cap = self.store.state.capacity
        for items in cache.entries.values():
            for i, p in enumerate(items):
                if p[0] == "panel_chunked":
                    off = p[6] * p[7]
                    # lint: ok(jax-recompile) statics are the staged
                    # payload's sticky pack-time caps plus the table
                    # capacity — one recompile per GROWTH event, not
                    # per batch (growth is log-bounded by design)
                    items[i] = (p[0], self._repad_i32(p[1], off, p[8], cap),
                                *p[2:])
                elif p[0] == "panel":
                    _, i32, f32, b_cap, d2, u_cap = p[:6]
                    # lint: ok(jax-recompile) staged caps + capacity
                    # (see the panel_chunked arm)
                    items[i] = (p[0], self._repad_i32(i32, b_cap * d2,
                                                      u_cap, cap),
                                *p[2:])
                elif p[0] == "coo":
                    _, i32, f32, b_cap, nnz_cap, u_cap = p[:6]
                    # lint: ok(jax-recompile) staged caps + capacity
                    # (see the panel_chunked arm)
                    items[i] = (p[0], self._repad_i32(i32, 2 * nnz_cap,
                                                      u_cap, cap),
                                *p[2:])
                else:  # pragma: no cover - devbatch payloads never repad
                    raise ValueError(f"cannot repad payload {p[0]!r}")
        cache.capacity = cap
        cache.stale_pads = False
        log.info("device cache repadded to capacity %d", cap)

    def _warm_pair_exec(self, arrays, statics) -> None:
        """Background-compile the two-batches-per-dispatch replay variant
        (packed_panel_train_chunked2) for this payload shape. Launched
        from the staging pass so the compile overlaps its streaming;
        replay pairs only once the executable is ready, so the compile
        never extends any epoch (a paired first call would cost ~18 s
        in-line — measured, epoch 2 of the criteo V16 run).

        The pair program is compiled with has_cnt=False regardless of the
        payload statics: it serves REPLAY epochs only, whose counts tail
        is zeroed (_zero_counts), and with the fused-row table a
        zero-count apply_count costs a full row gather+scatter per step —
        measured ~8 ms/step at the avazu shape, +35% on the epoch. The
        count-side v_live refresh it would perform is subsumed: cnt is
        frozen during replay, so any (w!=0 & cnt>thr) activation can only
        arise from a w change, which apply_grad's own per-row refresh
        already handles. unpack_panel with has_counts=False simply never
        reads the (zeroed) tail of the staged f32 buffer.

        The exec key includes the TABLE CAPACITY: a dictionary store can
        grow between the warm and the replay (an exec compiled at an
        intermediate capacity would fail the AOT shape check), so a
        stale-capacity exec is simply never found and the replay entry
        re-warms at the live capacity."""
        key = statics + (self.store.state.capacity,)
        if key in self._pair_execs or self.mesh is not None:
            return
        # evict same-shape execs compiled at older capacities: each is a
        # dead ~18 s XLA artifact after dictionary growth, and repeated
        # growths would otherwise accumulate them for the life of the run
        for stale in [k for k in self._pair_execs if k[:-1] == statics]:
            del self._pair_execs[stale]
        self._pair_execs[key] = None  # claimed; ready when not None

        def sds(x):
            return None if x is None else jax.ShapeDtypeStruct(x.shape,
                                                               x.dtype)

        state_s = jax.tree_util.tree_map(sds, self.store.state)
        pa = tuple(sds(t) for t in arrays)
        b_cap, width, u_cap, _, binary = statics

        def build():
            try:
                lowered = self._packed_panel_train_chunked2.lower(
                    state_s, pa, pa, b_cap, width, u_cap, False, binary)
                self._pair_execs[key] = lowered.compile()
            except Exception as e:  # pragma: no cover - best-effort warm
                log.warning("pair-replay precompile failed "
                            "(replaying per-step): %s", e)

        threading.Thread(target=build, name="pair-exec-compile",
                         daemon=True).start()

    def _replay_cached(self, job_type: int, epoch: int,
                       cache: _DeviceBatchCache, prog: Progress) -> None:
        """Steady-state epoch: replay HBM-resident staged batches — zero
        host->device transfers, shuffle = per-epoch batch permutation.
        Multi-host: every host replays the identical payload sequence
        (same counts, same epoch-seeded permutation), so the synchronized
        step schedule holds with no DCN handshakes; the dead-host
        watchdog stays armed for the collective-bearing steps."""
        import contextlib
        p = self.param
        is_train = job_type == K_TRAINING
        guard = (self.monitor.collective() if self.monitor is not None
                 else contextlib.nullcontext())
        pending: list = []
        cur_part = 0
        reports = self._part_reports(job_type)
        before = Progress(nrows=prog.nrows, loss=prog.loss, auc=prog.auc)
        # consecutive train batches with identical statics replay as
        # PAIRS through one dispatch (packed_panel_train_chunked2);
        # ``held`` is the batch awaiting a partner
        held = None

        def flush_held():
            nonlocal held
            if held is not None:
                self._dispatch_packed(job_type, held, pending)
                held = None

        def dispatch_pair(a, b, exec_):
            pa = (a[1], a[2], a[3], a[4], a[5])
            pb = (b[1], b[2], b[3], b[4], b[5])
            self.store.state, o1, a1, o2, a2 = exec_(
                self.store.state, pa, pb)
            pending.append((a[11], o1, a1))
            pending.append((b[11], o2, a2))
            self._paired_dispatches = getattr(
                self, "_paired_dispatches", 0) + 1
        with guard:
            for part, payload in cache.iter_parts(
                    is_train and p.shuffle > 0, seed=epoch):
                if reports and part != cur_part:
                    cur_part = part
                    if self._row_due(job_type):
                        flush_held()
                        self._merge_pending(pending, prog)
                        pending = []
                        self._report_part(job_type, before, prog)
                        before = Progress(nrows=prog.nrows, loss=prog.loss,
                                          auc=prog.auc)
                exec_ = None
                if is_train and payload[0] == "panel_chunked":
                    statics = payload[6:11]
                    key = statics + (self.store.state.capacity,)
                    if key not in self._pair_execs:
                        # no exec for this shape AT THIS CAPACITY yet —
                        # the cache staged before the warm hook existed
                        # (a resumed process), or the dictionary grew
                        # past the warm-time capacity: compile in the
                        # background, pair from the NEXT epoch on
                        self._warm_pair_exec(payload[1:6], statics)
                    exec_ = self._pair_execs.get(key)
                if exec_ is not None:
                    if held is None:
                        held = payload
                    elif held[6:11] == payload[6:11]:
                        a, held = held, None
                        dispatch_pair(a, payload, exec_)
                    else:
                        # statics differ (e.g. a ragged-tail shape):
                        # dispatch the held one alone, hold this one
                        a, held = held, payload
                        self._dispatch_packed(job_type, a, pending)
                else:
                    flush_held()
                    self._dispatch_packed(job_type, payload, pending)
                if len(pending) >= self._MERGE_CAP:
                    self._merge_pending(pending, prog)
                    pending = []
            flush_held()
            if cache.partial:
                # streamed parts follow this replay — the epoch-final
                # (penalty, nnz) eval belongs to the epoch's END, not
                # here (it would both waste a fetch RTT and leave stale
                # scalars for run()'s epoch line)
                self._merge_pending(pending, prog)
            else:
                self._final_merge(job_type, pending, prog)
        self._report_part(job_type, before, prog)

    def _final_merge(self, job_type: int, pending: list, prog: Progress
                     ) -> None:
        """Epoch-final metric fetch; training epochs piggyback the store's
        (penalty, nnz) scalars on the same transfer (run() reads them via
        _take_eval_scalars) — one RTT instead of two per epoch."""
        extra = self.store.evaluate_dev() if job_type == K_TRAINING else ()
        vals = self._merge_pending(pending, prog, extra=extra)
        if extra:
            self._eval_scalars = (vals[0], vals[1])

    def _take_eval_scalars(self):
        s = getattr(self, "_eval_scalars", None)
        self._eval_scalars = None
        return s if s is not None else self.store.evaluate()

    def _run_pred_executor(self, prog: Progress) -> None:
        """task=pred through serve's PredictExecutor (ISSUE 2 satellite):
        slice reader blocks into batch_size windows, score each through
        the shared bucketed predict program, stream predictions to
        pred_out with the usual formatting. The executor maps keys with
        insert=False, so prediction no longer grows the dictionary on
        unseen validation ids (their contribution is zero either way)."""
        from ..serve.executor import PredictExecutor
        p = self.param
        ex = PredictExecutor(self.store, loss=self.loss)
        reader = Reader(p.data_val or p.data_in, p.data_format, 0, 1,
                        chunk_bytes=256 << 20)
        pending: list = []
        for blk in reader:
            s = 0
            while s < blk.size:
                e = min(s + p.batch_size, blk.size)
                sub = blk.slice(s, e)
                s = e
                scores, objv, auc = ex.predict(sub)
                if p.pred_out:
                    self._save_pred(scores, sub.label)
                pending.append((sub.size, objv, auc))
                if len(pending) >= self._MERGE_CAP:
                    self._merge_pending(pending, prog)
                    pending = []
        self._merge_pending(pending, prog)

    def _iterate_parts(self, job_type: int, epoch: int, n_jobs: int,
                       prog: Progress) -> None:
        """IterateData (sgd_learner.cc:201-317) — fused-step version over
        all of this epoch's parts, produced by a WorkloadPool-fed thread
        pool (data/producer_pool.py) and consumed in canonical order."""
        import os
        p = self.param
        if job_type == K_TRAINING and self._wal is not None:
            # new delta window per training epoch: step numbering is
            # (epoch, step-within-epoch) so a replayed chain can name
            # the exact batch boundary it recovered to. _wal_skip (the
            # recovery fast-forward) deliberately survives this reset.
            self._wal_epoch = epoch
            self._wal_step = 0
            self._wal_lo = 0
            self._wal_touched = []
        cache = self._get_cache(job_type)
        stream_parts = list(range(n_jobs))
        if cache is not None and cache.ready:
            stale = (cache.capacity is not None
                     and (cache.stale_pads
                          or cache.capacity != self.store.state.capacity))
            if stale and cache.repadable:
                # dictionary growth since packing: rewrite each staged
                # slot tail to pad out-of-bounds at the LIVE capacity —
                # stale pads fall IN bounds and would alias real rows
                # (and duplicate indices under the kernels' unique-slots
                # declaration)
                self._repad_cache(cache)
                stale = False
            if stale:
                # staged slot padding is only truthful at the staging
                # capacity (pad_slots_oob) — impossible for fixed data,
                # guarded anyway
                cache.invalidate("store capacity changed since staging")
            else:
                # replay the staged prefix; a partial cache streams the
                # remaining parts below in the same canonical order (the
                # cached set is a prefix, _DeviceBatchCache._freeze)
                self._replay_cached(job_type, epoch, cache, prog)
                if not cache.partial:
                    return
                cached = cache.parts()
                stream_parts = [q for q in stream_parts if q not in cached]
        push_cnt = (job_type == K_TRAINING and epoch == 0
                    and self.do_embedding)
        from ..ops.batch import mesh_dim_min
        dim_min = 8 if self.mesh is None else mesh_dim_min(p.mesh_dp)
        hashed_fast = self.store.hashed and self.mesh is None
        b_cap_train = bucket(p.batch_size, dim_min)
        cached_uri = self._cached_uri(job_type)
        is_train = job_type == K_TRAINING
        # the packed steps' counts section (and so their jit signature) is
        # pinned for the whole run: epochs >= 1 ship zero counts instead of
        # flipping the has_cnt static and recompiling every shape variant
        want_counts = is_train and self.do_embedding
        job = "train" if is_train else "eval"
        n_workers = p.num_producers or max(1, min(4, os.cpu_count() or 1))
        # producer-side chunked-run layout for panel training: streamed
        # steps take the fast chunked step instead of the unsorted
        # scatter, with the host sort on the producer threads. Off while
        # the cache may still stage — there the device chunker builds
        # the same layout from buffers already on the chip, and host
        # chunks would double the bytes staged over the slow link.
        # Opt-in — see SGDLearnerParam.stream_chunks for the core math.
        cache_may_stage = (cache is not None and cache.alive
                           and not cache.frozen)
        # the cold tier rewrites packed payloads at staging time
        # (capacity/tier.route_payload): the chunked layout has no
        # rewritable index cells and raw device lanes bypass the host
        # slots section entirely, so both producer fast paths force off
        # while the tier routes
        tier_on = self.store.tier is not None
        stream_chunk = (is_train and hashed_fast and p.stream_chunks
                        and not cache_may_stage and not tier_on)
        # on-device unique-key dedup (ISSUE 13): raw token lanes +
        # in-step sort — streamed hashed training only, past the
        # epoch-0 count push (prepare_hashed also guards fill_counts),
        # never while a cache may stage (its regime replays from HBM)
        # and never with stream_chunks (the chunked layout needs the
        # host inverse). See SGDLearnerParam.device_dedup.
        device_dedup = (is_train and hashed_fast and p.device_dedup
                        and not stream_chunk and not cache_may_stage
                        and not push_cnt and not tier_on)

        from ..data.pack_stream import timed_reader
        from ..obs import trace
        parse_c, pack_c = self._stage_c["parse"], self._stage_c["pack"]

        def packed(part, fn, *args, **kw):
            # pack-stage accounting (the thread-mode twin of
            # pack_stream.spec_iter's instrumentation): one counter inc
            # + one trace span per prepared batch, on the producer thread
            t0 = time.perf_counter()
            with trace.span("producer.pack", part=part):
                out = fn(*args, **kw)
            pack_c.inc(time.perf_counter() - t0)
            return out

        def make_iter(part):
            # EVERYTHING host-side happens on producer threads so it
            # overlaps device execution. Hashed mode is stateless (no
            # dictionary), so localization AND packing run here; the
            # dictionary store mutates host state on insert, so only
            # parse+compact runs here and the consumer maps keys.
            g_idx = self._host_rank * n_jobs + part
            g_num = n_jobs * self._num_hosts
            if cached_uri is not None:
                from ..data.cached import CachedBatchReader
                rdr = CachedBatchReader(
                    cached_uri, g_idx, g_num, p.batch_size,
                    shuffle=is_train and p.shuffle > 0,
                    neg_sampling=p.neg_sampling if is_train else 1.0,
                    seed=epoch * max(g_num, 1) + g_idx,
                    need_counts=push_cnt)
                for sub, uniq, cnts in timed_reader(rdr, parse_c, part):
                    if hashed_fast:
                        yield ("ready", sub, packed(
                            part, self._prepare_from_uniq, sub, uniq,
                            cnts, want_counts, push_cnt, dim_min, job,
                            b_cap_train if is_train else None,
                            stream_chunk=stream_chunk))
                    else:
                        yield ("compact", sub, (sub, uniq, cnts))
                return
            # count-min admission over the streamed ingest (ISSUE 19):
            # per-(seed, epoch, global part) filter, the thread-mode
            # twin of spec_iter's — training passes only (eval reads
            # whatever the table holds)
            from ..capacity.sketch import make_admission
            admit = make_admission(
                self.store.param.hash_capacity,
                self.store.param.admit_min_count,
                self.store.param.seed, epoch, g_idx) if is_train else None
            reader = self._make_reader(job_type, epoch, g_idx, g_num)
            for blk in timed_reader(reader, parse_c, part):
                if hashed_fast:
                    yield ("ready", blk, packed(
                        part, self._prepare_hashed, blk, want_counts,
                        push_cnt, dim_min, job,
                        b_cap_train if is_train else None,
                        stream_chunk=stream_chunk,
                        device_dedup=device_dedup, admit=admit))
                else:
                    yield ("compact", blk, packed(
                        part, compact, blk, need_counts=push_cnt))

        from ..data.producer_pool import (OrderedProducerPool,
                                          ProcessProducerPool)
        from ..tracker.workload_pool import (WorkloadPool,
                                             WorkloadPoolParam)
        wp = WorkloadPool(WorkloadPoolParam(
            straggler_timeout=p.straggler_timeout))
        # producer transport for this epoch's streamed parts: worker
        # PROCESSES + shared-memory ring when the packing is stateless
        # (hashed fast path), this is a training pass, and no device
        # cache is staging (staged payloads would pin ring-backed device
        # buffers forever) — otherwise producer threads. Both transports
        # share the WorkloadPool contract, canonical consumption order,
        # and the packing code (data/pack_stream.py).
        use_process = (self._resolve_producer_mode() == "process"
                       and is_train and hashed_fast and stream_parts
                       and (cache is None or not cache.staging))
        self._last_producer_mode = "process" if use_process else "thread"
        if use_process:
            from ..data.pack_stream import StreamSpec, spec_iter
            import functools
            spec = StreamSpec(
                parts=tuple(stream_parts), n_jobs=n_jobs,
                host_rank=self._host_rank, num_hosts=self._num_hosts,
                data_in=p.data_in, data_format=p.data_format,
                cached_uri=cached_uri, batch_size=p.batch_size,
                shuffle=p.shuffle, neg_sampling=p.neg_sampling,
                epoch=epoch,
                hash_capacity=self.store.param.hash_capacity,
                want_counts=want_counts, fill_counts=push_cnt,
                dim_min=dim_min, job=job, b_cap=b_cap_train,
                stream_chunk=stream_chunk, need_label=False,
                device_dedup=device_dedup,
                admit_min_count=self.store.param.admit_min_count,
                admit_seed=self.store.param.seed,
                caps=self._shapes.snapshot(),
                trace_id=trace.trace_id())
            slot_mb = p.ring_slot_mb or max(
                1, (p.batch_size * 320) >> 20)
            # obs_registry: workers report their parse/pack/ring-wait
            # seconds into THIS learner's registry through the pool's
            # snapshot channel — stage_stats() then spans both processes
            pool = ProcessProducerPool(
                len(stream_parts), functools.partial(spec_iter, spec),
                n_workers=n_workers, depth=p.producer_depth, pool=wp,
                slot_bytes=slot_mb << 20, obs_registry=self.obs)
        else:
            # the pool runs over the parts still streamed this epoch (all
            # of them, unless a partial cache replayed a prefix above);
            # logical pool indices map back to actual part ids —
            # make_iter instruments its own parse/pack stages
            pool = OrderedProducerPool(
                len(stream_parts), lambda i: make_iter(stream_parts[i]),
                n_workers=n_workers, depth=p.producer_depth, pool=wp,
                obs_registry=self.obs)
        pending: list = []
        cur_part = stream_parts[0] if stream_parts else 0
        reports = self._part_reports(job_type)
        before = Progress(nrows=prog.nrows, loss=prog.loss, auc=prog.auc)
        # process mode: each yielded item's arrays VIEW a ring slot.
        # Double-buffered staging — hold the newest two leases (batch
        # k+1 stages while batch k steps) and release a lease only once
        # the step consuming its views has completed (its objv scalar is
        # the fence; jnp.asarray may alias aligned host memory on some
        # backends, so "transfer done" alone is not enough).
        import collections
        inflight: "collections.deque" = collections.deque()

        def retire(keep: int) -> None:
            while len(inflight) > keep:
                lease, fence = inflight.popleft()
                if fence is not None:
                    jax.block_until_ready(fence)
                lease.release()

        # double-buffered H2D staging (ISSUE 7): a "ready" item's packed
        # buffers are copied to the device the moment they arrive
        # (_stage_payload — an async enqueue on accelerator backends)
        # but its STEP dispatches one iteration later, so batch k+1's
        # host->device transfer rides under batch k's device step
        # instead of serializing in front of its own. The one-deep
        # lookahead holds (part, staged item, ring lease, producer span).
        lookahead: "collections.deque" = collections.deque()

        def dispatch_entry(entry) -> None:
            e_part, e_item, e_lease, e_span = entry
            n_before = len(pending)
            if trace.active():
                # consumer-side span pointing at the exact producer span
                # that packed this batch (the id rode the ring slot
                # header across the process boundary)
                # step_num makes this a StepTraceAnnotation under
                # DIFACTO_TRACE_DEVICE: the profiler's per-step device
                # timeline aligns with the part cadence
                with trace.span("consumer.dispatch", part=e_part,
                                step_num=e_part,
                                producer_span=e_span):
                    self._dispatch_item(job_type, e_item, push_cnt,
                                        want_counts, job, dim_min,
                                        pending, cache=cache, part=e_part)
            else:
                self._dispatch_item(job_type, e_item, push_cnt,
                                    want_counts, job, dim_min, pending,
                                    cache=cache, part=e_part)
            if e_lease is not None:
                fence = (pending[-1][1] if len(pending) > n_before
                         else None)
                inflight.append((e_lease, fence))
                retire(keep=2)

        for i, item in pool:
            part = stream_parts[i]
            if part != cur_part:
                # drain the lookahead so part-boundary rows and merges
                # account every batch of the finished part
                while lookahead:
                    dispatch_entry(lookahead.popleft())
                cur_part = part
                if reports and self._row_due(job_type):
                    self._merge_pending(pending, prog)
                    pending = []
                    self._report_part(job_type, before, prog)
                    before = Progress(nrows=prog.nrows, loss=prog.loss,
                                      auc=prog.auc)
            if use_process:
                self._absorb_payload_caps(job, item)
            lease = pool.pop_lease() if use_process else None
            span = pool.last_producer_span if use_process else 0
            if item[0] == "ready":
                staged = ("ready", item[1], self._stage_payload(item[2]))
                lookahead.append((part, staged, lease, span))
                while len(lookahead) > 1:
                    dispatch_entry(lookahead.popleft())
            else:
                # consumer-mapped paths (dictionary store, mesh) keep
                # strict receive order: flush the staged batch first
                while lookahead:
                    dispatch_entry(lookahead.popleft())
                dispatch_entry((part, item, lease, span))
            if len(pending) >= self._MERGE_CAP:
                self._merge_pending(pending, prog)
                pending = []
        while lookahead:
            dispatch_entry(lookahead.popleft())
        if job_type == K_TRAINING and self._wal is not None:
            # seal the epoch with a boundary segment (written even when
            # the window is empty): replay reads it as "this epoch
            # completed", so a crash after here resumes at the next
            # epoch instead of re-entering this one with a skip
            self._wal_flush(boundary=True)
        self._final_merge(job_type, pending, prog)
        retire(keep=0)
        # process mode: the workers' parse/pack/ring-wait seconds arrived
        # through the pool's obs snapshot channel — nothing to copy here
        self._report_part(job_type, before, prog)
        if cache is not None:
            cache.finish_pass()

    def _dispatch_packed(self, job_type: int, payload, pending: list,
                         label=None) -> None:
        """Run the fused step on an already-staged packed batch. ``payload``
        = (layout, i32_dev, f32_dev, b_cap, dim2, u_cap, want_counts,
        binary, nrows); dim2 is the panel width or the COO nnz_cap.
        Traverses the ``step.device`` chaos injection point (step.py)
        and accounts the dispatch into stage_seconds_total{stage=step}
        + the train_step_seconds histogram."""
        from ..step import fire_step_fault
        fire_step_fault()
        # table row traffic of this dispatch: u_cap fused rows pulled,
        # and pushed again when training (updaters.gather_bytes; the
        # serve path counts its own under path="serve")
        from ..updaters.sgd_updater import gather_bytes
        u_cap = (payload[2].shape[0] if payload[0] == "devbatch"
                 else payload[8] if payload[0] == "panel_chunked"
                 else payload[5])
        per_dir = gather_bytes(self.store.param, self.store.state.capacity,
                               u_cap)
        self._gather_c.inc(per_dir * (2 if job_type == K_TRAINING else 1))
        t0 = time.perf_counter()
        try:
            self._dispatch_packed_inner(job_type, payload, pending, label)
        finally:
            dt = time.perf_counter() - t0
            self._stage_c["step"].inc(dt)
            self._step_h.observe(dt)

    def _dispatch_packed_inner(self, job_type: int, payload, pending: list,
                               label=None) -> None:
        is_train = job_type == K_TRAINING
        if payload[0] == "devbatch":
            # cached replay of a staged mesh/multi-host global batch
            _, dev, slots, nrows = payload
            if is_train:
                self.store.state, objv, auc = self._train_step(
                    self.store.state, dev, slots)
            else:
                _, objv, auc = self._eval_step(self.store.state, dev,
                                               slots)
            pending.append((nrows, objv, auc))
            return
        if payload[0] == "panel_chunked":
            # cached replay fast path (train only): packed panel + the
            # staged chunked-run backward layout
            (_, i32, f32, ci, cl, cv, b_cap, d2, u_cap, want_counts,
             binary, nrows) = payload
            # lint: ok(jax-recompile) payload statics are ShapeSchedule
            # caps / bucket rungs recorded at pack or staging time —
            # bounded by the sticky-cap contract, which provenance
            # cannot follow through the payload tuple and device cache
            self.store.state, objv, auc = self._packed_panel_train_chunked(
                self.store.state, i32, f32, ci, cl, cv, b_cap, d2, u_cap,
                want_counts, binary)
            pending.append((nrows, objv, auc))
            return
        (layout, i32, f32, b_cap, d2, u_cap, want_counts, binary,
         nrows) = payload
        if layout == "panel_raw":
            # device-dedup streamed payload (train-only by the
            # _iterate_parts gate): raw token lanes, slots + inverse
            # derived in-step (ops/fused.dedup_tokens)
            # lint: ok(jax-recompile) sticky pack-time caps (above)
            self.store.state, objv, auc = self._packed_panel_train_raw(
                self.store.state, i32, f32, b_cap, d2, u_cap, binary)
            pending.append((nrows, objv, auc))
            return
        if layout == "panel":
            if is_train:
                # lint: ok(jax-recompile) payload statics are sticky
                # ShapeSchedule caps recorded at pack time (see above)
                self.store.state, objv, auc = self._packed_panel_train(
                    self.store.state, i32, f32, b_cap, d2, u_cap,
                    want_counts, binary)
            else:
                # lint: ok(jax-recompile) sticky pack-time caps (above)
                pred, objv, auc = self._packed_panel_eval(
                    self.store.state, i32, f32, b_cap, d2, u_cap, binary)
        else:
            if is_train:
                # lint: ok(jax-recompile) sticky pack-time caps (above)
                self.store.state, objv, auc = self._packed_train(
                    self.store.state, i32, f32, b_cap, d2, u_cap,
                    want_counts, binary)
            else:
                # lint: ok(jax-recompile) sticky pack-time caps (above)
                pred, objv, auc = self._packed_eval(
                    self.store.state, i32, f32, b_cap, d2, u_cap, binary)
        if job_type == K_PREDICTION and self.param.pred_out:
            self._save_pred(jaxtrace.fetch(pred, point="sgd.pred")[:nrows],
                            label)
        pending.append((nrows, objv, auc))

    def _dispatch_item(self, job_type: int, item, push_cnt: bool,
                       want_counts: bool, job: str, dim_min: int,
                       pending: list,
                       cache: Optional[_DeviceBatchCache] = None,
                       part: int = 0) -> None:
        """Consume one produced batch: stage + run the fused device step.
        ``want_counts``/``job`` arrive from _iterate_parts so producer-side
        packing and this consumer agree on the run-stable has_cnt static
        and the shape-schedule key."""
        p = self.param
        kind, blk, payload = item
        is_train = job_type == K_TRAINING
        if kind == "ready":
            self._dispatch_prepared(job_type, blk, payload, push_cnt,
                                    want_counts, pending, cache, part)
            return

        cblk, uniq, cnts = payload
        slots_np, remap, cnts = self.store.map_keys_dedup(uniq, cnts)
        if remap is not None:
            # in-batch slot collisions / unsorted slots: point the COO
            # entries at the deduped sorted rows so colliding features
            # alias (their gradients segment-sum together on device)
            cblk = dataclasses.replace(
                cblk, index=remap[cblk.index].astype(np.uint32))
        if self.mesh is None:
            # dictionary store, flat device: pack the SAME panel/COO
            # two-buffer payloads the hashed producers build and dispatch
            # through the shared prepared path — so exact-id runs take
            # the panel + chunked-run fast step too (they used to pack
            # plain COO and dispatch the unsorted backward: 13.0 vs
            # 2.6 s steady epochs on the 2M-row criteo stand-in)
            dev_payload = self._pack_mapped(blk, cblk, slots_np, cnts,
                                            want_counts, push_cnt,
                                            dim_min, job)
            self._dispatch_prepared(job_type, blk, dev_payload, push_cnt,
                                    want_counts, pending, cache, part)
            return
        n_uniq = len(slots_np)
        u_cap = self._shapes.cap(job + ".u", n_uniq)
        b_cap = self._shapes.cap(job + ".b", blk.size, dim_min)
        nnz_cap = self._shapes.cap(job + ".nnz", blk.nnz, dim_min)
        slots = self.store.pad_slots(slots_np, u_cap)
        from ..ops.batch import panel_width
        width = panel_width(cblk, b_cap)
        if width is not None:
            # mesh panel path: the SAME panel forward + chunked-run
            # backward as the single-host packed path, dp-sharded
            # (round-4 verdict #1 — the mesh step used to dispatch
            # the unsorted COO backward, ~2x slower at bench shapes)
            width = self._shapes.cap(job + ".w", width, exact=True)
            dev = self._panel_host_batch(
                cblk, n_uniq, b_cap, width, u_cap,
                dp_div=self.param.mesh_dp,
                with_chunks=is_train)
            self._mesh_panel_steps = getattr(
                self, "_mesh_panel_steps", 0) + 1
        else:
            dev = pad_batch(cblk, num_uniq=n_uniq,
                            batch_cap=b_cap, nnz_cap=nnz_cap)
        from ..parallel import batch_sharding, shard_pytree
        dev = shard_pytree(dev, batch_sharding(self.mesh))
        if push_cnt:
            c = np.zeros(u_cap, dtype=np.float32)
            c[:len(cnts)] = cnts
            self.store.state = self._apply_count(
                self.store.state, slots, jnp.asarray(c))
        from ..updaters.sgd_updater import gather_bytes
        per_dir = gather_bytes(self.store.param,
                               self.store.state.capacity, u_cap)
        self._gather_c.inc(per_dir * (2 if job_type == K_TRAINING else 1))
        if job_type == K_TRAINING:
            self.store.state, objv, auc = self._train_step(
                self.store.state, dev, slots)
        else:
            pred, objv, auc = self._eval_step(self.store.state, dev,
                                              slots)
        if cache is not None and cache.staging:
            cache.add(part, ("devbatch", dev, slots, blk.size),
                      self._payload_nbytes((dev, slots)),
                      capacity=self.store.state.capacity)
        if job_type == K_PREDICTION and p.pred_out:
            # stream predictions per batch (SavePred,
            # sgd_learner.cc:231-238) — don't buffer the dataset
            self._save_pred(jaxtrace.fetch(pred, point="sgd.pred")
                            [:blk.size], blk.label)
        pending.append((blk.size, objv, auc))

    def _pack_mapped(self, blk, cblk, slots_np, cnts,
                     want_counts: bool, push_cnt: bool, dim_min: int,
                     job: str):
        """Packed two-buffer payload for a consumer-mapped batch (the
        dictionary store maps keys on the consumer thread because
        map_keys mutates host state) — the same panel/COO layouts
        _prepare_hashed builds on producer threads, so both store modes
        dispatch the identical prepared path. ``slots_np`` is sorted
        unique (map_keys_dedup contract), and ``cblk.index`` already
        addresses its lanes — the dictionary never aliases distinct
        ids."""
        from ..store.local import pad_slots_oob
        n_uniq = len(slots_np)
        u_cap = self._shapes.cap(job + ".u", n_uniq)
        b_cap = self._shapes.cap(job + ".b", blk.size, dim_min)
        if want_counts:
            counts = cnts if push_cnt and cnts is not None \
                else np.zeros(0, np.float32)  # keep the section, zeroed
        else:
            counts = None
        # pad base = capacity at STEP time: map_keys already grew the
        # state for this batch's inserts, and the dispatch below runs on
        # this same thread before any further growth
        padded = pad_slots_oob(slots_np.astype(np.int32), u_cap,
                               self.store.state.capacity)
        return self._pack_payload(cblk, n_uniq, padded, b_cap, dim_min,
                                  job, counts=counts)

    def _stage_payload(self, payload):
        """Issue a packed payload's host->device copies NOW (an async
        enqueue on accelerator backends) and return the payload with
        device arrays in place of the numpy ones — the staging half of
        _dispatch_prepared, split out so the consumer loop can
        double-buffer: batch k+1's transfer overlaps batch k's step.
        Counted into stage_seconds_total{stage=transfer}; the later
        jnp.asarray in _dispatch_prepared is an identity on the staged
        arrays.

        The single tier-routing chokepoint (ISSUE 19): with a cold tier
        on, the payload's logical slots become device hot rows here —
        promotes/demotes ride this same dispatch thread, between the
        previous step's enqueue and this batch's H2D copies."""
        t0 = time.perf_counter()
        if self.store.tier is not None and payload[0] in ("panel", "coo"):
            from ..capacity.tier import route_payload
            payload = route_payload(self.store.tier, payload)
        if payload[0] == "panel_chunked":
            (_, i32, f32, (ci, cl, cv), binary, b_cap, d2, u_cap) = payload
            out = ("panel_chunked", jnp.asarray(i32), jnp.asarray(f32),
                   (jnp.asarray(ci), jnp.asarray(cl),
                    None if cv is None else jnp.asarray(cv)),
                   binary, b_cap, d2, u_cap)
        else:
            layout, i32, f32, binary, b_cap, d2, u_cap = payload
            out = (layout, jnp.asarray(i32), jnp.asarray(f32), binary,
                   b_cap, d2, u_cap)
        self._add_stage("transfer_s", time.perf_counter() - t0)
        return out

    def _dispatch_prepared(self, job_type: int, blk, payload,
                           push_cnt: bool, want_counts: bool,
                           pending: list,
                           cache: Optional[_DeviceBatchCache],
                           part: int) -> None:
        """Stage + run one packed-payload batch (both store modes), then
        hand the staged device buffers to the replay cache. Payload
        arrays may be numpy (direct path) or already on device
        (_stage_payload's double-buffered path)."""
        is_train = job_type == K_TRAINING
        if is_train and self._wal is not None and self._wal_skip > 0:
            # recovery fast-forward (durability/recover.py): this
            # batch's effects were already applied by WAL replay —
            # deterministic data order makes the skipped prefix exactly
            # the replayed prefix, so the continued trajectory is the
            # unkilled one. Advancing _wal_lo keeps the first post-skip
            # window full-width instead of flushing immediately.
            self._wal_skip -= 1
            self._wal_step += 1
            self._wal_lo = self._wal_step
            return
        t0 = time.perf_counter()
        if payload[0] == "panel_chunked":
            # producer-side chunked layout (stream_chunks): the host
            # sort already ran on the producer thread, so both
            # streamed dispatch AND cache staging use these chunks
            (_, i32, f32, (ci_np, cl_np, cv_np), binary, b_cap, d2,
             u_cap) = payload
            layout = "panel"
            i32, f32 = jnp.asarray(i32), jnp.asarray(f32)
            ci, cl = jnp.asarray(ci_np), jnp.asarray(cl_np)
            cv = None if cv_np is None else jnp.asarray(cv_np)
            chunked = True
        else:
            layout, i32, f32, binary, b_cap, d2, u_cap = payload
            i32, f32 = jnp.asarray(i32), jnp.asarray(f32)
            chunked = False
        self._add_stage("transfer_s", time.perf_counter() - t0)
        wc = want_counts if is_train else False
        staging = (cache is not None and cache.staging
                   and layout == "panel" and is_train)
        if staging and not chunked:
            # cache-eligible panel training: build the chunked-run
            # layout ONCE at staging time and dispatch epoch 0 through
            # the SAME chunked step the replays use — one compiled
            # train variant per run, and every epoch takes the chunked
            # backward (docs/perf_notes.md)
            # lint: ok(jax-recompile) statics are this batch's sticky
            # pack-time caps — same bounded set the packed step uses
            ci, cl, cv = self._panel_chunk_packed(i32, f32, b_cap, d2,
                                                  u_cap, binary)
            chunked = True
        if chunked:
            dev_payload = ("panel_chunked", i32, f32, ci, cl, cv, b_cap,
                           d2, u_cap, wc, binary, blk.size)
        else:
            dev_payload = (layout, i32, f32, b_cap, d2, u_cap, wc,
                           binary, blk.size)
        self._dispatch_packed(job_type, dev_payload, pending,
                              label=blk.label)
        if is_train and self._wal is not None:
            self._wal_touch(layout, i32, b_cap, d2, u_cap)
        if cache is not None and cache.staging and layout != "panel_raw":
            # keep the staged buffers for HBM replay; the counts tail
            # (epoch-0 feature-count push) is zeroed on device so a
            # replayed step never re-counts
            if wc and push_cnt:
                # lint: ok(jax-recompile) u_cap is a sticky pack-time cap
                f32 = self._zero_counts(f32, u_cap)
            nbytes = i32.nbytes + f32.nbytes
            # capacity recorded for the dictionary store: its staged OOB
            # slot padding is only truthful while the table keeps the
            # staging capacity (constant in hashed mode)
            if chunked and is_train:
                nbytes += ci.nbytes + cl.nbytes + (
                    0 if cv is None else cv.nbytes)
                cache.add(part,
                          ("panel_chunked", i32, f32, ci, cl, cv, b_cap,
                           d2, u_cap, wc, binary, blk.size),
                          nbytes, capacity=self.store.state.capacity)
                # start the pair-replay compile while this staging pass
                # still streams (it has ~30s of host/transfer time to
                # hide the ~18s compile behind) — unless that add just
                # froze or invalidated the cache (no replay will ever
                # use the executable), or the cache is repadable (the
                # dictionary table is still growing this pass: an exec
                # compiled now would be keyed at a soon-stale capacity;
                # the replay entry warms it at the frozen capacity and
                # pairs from epoch 2 on)
                if cache.staging and not cache.repadable:
                    self._warm_pair_exec((i32, f32, ci, cl, cv),
                                         (b_cap, d2, u_cap, wc, binary))
            else:
                cache.add(part,
                          (layout, i32, f32, b_cap, d2, u_cap, wc,
                           binary, blk.size),
                          nbytes, capacity=self.store.state.capacity)

    def _wal_touch(self, layout: str, i32, b_cap: int, d2: int,
                   u_cap: int) -> None:
        """Record the slots a just-dispatched training batch touched
        (durability/wal.py). The slots section sits at a fixed offset
        of the packed i32 buffer — panel: after the [b_cap, width]
        index panel; COO: after the two [nnz_cap] lanes (data/
        pack_stream.pack_payload) — so this is one tiny host slice, no
        repacking. OOB padding lanes (pad_slots_oob) are dropped."""
        if layout == "coo":
            off = 2 * d2
        elif layout == "panel":
            off = b_cap * d2
        else:  # pragma: no cover - panel_raw is gated off in init
            raise RuntimeError(
                f"WAL cannot observe layout {layout!r}: no host slots "
                "section")
        sl = np.asarray(i32[off:off + u_cap]).astype(np.int32)
        self._wal_touched.append(sl[sl < self.store.state.capacity])
        self._wal_step += 1
        if self._wal_step - self._wal_lo >= self.param.wal_flush_batches:
            self._wal_flush()

    def _wal_flush(self, boundary: bool = False) -> None:
        """Seal the open delta window as one CRC'd segment: gather the
        touched rows' CURRENT values from the device (post-step at the
        window end — the log stores values, not deltas, so a slot's
        last logged value is its value at head) and append. A failed
        append (disk error, injected fault) RETAINS the window: the
        slots stay queued and the next flush logs their values at ITS
        window end, still correct under value semantics — a transient
        write failure widens the RPO, never corrupts the chain."""
        if self._wal is None \
                or (self._wal_step == self._wal_lo and not boundary):
            return
        if self._wal_touched:
            touched = np.unique(np.concatenate(self._wal_touched))
            arrays = self.store.wal_touched_rows(touched)
        else:
            touched = np.zeros(0, np.int32)
            arrays = {}
        from ..utils.faultinject import FaultInjected
        try:
            path = self._wal.append(touched, arrays, self._wal_epoch,
                                    self._wal_lo, self._wal_step,
                                    boundary=boundary)
        except (FaultInjected, OSError) as e:
            self._wal_fail_c.inc()
            log.warning("wal append failed (%s); window retained to "
                        "the next flush", e)
            return
        self._wal_lo = self._wal_step
        self._wal_touched = []
        if path is not None and self._replica is not None:
            self._replica.push([path],
                               generation=self._wal.generation,
                               epoch=self._wal.base_epoch)

    def _panel_host_batch(self, cblk, n_uniq: int, b_cap: int, width: int,
                          u_cap: int, dp_div: int, row_base: int = 0,
                          b_fill: Optional[int] = None,
                          num_rows: Optional[int] = None,
                          force_vals: bool = False,
                          with_chunks: bool = True):
        """Host-side (numpy) PanelBatch for the mesh paths — the SAME
        panel + chunked-run layout the single-host packed path stages on
        device (round-4 verdict #1: the mesh step must not fall back to
        the unsorted COO backward). ``cblk`` may be None (an out-of-data
        SPMD host ships an all-pad batch so the synchronized schedule
        holds); chunk row ids address the GLOBAL dp row space via
        ``row_base``/``b_fill``; the chunk count rounds up to a multiple
        of ``dp_div`` so the [C, L] arrays shard evenly over dp."""
        from ..ops.batch import (PanelBatch, _panel_arrays, chunk_cap,
                                 panel_chunk_tokens_np)
        if b_fill is None:
            b_fill = b_cap
        C = -(-chunk_cap(u_cap, b_cap * width) // dp_div) * dp_div
        if cblk is not None:
            idx, vals, labels, rweight, row_mask = _panel_arrays(
                cblk, b_cap, width)
            if vals is None and force_vals:
                # uniform full-batch binary block: every cell is a real
                # token of value 1. The SPMD schedule materializes values
                # so the jit signature (vals present) is identical across
                # hosts and steps regardless of local raggedness.
                vals = np.ones((b_cap, width), dtype=np.float32)
        else:
            idx = np.zeros((b_cap, width), dtype=np.int32)
            vals = np.zeros((b_cap, width), dtype=np.float32) \
                if force_vals else None
            labels = np.zeros(b_cap, dtype=np.float32)
            rweight = np.zeros(b_cap, dtype=np.float32)
            row_mask = np.zeros(b_cap, dtype=np.float32)
        ci = cl = cv = None
        if with_chunks:
            if cblk is not None:
                fv = None if vals is None else vals.reshape(-1)
                ci, cl, cv = panel_chunk_tokens_np(
                    idx.reshape(-1), fv, u_cap, b_fill, width,
                    C=C, row_base=row_base)
            else:
                from ..ops.batch import CHUNK_L
                ci = np.full((C, CHUNK_L), b_fill, dtype=np.int32)
                cl = np.full(C, u_cap, dtype=np.int32)
                cv = (np.zeros((C, CHUNK_L), dtype=np.float32)
                      if force_vals else None)
        return PanelBatch(
            idx=idx, vals=vals, labels=labels, rweight=rweight,
            row_mask=row_mask,
            num_rows=np.int32(num_rows if num_rows is not None
                              else (cblk.size if cblk is not None else 0)),
            num_uniq=np.int32(n_uniq),
            chunk_idx=ci, chunk_lane=cl, chunk_vals=cv)

    def _save_pred(self, pred: np.ndarray, label) -> None:
        """SavePred (sgd_learner.h:72-83); per-rank output file. The batch
        is bulk-formatted into ONE write — a per-row f.write loop measured
        Python-bound (~100k rows/s) on million-row pred tasks, while the
        reference streams per batch in C++ (sgd_learner.h:72-83)."""
        if self._fo_pred is None:
            from ..utils import stream
            self._fo_pred = stream.open_stream(
                f"{self.param.pred_out}_part-{self._host_rank}", "w")
        out = 1.0 / (1.0 + np.exp(-pred)) if self.param.pred_prob else pred
        n = len(out)
        if n == 0:
            return
        if label is not None:
            inter = np.empty(2 * n, dtype=np.float64)
            inter[0::2] = np.asarray(label)[:n]
            inter[1::2] = out
            self._fo_pred.write(("%g\t%g\n" * n) % tuple(inter))
        else:
            self._fo_pred.write(("%g\n" * n) % tuple(out))
