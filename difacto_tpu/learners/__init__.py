from .base import Learner, register
from .sgd import SGDLearner, SGDLearnerParam

__all__ = ["Learner", "register", "SGDLearner", "SGDLearnerParam"]
