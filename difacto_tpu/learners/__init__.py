from .base import Learner, register
from .bcd import BCDLearner, BCDLearnerParam, BCDProgress
from .lbfgs import LBFGSLearner, LBFGSLearnerParam, LBFGSProgress
from .sgd import SGDLearner, SGDLearnerParam

__all__ = ["Learner", "register", "SGDLearner", "SGDLearnerParam",
           "LBFGSLearner", "LBFGSLearnerParam", "LBFGSProgress",
           "BCDLearner", "BCDLearnerParam", "BCDProgress"]
