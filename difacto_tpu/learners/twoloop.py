"""Vector-free L-BFGS two-loop recursion (Chen et al., NIPS'15).

Equivalent of the reference's ``lbfgs::Twoloop`` (src/lbfgs/lbfgs_twoloop.h):
the classic two-loop runs in the (2m+1)-dim basis b = [s_0..s_{m-1},
y_0..y_{m-1}, grad] using only the Gram matrix B[i][j] = <b_i, b_j>, so the
O(N) work is m inner products + one linear combination — on TPU one
(2m+1, N) matmul and one matvec, with XLA psums when N is sharded.

Differences from the reference (performance-only, same values):
- B is recomputed from the basis each epoch (one einsum) instead of the
  incremental CalcIncreB/ApplyIncreB shift bookkeeping (twoloop.h:19-66) that
  saved network rounds in the parameter-server setting.
- the delta coefficients are solved on the host in float64, like the
  reference's double-precision B (twoloop.h:40).
"""

from __future__ import annotations

from typing import List

import numpy as np


def calc_delta(B: np.ndarray) -> np.ndarray:
    """Two-loop in the Gram basis (CalcDelta, lbfgs_twoloop.h:98-125).

    B is (2m+1, 2m+1) float64 with basis order [s..., y..., grad]; returns
    delta (2m+1,) such that direction p = sum_i delta_i * b_i.
    """
    m = (B.shape[0] - 1) // 2
    d = np.zeros(2 * m + 1, dtype=np.float64)
    d[2 * m] = -1.0
    alpha = np.zeros(m, dtype=np.float64)
    for i in range(m - 1, -1, -1):
        alpha[i] = float(d @ B[:, i]) / (B[i, m + i] + 1e-10)
        d[m + i] -= alpha[i]
    d *= B[m - 1, 2 * m - 1] / (B[2 * m - 1, 2 * m - 1] + 1e-10)
    for i in range(m):
        beta = float(d @ B[m + i, :]) / (B[i, m + i] + 1e-10)
        d[i] += alpha[i] - beta
    return d


def calc_direction(s: List[np.ndarray], y: List[np.ndarray],
                   grad: np.ndarray) -> np.ndarray:
    """Direction p from history + gradient (CalcDirection, twoloop.h:77-96).

    Host reference implementation in float64 — the learner uses the same
    arithmetic with jnp arrays (basis matmul for B, matvec for p).
    """
    assert len(s) == len(y)
    if not s:
        return -grad
    basis = np.stack([*s, *y, grad]).astype(np.float64)
    B = basis @ basis.T
    delta = calc_delta(B)
    return delta @ basis


def naive_two_loop(s: List[np.ndarray], y: List[np.ndarray],
                   grad: np.ndarray) -> np.ndarray:
    """Textbook O(mN) two-loop (the test oracle, cf. the reference's
    tests/cpp/lbfgs_twoloop_test.cc naive implementation)."""
    q = grad.astype(np.float64).copy()
    m = len(s)
    alpha = np.zeros(m)
    for i in range(m - 1, -1, -1):
        alpha[i] = (s[i] @ q) / (y[i] @ s[i] + 1e-10)
        q -= alpha[i] * y[i]
    q *= (s[-1] @ y[-1]) / (y[-1] @ y[-1] + 1e-10)
    for i in range(m):
        beta = (y[i] @ q) / (y[i] @ s[i] + 1e-10)
        q += (alpha[i] - beta) * s[i]
    return -q
