"""Log-tailing online trainer (ISSUE 17b/17c).

Drives the existing :class:`SGDLearner` through its normal streamed
epoch machinery (``_run_epoch`` → producer pool → fused steps), but the
"epoch" unit is one sealed log segment: the tailing reader
(online/tail.py) blocks on the next seal, the trainer points
``data_in`` at that one segment file and runs a training pass over it.
Because segment files are ordinary rec2 members and each pass uses
``shuffle=0`` with a single job, replaying the same sealed log offline
(``online_replay=1``) issues the *identical* sequence of
``_run_epoch(seg, ...)`` calls over the identical bytes — which is the
trajectory-integrity contract: the replayed checkpoint is
byte-identical to the online one.

Checkpoints follow a WALL-CLOCK cadence (``online_ckpt_interval_s``),
not an epoch cadence — a continuous stream has no natural epoch
boundary — through the learner's verified-manifest path
(``_save_checkpoint``: save-with-aux, meta marker last, rank-0 family
pruning under ``ckpt_keep``; fs-sharded families included). Crash
recovery is the existing ``auto_resume`` walk-back: the completed epoch
the learner resumes IS the last trained-through segment, so the trainer
restarts tailing at the next one. ``wal_flush_batches`` composes
unchanged — the WAL/replication/ladder machinery lives entirely inside
the shared ``_save_checkpoint``/``_try_resume`` paths this trainer
already drives — and changes the tradeoff the cadence knob expresses:
with a WAL, ``online_ckpt_interval_s`` prices only checkpoint IO, not
freshness-vs-durability, because a crash mid-interval replays forward
from the delta log instead of refalling to the last wall-clock commit
(docs/serving.md "Durability & recovery").

Freshness SLO gauges (process-global registry, so they ride any
in-process server's ``#metrics`` and the trainer's ``metrics_path``
JSONL → ``tools/obs_report.py``):

- ``train_behind_serve_s`` — seconds the oldest sealed-but-untrained
  segment has been waiting (0 when trained through the newest seal);
  seal timestamps are CLOCK_MONOTONIC (machine-wide), written by the
  logging process into ``log.idx.jsonl``.
- ``online_rows_behind`` — rows in sealed segments not yet trained.

Each committed generation is pushed to the fleet (online/loop.py) so
the served ``model_generation`` continuously advances.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional, Tuple

from ..config import KWArgs, parse_endpoints
from ..obs import gauge
from ..utils.locktrace import mutex
from .log import read_index
from .tail import TailReader

log = logging.getLogger("difacto_tpu")

_g_behind_s = gauge(
    "train_behind_serve_s",
    "seconds the oldest sealed-but-untrained log segment has waited "
    "(0 = trained through the newest seal)")
_g_rows_behind = gauge(
    "online_rows_behind",
    "rows in sealed log segments the online trainer has not trained yet")


class OnlineTrainer:
    def __init__(self, param, learner_kwargs: KWArgs):
        self.param = param
        # the learner consumes one SEGMENT FILE per pass: rec format,
        # one job, no shuffle (batch order = arrival order), no device
        # cache (every segment is new data — staging would never replay).
        # Appended AFTER the user's kwargs so they win (last occurrence
        # wins, config.init_allow_unknown).
        forced = [("data_format", "rec"), ("num_jobs_per_epoch", "1"),
                  ("shuffle", "0"), ("device_cache_mb", "0"),
                  ("data_in", param.online_log_dir)]
        from ..learners import Learner
        self.learner = Learner.create("sgd")
        self.leftover = self.learner.init(list(learner_kwargs) + forced)
        self._mu = mutex()
        self._trained_through = -1
        self._generations = 0
        self._stop = threading.Event()

    # ------------------------------------------------------------ state
    def stop(self) -> None:
        """Ask the tail loop to exit after the current segment."""
        self._stop.set()

    def trained_through(self) -> int:
        with self._mu:
            return self._trained_through

    def generations(self) -> int:
        with self._mu:
            return self._generations

    # ------------------------------------------------------------- run
    def run(self) -> int:
        """Tail the log until it ends (``log.end``), the replay prefix
        drains, ``online_max_seconds`` elapses, or :meth:`stop`. Returns
        the last trained-through segment (-1 = none)."""
        from ..learners.sgd import K_TRAINING
        from ..utils.progress import Progress
        op = self.param
        ln = self.learner
        p = ln.param
        if not p.model_out:
            raise ValueError("task=online needs model_out")
        endpoints = (parse_endpoints(op.online_endpoints)
                     if op.online_endpoints else [])
        ln._init_run_state()
        start_seg = 0
        if p.auto_resume:
            resumed = ln._try_resume()
            if resumed is not None:
                start_seg = resumed + 1
                log.info("online: auto-resumed through segment %d",
                         resumed)
        trained = start_seg - 1
        last_saved = trained
        last_ckpt = time.monotonic()
        tail = TailReader(op.online_log_dir, start_seg=start_seg,
                          poll_s=op.online_poll_s,
                          replay=op.online_replay,
                          max_seconds=op.online_max_seconds,
                          stop=self._stop)
        for seg, path in tail:
            # one training pass over exactly this sealed segment; the
            # segment index is the epoch, so epoch-derived behavior
            # (embedding count push on epoch 0 only) matches a replay
            ln.param.data_in = path
            prog = Progress()
            ln._run_epoch(seg, K_TRAINING, prog)
            trained = seg
            with self._mu:
                self._trained_through = seg
            self._update_freshness(trained)
            log.info("online: segment %d trained (%s)", seg, prog.text())
            now = time.monotonic()
            if (op.online_ckpt_interval_s > 0
                    and now - last_ckpt >= op.online_ckpt_interval_s):
                self._commit(trained, endpoints)
                last_saved = trained
                last_ckpt = time.monotonic()
        if trained > last_saved:
            # the log ended (or the loop was stopped) past the last
            # committed generation: commit the tail so nothing trained
            # is lost and the fleet serves the final state
            self._commit(trained, endpoints)
        self._update_freshness(trained)
        log.info("online: done, trained through segment %d", trained)
        ln.store.save(ln._model_name(p.model_out, -1), p.has_aux)
        if ln.store.fs_count > 1:
            ln.store.publish_shard_stats()
        ln.stop()
        return trained

    # --------------------------------------------------------- internal
    def _commit(self, seg: int, endpoints: List[Tuple[str, int]]) -> None:
        """One committed generation: verified checkpoint (meta marker
        last, family pruning) then a best-effort fleet push."""
        ln = self.learner
        ln._save_checkpoint(seg)
        with self._mu:
            self._generations += 1
        if endpoints:
            from .loop import push_reload
            push_reload(endpoints,
                        ln.param.model_out + f"_iter-{seg}")

    def _update_freshness(self, trained: int) -> None:
        behind_rows = 0
        oldest_ts: Optional[float] = None
        for entry in read_index(self.param.online_log_dir):
            try:
                seg, rows, ts = (int(entry["seg"]), int(entry["rows"]),
                                 float(entry["ts"]))
            except (KeyError, TypeError, ValueError):
                continue
            if seg > trained:
                behind_rows += rows
                if oldest_ts is None or ts < oldest_ts:
                    oldest_ts = ts
        behind_s = (max(0.0, time.monotonic() - oldest_ts)
                    if oldest_ts is not None else 0.0)
        _g_behind_s.set(behind_s)
        _g_rows_behind.set(float(behind_rows))
