"""Loop orchestrator: trainer generations → fleet reloads (ISSUE 17d).

Each generation the online trainer commits (online/trainer.py
``_save_checkpoint``) is pushed to the serve fleet over the existing
``#reload`` control line (serve/reload.py handles verification,
blue/green swaps, and typed walk-back on a pruned/torn generation).
Pushes are best-effort per endpoint: a replica that is down, draining,
or mid-rotation is logged and skipped — its own reload watcher
(``serve_reload_poll_s``) or the next push catches it up, and the
router keeps balancing around it meanwhile. The loop therefore never
blocks training on a slow or dead replica.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Tuple

from ..obs import counter
from ..serve.fleet import EndpointRpc

log = logging.getLogger("difacto_tpu")

_c_pushes = counter(
    "online_reload_pushes_total",
    "per-endpoint #reload pushes attempted by the online loop")


def push_reload(endpoints: List[Tuple[str, int]], model_path: str,
                timeout: float = 10.0) -> Dict[str, int]:
    """Push ``#reload <model_path>`` to every endpoint. Returns
    ``{"ok": n, "failed": n}``; failures are logged, never raised —
    the incumbent model keeps serving on a failed replica (the
    reloader's contract) and training never stalls on the fleet."""
    ok = failed = 0
    for host, port in endpoints:
        _c_pushes.inc()
        try:
            rpc = EndpointRpc(host, port, timeout=timeout)
            try:
                out = rpc.call("#reload " + model_path)
            finally:
                rpc.close()
        except (OSError, ValueError) as e:
            # ConnectionError (incl. the !err reply path) is an OSError
            failed += 1
            log.warning("reload push to %s:%d failed: %s", host, port, e)
            continue
        if out.get("ok", False):
            ok += 1
            log.info("reload push to %s:%d -> generation %s", host, port,
                     out.get("model_generation"))
        else:
            # typed reloader walk-back (e.g. the generation was pruned
            # between the save and this push): old model keeps serving,
            # the next committed generation catches the replica up
            failed += 1
            log.warning("reload push to %s:%d rejected: %s", host, port,
                        out.get("error"))
    return {"ok": ok, "failed": failed}
