"""Online continuous learning (ISSUE 17): serve→log→train→reload.

The composition layer over everything the previous PRs built: the serve
path logs served rows with a delayed-label feedback join into an
append-only rec2 segment log (log.py), a tailing trainer drives the
existing SGDLearner over each sealed segment through the normal
streamed pipeline with wall-clock verified checkpoints and
``auto_resume`` crash recovery (tail.py, trainer.py), freshness is a
measured SLO (``train_behind_serve_s`` / ``online_rows_behind`` /
``serve_generation_age_s`` — docs/observability.md), and every
committed generation is pushed to the fleet's hot-reload machinery so
the served model continuously advances (loop.py). ``task=online``
(__main__.py) is the CLI entry; docs/serving.md "Continuous learning"
is the runbook.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

from ..config import KWArgs, Param
from .log import OnlineLog, read_index, seg_path
from .loop import push_reload
from .tail import TailReader
from .trainer import OnlineTrainer

log = logging.getLogger("difacto_tpu")


@dataclass
class OnlineParam(Param):
    """task=online knobs (docs/serving.md "Continuous learning").
    Learner knobs (lr, model_out, auto_resume, ckpt_keep, mesh_fs, ...)
    pass through to the SGD learner unchanged."""
    # the training log directory the serve fleet appends to
    online_log_dir: str = ""
    # wall-clock seconds between committed generations (verified
    # checkpoint + fleet reload push); 0 = only the final commit
    online_ckpt_interval_s: float = field(default=5.0, metadata=dict(lo=0))
    # tail poll while waiting on the next seal
    online_poll_s: float = field(default=0.05, metadata=dict(lo=0.001))
    # offline replay of a finished log prefix: stop at the first gap
    # instead of tailing (the trajectory-integrity path)
    online_replay: bool = False
    # exit after this many wall seconds of tailing; 0 = until log.end
    online_max_seconds: float = field(default=0.0, metadata=dict(lo=0))
    # "host:port,host:port" serve replicas to push #reload to on every
    # committed generation; empty = rely on the replicas' own watchers
    online_endpoints: str = ""


def run_online(kwargs: KWArgs) -> KWArgs:
    """CLI entry for task=online (__main__.py): build the tailing
    trainer over the shared log directory and run it to completion."""
    param, remain = OnlineParam.init_allow_unknown(kwargs)
    if not param.online_log_dir:
        raise ValueError("please set online_log_dir")
    trainer = OnlineTrainer(param, remain)
    leftover = trainer.leftover
    trainer.run()
    return leftover


__all__ = ["OnlineParam", "run_online", "OnlineTrainer", "OnlineLog",
           "TailReader", "push_reload", "read_index", "seg_path"]
