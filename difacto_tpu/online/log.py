"""Append-only training log for the serve→log→train loop (ISSUE 17a).

The serve path appends every served row here; the online trainer tails
the sealed segments (online/tail.py). Three layers:

- **Live tail with a feedback join.** ``append`` enqueues each served
  row (a size-1 RowBlock straight from the serve parser) keyed by a row
  id; ``label`` joins a delayed label reported by the client
  (tools/loadgen.py feedback mode, or any client speaking ``#label``)
  onto its pending row within a ``label_delay_s`` horizon. Rows resolve
  STRICTLY in arrival order — a resolved row is one whose label arrived
  or whose horizon expired — so the sealed log is a faithful temporal
  record of the served stream, not a reordering of it.
- **Horizon default.** An unlabeled row past the horizon resolves to
  the configured default: ``drop`` (excluded from training) or
  ``negative`` (label 0.0 — the standard ad-click convention: an
  impression with no click within the attribution window is a
  non-click).
- **Sealed segments.** Every ``segment_rows`` resolved rows concatenate
  into one RowBlock and seal as ``seg-NNNNNN.rec2`` through the normal
  rec2 writer (data/rec.py: page-aligned sections, per-section CRC32,
  tmp+``os.replace``) — the atomic rename IS the seal marker the tailer
  blocks on, and the segment is readable by every existing rec path
  (the trajectory-integrity contract: replaying the sealed log offline
  through the streamed trainer reproduces the online checkpoint).
  Each seal also appends one JSON line to ``log.idx.jsonl``
  (``{"seg", "rows", "ts"}``; ``ts`` is ``time.monotonic()`` —
  CLOCK_MONOTONIC is machine-wide on Linux, the same clock convention
  obs trace events use, so the trainer process can subtract it from its
  own monotonic clock for the ``train_behind_serve_s`` gauge).
  ``end()`` seals the partial buffer and drops a ``log.end`` marker so
  a draining tailer terminates instead of polling forever. Stray files
  (the index, the end marker, ``*.tmp``) are invisible to rec readers —
  ``rec_members`` filters to member suffixes.

Fault points (utils/faultinject.py): ``online.log.append`` (an ``err``
surfaces to the caller — the serve path counts the drop and keeps
serving), ``online.label_join`` (an ``err`` surfaces as a typed ``!err``
reply to the reporting client), ``online.seal`` (an ``err`` keeps the
resolved buffer in memory and retries on the next advance — rows are
never lost to a transient seal failure).

Thread safety: one mutex guards all mutable state; the serve
connection threads (append/label) and any poller share it.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import re
import time
from typing import Dict, List, Optional

import numpy as np

from ..data.rec import write_rec_block
from ..data.rowblock import RowBlock
from ..obs import counter
from ..utils import faultinject
from ..utils.locktrace import mutex

log = logging.getLogger("difacto_tpu")

END_MARKER = "log.end"
INDEX_NAME = "log.idx.jsonl"
_SEG_RE = re.compile(r"^seg-(\d+)\.rec2$")

_c_logged = counter("online_rows_logged_total",
                    "served rows appended to the online training log")
_c_joined = counter("online_labels_joined_total",
                    "delayed labels joined onto a pending logged row")
_c_defaulted = counter(
    "online_label_defaults_total",
    "logged rows that passed the label_delay_s horizon unlabeled and "
    "resolved to the configured default (drop or negative)")
_c_sealed = counter("online_segments_sealed_total",
                    "training-log segments sealed (tmp+rename committed)")
_c_seal_failures = counter(
    "online_seal_failures_total",
    "segment seal attempts that failed (buffer retained, retried)")


def seg_path(log_dir: str, seg: int) -> str:
    return os.path.join(log_dir, f"seg-{seg:06d}.rec2")


def list_segments(log_dir: str) -> List[int]:
    """Sorted indices of the sealed segments present in ``log_dir``."""
    out = []
    try:
        names = os.listdir(log_dir)
    except FileNotFoundError:
        return out
    for name in names:
        m = _SEG_RE.match(name)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def read_index(log_dir: str) -> List[Dict]:
    """Parse ``log.idx.jsonl`` — tolerant of a torn final line (the
    index is advisory freshness metadata; the rename is the seal)."""
    out: List[Dict] = []
    path = os.path.join(log_dir, INDEX_NAME)
    try:
        with open(path, "r") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    log.debug("torn index line in %s: %r", path, line[:80])
    except FileNotFoundError:
        pass
    return out


class _Pending:
    __slots__ = ("rid", "blk", "t", "label")

    def __init__(self, rid: int, blk: RowBlock, t: float):
        self.rid = rid
        self.blk = blk
        self.t = t
        self.label: Optional[float] = None


class OnlineLog:
    def __init__(self, log_dir: str, segment_rows: int = 256,
                 label_delay_s: float = 1.0,
                 label_default: str = "negative"):
        if label_default not in ("drop", "negative"):
            raise ValueError(
                f"label_default={label_default!r} (want drop|negative)")
        os.makedirs(log_dir, exist_ok=True)
        self.log_dir = log_dir
        self.segment_rows = int(segment_rows)
        self.label_delay_s = float(label_delay_s)
        self.label_default = label_default
        self._mu = mutex()
        self._pending: "collections.deque[_Pending]" = collections.deque()
        self._by_id: Dict[int, _Pending] = {}
        self._buf: List[RowBlock] = []   # resolved rows awaiting a seal
        self._next_id = 0
        self._rows_logged = 0
        self._rows_dropped = 0
        self._ended = False
        # resume numbering after a restart: never overwrite a sealed seg
        segs = list_segments(log_dir)
        self._seg = (segs[-1] + 1) if segs else 0

    # ------------------------------------------------------------ serve
    def append(self, blk: RowBlock, row_id: Optional[int] = None) -> int:
        """Log one served row; returns its row id (auto-assigned when
        the client did not supply one). An injected ``err`` propagates —
        the serve path treats it like any IO failure (drop + count)."""
        if blk.size != 1:
            raise ValueError(f"online log appends single rows, got "
                             f"size={blk.size}")
        faultinject.act_default(faultinject.fire("online.log.append"))
        now = time.monotonic()
        with self._mu:
            if row_id is None:
                row_id = self._next_id
            self._next_id = max(self._next_id, row_id + 1)
            rec = _Pending(row_id, blk, now)
            self._pending.append(rec)
            # last append wins on a duplicate id: the stale entry stays
            # in arrival order but can no longer be labeled
            self._by_id[row_id] = rec
            self._rows_logged += 1
            _c_logged.inc()
            self._advance_locked(now)
        return row_id

    def label(self, row_id: int, y: float) -> bool:
        """Join a delayed label onto its pending row. Returns False when
        the row already resolved (past horizon / sealed) or was never
        logged — the feedback channel is best-effort by design."""
        faultinject.act_default(faultinject.fire("online.label_join"))
        now = time.monotonic()
        with self._mu:
            rec = self._by_id.get(row_id)
            if rec is None or rec.label is not None:
                return False
            rec.label = float(y)
            _c_joined.inc()
            self._advance_locked(now)
        return True

    def poll(self) -> None:
        """Advance horizon expiry without new traffic (idle streams)."""
        with self._mu:
            self._advance_locked(time.monotonic())

    # ------------------------------------------------------------ drain
    def flush(self) -> None:
        """Force-resolve every pending row (horizon defaults applied
        immediately) and seal the partial buffer. Safe to call from a
        restarting replica — it does NOT terminate the log."""
        with self._mu:
            self._advance_locked(time.monotonic(), force=True)
            if self._buf:
                self._seal_locked()

    def end(self) -> None:
        """Flush, then drop the ``log.end`` marker: tailing readers
        drain the remaining sealed segments and terminate."""
        self.flush()
        with self._mu:
            if not self._ended:
                with open(os.path.join(self.log_dir, END_MARKER),
                          "w") as f:
                    f.write("end\n")
                self._ended = True

    def stats(self) -> Dict:
        with self._mu:
            return {
                "rows_logged": self._rows_logged,
                "rows_dropped": self._rows_dropped,
                "pending": len(self._pending),
                "buffered": len(self._buf),
                "next_seg": self._seg,
            }

    # --------------------------------------------------------- internal
    def _advance_locked(self, now: float, force: bool = False) -> None:
        """Resolve the head of the pending queue while it is resolvable
        (labeled, or past the horizon); seal on every full buffer.
        Strict arrival order: a labeled row behind an unlabeled,
        in-horizon head waits for the head."""
        while self._pending:
            rec = self._pending[0]
            if (rec.label is None and not force
                    and now - rec.t < self.label_delay_s):
                break
            self._pending.popleft()
            if self._by_id.get(rec.rid) is rec:
                del self._by_id[rec.rid]
            if rec.label is None:
                _c_defaulted.inc()
                if self.label_default == "drop":
                    self._rows_dropped += 1
                    continue
                y = 0.0
            else:
                y = rec.label
            blk = rec.blk
            self._buf.append(RowBlock(
                offset=blk.offset,
                label=np.array([y], dtype=np.float32),
                index=blk.index, value=blk.value, weight=blk.weight))
            if len(self._buf) >= self.segment_rows:
                self._seal_locked()

    def _seal_locked(self) -> None:
        """Concat the resolved buffer and commit it as the next segment.
        Any failure (injected or real IO) keeps the buffer for the next
        advance — a transient seal failure never loses rows."""
        try:
            faultinject.act_default(faultinject.fire("online.seal"))
            blk = (self._buf[0] if len(self._buf) == 1
                   else RowBlock.concat(self._buf))
            write_rec_block(seg_path(self.log_dir, self._seg), blk)
        except (faultinject.FaultInjected, OSError) as e:
            _c_seal_failures.inc()
            log.warning("online log: seal of seg %d failed (%s); "
                        "buffer retained", self._seg, e)
            return
        rows = len(self._buf)
        self._buf = []
        with open(os.path.join(self.log_dir, INDEX_NAME), "a") as f:
            f.write(json.dumps({"seg": self._seg, "rows": rows,
                                "ts": time.monotonic()}) + "\n")
        _c_sealed.inc()
        self._seg += 1
