"""Tailing segment reader: blocks on the next seal (ISSUE 17b).

The online trainer consumes the training log one sealed segment at a
time. A segment's seal marker is the segment file itself — rec2 writes
commit with tmp+``os.replace`` (data/rec.py), so ``seg-NNNNNN.rec2``
either exists complete or not at all; the tailer never sees a torn
member. The iterator yields ``(seg_index, path)`` in order and, when
the next segment has not sealed yet, polls until one of:

- the segment appears (the normal tail case);
- ``log.end`` exists and the segment still does not (the writer
  terminated the log; the end marker is written AFTER the final seal,
  so re-checking the segment first makes the hand-off race-free);
- ``replay=True`` (offline replay over a finished prefix: stop at the
  first gap instead of waiting — the trajectory-integrity path);
- the caller's ``stop`` event is set, or ``max_seconds`` elapsed.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Iterator, Optional, Tuple

from .log import END_MARKER, seg_path


class TailReader:
    def __init__(self, log_dir: str, start_seg: int = 0,
                 poll_s: float = 0.05, replay: bool = False,
                 max_seconds: float = 0.0,
                 stop: Optional[threading.Event] = None):
        self.log_dir = log_dir
        self.start_seg = int(start_seg)
        self.poll_s = float(poll_s)
        self.replay = replay
        self.max_seconds = float(max_seconds)
        self.stop = stop

    def _ended(self) -> bool:
        return os.path.exists(os.path.join(self.log_dir, END_MARKER))

    def __iter__(self) -> Iterator[Tuple[int, str]]:
        seg = self.start_seg
        deadline = (time.monotonic() + self.max_seconds
                    if self.max_seconds > 0 else None)
        while True:
            path = seg_path(self.log_dir, seg)
            if os.path.exists(path):
                yield seg, path
                seg += 1
                continue
            if self.replay or self._ended():
                # end marker lands after the final seal; the exists()
                # check above already re-ran this iteration, so a
                # missing segment here really is the end of the log
                return
            if self.stop is not None and self.stop.is_set():
                return
            if deadline is not None and time.monotonic() >= deadline:
                return
            time.sleep(self.poll_s)
