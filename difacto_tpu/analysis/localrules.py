"""Single-file rules: concurrency hygiene, the monotonic-clock
contract, exception discipline, and the three JAX tracing rules.

Each rule is a function ``(SourceFile) -> list[Finding]`` registered
with :func:`core.rule`. They share one parsed AST (with ``.parent``
links) per file and a handful of helpers from :mod:`core`; none of them
import anything outside the stdlib. Per-rule fixtures live in
``tests/test_lint.py`` — every rule has at least one true-positive and
one suppressed/negative fixture there.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .core import (Finding, SourceFile, call_name, dotted,
                   enclosing_function, from_imports, import_aliases,
                   node_key, rule, statement_of)


def _walk_calls(tree) -> List[ast.Call]:
    """Call nodes of a tree — or of a whole SourceFile, in which case
    the file's shared node index is reused instead of re-walking."""
    if isinstance(tree, SourceFile):
        return tree.call_nodes()
    return [n for n in ast.walk(tree) if isinstance(n, ast.Call)]


def _assign_key(call: ast.Call) -> Optional[str]:
    """Key of the single name/attribute a call's value is bound to, or
    None when unbound (bare expression, tuple target, nested use)."""
    stmt = statement_of(call)
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
            and stmt.value is call:
        return node_key(stmt.targets[0]) or None
    if isinstance(stmt, ast.AnnAssign) and stmt.value is call:
        return node_key(stmt.target) or None
    return None


def _method_calls_on(tree, key: str, methods: Set[str]) -> bool:
    """Is there any ``<key>.m(...)`` call with m in methods?"""
    for c in _walk_calls(tree):
        if isinstance(c.func, ast.Attribute) and c.func.attr in methods \
                and node_key(c.func.value) == key:
            return True
    return False


def _in_withitem(node) -> bool:
    """Is this expression (possibly wrapped, e.g. ``closing(...)``) the
    context expression of a ``with`` statement?"""
    cur = node
    while cur is not None and not isinstance(cur, ast.stmt):
        parent = getattr(cur, "parent", None)
        if isinstance(parent, ast.withitem):
            return True
        cur = parent
    return False


# ---------------------------------------------------------------------------


@rule("thread-daemon",
      "threads must be created with daemon= or joined")
def check_thread_daemon(sf: SourceFile) -> List[Finding]:
    out = []
    for call in _walk_calls(sf):
        cn = call_name(call)
        if not (cn == "Thread" or cn.endswith(".Thread")):
            continue
        if any(kw.arg == "daemon" for kw in call.keywords):
            continue
        key = _assign_key(call)
        if key and _method_calls_on(sf, key, {"join"}):
            continue
        out.append(sf.finding(
            "thread-daemon", call,
            "thread created without daemon= and never joined — a "
            "non-daemon thread blocks interpreter exit; pass "
            "daemon=True or join() it on every path"))
    return out


@rule("lock-release",
      "Lock.acquire() needs `with lock:` or finally: release()")
def check_lock_release(sf: SourceFile) -> List[Finding]:
    out = []
    for call in _walk_calls(sf):
        if not (isinstance(call.func, ast.Attribute)
                and call.func.attr == "acquire"):
            continue
        key = node_key(call.func.value)
        if not key:
            continue
        # accept a matching `finally: release()` anywhere in the
        # enclosing function — it may guard the acquire from an
        # ancestor Try OR follow it as a sibling (`if not
        # lock.acquire(timeout=...): return` then try/finally)
        scope = enclosing_function(call) or sf.tree
        released = False
        for t in ast.walk(scope):
            if not isinstance(t, ast.Try):
                continue
            for stmt in t.finalbody:
                for c in _walk_calls(stmt):
                    if isinstance(c.func, ast.Attribute) \
                            and c.func.attr == "release" \
                            and node_key(c.func.value) == key:
                        released = True
        if not released:
            out.append(sf.finding(
                "lock-release", call,
                f"{key.lstrip('.')}.acquire() outside `with` without a "
                f"finally: release() — an exception between acquire and "
                f"release deadlocks every other holder"))
    return out


# resources whose open must pair with a close on every path
_OPEN_EXACT = {"open", "io.open", "os.fdopen", "gzip.open",
               "socket.socket", "socket.create_connection", "mmap.mmap"}
_CLOSERS = {"close", "shutdown", "unlink", "release", "detach",
            "terminate", "fileno"}  # fileno: fd handed to an owning wrapper


def _is_opener(cn: str) -> bool:
    return cn in _OPEN_EXACT or cn == "SharedMemory" \
        or cn.endswith(".SharedMemory")


def _name_escapes(scope, key: str, binder: ast.stmt) -> bool:
    """Does the bound resource leave this scope (returned, yielded,
    stored in a container/attribute, or passed to another call)? An
    escaped resource is some other owner's to close."""
    for n in ast.walk(scope):
        if not (isinstance(n, ast.Name) and n.id == key
                and isinstance(n.ctx, ast.Load)):
            continue
        parent = getattr(n, "parent", None)
        if isinstance(parent, (ast.Return, ast.Yield, ast.YieldFrom,
                               ast.Tuple, ast.List, ast.Dict, ast.Set)):
            return True
        if isinstance(parent, ast.Call) and n in parent.args:
            return True
        if isinstance(parent, ast.keyword):
            return True
        if isinstance(parent, (ast.Assign, ast.AnnAssign)) \
            and getattr(parent, "value", None) is n \
                and statement_of(parent) is not binder:
            return True  # re-bound elsewhere: aliased, owner unclear
        if isinstance(parent, ast.Subscript):
            return True
    return False


@rule("resource-close",
      "sockets/files/SharedMemory/mmap need `with` or a close on "
      "every path")
def check_resource_close(sf: SourceFile) -> List[Finding]:
    out = []
    for call in _walk_calls(sf):
        cn = call_name(call)
        if not _is_opener(cn):
            continue
        if _in_withitem(call):
            continue
        stmt = statement_of(call)
        if isinstance(stmt, ast.Return) or any(
                isinstance(p, (ast.Yield, ast.YieldFrom))
                for p in ast.walk(stmt)):
            continue  # handed to the caller: theirs to close
        key = _assign_key(call)
        if key is None:
            # not bound to a name: `f(open(p))` leaks the handle, a bare
            # `socket.socket()` statement leaks the fd
            out.append(sf.finding(
                "resource-close", call,
                f"{cn}(...) opened without a binding or `with` — the "
                f"handle can never be closed"))
            continue
        if key.startswith("."):
            # self/obj attribute: accept when the module closes that
            # attribute somewhere (close()/stop() methods, __exit__)
            if _method_calls_on(sf, key, _CLOSERS):
                continue
        else:
            scope = enclosing_function(call) or sf.tree
            if _method_calls_on(scope, key, _CLOSERS):
                continue
            if _name_escapes(scope, key, stmt):
                continue
        out.append(sf.finding(
            "resource-close", call,
            f"{cn}(...) bound to {key.lstrip('.')} is never closed — "
            f"use `with`, or close it in a finally/close() path"))
    return out


@rule("wall-clock",
      "durations and deadlines must use time.monotonic()")
def check_wall_clock(sf: SourceFile) -> List[Finding]:
    time_aliases = import_aliases(sf.tree, "time")
    time_members = {alias for alias, orig in
                    from_imports(sf.tree, "time").items() if orig == "time"}
    out = []
    for call in _walk_calls(sf):
        f = call.func
        hit = (isinstance(f, ast.Attribute) and f.attr == "time"
               and isinstance(f.value, ast.Name)
               and f.value.id in time_aliases) \
            or (isinstance(f, ast.Name) and f.id in time_members)
        if hit:
            out.append(sf.finding(
                "wall-clock", call,
                "time.time() is wall clock: NTP steps/slew corrupt "
                "durations and deadlines — use time.monotonic() (the "
                "obs clock contract); a true timestamp-of-record may "
                "suppress with `# lint: ok(wall-clock)`"))
    return out


_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log"}
_COUNT_METHODS = {"inc", "observe", "set", "record_error", "record_shed",
                  "set_exception", "print_exc", "format_exc",
                  "count_swallowed"}


def _handler_reports(handler: ast.ExceptHandler) -> bool:
    """A broad handler is acceptable when the error is visibly routed
    somewhere: re-raised, logged, counted, printed, formatted for a
    result channel — or when the bound exception name is referenced at
    all (captured into an err list, stuffed into a message, ...)."""
    for n in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if isinstance(n, ast.Raise):
            return True
        if isinstance(n, ast.Call):
            fn = n.func
            if isinstance(fn, ast.Attribute) \
                    and fn.attr in (_LOG_METHODS | _COUNT_METHODS):
                return True
            if isinstance(fn, ast.Name) and fn.id == "print":
                return True
        if handler.name and isinstance(n, ast.Name) \
                and n.id == handler.name and isinstance(n.ctx, ast.Load):
            return True
    return False


@rule("broad-except",
      "broad excepts must log-and-count, re-raise, or narrow")
def check_broad_except(sf: SourceFile) -> List[Finding]:
    out = []
    for node in sf.walk():
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            out.append(sf.finding(
                "broad-except", node,
                "bare `except:` also swallows SystemExit and "
                "KeyboardInterrupt — catch `Exception` at most, and "
                "log what was caught"))
            continue
        names = [node.type] if not isinstance(node.type, ast.Tuple) \
            else list(node.type.elts)
        broad = any(dotted(n) in ("Exception", "BaseException")
                    for n in names)
        if broad and not _handler_reports(node):
            out.append(sf.finding(
                "broad-except", node,
                "`except Exception` that neither re-raises, logs, nor "
                "counts — failures vanish silently; log-and-count (obs "
                "counter) or narrow the type"))
    return out


# ---------------------------------------------------------------------------
# JAX tracing rules


def _donated_indices(call: ast.Call) -> Set[int]:
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        consts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
        return {c.value for c in consts
                if isinstance(c, ast.Constant) and isinstance(c.value, int)}
    return set()


def _is_jit_call(call: ast.Call) -> bool:
    cn = call_name(call)
    if cn == "jit" or cn.endswith(".jit"):
        return True
    if (cn == "partial" or cn.endswith(".partial")) and call.args:
        a0 = call.args[0]
        an = dotted(a0)
        return an == "jit" or an.endswith(".jit")
    return False


@rule("jax-donate",
      "a buffer donated via donate_argnums must not be read after "
      "the call")
def check_jax_donate(sf: SourceFile) -> List[Finding]:
    # jitted-with-donation wrappers bound to a name in this file
    wrappers: Dict[str, Set[int]] = {}
    for call in _walk_calls(sf):
        if not _is_jit_call(call):
            continue
        idx = _donated_indices(call)
        if not idx:
            continue
        key = _assign_key(call)
        if key and not key.startswith("."):
            wrappers[key] = idx
    out = []
    for call in _walk_calls(sf):
        name = call_name(call)
        donated = wrappers.get(name)
        if not donated:
            continue
        stmt = statement_of(call)
        scope = enclosing_function(call) or sf.tree
        # `x = f(x)` rebinds the donated name — the canonical safe idiom
        rebound: Set[str] = set()
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        rebound.add(n.id)
        for i in sorted(donated):
            if i >= len(call.args):
                continue
            arg = call.args[i]
            if not isinstance(arg, ast.Name) or arg.id in rebound:
                continue
            for n in ast.walk(scope):
                if isinstance(n, ast.Name) and n.id == arg.id \
                        and isinstance(n.ctx, ast.Load) \
                        and n.lineno > stmt.end_lineno:
                    out.append(sf.finding(
                        "jax-donate", n,
                        f"`{arg.id}` was donated to `{name}` "
                        f"(donate_argnums={i}) on line {call.lineno} — "
                        f"its buffer is deleted after the call; reading "
                        f"it here is undefined"))
                    break
    return out


def _jitted_functions(sf: SourceFile) -> List[ast.FunctionDef]:
    """FunctionDefs that are jit targets: decorated with jit /
    partial(jit, ...) or passed by name to a jit(...) call."""
    jit_arg_names: Set[str] = set()
    for call in _walk_calls(sf):
        if _is_jit_call(call) and call.args:
            a0 = call.args[0] if call_name(call).endswith("jit") \
                or call_name(call) == "jit" else \
                (call.args[1] if len(call.args) > 1 else None)
            if isinstance(a0, ast.Name):
                jit_arg_names.add(a0.id)
    out = []
    for node in sf.walk():
        if not isinstance(node, ast.FunctionDef):
            continue
        jitted = node.name in jit_arg_names
        for dec in node.decorator_list:
            dn = dotted(dec)
            if dn == "jit" or dn.endswith(".jit"):
                jitted = True
            if isinstance(dec, ast.Call) and _is_jit_call(dec):
                jitted = True
        if jitted:
            out.append(node)
    return out


@rule("jax-jit-capture",
      "jitted functions must not close over self/cls state")
def check_jax_jit_capture(sf: SourceFile) -> List[Finding]:
    out = []
    for fn in _jitted_functions(sf):
        params = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                  + fn.args.kwonlyargs)}
        if "self" in params or "cls" in params:
            out.append(sf.finding(
                "jax-jit-capture", fn,
                f"`{fn.name}` is jitted with self/cls as a traced "
                f"argument — jit retraces per instance and pins the "
                f"object in the compile cache; jit a free function of "
                f"explicit arrays instead"))
            continue
        for n in ast.walk(ast.Module(body=fn.body, type_ignores=[])):
            if isinstance(n, ast.Name) and n.id in ("self", "cls") \
                    and isinstance(n.ctx, ast.Load):
                out.append(sf.finding(
                    "jax-jit-capture", n,
                    f"jitted `{fn.name}` closes over `{n.id}` — the "
                    f"capture is baked in at trace time, so later "
                    f"mutations are silently ignored; pass the value "
                    f"as an argument"))
                break
    return out


# numpy attributes that are trace-safe metadata, not host array ops
_NP_OK = {"dtype", "iinfo", "finfo", "result_type", "promote_types",
          "can_cast", "isscalar", "ndim", "shape",
          "float16", "float32", "float64", "int8", "int16", "int32",
          "int64", "uint8", "uint16", "uint32", "uint64", "bool_",
          "bfloat16"}
_HOST_MODULES = {"time", "random", "os"}


@rule("jax-host-call",
      "no host numpy / side-effect calls inside traced code")
def check_jax_host_call(sf: SourceFile) -> List[Finding]:
    np_aliases = import_aliases(sf.tree, "numpy")
    out = []
    for fn in _jitted_functions(sf):
        for call in _walk_calls(ast.Module(body=fn.body, type_ignores=[])):
            cn = call_name(call)
            head, _, tail = cn.partition(".")
            msg = None
            if head in np_aliases and tail and tail not in _NP_OK:
                msg = (f"host numpy call `{cn}(...)` inside jitted "
                       f"`{fn.name}` runs at trace time on abstract "
                       f"values (or forces a device sync) — use "
                       f"jax.numpy")
            elif head in _HOST_MODULES and tail:
                msg = (f"side-effecting host call `{cn}(...)` inside "
                       f"jitted `{fn.name}` only runs at trace time — "
                       f"hoist it out of the traced function")
            elif cn == "print":
                msg = (f"print() inside jitted `{fn.name}` fires at "
                       f"trace time only — use jax.debug.print")
            if msg:
                out.append(sf.finding("jax-host-call", call, msg))
    return out
