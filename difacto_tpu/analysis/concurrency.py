"""Interprocedural concurrency rules: lock-order cycles, blocking calls
under locks, and Condition-wait discipline.

Built on :mod:`callgraph`. The model identifies every lock object in the
tree — ``threading.Lock/RLock/Condition`` (and the traced
``utils.locktrace.mutex/rmutex/condition`` factories) bound to a module
global, a ``self.attr`` class attribute, or a function local — then
propagates *held-lock sets* along the call graph:

- every ``with lock:`` / ``lock.acquire()`` region is a held region;
- a call made inside a held region orders the held locks BEFORE every
  lock the callee (transitively) acquires — thread hand-off edges
  (``Thread(target=...)``, ``submit``, ``pool.map``) do NOT propagate,
  the target runs on another thread with an empty held set;
- the resulting global lock-acquisition-order graph must be acyclic: a
  cycle means two threads can interleave into a deadlock, and the
  finding carries one witness path per direction so the report shows
  BOTH call chains that disagree on the order.

Lock identity is the *declaration site* (``rel.py::Class.attr`` /
``rel.py::global`` / ``rel.py::func.local``): all instances of one class
attribute collapse onto one node. That abstraction makes the analysis
tractable and matches the runtime tracer (utils/locktrace.py keys edges
by creation site), at the cost of two documented blind spots — self
edges (two *instances* of the same attribute lock) are skipped, and
locks reached only through unresolvable dynamic calls are invisible.

Two flow rules ride the same model:

- **lock-blocking** — a blocking operation (socket accept/recv/send*,
  ``queue.put/get`` without timeout, bare ``join()``, ``time.sleep``,
  ``subprocess.*``, ``SharedMemory`` create/unlink, untimed
  ``Event.wait``) executed — directly or through the call graph — while
  a lock is held turns every waiter on that lock into a hang. Blocking
  ops inside the fault-injection module itself (the ``delay_ms`` chaos
  kind IS a sleep) are exempt.
- **cond-wait-while** (local) — ``Condition.wait()`` outside a
  ``while``-predicate loop misses spurious wakeups and notify races;
  ``wait_for`` carries its own predicate and is always fine.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import CallGraph, FuncInfo, get_callgraph
from .core import (Finding, Project, SourceFile, call_name, node_key,
                   rule)

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)
_SCOPES = (_FUNC_DEFS[0], _FUNC_DEFS[1], ast.Lambda, ast.ClassDef)

# ctor member -> lock kind (threading.* and utils/locktrace.* factories)
_LOCK_CTORS = {"Lock": "Lock", "RLock": "RLock", "Condition": "Condition",
               "mutex": "Lock", "rmutex": "RLock", "condition": "Condition"}
_LOCK_MODULES = ("threading", "locktrace")
# distinctive socket methods (receiver-agnostic); send/connect only fire
# on receivers assigned from a socket constructor
_SOCKET_METHODS = {"accept", "recv", "recvfrom", "recv_into", "sendall",
                   "sendto"}
_SOCKET_METHODS_TYPED = {"send", "connect"}


# ---------------------------------------------------------------------------
# lock discovery


@dataclass
class LockInfo:
    lock_id: str        # "rel.py::Class.attr" | "rel.py::name" | "...::f.x"
    kind: str           # Lock | RLock | Condition
    path: str
    line: int           # ctor call line == locktrace creation-site line
    scope: str          # module | class | local
    key: str            # node_key of the binding target ("x" or ".attr")


def _lock_ctor_kind(sf: SourceFile, call: ast.Call,
                    bare: Dict[str, str]) -> Optional[str]:
    cn = call_name(call)
    if not cn:
        return None
    if "." in cn:
        head, _, last = cn.rpartition(".")
        if last in _LOCK_CTORS and head.split(".")[-1] in _LOCK_MODULES:
            return _LOCK_CTORS[last]
        return None
    return bare.get(cn)


def _bare_lock_names(sf: SourceFile) -> Dict[str, str]:
    """Names bound by ``from threading import Lock`` /
    ``from ..utils.locktrace import mutex`` — local name -> kind."""
    out: Dict[str, str] = {}
    for node in sf.walk():
        if isinstance(node, ast.ImportFrom) and node.module:
            if node.module.split(".")[-1] in _LOCK_MODULES:
                for a in node.names:
                    if a.name in _LOCK_CTORS:
                        out[a.asname or a.name] = _LOCK_CTORS[a.name]
    return out


def _enclosing(node, kinds):
    cur = getattr(node, "parent", None)
    while cur is not None:
        if isinstance(cur, kinds):
            return cur
        cur = getattr(cur, "parent", None)
    return None


def discover_locks(sf: SourceFile, cg: Optional[CallGraph] = None) \
        -> List[LockInfo]:
    """Every lock bound in this file, with its declaration identity."""
    if sf.tree is None:
        return []
    bare = _bare_lock_names(sf)
    out: List[LockInfo] = []
    for node in sf.walk():
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if value is None:
            continue
        # unwrap collections of locks: self._locks = [Lock() for ...]
        ctor: Optional[ast.Call] = None
        cands = [value]
        if isinstance(value, (ast.ListComp, ast.SetComp)):
            cands = [value.elt]
        elif isinstance(value, (ast.List, ast.Tuple)):
            cands = list(value.elts)
        for c in cands:
            if isinstance(c, ast.Call) \
                    and _lock_ctor_kind(sf, c, bare) is not None:
                ctor = c
                break
        if ctor is None:
            continue
        kind = _lock_ctor_kind(sf, ctor, bare)
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        if len(targets) != 1:
            continue
        tgt = targets[0]
        key = node_key(tgt)
        if not key:
            continue
        if isinstance(tgt, ast.Attribute):
            cls = _enclosing(tgt, ast.ClassDef)
            if cls is None:
                continue
            lock_id = f"{sf.rel}::{cls.name}{key}"
            scope = "class"
        elif _enclosing(tgt, _FUNC_DEFS) is None \
                and _enclosing(tgt, ast.ClassDef) is not None:
            # class-body declaration (`class C: _mu = Lock()`): acquired
            # through `self._mu`, so index it like an attribute lock
            cls = _enclosing(tgt, ast.ClassDef)
            key = "." + key
            lock_id = f"{sf.rel}::{cls.name}{key}"
            scope = "class"
        else:
            fn = _enclosing(tgt, _FUNC_DEFS)
            if fn is None:
                lock_id = f"{sf.rel}::{key}"
                scope = "module"
            else:
                chain = [fn.name]
                outer = _enclosing(fn, _FUNC_DEFS)
                while outer is not None:
                    chain.append(outer.name)
                    outer = _enclosing(outer, _FUNC_DEFS)
                lock_id = f"{sf.rel}::{'.'.join(reversed(chain))}.{key}"
                scope = "local"
        out.append(LockInfo(lock_id, kind, sf.rel, ctor.lineno, scope,
                            key))
    return out


# ---------------------------------------------------------------------------
# per-file auxiliary typing (queues, events, sockets, threads, shm)

_TYPE_CTORS = {"Queue": "queue", "SimpleQueue": "queue",
               "LifoQueue": "queue", "PriorityQueue": "queue",
               "JoinableQueue": "queue",
               "Event": "event", "SharedMemory": "shm",
               "Thread": "thread", "Process": "thread",
               "socket": "socket", "create_connection": "socket",
               "create_server": "socket"}


def _typed_keys(sf: SourceFile) -> Dict[str, str]:
    types: Dict[str, str] = {}
    for node in sf.walk():
        if not isinstance(node, ast.Call):
            continue
        last = call_name(node).split(".")[-1]
        t = _TYPE_CTORS.get(last)
        if t is None:
            continue
        stmt = node
        while stmt is not None and not isinstance(stmt, ast.stmt):
            stmt = getattr(stmt, "parent", None)
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and stmt.value is node:
            key = node_key(stmt.targets[0])
            if key:
                types[key] = t
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is node:
            key = node_key(stmt.target)
            if key:
                types[key] = t
    return types


def _has_timeout(call: ast.Call, is_put: bool = False) -> bool:
    for kw in call.keywords:
        if kw.arg == "timeout":
            return True
        if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
    n = len(call.args)
    base = 1 if is_put else 0   # put's first positional is the item
    if n >= base + 2:
        return True             # (block, timeout) positionals
    if n == base + 1 and isinstance(call.args[base], ast.Constant) \
            and call.args[base].value is False:
        return True             # block=False positionally
    return False


def _blocking_desc(sf: SourceFile, call: ast.Call, types: Dict[str, str],
                   time_names: Set[str], subprocess_names: Set[str],
                   cond_keys: Set[str]) -> Optional[str]:
    cn = call_name(call)
    head = cn.split(".")[0] if cn else ""
    last = cn.split(".")[-1] if cn else ""
    if head in time_names and last == "sleep":
        return "time.sleep()"
    if head in subprocess_names and "." in cn:
        return f"subprocess.{last}()"
    if last == "create_connection" and head in ("socket", "sock"):
        return "socket.create_connection()"
    if last == "SharedMemory" or cn.endswith(".SharedMemory"):
        for kw in call.keywords:
            if kw.arg == "create" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is True:
                return "SharedMemory(create=True)"
        return None
    if not isinstance(call.func, ast.Attribute):
        return None
    m = call.func.attr
    key = node_key(call.func.value)
    if m in _SOCKET_METHODS:
        return f"socket {m}()"
    if m in _SOCKET_METHODS_TYPED and types.get(key) == "socket":
        return f"socket {m}()"
    if m == "join":
        if not call.args and not call.keywords:
            # bare join(): Thread/Process/pool — str.join always takes
            # an iterable argument, so a 0-arg join is never the string
            # method
            return "join()"
        if types.get(key) == "thread":
            return "join()"
        return None
    if m in ("get", "put") and types.get(key) == "queue":
        if not _has_timeout(call, is_put=(m == "put")):
            return f"queue.{m}() without timeout"
        return None
    if m == "wait" and (types.get(key) == "event" or key in cond_keys):
        if not call.args and not any(kw.arg == "timeout"
                                     for kw in call.keywords):
            return "wait() without timeout"
    return None


# ---------------------------------------------------------------------------
# per-function scan


@dataclass
class _FuncFacts:
    qual: str
    sf: SourceFile
    direct_acq: Dict[str, str] = field(default_factory=dict)  # lock->site
    direct_block: Dict[str, int] = field(default_factory=dict)  # desc->line
    # (held ((lock, site)...), call node) — for interprocedural edges
    call_events: List[Tuple[Tuple[Tuple[str, str], ...], ast.Call]] = \
        field(default_factory=list)
    # (src, dst, holder_site, acquire_site) — direct syntactic nesting
    direct_edges: List[Tuple[str, str, str, str]] = field(
        default_factory=list)
    # (held, desc, node) — blocking op with a lock held, in THIS body
    block_events: List[Tuple[Tuple[Tuple[str, str], ...], str,
                             ast.Call]] = field(default_factory=list)
    # shared-state accesses for the race pass (analysis/races.py):
    # (held lock ids, node) where node is a `self.attr`/`cls.attr`
    # Attribute, or a Name that is free / global / nonlocal / a closure
    # cell in this scope — recorded in the SAME walk that tracks held
    # sets, so the race pass never re-walks a function body
    access_events: List[Tuple[Tuple[str, ...], ast.AST]] = \
        field(default_factory=list)
    local_names: Set[str] = field(default_factory=set)
    global_names: Set[str] = field(default_factory=set)
    cell_names: Set[str] = field(default_factory=set)


class _Scanner:
    """Walks one function body tracking the held-lock set."""

    def __init__(self, model: "ConcurrencyModel", fi: FuncInfo):
        self.model = model
        self.fi = fi
        self.sf = fi.sf
        self.facts = _FuncFacts(fi.qual, fi.sf)
        self.types = model.file_types[fi.sf.rel]
        self.time_names = model.file_time_names[fi.sf.rel]
        self.subprocess_names = model.file_subprocess_names[fi.sf.rel]
        self.cond_keys = model.file_cond_keys[fi.sf.rel]
        self._scope_names()

    def _scope_names(self) -> None:
        """Name classification for the race pass: names bound in THIS
        scope (locals), ``global``/``nonlocal`` declarations, and
        closure cells (locals a nested def also references)."""
        node = self.fi.node
        body = node.body if node is not None else self.sf.tree.body
        locs: Set[str] = set()
        gl: Set[str] = set()
        nl: Set[str] = set()
        nested: List[ast.AST] = []
        if node is not None and isinstance(node, _FUNC_DEFS):
            a = node.args
            for arg in (a.posonlyargs + a.args + a.kwonlyargs
                        + ([a.vararg] if a.vararg else [])
                        + ([a.kwarg] if a.kwarg else [])):
                locs.add(arg.arg)
        stack = list(body)
        while stack:
            n = stack.pop()
            if isinstance(n, _SCOPES):
                if isinstance(n, (*_FUNC_DEFS, ast.ClassDef)):
                    locs.add(n.name)
                nested.append(n)
                continue
            if isinstance(n, ast.Global):
                gl.update(n.names)
                continue
            if isinstance(n, ast.Nonlocal):
                nl.update(n.names)
                continue
            if isinstance(n, ast.Name) \
                    and isinstance(n.ctx, (ast.Store, ast.Del)):
                locs.add(n.id)
            elif isinstance(n, (ast.Import, ast.ImportFrom)):
                for al in n.names:
                    locs.add((al.asname or al.name).split(".")[0])
            elif isinstance(n, ast.ExceptHandler) and n.name:
                locs.add(n.name)
            stack.extend(ast.iter_child_nodes(n))
        locs -= gl | nl
        used_below: Set[str] = set()
        for sub in nested:
            for n in ast.walk(sub):
                if isinstance(n, ast.Name):
                    used_below.add(n.id)
                elif isinstance(n, ast.Nonlocal):
                    used_below.update(n.names)
        self._locals = locs
        self._globals = gl
        self._nonlocals = nl
        self._cells = locs & used_below
        self.facts.local_names = locs
        self.facts.global_names = gl
        self.facts.cell_names = self._cells

    def _site(self, node) -> str:
        return f"{self.sf.rel}:{getattr(node, 'lineno', 0)}"

    def resolve_lock(self, expr) -> Optional[str]:
        """Lock id for an acquisition expression, or None."""
        while isinstance(expr, ast.Subscript):
            expr = expr.value
        model = self.model
        if isinstance(expr, ast.Name):
            # lexical chain: locals of enclosing functions, then module
            prefix = self.fi.qual.split("::", 1)[1]
            parts = [] if prefix == "<module>" else prefix.split(".")
            while True:
                cand = f"{self.sf.rel}::{'.'.join(parts + [expr.id])}" \
                    if parts else f"{self.sf.rel}::{expr.id}"
                if cand in model.locks:
                    return cand
                if not parts:
                    return None
                parts.pop()
        if isinstance(expr, ast.Attribute):
            attr = "." + expr.attr
            cls = self.fi.cls
            if cls is not None:
                cand = f"{cls.sf.rel}::{cls.name}{attr}"
                if cand in model.locks:
                    return cand
                for base in cls.bases:
                    for bi in model.cg.classes.get(base, []):
                        cand = f"{bi.sf.rel}::{bi.name}{attr}"
                        if cand in model.locks:
                            return cand
            matches = model.attr_locks.get(attr, [])
            if len(matches) == 1:
                return matches[0]
        return None

    # ----------------------------------------------------------- events
    def _note_acquire(self, lock: str, node,
                      held: List[Tuple[str, str]]) -> None:
        site = self._site(node)
        self.facts.direct_acq.setdefault(lock, site)
        for h, hsite in held:
            if h != lock:
                self.facts.direct_edges.append((h, lock, hsite, site))

    def handle_call(self, call: ast.Call,
                    held: List[Tuple[str, str]]) -> None:
        fn = call.func
        if isinstance(fn, ast.Attribute) \
                and fn.attr in ("acquire", "release", "locked"):
            return  # lock operations are handled by the region tracker
        desc = _blocking_desc(self.sf, call, self.types, self.time_names,
                              self.subprocess_names, self.cond_keys)
        if desc is not None and self.sf.rel != self.model.kinds_rel:
            self.facts.direct_block.setdefault(desc, call.lineno)
            eff = list(held)
            if desc.startswith("wait()"):
                # Condition.wait releases its own lock while waiting
                own = self.resolve_lock(fn.value) \
                    if isinstance(fn, ast.Attribute) else None
                eff = [(h, s) for h, s in eff if h != own]
            if eff:
                self.facts.block_events.append((tuple(eff), desc, call))
        if held:
            site = self.model.cg.by_node.get(id(call))
            if site is not None and site.kind == "call" and site.targets:
                self.facts.call_events.append((tuple(held), call))

    # ------------------------------------------------------------- walk
    def run(self) -> _FuncFacts:
        node = self.fi.node
        body = node.body if node is not None else self.sf.tree.body
        self.visit_stmts(body, [])
        return self.facts

    def visit_stmts(self, stmts, held: List[Tuple[str, str]]) -> None:
        held = list(held)
        for stmt in stmts:
            self.visit_node(stmt, held)
            # bare acquire()/release() sequencing: effective from the
            # statement AFTER the acquire, gone after the release
            for lock, node, op in self._lock_ops(stmt):
                if op == "acquire":
                    self._note_acquire(lock, node, held)
                    held.append((lock, self._site(node)))
                else:
                    for i in range(len(held) - 1, -1, -1):
                        if held[i][0] == lock:
                            del held[i]
                            break

    def _lock_ops(self, stmt):
        """acquire()/release() calls at THIS statement's level only —
        simple statements entirely, compound statements just their test
        / iterable expression (ops inside nested suites sequence inside
        those suites)."""
        if isinstance(stmt, (ast.Expr, ast.Assign, ast.AnnAssign,
                             ast.AugAssign, ast.Return, ast.Assert)):
            exprs = [stmt]
        elif isinstance(stmt, (ast.If, ast.While)):
            exprs = [stmt.test]
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            exprs = [stmt.iter]
        else:
            return []
        out = []
        for e in exprs:
            for node in ast.walk(e):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("acquire", "release"):
                    lock = self.resolve_lock(node.func.value)
                    if lock is not None:
                        out.append((lock, node, node.func.attr))
        return out

    def visit_node(self, node, held: List[Tuple[str, str]]) -> None:
        if isinstance(node, _SCOPES):
            return  # separate scope: scanned with its own empty held set
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id in ("self", "cls") \
                and not (node.attr.startswith("__")
                         and node.attr.endswith("__")):
            self.facts.access_events.append(
                (tuple(h for h, _ in held), node))
        elif isinstance(node, ast.Name):
            nid = node.id
            if nid in self._globals or nid in self._nonlocals \
                    or nid in self._cells \
                    or (nid not in self._locals
                        and isinstance(node.ctx, ast.Load)):
                self.facts.access_events.append(
                    (tuple(h for h, _ in held), node))
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = list(held)
            for item in node.items:
                self.visit_node(item.context_expr, inner)
                lock = self.resolve_lock(item.context_expr)
                if lock is not None:
                    self._note_acquire(lock, item.context_expr, inner)
                    inner.append((lock, self._site(item.context_expr)))
            self.visit_stmts(node.body, inner)
            return
        if isinstance(node, ast.Call):
            self.handle_call(node, held)
        for _fname, value in ast.iter_fields(node):
            if isinstance(value, list):
                if value and isinstance(value[0], ast.stmt):
                    self.visit_stmts(value, held)
                else:
                    for v in value:
                        if isinstance(v, ast.AST):
                            self.visit_node(v, held)
            elif isinstance(value, ast.AST):
                self.visit_node(value, held)


# ---------------------------------------------------------------------------
# the whole-program model


@dataclass
class OrderEdge:
    src: str
    dst: str
    holder_site: str
    acquire_site: str
    chain: Tuple[str, ...]   # function quals, caller first


class ConcurrencyModel:
    def __init__(self, project: Project):
        self.project = project
        self.cg = get_callgraph(project)
        self.kinds_rel = project.kinds_file
        self.locks: Dict[str, LockInfo] = {}
        self.attr_locks: Dict[str, List[str]] = {}
        self.file_types: Dict[str, Dict[str, str]] = {}
        self.file_time_names: Dict[str, Set[str]] = {}
        self.file_subprocess_names: Dict[str, Set[str]] = {}
        self.file_cond_keys: Dict[str, Set[str]] = {}
        for sf in project.files:
            if sf.tree is None:
                self.file_types[sf.rel] = {}
                self.file_time_names[sf.rel] = set()
                self.file_subprocess_names[sf.rel] = set()
                self.file_cond_keys[sf.rel] = set()
                continue
            for li in discover_locks(sf, self.cg):
                self.locks.setdefault(li.lock_id, li)
            self.file_types[sf.rel] = _typed_keys(sf)
            self.file_time_names[sf.rel] = _module_names(sf, "time")
            self.file_subprocess_names[sf.rel] = _module_names(
                sf, "subprocess")
        for lid, li in self.locks.items():
            if li.scope == "class":
                self.attr_locks.setdefault(li.key, []).append(lid)
        for sf in project.files:
            self.file_cond_keys[sf.rel] = {
                li.key for li in self.locks.values()
                if li.path == sf.rel and li.kind == "Condition"}
        self.facts: Dict[str, _FuncFacts] = {}
        for qual in sorted(self.cg.funcs):
            fi = self.cg.funcs[qual]
            if fi.sf.tree is None:
                continue
            self.facts[qual] = _Scanner(self, fi).run()
        self._propagate()
        self.edges: Dict[Tuple[str, str], OrderEdge] = {}
        self._build_edges()
        self.cycles: List[List[str]] = _find_cycles(
            {e for e in self.edges})

    # ------------------------------------------------------ propagation
    def _propagate(self) -> None:
        """acq_closure[f]: lock -> (via callee | None, site); similarly
        block_closure[f]: desc -> (via, line). Fixpoint over call edges
        (thread edges excluded — held sets do not cross threads)."""
        callees: Dict[str, List[str]] = {}
        callers: Dict[str, Set[str]] = {}
        for qual in self.facts:
            outs: List[str] = []
            for site in self.cg.calls.get(qual, []):
                if site.kind != "call":
                    continue
                for t in site.targets:
                    if t in self.facts and t != qual:
                        outs.append(t)
                        callers.setdefault(t, set()).add(qual)
            callees[qual] = sorted(set(outs))
        self.acq_closure: Dict[str, Dict[str, Tuple[Optional[str], str]]]\
            = {q: {lk: (None, site)
                   for lk, site in f.direct_acq.items()}
               for q, f in self.facts.items()}
        self.block_closure: Dict[str, Dict[str,
                                           Tuple[Optional[str], int]]] = \
            {q: {d: (None, ln) for d, ln in f.direct_block.items()}
             for q, f in self.facts.items()}
        work = sorted(self.facts)
        pending = set(work)
        while work:
            q = work.pop()
            pending.discard(q)
            grew = False
            acq = self.acq_closure[q]
            blk = self.block_closure[q]
            for t in callees.get(q, []):
                for lk, (_via, site) in self.acq_closure[t].items():
                    if lk not in acq:
                        acq[lk] = (t, site)
                        grew = True
                for d, (_via, ln) in self.block_closure[t].items():
                    if d not in blk:
                        blk[d] = (t, ln)
                        grew = True
            if grew:
                for c in callers.get(q, ()):
                    if c not in pending:
                        pending.add(c)
                        work.append(c)

    def chain_for(self, start: str, lock: str) -> Tuple[str, ...]:
        """Witness call chain from ``start`` to the function that
        directly acquires ``lock``."""
        chain = [start]
        seen = {start}
        cur = start
        while True:
            via, _site = self.acq_closure[cur].get(lock, (None, ""))
            if via is None or via in seen:
                return tuple(chain)
            chain.append(via)
            seen.add(via)
            cur = via

    def block_chain_for(self, start: str, desc: str) -> Tuple[str, ...]:
        chain = [start]
        seen = {start}
        cur = start
        while True:
            via, _ln = self.block_closure[cur].get(desc, (None, 0))
            if via is None or via in seen:
                return tuple(chain)
            chain.append(via)
            seen.add(via)
            cur = via

    # ------------------------------------------------------------ edges
    def _build_edges(self) -> None:
        for qual in sorted(self.facts):
            f = self.facts[qual]
            for src, dst, hsite, asite in f.direct_edges:
                self.edges.setdefault(
                    (src, dst),
                    OrderEdge(src, dst, hsite, asite, (qual,)))
            for held, call in f.call_events:
                site = self.cg.by_node[id(call)]
                for t in sorted(site.targets):
                    closure = self.acq_closure.get(t)
                    if not closure:
                        continue
                    for lk in sorted(closure):
                        asite = closure[lk][1]
                        chain = (qual,) + self.chain_for(t, lk)
                        for h, hsite in held:
                            if h == lk:
                                continue
                            self.edges.setdefault(
                                (h, lk),
                                OrderEdge(h, lk, hsite, asite, chain))

    # --------------------------------------------------------- findings
    def _mk_finding(self, rule_id: str, path: str, line: int,
                    msg: str) -> Finding:
        sf = next((s for s in self.project.files if s.rel == path), None)
        snippet = sf.line_text(line) if sf is not None else ""
        return Finding(rule_id, path, line, msg, snippet=snippet)

    def order_findings(self) -> List[Finding]:
        out = []
        for cycle in self.cycles:
            # rotate deterministically to the smallest lock id
            k = cycle.index(min(cycle))
            cyc = cycle[k:] + cycle[:k]
            legs = []
            for i, src in enumerate(cyc):
                dst = cyc[(i + 1) % len(cyc)]
                e = self.edges[(src, dst)]
                legs.append(
                    f"[{_short(src)} then {_short(dst)}] via "
                    f"{_fmt_chain(e.chain)}: acquires {_short(dst)} at "
                    f"{e.acquire_site} while holding {_short(src)} "
                    f"(from {e.holder_site})")
            first = self.edges[(cyc[0], cyc[1 % len(cyc)])]
            path, _, line = first.holder_site.rpartition(":")
            msg = (f"potential deadlock: lock-order cycle "
                   f"{' -> '.join(_short(c) for c in cyc)} -> "
                   f"{_short(cyc[0])}; " + "; ".join(legs)
                   + " — pick one global acquisition order and make "
                     "every path follow it")
            out.append(self._mk_finding("lock-order", path, int(line),
                                        msg))
        return out

    def blocking_findings(self) -> List[Finding]:
        out = []
        seen: Set[Tuple[str, int, str]] = set()
        for qual in sorted(self.facts):
            f = self.facts[qual]
            for held, desc, node in f.block_events:
                key = (f.sf.rel, node.lineno, desc)
                if key in seen:
                    continue
                seen.add(key)
                locks = ", ".join(_short(h) for h, _ in held)
                out.append(self._mk_finding(
                    "lock-blocking", f.sf.rel, node.lineno,
                    f"blocking {desc} while holding {locks} — every "
                    f"other thread waiting on the lock stalls behind "
                    f"this call; move it outside the critical section "
                    f"or bound it with a timeout"))
            for held, call in f.call_events:
                site = self.cg.by_node[id(call)]
                for t in sorted(site.targets):
                    blk = self.block_closure.get(t)
                    if not blk:
                        continue
                    for desc in sorted(blk):
                        # only flag ops the callee itself introduces —
                        # direct ops at this site were reported above
                        key = (f.sf.rel, call.lineno, desc)
                        if key in seen:
                            continue
                        seen.add(key)
                        locks = ", ".join(_short(h) for h, _ in held)
                        chain = (qual,) + self.block_chain_for(t, desc)
                        out.append(self._mk_finding(
                            "lock-blocking", f.sf.rel, call.lineno,
                            f"call can block ({desc} reachable via "
                            f"{_fmt_chain(chain)}) while holding "
                            f"{locks} — a stall there wedges every "
                            f"waiter on the lock; restructure or bound "
                            f"the wait"))
        return out

    # ------------------------------------------------------------- json
    def to_json(self) -> dict:
        return {
            "locks": {lid: {"kind": li.kind, "path": li.path,
                            "line": li.line, "scope": li.scope}
                      for lid, li in sorted(self.locks.items())},
            "edges": [{"src": e.src, "dst": e.dst,
                       "holder_site": e.holder_site,
                       "acquire_site": e.acquire_site,
                       "chain": list(e.chain)}
                      for (_s, _d), e in sorted(self.edges.items())],
            "cycles": [list(c) for c in self.cycles],
            "ambiguous_methods": dict(sorted(
                self.cg.ambiguous.items())),
        }


def _module_names(sf: SourceFile, module: str) -> Set[str]:
    out = set()
    for node in sf.walk():
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == module:
                    out.add(a.asname or a.name)
    return out


def _short(lock_id: str) -> str:
    path, _, name = lock_id.partition("::")
    return f"{path.rsplit('/', 1)[-1]}::{name}"


def _fmt_chain(chain: Tuple[str, ...], limit: int = 6) -> str:
    names = [q.split("::", 1)[1] for q in chain[:limit]]
    if len(chain) > limit:
        names.append("...")
    return " -> ".join(names)


def _find_cycles(edges: Set[Tuple[str, str]]) -> List[List[str]]:
    """Cycles in the lock-order graph: one representative cycle per
    strongly connected component with >= 2 nodes (self edges are
    excluded upstream). Deterministic."""
    adj: Dict[str, List[str]] = {}
    nodes: Set[str] = set()
    for a, b in edges:
        adj.setdefault(a, []).append(b)
        nodes.add(a)
        nodes.add(b)
    for k in adj:
        adj[k].sort()
    # Tarjan SCC, iterative
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    counter = [0]
    sccs: List[List[str]] = []

    for root in sorted(nodes):
        if root in index:
            continue
        work = [(root, iter(adj.get(root, [])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(adj.get(w, []))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))
    # extract one concrete cycle per SCC: BFS from the smallest node
    # back to itself inside the component (an SCC guarantees the path)
    cycles = []
    for comp in sccs:
        comp_set = set(comp)
        start = comp[0]
        parent: Dict[str, str] = {}
        seen = {start}
        frontier = [start]
        closer: Optional[str] = None
        while frontier and closer is None:
            nxt_frontier = []
            for v in frontier:
                for w in adj.get(v, []):
                    if w not in comp_set:
                        continue
                    if w == start:
                        closer = v
                        break
                    if w not in seen:
                        seen.add(w)
                        parent[w] = v
                        nxt_frontier.append(w)
                if closer is not None:
                    break
            frontier = nxt_frontier
        path = []
        cur = closer if closer is not None else start
        while cur != start:
            path.append(cur)
            cur = parent[cur]
        path.append(start)
        path.reverse()
        cycles.append(path)
    return cycles


def get_model(project: Project) -> ConcurrencyModel:
    m = getattr(project, "_concurrency_model", None)
    if m is None or m.project is not project:
        m = ConcurrencyModel(project)
        project._concurrency_model = m  # type: ignore[attr-defined]
    return m


# ---------------------------------------------------------------------------
# rules


@rule("lock-order",
      "the global lock-acquisition order must be acyclic (deadlock)",
      cross=True)
def check_lock_order(project: Project) -> List[Finding]:
    return get_model(project).order_findings()


@rule("lock-blocking",
      "no blocking calls (socket/queue/join/sleep/subprocess/shm) "
      "while holding a lock", cross=True)
def check_lock_blocking(project: Project) -> List[Finding]:
    return get_model(project).blocking_findings()


@rule("cond-wait-while",
      "Condition.wait() must sit inside a while-predicate loop")
def check_cond_wait(sf: SourceFile) -> List[Finding]:
    bare = _bare_lock_names(sf)
    cond_keys = set()
    for node in sf.walk():
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            value = node.value
            if isinstance(value, ast.Call) \
                    and _lock_ctor_kind(sf, value, bare) == "Condition":
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                if len(targets) == 1:
                    key = node_key(targets[0])
                    if key:
                        cond_keys.add(key)
    out = []
    for node in sf.walk():
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "wait"
                and node_key(node.func.value) in cond_keys):
            continue
        cur = getattr(node, "parent", None)
        in_while = False
        while cur is not None and not isinstance(cur, _FUNC_DEFS):
            if isinstance(cur, ast.While):
                in_while = True
                break
            cur = getattr(cur, "parent", None)
        if not in_while:
            out.append(sf.finding(
                "cond-wait-while", node,
                "Condition.wait() outside a while-predicate loop — "
                "spurious wakeups and missed notifies are part of the "
                "contract; re-check the predicate: `while not pred: "
                "cond.wait()` (or use wait_for)"))
    return out
