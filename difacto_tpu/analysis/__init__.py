"""difacto-lint: an AST-based project analyzer (docs/static_analysis.md).

The tree is ~16k lines of multiprocess/multithreaded Python whose
correctness rests on conventions no generic tool checks: fault-point and
metric names are free strings that must stay in sync with the chaos
suite and the docs catalogs, ``#control`` lines must match on both ends
of the wire, shm-ring leases and sockets must be released on every path,
and the JAX hot loop silently miscompiles if a donated buffer is reused
or a jitted closure captures mutable state. This package encodes those
conventions as checkable rules — stdlib ``ast`` only, no new deps.

Layout:

- :mod:`core`        — rule framework: findings, ``# lint: ok(rule-id)``
  inline suppressions, the checked-in baseline, output formats, exit
  codes, the project index cross-file rules read.
- :mod:`localrules`  — single-file rules (thread lifecycle, lock
  release, resource close, the monotonic-clock contract, broad
  excepts, the three JAX tracing rules).
- :mod:`crossrules`  — project-wide registry-drift rules (fault points,
  metric names, ``#control`` lines, config knobs).
- :mod:`callgraph`   — the project-wide call graph (imports, methods,
  thread hand-off edges) the interprocedural layer is built on.
- :mod:`concurrency` — held-lock-set propagation over the call graph:
  lock-order cycle detection (``lock-order``), blocking-calls-under-
  lock (``lock-blocking``), Condition-wait discipline
  (``cond-wait-while``); the static half of the lock sentinel
  (utils/locktrace.py is the runtime half, tools/lockmap.py the
  merged view). Its one walk per function also records the shared-
  state accesses the race pass reads.
- :mod:`races`       — Eraser-style data-race detection (``data-race``):
  thread-root discovery, the shared-state index, per-field lockset
  intersection and GuardedBy inference; the static half of the
  shared-state sentinel (utils/shared.py is the runtime half).
- :mod:`jaxflow`     — JAX compile/transfer flow analysis
  (``jax-recompile`` compile-key boundedness, ``jax-host-sync``
  implicit device->host coercions on the hot path,
  ``jax-donate-flow`` cross-edge donation safety, ``jax-dtype64``
  fp32-pipeline drift); the static half of the jit/transfer sentinel
  (utils/jaxtrace.py is the runtime half, tools/jitmap.py the merged
  view).
- :mod:`shardflow`   — sharding-flow analysis (``jax-shard-break``
  fs-scoped programs must pin their output layout / no capacity-axis
  breakers, ``jax-shard-replicate`` no table-sized replication,
  ``jax-shard-pallas`` pallas kernels only behind the resolve_backend
  typed guard); the static half of the sharding sentinel
  (utils/hloscan.py — the compiled-HLO collective/memory scan — is
  the runtime half, tools/hlomap.py the merged view).
- :mod:`cli`         — ``python -m difacto_tpu.analysis`` /
  ``tools/lint.py`` / ``make lint`` (``--changed-only`` for the
  incremental loop; ``--format=sarif`` for code scanning).
"""

from .core import Finding, Project, all_rules, run_project  # noqa: F401
