"""Project-wide call graph for the interprocedural concurrency pass.

Built once per :class:`core.Project` from the same parsed ASTs the local
rules use. Nodes are functions (module functions, methods, nested
functions, plus one ``<module>`` pseudo-function per file for top-level
code); edges are call sites with a best-effort resolution to their
possible targets:

- **plain names** resolve through lexical scope: nested functions of the
  enclosing function, module-level functions of the same file, then
  ``from m import f`` object imports (including classes, which resolve
  to their ``__init__``);
- **module attributes** (``alias.f(...)``) resolve through the file's
  import table, including relative imports (``from ..utils import
  faultinject`` -> ``faultinject.fire`` lands in utils/faultinject.py);
- **``self.m(...)``** resolves in the enclosing class, then its bases
  (by name, within the project), then falls back to the attribute
  heuristic;
- **attribute calls** (``obj.m(...)``) use the attribute heuristic:
  every project method named ``m`` is a candidate, capped at
  :data:`MAX_METHOD_FANOUT` definitions — past the cap the call is left
  unresolved (recorded in ``ambiguous``) rather than fanning out to
  half the tree. This is deliberate over-approximation: for lock-order
  analysis a superset of real targets is safe, an unbounded superset is
  noise.
- **thread hand-offs** — ``Thread(target=f)`` / ``Process(target=f)``
  constructors, ``pool.submit(f, ...)`` and ``pool.map(f, it)`` — are
  separate ``thread``-kind edges: the target runs on another thread, so
  callers must NOT propagate held locks across them (the concurrency
  pass treats them as reachability-only). The target REFERENCE resolves
  through every form the tree actually uses: a plain name, ``self.m`` /
  ``mod.f`` dotted refs, ``functools.partial(f, ...)`` (the first
  positional is the callee), ``lambda: f(...)`` (every call inside the
  lambda body is a target), and a local alias (``run = self._loop;
  Thread(target=run)`` — single-assignment locals are chased one level
  at a time up to a small depth cap).

Known blind spots (documented in docs/static_analysis.md): calls through
variables holding functions (other than the single-assignment thread-
target aliases above), ``super()`` chains, ``getattr`` dispatch, and
decorator indirection all resolve to nothing.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .core import Project, SourceFile, dotted

# attribute-call resolution gives up past this many same-named methods
MAX_METHOD_FANOUT = 8

# method names shadowed by builtin collection/str methods: an unqualified
# `obj.get(...)` is a dict read a thousand times for every WorkloadPool
# dispatch, so the attribute heuristic skips them (self.m / Class.m and
# module-qualified calls still resolve precisely)
BUILTIN_SHADOWED = frozenset({
    "get", "add", "clear", "pop", "popleft", "update", "keys", "values",
    "items", "append", "appendleft", "extend", "remove", "discard",
    "copy", "sort", "insert", "index", "count", "setdefault",
    "split", "rsplit", "strip", "lstrip", "rstrip", "partition",
    "startswith", "endswith", "encode", "decode", "format", "lower",
    "upper", "replace", "find", "rfind", "search", "match", "group",
    "resolve",  # pathlib.Path.resolve on every checkpoint/config path
})

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


@dataclass
class ClassInfo:
    qual: str                       # "rel.py::Class"
    name: str
    sf: SourceFile
    node: ast.ClassDef
    bases: List[str] = field(default_factory=list)   # simple base names
    methods: Dict[str, str] = field(default_factory=dict)  # name -> qual


@dataclass
class FuncInfo:
    qual: str                       # "rel.py::Class.method" / "rel.py::f"
    name: str
    sf: SourceFile
    node: Optional[ast.AST]         # None for the <module> pseudo-func
    cls: Optional[ClassInfo] = None


@dataclass
class CallSite:
    node: ast.Call
    kind: str                       # "call" | "thread"
    targets: Tuple[str, ...]        # resolved FuncInfo quals
    # True when the targets came from the MULTI-candidate attribute
    # heuristic: a safe over-approximation for lock-order analysis, but
    # the race pass must not smear thread-root reachability through it
    fuzzy: bool = False


def _module_name(rel: str) -> str:
    parts = rel[:-3].split("/") if rel.endswith(".py") else rel.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class CallGraph:
    """Index + resolver. ``calls[qual]`` lists every call site inside a
    function in source order; ``by_node[id(call)]`` finds the same
    record from an AST node (the concurrency walker's entry)."""

    def __init__(self, project: Project):
        self.project = project
        self.funcs: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, List[ClassInfo]] = {}       # by name
        self.module_funcs: Dict[str, Dict[str, str]] = {}   # rel -> name->qual
        self.module_classes: Dict[str, Dict[str, ClassInfo]] = {}
        self.methods_by_name: Dict[str, List[str]] = {}
        self.modname_to_rel: Dict[str, str] = {}
        self.calls: Dict[str, List[CallSite]] = {}
        self.by_node: Dict[int, CallSite] = {}
        self.owner_of: Dict[int, str] = {}   # id(ast node) -> func qual
        self.ambiguous: Dict[str, int] = {}  # method name -> defs (over cap)
        self._def_qual: Dict[int, str] = {}  # id(def node) -> qual
        self._fuzzy = False   # sticky per-call-site heuristic marker
        self._imports: Dict[str, Tuple[Dict[str, str],
                                       Dict[str, Tuple[str, str]]]] = {}
        for sf in project.files:
            if sf.tree is not None:
                self.modname_to_rel[_module_name(sf.rel)] = sf.rel
        for sf in project.files:
            if sf.tree is not None:
                self._collect_defs(sf)
        for sf in project.files:
            if sf.tree is not None:
                self._imports[sf.rel] = self._collect_imports(sf)
        for sf in project.files:
            if sf.tree is not None:
                self._collect_calls(sf)

    # ------------------------------------------------------- definitions
    def _collect_defs(self, sf: SourceFile) -> None:
        mod_q = sf.rel + "::<module>"
        self.funcs[mod_q] = FuncInfo(mod_q, "<module>", sf, None)
        self.module_funcs.setdefault(sf.rel, {})
        self.module_classes.setdefault(sf.rel, {})

        def walk(body, prefix: str, cls: Optional[ClassInfo],
                 top: bool) -> None:
            for stmt in body:
                if isinstance(stmt, _FUNC_DEFS):
                    qual = f"{sf.rel}::{prefix}{stmt.name}"
                    self.funcs[qual] = FuncInfo(qual, stmt.name, sf,
                                                stmt, cls)
                    self._def_qual[id(stmt)] = qual
                    if cls is not None and prefix == cls.qual.split(
                            "::", 1)[1] + ".":
                        cls.methods[stmt.name] = qual
                        self.methods_by_name.setdefault(
                            stmt.name, []).append(qual)
                    elif top:
                        self.module_funcs[sf.rel][stmt.name] = qual
                    # nested defs keep the class context for `self`
                    walk(stmt.body, prefix + stmt.name + ".", cls, False)
                elif isinstance(stmt, ast.ClassDef):
                    ci = ClassInfo(f"{sf.rel}::{prefix}{stmt.name}",
                                   stmt.name, sf, stmt,
                                   bases=[dotted(b).split(".")[-1]
                                          for b in stmt.bases if dotted(b)])
                    self.classes.setdefault(stmt.name, []).append(ci)
                    if top:
                        self.module_classes[sf.rel][stmt.name] = ci
                    walk(stmt.body, prefix + stmt.name + ".", ci, False)
                else:
                    # defs inside if/try blocks at the same level
                    for sub in ast.iter_child_nodes(stmt):
                        if isinstance(sub, (ast.ClassDef, *_FUNC_DEFS)):
                            walk([sub], prefix, cls, top)

        walk(sf.tree.body, "", None, True)

    # ----------------------------------------------------------- imports
    def _collect_imports(self, sf: SourceFile):
        """(module aliases: name -> dotted module,
        object imports: name -> (module dotted, member))."""
        aliases: Dict[str, str] = {}
        objs: Dict[str, Tuple[str, str]] = {}
        my_mod = _module_name(sf.rel)
        my_pkg_parts = my_mod.split(".")
        if not sf.rel.endswith("__init__.py"):
            my_pkg_parts = my_pkg_parts[:-1]
        for node in sf.walk():
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        aliases[a.asname] = a.name
                    else:
                        aliases[a.name.split(".")[0]] = a.name.split(".")[0]
                        aliases.setdefault(a.name, a.name)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base_parts = my_pkg_parts[:len(my_pkg_parts)
                                              - (node.level - 1)]
                    base = ".".join(base_parts)
                    if node.module:
                        base = f"{base}.{node.module}" if base \
                            else node.module
                else:
                    base = node.module or ""
                for a in node.names:
                    name = a.asname or a.name
                    if f"{base}.{a.name}" in self.modname_to_rel:
                        aliases[name] = f"{base}.{a.name}"
                    elif base in self.modname_to_rel:
                        objs[name] = (base, a.name)
        return aliases, objs

    # --------------------------------------------------------- resolvers
    def _class_init(self, ci: ClassInfo) -> Tuple[str, ...]:
        q = ci.methods.get("__init__")
        return (q,) if q else ()

    def _resolve_in_class(self, ci: Optional[ClassInfo], method: str,
                          depth: int = 0) -> Tuple[str, ...]:
        if ci is None or depth > 4:
            return ()
        q = ci.methods.get(method)
        if q:
            return (q,)
        for base in ci.bases:
            for bi in self.classes.get(base, []):
                got = self._resolve_in_class(bi, method, depth + 1)
                if got:
                    return got
        return ()

    def _method_heuristic(self, method: str) -> Tuple[str, ...]:
        if method.startswith("__") and method.endswith("__"):
            return ()
        if method in BUILTIN_SHADOWED:
            return ()
        quals = self.methods_by_name.get(method, [])
        if not quals:
            return ()
        if len(quals) > MAX_METHOD_FANOUT:
            self.ambiguous[method] = len(quals)
            return ()
        out = tuple(sorted(set(quals)))
        if len(out) > 1:
            # several same-named candidates: over-approximation, marked
            # so CallSite.fuzzy reaches the race pass
            self._fuzzy = True
        return out

    def _resolve_name(self, sf: SourceFile, owner_qual: str,
                      name: str) -> Tuple[str, ...]:
        # nested functions of the lexically enclosing chain
        prefix = owner_qual.split("::", 1)[1] if "::" in owner_qual else ""
        parts = prefix.split(".") if prefix and prefix != "<module>" else []
        while True:
            cand = f"{sf.rel}::{'.'.join(parts + [name])}" if parts \
                else f"{sf.rel}::{name}"
            if cand in self.funcs and cand != owner_qual:
                return (cand,)
            if not parts:
                break
            parts.pop()
        q = self.module_funcs.get(sf.rel, {}).get(name)
        if q:
            return (q,)
        ci = self.module_classes.get(sf.rel, {}).get(name)
        if ci is not None:
            return self._class_init(ci)
        aliases, objs = self._imports.get(sf.rel, ({}, {}))
        if name in objs:
            mod, member = objs[name]
            rel = self.modname_to_rel.get(mod)
            if rel:
                q = self.module_funcs.get(rel, {}).get(member)
                if q:
                    return (q,)
                ci = self.module_classes.get(rel, {}).get(member)
                if ci is not None:
                    return self._class_init(ci)
        return ()

    def _resolve_dotted(self, sf: SourceFile, owner: FuncInfo,
                        cn: str) -> Tuple[str, ...]:
        parts = cn.split(".")
        if parts[0] in ("self", "cls") and len(parts) == 2:
            got = self._resolve_in_class(owner.cls, parts[1])
            return got or self._method_heuristic(parts[1])
        aliases, _objs = self._imports.get(sf.rel, ({}, {}))
        if parts[0] in aliases:
            # longest module prefix match: `import a.b` binds `a`, and
            # `a.b.f` must land in module a.b
            mod = aliases[parts[0]]
            rest = parts[1:]
            while rest and f"{mod}.{rest[0]}" in self.modname_to_rel:
                mod = f"{mod}.{rest[0]}"
                rest = rest[1:]
            rel = self.modname_to_rel.get(mod)
            if rel and len(rest) == 1:
                q = self.module_funcs.get(rel, {}).get(rest[0])
                if q:
                    return (q,)
                ci = self.module_classes.get(rel, {}).get(rest[0])
                if ci is not None:
                    return self._class_init(ci)
            if rel and len(rest) == 2:
                ci = self.module_classes.get(rel, {}).get(rest[0])
                if ci is not None:
                    got = self._resolve_in_class(ci, rest[1])
                    if got:
                        return got
            # the head names a MODULE (project or stdlib): whatever the
            # attribute is, it is not some project class's method — do
            # not fall through to the attribute heuristic (that is how
            # `subprocess.run` would smear into SGDLearner.run)
            return ()
        return self._method_heuristic(parts[-1])

    def resolve(self, sf: SourceFile, owner: FuncInfo,
                call: ast.Call) -> Tuple[str, Tuple[str, ...]]:
        """(kind, target quals) for one call node."""
        fn = call.func
        cn = dotted(fn)
        # thread hand-offs first: the target runs on another thread
        if cn and (cn == "Thread" or cn.endswith(".Thread")
                   or cn == "Process" or cn.endswith(".Process")):
            for kw in call.keywords:
                if kw.arg == "target":
                    return ("thread", self._resolve_ref(sf, owner,
                                                        kw.value))
            return ("call", ())
        if isinstance(fn, ast.Attribute) and fn.attr == "submit" \
                and call.args:
            tgt = self._resolve_ref(sf, owner, call.args[0])
            if tgt:
                return ("thread", tgt)
        if isinstance(fn, ast.Attribute) and fn.attr == "map" \
                and len(call.args) >= 2:
            tgt = self._resolve_ref(sf, owner, call.args[0])
            if tgt:
                return ("thread", tgt)
        if isinstance(fn, ast.Attribute) and "." not in cn:
            # receiver is not a name chain (a call result, subscript,
            # ...): `x().m()` still dispatches on a project method named
            # m — use the attribute heuristic directly
            return ("call", self._method_heuristic(fn.attr))
        if not cn:
            return ("call", ())
        if "." not in cn:
            return ("call", self._resolve_name(sf, owner.qual, cn))
        return ("call", self._resolve_dotted(sf, owner, cn))

    def _resolve_ref(self, sf: SourceFile, owner: FuncInfo,
                     expr, depth: int = 0) -> Tuple[str, ...]:
        """Resolve a function REFERENCE (thread target, submit arg):
        names, ``self.m``/``mod.f`` attributes, ``functools.partial``
        wrappers, lambdas, and single-assignment local aliases."""
        if depth > 3:
            return ()
        if isinstance(expr, ast.Name):
            got = self._resolve_name(sf, owner.qual, expr.id)
            if got:
                return got
            alias = self._local_alias(owner, expr.id)
            if alias is not None:
                return self._resolve_ref(sf, owner, alias, depth + 1)
            return ()
        if isinstance(expr, ast.Attribute):
            dn = dotted(expr)
            if dn:
                return self._resolve_dotted(sf, owner, dn)
            return ()
        if isinstance(expr, ast.Lambda):
            # `target=lambda: f(x)` — the lambda runs on the new thread,
            # so every call inside its body is a thread target (the
            # lambda shares the owner's lexical scope for resolution)
            out: List[str] = []
            for node in ast.walk(expr.body):
                if isinstance(node, ast.Call):
                    _kind, tgts = self.resolve(sf, owner, node)
                    out.extend(tgts)
            return tuple(sorted(set(out)))
        if isinstance(expr, ast.Call):
            cn = dotted(expr.func)
            if cn and (cn == "partial" or cn.endswith(".partial")) \
                    and expr.args:
                return self._resolve_ref(sf, owner, expr.args[0],
                                         depth + 1)
        return ()

    def _local_alias(self, owner: FuncInfo, name: str):
        """The value of the LAST single-target ``name = <expr>`` in the
        owner function, when the value is a plausible callable reference
        (name/attribute/partial/lambda). Conservative: only one binding
        shape is chased; anything fancier stays unresolved."""
        node = owner.node
        if node is None:
            return None
        found = None
        for stmt in ast.walk(node):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and stmt.targets[0].id == name \
                    and isinstance(stmt.value, (ast.Name, ast.Attribute,
                                                ast.Lambda, ast.Call)):
                found = stmt.value
        if isinstance(found, ast.Name) and found.id == name:
            return None
        return found

    # -------------------------------------------------------- call sites
    def _collect_calls(self, sf: SourceFile) -> None:
        # map every node to its owning function (innermost def)
        def tag(node, qual: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _FUNC_DEFS):
                    inner = self._qual_of_def(sf, child, qual)
                    self.owner_of[id(child)] = qual
                    tag(child, inner)
                elif isinstance(child, ast.ClassDef):
                    self.owner_of[id(child)] = qual
                    tag(child, qual)  # class body stmts run at def time
                else:
                    self.owner_of[id(child)] = qual
                    tag(child, qual)

        mod_q = sf.rel + "::<module>"
        self.owner_of[id(sf.tree)] = mod_q
        tag(sf.tree, mod_q)
        # class bodies re-tag: methods' quals were computed in
        # _collect_defs; _qual_of_def reuses them via a reverse lookup
        for node in sf.walk():
            if not isinstance(node, ast.Call):
                continue
            owner_qual = self.owner_of.get(id(node), mod_q)
            owner = self.funcs.get(owner_qual) \
                or self.funcs[mod_q]
            self._fuzzy = False
            kind, targets = self.resolve(sf, owner, node)
            site = CallSite(node, kind, targets, self._fuzzy)
            self.calls.setdefault(owner.qual, []).append(site)
            self.by_node[id(node)] = site
        for sites in self.calls.values():
            sites.sort(key=lambda s: (s.node.lineno, s.node.col_offset))

    def _qual_of_def(self, sf: SourceFile, node, outer_qual: str) -> str:
        """Qual of a def encountered while tagging: the record
        _collect_defs made, or the owner when the def went unrecorded
        (e.g. a def synthesized inside an exotic construct)."""
        return self._def_qual.get(id(node), outer_qual)


def get_callgraph(project: Project) -> CallGraph:
    """One CallGraph per Project instance (the concurrency rules share
    it; building twice would double the whole-tree pass)."""
    cg = getattr(project, "_callgraph_cache", None)
    if cg is None or cg.project is not project:
        cg = CallGraph(project)
        project._callgraph_cache = cg  # type: ignore[attr-defined]
    return cg
