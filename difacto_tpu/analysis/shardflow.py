"""Sharding-flow analysis (difacto-lint v5): mesh/PartitionSpec
provenance through the state-carrying programs.

PRs 12-13 made the slot table mesh-sharded — contiguous fs key ranges
pinned inside every state-returning program via ``step.state_constrainer``
(``jax.lax.with_sharding_constraint``) — and made the table kernels
explicit (``ops/fused.py`` pallas DMA backends behind the
``resolve_backend`` typed guard). Nothing checked those invariants: one
jit program that returns state WITHOUT the pin, one op that reorders or
re-materializes the sharded capacity axis, or one ``pallas_call`` reached
with a sharded operand silently reintroduces the single-device memory
wall the key-range sharding exists to avoid (PAPER.md §2). This pass is
the static half of that guarantee; ``utils/hloscan.py`` (the compiled-HLO
collective/memory scan) is the runtime half and ``tools/hlomap.py`` the
merged view — the same static model + runtime tracer + tier-1
dynamic⊆static pattern as locks (v2), races (v3) and compile/transfer
flow (v4).

Three rules, all cross-file (they read the call graph + jaxflow model):

- ``jax-shard-break`` — (a) every fs-scoped jit/pjit program that
  donates state must PIN its output layout: ``out_shardings=`` on the
  jit call, a ``state_constrainer``/``with_sharding_constraint`` in the
  returned expression, or a target threaded from a pinning builder
  (``make_step_fns(..., state_shardings=...)``); (b) ops that break the
  sharded capacity axis of a table-provenance array —
  reshape/concatenate/stack/sort/boolean-mask over the table or a
  ``state.<field>`` leaf inside a state program.
- ``jax-shard-replicate`` — table-sized replication: ``device_put`` /
  ``np.asarray`` / ``jnp.asarray`` of a table-provenance array without a
  (non-replicated) sharding in fs-aware code, and donated arguments fed
  from a replicating coercion at an exact call edge (donating a fresh
  replicated copy silently forfeits the sharded in-place update).
- ``jax-shard-pallas`` — ``pallas_call`` targets reachable outside the
  typed-error guard: an unguarded exact call edge into a kernel
  function, or a backend-dispatch argument that did not come from
  ``ops.fused.resolve_backend`` (the one place that fails typed on
  ``pallas`` + mesh) or a non-``"pallas"`` literal.

Honest blind spots (docs/static_analysis.md v5 catalog): provenance is
lexical (scope-chain bindings, one assignment hop) — values laundered
through containers or object attributes are invisible; fs-scoping keys
on the fs-table API surface (``state_sharding`` / ``sharding_tree`` /
``state_constrainer`` / ``fs_shard_bounds`` / ``FS_AXIS``), so a mesh
program built entirely from raw ``NamedSharding`` literals is out of
scope; table provenance is name-based (``table`` / ``state.<field>``
chains). The hloscan gate exists precisely because of these holes: the
compiled HLO cannot lie about an all-gather.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import CallGraph, get_callgraph
from .core import (Finding, Project, SourceFile, call_name, dotted,
                   enclosing_function, rule)
from .jaxflow import JitSite, _is_pallas_name, get_jax_model

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)

# the fs-table sharding API (parallel/mesh.py + step.py): a function
# whose scope touches one of these is building or placing the fs-sharded
# table, so its jit programs are in scope for the pin check
_FS_API = {"state_sharding", "sharding_tree", "state_constrainer",
           "fs_shard_bounds", "validate_fs_capacity", "FS_AXIS"}

# the pin primitives: a returned expression passing through one of these
# carries the fs layout out of the program
_PIN_CALLS = {"state_constrainer", "with_sharding_constraint"}

# layout-threading kwargs a pinning builder accepts/forwards
_PIN_KWARGS = {"state_shardings", "mesh"}

# np/jnp calls that reorder or re-materialize the capacity axis
_AXIS_BREAKERS = {"concatenate", "stack", "append", "sort", "argsort",
                  "compress"}
_ARRAY_MODULES = {"jnp", "np", "numpy", "jax"}

# coercions that materialize their argument on one device / the host
_REPLICATORS = {"device_put", "asarray", "array"}


def _last(cn: str) -> str:
    return cn.rsplit(".", 1)[-1]


def _own_body(func) -> List[ast.AST]:
    """Nodes of ``func``'s own body, nested function/lambda bodies
    excluded — a ``return`` inside a nested def is not ``func``'s."""
    out: List[ast.AST] = []
    stack: List[ast.AST] = list(func.body)
    while stack:
        n = stack.pop()
        out.append(n)
        for c in ast.iter_child_nodes(n):
            if isinstance(c, _FUNC_DEFS + (ast.Lambda,)):
                continue
            stack.append(c)
    return out


def _scope_chain(node) -> List[ast.AST]:
    """Enclosing function defs from innermost outward (lexical scopes a
    closure or nested builder reads its bindings from)."""
    chain = []
    cur = enclosing_function(node)
    while cur is not None:
        chain.append(cur)
        cur = enclosing_function(cur)
    return chain


def _params_of(func) -> List[str]:
    a = func.args
    return [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]


def _is_table_name(name: str) -> bool:
    return name == "table"


def _table_prov(expr, local_prov: Set[str]) -> bool:
    """Name-based table provenance: the ``table`` convention
    (ops/fused.py), any ``state.<field>`` / ``store.state.<field>``
    attribute chain, or a local name assigned from one."""
    if isinstance(expr, ast.Name):
        return _is_table_name(expr.id) or expr.id in local_prov
    if isinstance(expr, ast.Attribute):
        segs = dotted(expr).split(".")
        return len(segs) > 1 and "state" in segs[:-1] \
            or _is_table_name(segs[-1])
    return False


class ShardModel:
    """The whole-program sharding-flow model. Built once per Project
    (cached — the three rules, hlomap, and the tier-1 gate share it)."""

    def __init__(self, project: Project):
        self.project = project
        self.cg: CallGraph = get_callgraph(project)
        self.jax = get_jax_model(project)
        self._findings: Dict[str, List[Finding]] = {
            "jax-shard-break": [], "jax-shard-replicate": [],
            "jax-shard-pallas": []}
        self._fn_pins_memo: Dict[int, bool] = {}
        self.pinning_builders: Set[str] = set()       # bare def names
        self.state_programs: Dict[str, dict] = {}     # site_id -> verdict
        self.kernel_funcs: Set[str] = set()           # quals
        self.guarded_dispatchers: Dict[str, int] = {} # qual -> param idx
        self._find_pinning_builders()
        self._check_state_programs()
        self._check_axis_breaks()
        self._check_replication()
        self._check_pallas_reach()

    # ------------------------------------------------- pinning builders
    def _find_pinning_builders(self) -> None:
        """Fixpoint over bare def names: a builder pins when it accepts
        a layout kwarg (``state_shardings``/``mesh``) and reaches a
        ``state_constrainer``/``with_sharding_constraint`` call, either
        directly or by forwarding the kwarg into another pinning
        builder (``bench.build_step`` -> ``step.make_step_fns``)."""
        defs: Dict[str, List[ast.AST]] = {}
        for sf in self._sources():
            for n in sf.walk():
                if isinstance(n, _FUNC_DEFS):
                    defs.setdefault(n.name, []).append(n)
        self._defs_by_name = defs

        def accepts_layout(func) -> bool:
            return bool(_PIN_KWARGS & set(_params_of(func)))

        names = set()
        for name, nodes in defs.items():
            for func in nodes:
                if not accepts_layout(func):
                    continue
                if any(isinstance(n, ast.Call)
                       and _last(call_name(n)) in _PIN_CALLS
                       for n in ast.walk(func)):
                    names.add(name)
        changed = True
        while changed:
            changed = False
            for name, nodes in defs.items():
                if name in names:
                    continue
                for func in nodes:
                    if not accepts_layout(func):
                        continue
                    for n in ast.walk(func):
                        if isinstance(n, ast.Call) \
                                and _last(call_name(n)) in names \
                                and any(kw.arg in _PIN_KWARGS
                                        for kw in n.keywords):
                            names.add(name)
                            changed = True
                            break
                    if name in names:
                        break
        self.pinning_builders = names

    # ------------------------------------------------ rule 1a: the pin
    def _sources(self):
        for sf in self.project.files:
            if sf.tree is not None \
                    and not sf.rel.endswith("utils/jaxtrace.py"):
                yield sf

    def _fs_aware(self, scope) -> bool:
        for n in ast.walk(scope):
            if isinstance(n, ast.Name) and n.id in _FS_API:
                return True
            if isinstance(n, ast.Attribute) and n.attr in _FS_API:
                return True
        return False

    def _constrain_names(self, node) -> Set[str]:
        """Names bound from ``state_constrainer(...)`` in the lexical
        scope chain of ``node`` (the ``constrain = state_constrainer(
        shardings)`` convention)."""
        out: Set[str] = set()
        for scope in _scope_chain(node):
            for n in _own_body(scope):
                if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                        and isinstance(n.targets[0], ast.Name) \
                        and isinstance(n.value, ast.Call) \
                        and _last(call_name(n.value)) == \
                        "state_constrainer":
                    out.add(n.targets[0].id)
        return out

    def _binding_of(self, node, name: str):
        """(rhs_call, elem_index) when ``name`` is bound — directly or
        by tuple-unpack — from a Call in the lexical scope chain of
        ``node``; (None, None) otherwise."""
        for scope in _scope_chain(node):
            for n in _own_body(scope):
                if not isinstance(n, ast.Assign) \
                        or not isinstance(n.value, ast.Call):
                    continue
                for t in n.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        return n.value, None
                    if isinstance(t, (ast.Tuple, ast.List)):
                        for i, el in enumerate(t.elts):
                            if isinstance(el, ast.Name) and el.id == name:
                                return n.value, i
        return None, None

    def _pinning_call(self, call: ast.Call) -> bool:
        """A call that yields pinned programs: a pinning builder invoked
        WITH the layout kwarg threaded, or a pin primitive itself."""
        cn = _last(call_name(call))
        if cn in _PIN_CALLS:
            return True
        return cn in self.pinning_builders \
            and any(kw.arg in _PIN_KWARGS for kw in call.keywords)

    def _expr_pins(self, expr, anchor, constrain: Set[str]) -> bool:
        """Does ``expr`` (a returned value) pass state through a pin?
        True when it contains a call to a pin primitive, to a
        constrain-bound name, to a pinned local def, or to a name bound
        from a pinning-builder call."""
        for n in ast.walk(expr):
            if not isinstance(n, ast.Call):
                continue
            cn = call_name(n)
            if _last(cn) in _PIN_CALLS:
                return True
            if isinstance(n.func, ast.Name):
                nm = n.func.id
                if nm in constrain:
                    return True
                local = self._local_def(anchor, nm)
                if local is not None and self._fn_pins(local):
                    return True
                bcall, _ = self._binding_of(anchor, nm)
                if bcall is not None and self._pinning_call(bcall):
                    return True
        return False

    def _local_def(self, anchor, name: str):
        for scope in _scope_chain(anchor):
            for n in _own_body(scope):
                if isinstance(n, _FUNC_DEFS) and n.name == name:
                    return n
        return None

    def _fn_pins(self, func) -> bool:
        """A function pins when every path that can return state passes
        it through a pin: some returned expression contains a pinning
        call, or a returned name is bound from one."""
        memo = self._fn_pins_memo
        if id(func) in memo:
            return memo[id(func)]
        memo[id(func)] = False       # cycle guard: assume unpinned
        constrain = self._constrain_names(func) \
            | self._constrain_names_in(func)
        pinned = False
        for n in _own_body(func):
            if not isinstance(n, ast.Return) or n.value is None:
                continue
            if self._expr_pins(n.value, func, constrain):
                pinned = True
                break
            names = []
            if isinstance(n.value, ast.Name):
                names = [n.value.id]
            elif isinstance(n.value, ast.Tuple):
                names = [e.id for e in n.value.elts
                         if isinstance(e, ast.Name)]
            for nm in names:
                bcall = self._body_binding(func, nm)
                if bcall is not None and (
                        self._pinning_call(bcall)
                        or self._call_pins(bcall, func, constrain)):
                    pinned = True
                    break
            if pinned:
                break
        memo[id(func)] = pinned
        return pinned

    def _constrain_names_in(self, func) -> Set[str]:
        out: Set[str] = set()
        for n in _own_body(func):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name) \
                    and isinstance(n.value, ast.Call) \
                    and _last(call_name(n.value)) == "state_constrainer":
                out.add(n.targets[0].id)
        return out

    def _body_binding(self, func, name: str) -> Optional[ast.Call]:
        for n in _own_body(func):
            if not isinstance(n, ast.Assign) \
                    or not isinstance(n.value, ast.Call):
                continue
            for t in n.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return n.value
                if isinstance(t, (ast.Tuple, ast.List)) and any(
                        isinstance(e, ast.Name) and e.id == name
                        for e in t.elts):
                    return n.value
        return None

    def _call_pins(self, call: ast.Call, anchor, constrain: Set[str]
                   ) -> bool:
        """Does the value of ``call`` come out pinned? A call to a
        constrain-bound name, a pinned local def, or a name bound from
        a pinning-builder call."""
        if not isinstance(call.func, ast.Name):
            return False
        nm = call.func.id
        if nm in constrain:
            return True
        local = self._local_def(anchor, nm)
        if local is not None and self._fn_pins(local):
            return True
        bcall, _ = self._binding_of(anchor, nm)
        return bcall is not None and self._pinning_call(bcall)

    def _site_pinned(self, site: JitSite) -> Tuple[bool, str]:
        node = site.node
        if isinstance(node, ast.Call) and any(
                kw.arg == "out_shardings" for kw in node.keywords):
            return True, "out_shardings"
        constrain = self._constrain_names(node)
        t = site.target_node
        if isinstance(t, ast.Lambda):
            return (self._expr_pins(t.body, node, constrain), "lambda")
        if isinstance(t, _FUNC_DEFS):
            return (self._fn_pins(t), "target")
        # jit over a bare name the jaxflow pass could not resolve to a
        # def: a local binding from a builder call (the
        # `_, train_step, _ = make_step_fns(..., state_shardings=...)`
        # convention)
        if site.target_name not in ("<unknown>", "<lambda>"):
            bcall, _ = self._binding_of(node, site.target_name)
            if bcall is not None:
                return (self._pinning_call(bcall), "builder")
        return False, "unresolved"

    def _check_state_programs(self) -> None:
        for sid, site in sorted(self.jax.sites.items()):
            if site.kind != "jit" or not site.donates:
                continue
            scope = enclosing_function(site.node) or site.sf.tree
            if not self._fs_aware(scope):
                continue
            pinned, how = self._site_pinned(site)
            self.state_programs[sid] = {
                "target": site.target_name, "pinned": pinned, "pin": how,
                "donate_argnums": list(site.donates)}
            if not pinned:
                self._findings["jax-shard-break"].append(site.sf.finding(
                    "jax-shard-break", site.node,
                    f"jit program `{site.target_name}` donates state in "
                    f"fs-aware code but never pins its output layout — "
                    f"thread state_shardings through the step builder "
                    f"(step.state_constrainer) or pass out_shardings=, "
                    f"else GSPMD inference may re-partition or replicate "
                    f"the table and break the donated in-place update"))

    # --------------------------------------------- rule 1b: axis breaks
    def _state_scoped_funcs(self):
        """Functions in the state-program convention: a parameter named
        ``state`` or ``table`` (the step/updater/kernel surfaces the
        sharded arrays flow through)."""
        for sf in self._sources():
            for n in sf.walk():
                if isinstance(n, _FUNC_DEFS):
                    params = set(_params_of(n))
                    if "state" in params or "table" in params:
                        yield sf, n

    def _local_prov(self, func) -> Set[str]:
        """One assignment hop: names bound from a table-provenance
        expression inside ``func``."""
        out: Set[str] = set()
        for n in _own_body(func):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name) \
                    and _table_prov(n.value, out):
                out.add(n.targets[0].id)
        return out

    def _check_axis_breaks(self) -> None:
        for sf, func in self._state_scoped_funcs():
            prov = self._local_prov(func)
            for n in _own_body(func):
                if isinstance(n, ast.Call):
                    self._axis_break_call(sf, func, n, prov)
                elif isinstance(n, ast.Subscript) \
                        and _table_prov(n.value, prov) \
                        and isinstance(n.slice, ast.Compare):
                    self._findings["jax-shard-break"].append(sf.finding(
                        "jax-shard-break", n,
                        f"boolean mask over the capacity axis of "
                        f"`{dotted(n.value)}` — a data-dependent shape "
                        f"over the fs-sharded table axis forces a "
                        f"re-materialized (replicated) table; gather "
                        f"with a padded slot vector instead"))

    def _axis_break_call(self, sf: SourceFile, func, call: ast.Call,
                         prov: Set[str]) -> None:
        cn = call_name(call)
        seg = _last(cn)
        # method-form reshape on a table value: state.w.reshape(...)
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr == "reshape" \
                and _table_prov(call.func.value, prov):
            self._findings["jax-shard-break"].append(sf.finding(
                "jax-shard-break", call,
                f"`{dotted(call.func.value)}.reshape(...)` re-lays-out "
                f"the fs-sharded capacity axis — reshapes across the "
                f"table's axis 0 force GSPMD to re-materialize the "
                f"table; keep the capacity axis intact"))
            return
        if "." not in cn or cn.split(".", 1)[0] not in _ARRAY_MODULES:
            return
        if seg == "reshape" and call.args \
                and _table_prov(call.args[0], prov):
            self._findings["jax-shard-break"].append(sf.finding(
                "jax-shard-break", call,
                f"`{cn}` over a table-provenance array re-lays-out the "
                f"fs-sharded capacity axis; keep axis 0 intact"))
            return
        if seg not in _AXIS_BREAKERS or not call.args:
            return
        a0 = call.args[0]
        operands = a0.elts if isinstance(a0, (ast.Tuple, ast.List)) \
            else [a0]
        if not any(_table_prov(op, prov) for op in operands):
            return
        self._findings["jax-shard-break"].append(sf.finding(
            "jax-shard-break", call,
            f"`{cn}` over a table-provenance array breaks the sharded "
            f"capacity axis (axis 0 is the fs key-range dimension — "
            f"reordering or growing it on device re-materializes the "
            f"table across shards); do this on per-shard host views "
            f"(fs_shard_bounds) or on gathered rows, not the table"))

    # ------------------------------------------- rule 2: replication
    def _replicating_call(self, call: ast.Call) -> Optional[str]:
        """Why ``call`` replicates its argument, or None. device_put
        with no placement (or an explicit ``replicated(...)``) lands the
        whole array on one layout; np/jnp asarray materializes it."""
        cn = call_name(call)
        seg = _last(cn)
        if seg == "device_put":
            if len(call.args) < 2 and not call.keywords:
                return "device_put with no sharding"
            placements = list(call.args[1:]) + [
                kw.value for kw in call.keywords]
            for p in placements:
                if isinstance(p, ast.Call) \
                        and _last(call_name(p)) == "replicated":
                    return "device_put(..., replicated(mesh))"
            return None
        if seg in ("asarray", "array") and "." in cn \
                and cn.split(".", 1)[0] in ("np", "numpy", "jnp"):
            return f"{cn} materializes the full table on host/one device"
        if seg == "fetch" and "jaxtrace" in cn:
            return "jaxtrace.fetch pulls the full table to host"
        return None

    def _check_replication(self) -> None:
        # (a) table-provenance arrays re-placed in fs-aware functions
        for sf in self._sources():
            for n in sf.walk():
                if not isinstance(n, _FUNC_DEFS):
                    continue
                if not self._fs_aware(n):
                    continue
                prov = self._local_prov(n)
                for c in _own_body(n):
                    if not isinstance(c, ast.Call) or not c.args:
                        continue
                    why = self._replicating_call(c)
                    if why and _table_prov(c.args[0], prov):
                        self._findings["jax-shard-replicate"].append(
                            sf.finding(
                                "jax-shard-replicate", c,
                                f"table-sized replication: {why} — the "
                                f"fs-sharded table must move through "
                                f"put_global/shard_pytree with its "
                                f"state_sharding spec, never through a "
                                f"replicated or host copy (that is the "
                                f"single-device memory wall fs-sharding "
                                f"removes)"))
        # (b) donated arguments fed from a replicating coercion at the
        # exact call edges of the fs-scoped state programs
        for sid in sorted(self.state_programs):
            site = self.jax.sites[sid]
            for cs in site.call_sites:
                for d in site.donates:
                    if d >= len(cs.args):
                        continue
                    arg = cs.args[d]
                    why = None
                    if isinstance(arg, ast.Call):
                        why = self._replicating_call(arg)
                    elif isinstance(arg, ast.Name):
                        bcall, _ = self._binding_of(cs, arg.id)
                        if bcall is not None:
                            why = self._replicating_call(bcall)
                    if why:
                        csf = self._sf_of(cs, site)
                        self._findings["jax-shard-replicate"].append(
                            csf.finding(
                                "jax-shard-replicate", cs,
                                f"donated argument {d} of "
                                f"`{site.target_name}` is fed from a "
                                f"replicating coercion ({why}) — the "
                                f"donated state must arrive under its "
                                f"fs sharding or the in-place table "
                                f"update degrades to a full copy"))

    def _sf_of(self, node, site: JitSite) -> SourceFile:
        for sf in self.project.files:
            if sf.tree is not None and node in sf.walk():
                return sf
        return site.sf

    # ------------------------------------------ rule 3: pallas guards
    def _check_pallas_reach(self) -> None:
        # kernel functions: contain a pallas_call (ops/fused.py DMA
        # kernels); grown by unguarded exact edges from callers
        kern: Set[str] = set()
        for qual, fi in self.cg.funcs.items():
            if fi.node is None or fi.sf.rel.endswith("utils/jaxtrace.py"):
                continue
            for n in ast.walk(fi.node):
                if isinstance(n, ast.Call) \
                        and _is_pallas_name(call_name(n)):
                    kern.add(qual)
                    break
        guarded_edges: List[Tuple[str, object]] = []
        changed = True
        while changed:
            changed = False
            for caller, csites in self.cg.calls.items():
                if caller in kern or caller.endswith("::<module>"):
                    continue
                for cs in csites:
                    if cs.kind != "call" or cs.fuzzy:
                        continue
                    if not any(t in kern for t in cs.targets):
                        continue
                    if not self._pallas_guarded(cs.node):
                        kern.add(caller)
                        changed = True
                        break
                if changed:
                    break
        self.kernel_funcs = kern
        # dispatchers: non-kernel functions whose kernel edges sit under
        # a `backend == "pallas"` guard on one of their own parameters
        for caller, csites in self.cg.calls.items():
            fi = self.cg.funcs.get(caller)
            if fi is None or fi.node is None or caller in kern:
                continue
            for cs in csites:
                if cs.kind != "call" or cs.fuzzy \
                        or not any(t in kern for t in cs.targets):
                    continue
                idx = self._guard_param_index(cs.node, fi.node)
                if idx is not None:
                    self.guarded_dispatchers[caller] = idx
        # every exact caller of a dispatcher must pass a backend that
        # went through resolve_backend (or a safe literal)
        for caller, csites in self.cg.calls.items():
            for cs in csites:
                if cs.kind != "call" or cs.fuzzy:
                    continue
                for t in cs.targets:
                    if t in self.guarded_dispatchers:
                        self._check_dispatch_arg(caller, cs, t)

    def _pallas_guarded(self, node) -> bool:
        cur = getattr(node, "parent", None)
        while cur is not None and not isinstance(cur, _FUNC_DEFS):
            if isinstance(cur, (ast.If, ast.IfExp)) and any(
                    isinstance(k, ast.Constant) and k.value == "pallas"
                    for k in ast.walk(cur.test)):
                return True
            cur = getattr(cur, "parent", None)
        return False

    def _guard_param_index(self, call_node, func) -> Optional[int]:
        """Param index of the dispatcher's own backend guard: the
        enclosing ``if <name> == "pallas"`` test names a parameter."""
        cur = getattr(call_node, "parent", None)
        while cur is not None and cur is not func:
            if isinstance(cur, (ast.If, ast.IfExp)):
                for cmp in ast.walk(cur.test):
                    if not isinstance(cmp, ast.Compare):
                        continue
                    sides = [cmp.left] + list(cmp.comparators)
                    if not any(isinstance(s, ast.Constant)
                               and s.value == "pallas" for s in sides):
                        continue
                    for s in sides:
                        if isinstance(s, ast.Name):
                            params = _params_of(func)
                            if s.id in params:
                                return params.index(s.id)
            cur = getattr(cur, "parent", None)
        return None

    def _check_dispatch_arg(self, caller: str, cs, target: str) -> None:
        fi = self.cg.funcs.get(target)
        if fi is None or fi.node is None:
            return
        idx = self.guarded_dispatchers[target]
        params = _params_of(fi.node)
        pname = params[idx]
        from .jaxflow import _self_shift
        shift = _self_shift(fi.node, fi)
        arg = None
        pos = idx - shift
        if 0 <= pos < len(cs.node.args):
            arg = cs.node.args[pos]
        for kw in cs.node.keywords:
            if kw.arg == pname:
                arg = kw.value
        if arg is None:
            # parameter left to its default: safe iff the default is
            # not the literal "pallas"
            defaults = fi.node.args.defaults
            dpos = idx - (len(params) - len(defaults))
            if 0 <= dpos < len(defaults):
                d = defaults[dpos]
                if isinstance(d, ast.Constant) and d.value == "pallas":
                    arg = d
            if arg is None:
                return
        if self._backend_arg_safe(arg, cs.node):
            return
        if self._under_resolved_guard(cs.node):
            # `if backend == "pallas": ...fm_update_rows(backend="pallas")`
            # where `backend` itself came from resolve_backend: the
            # literal is re-stating a proven resolution, not bypassing it
            return
        csf = self.cg.funcs[caller].sf if caller in self.cg.funcs \
            else fi.sf
        self._findings["jax-shard-pallas"].append(csf.finding(
            "jax-shard-pallas", cs.node,
            f"`{fi.node.name}` can reach a pallas_call kernel, but the "
            f"backend argument `{pname}` did not come from "
            f"ops.fused.resolve_backend — the one guard that fails "
            f"typed on pallas + sharded table; route the knob through "
            f"resolve_backend(knob, mesh=...) so a mesh run cannot "
            f"reach the GSPMD-opaque kernel"))

    def _under_resolved_guard(self, node) -> bool:
        """True when ``node`` sits under an ``if <x> == "pallas"`` guard
        whose tested name is itself resolve_backend-derived (scope-chain
        binding) — the one sanctioned way to hand a dispatcher the
        literal backend it already proved."""
        cur = getattr(node, "parent", None)
        while cur is not None and not isinstance(cur, _FUNC_DEFS + (
                ast.Module,)):
            if isinstance(cur, (ast.If, ast.IfExp)):
                for cmp in ast.walk(cur.test):
                    if not isinstance(cmp, ast.Compare):
                        continue
                    sides = [cmp.left] + list(cmp.comparators)
                    if not any(isinstance(s, ast.Constant)
                               and s.value == "pallas" for s in sides):
                        continue
                    for s in sides:
                        if isinstance(s, ast.Name):
                            bcall, _ = self._binding_of(node, s.id)
                            if bcall is not None and _last(call_name(
                                    bcall)) == "resolve_backend":
                                return True
                        if isinstance(s, ast.Attribute) \
                                and self._backend_arg_safe(s, node):
                            return True
            cur = getattr(cur, "parent", None)
        return False

    def _backend_arg_safe(self, arg, anchor) -> bool:
        if isinstance(arg, ast.Constant):
            return arg.value != "pallas"
        name = None
        if isinstance(arg, ast.Name):
            name = arg.id
        elif isinstance(arg, ast.Attribute):
            # attribute backends (self._backend): resolve by the
            # node_key convention — any `.attr = resolve_backend(...)`
            # binding in the same file sanctions every `.attr` read
            attr = arg.attr
            for sf in self._sources():
                for n in sf.walk():
                    if isinstance(n, ast.Assign) \
                            and isinstance(n.value, ast.Call) \
                            and _last(call_name(n.value)) == \
                            "resolve_backend" \
                            and any(isinstance(t, ast.Attribute)
                                    and t.attr == attr
                                    for t in n.targets):
                        return True
            return False
        if name is None:
            return False
        bcall, _ = self._binding_of(anchor, name)
        if bcall is None:
            return False
        if _last(call_name(bcall)) == "resolve_backend":
            return True
        return False

    # ----------------------------------------------------------- views
    def to_json(self) -> dict:
        """The static model hlomap and the tier-1 gate consume: the
        fs-scoped state programs with their pin verdicts, the pallas
        reachability sets, and the full jit-site universe (dynamic
        hloscan sites must be a subset)."""
        return {
            "state_programs": {sid: dict(rec) for sid, rec in
                               sorted(self.state_programs.items())},
            "pinning_builders": sorted(self.pinning_builders),
            "kernel_functions": sorted(self.kernel_funcs),
            "guarded_dispatchers": {q: i for q, i in sorted(
                self.guarded_dispatchers.items())},
            "sites": sorted(self.jax.sites),
        }


def get_shard_model(project: Project) -> ShardModel:
    m = getattr(project, "_shard_model", None)
    if m is None or m.project is not project:
        m = ShardModel(project)
        project._shard_model = m  # type: ignore[attr-defined]
    return m


# ---------------------------------------------------------------------------
# rule registrations


@rule("jax-shard-break",
      "fs-scoped state programs must pin their output layout; no ops "
      "that break the sharded capacity axis", cross=True)
def check_jax_shard_break(project: Project) -> List[Finding]:
    return list(get_shard_model(project)._findings["jax-shard-break"])


@rule("jax-shard-replicate",
      "no table-sized replication: the fs-sharded table never moves "
      "through a replicated or host copy", cross=True)
def check_jax_shard_replicate(project: Project) -> List[Finding]:
    return list(
        get_shard_model(project)._findings["jax-shard-replicate"])


@rule("jax-shard-pallas",
      "pallas_call kernels reachable only through the resolve_backend "
      "typed guard (pallas is GSPMD-opaque)", cross=True)
def check_jax_shard_pallas(project: Project) -> List[Finding]:
    return list(get_shard_model(project)._findings["jax-shard-pallas"])
