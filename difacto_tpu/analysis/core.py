"""Rule framework for difacto-lint (docs/static_analysis.md).

Everything rule authors touch lives here: the :class:`Finding` record,
the rule registry (:func:`rule` decorator), per-line ``# lint:
ok(rule-id)`` suppressions, the checked-in baseline for grandfathered
findings, the project index cross-file rules read, and the three output
formats (``text`` for humans, ``json`` for tooling, ``github`` for PR
annotations).

Exit-code contract (stable — CI and the Makefile depend on it):

- ``0`` — clean: no unsuppressed, non-baselined findings.
- ``1`` — findings to fix (or to baseline intentionally).
- ``2`` — usage or internal error (bad flags, unreadable baseline).

Fingerprints are line-number free — ``sha1(rule | relpath | stripped
source line | occurrence#)`` — so a baseline survives unrelated edits
above a grandfathered finding; it expires only when the flagged line
itself changes (which is exactly when a human should re-look).
"""

from __future__ import annotations

import ast
import hashlib
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Tuple

BASELINE_VERSION = 1
JSON_VERSION = 1

# ---------------------------------------------------------------------------
# findings


@dataclass
class Finding:
    rule: str
    path: str            # repo-relative, forward slashes
    line: int            # 1-based; 0 for file-level findings
    message: str
    snippet: str = ""    # stripped source line (fingerprint input)
    suppressed: bool = False   # hit a `# lint: ok(...)` pragma
    baselined: bool = False    # matched the checked-in baseline
    occurrence: int = 0        # disambiguates identical (rule,path,snippet)

    def fingerprint(self) -> str:
        raw = f"{self.rule}|{self.path}|{self.snippet}|{self.occurrence}"
        return hashlib.sha1(raw.encode("utf-8")).hexdigest()[:16]

    @property
    def active(self) -> bool:
        return not (self.suppressed or self.baselined)

    def to_json(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "message": self.message, "fingerprint": self.fingerprint(),
            "suppressed": self.suppressed, "baselined": self.baselined,
        }


# ---------------------------------------------------------------------------
# rule registry


@dataclass
class Rule:
    rule_id: str
    summary: str
    check: Callable          # SourceFile -> findings  |  Project -> findings
    cross: bool = False


RULES: Dict[str, Rule] = {}


def rule(rule_id: str, summary: str, cross: bool = False):
    """Register a rule. Local rules take a :class:`SourceFile`, cross
    rules take the whole :class:`Project`."""
    def deco(fn):
        RULES[rule_id] = Rule(rule_id, summary, fn, cross)
        return fn
    return deco


def all_rules() -> Dict[str, Rule]:
    # import for side effect: the @rule decorators populate RULES
    from . import (concurrency, crossrules, jaxflow,  # noqa: F401
                   localrules, races, shardflow)
    return RULES


# ---------------------------------------------------------------------------
# source files and suppressions

_SUPPRESS_RE = re.compile(r"#\s*lint:\s*ok\(([a-zA-Z0-9_\-, ]+)\)")


class SourceFile:
    """One parsed lint target: text, AST with ``.parent`` links, and the
    per-line suppression map (a pragma covers its own line and, when it
    stands alone, the first code line after it).

    The node index is SHARED: :meth:`walk` / :meth:`call_nodes` cache
    the flat node list once, so the local, cross, concurrency, and race
    passes all read one traversal instead of each re-walking the tree
    (the whole-file ``ast.walk`` was the analyzer's hottest loop)."""

    def __init__(self, path: Path, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.parse_error: Optional[str] = None
        self._nodes: Optional[List[ast.AST]] = None
        self._calls: Optional[List[ast.Call]] = None
        try:
            self.tree: Optional[ast.AST] = ast.parse(text)
        except SyntaxError as e:
            self.tree = None
            self.parse_error = f"{e.msg} (line {e.lineno})"
        if self.tree is not None:
            nodes = list(ast.walk(self.tree))
            self._nodes = nodes
            for node in nodes:
                for child in ast.iter_child_nodes(node):
                    child.parent = node  # type: ignore[attr-defined]
        self.suppressions: Dict[int, set] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            ids = {s.strip() for s in m.group(1).split(",") if s.strip()}
            self.suppressions.setdefault(i, set()).update(ids)
            if line.lstrip().startswith("#"):
                # standalone pragma: covers the next CODE line — blank
                # lines and the rationale's continuation comment lines
                # in between don't break the attachment
                j = i + 1
                while j <= len(self.lines) \
                        and (not self.lines[j - 1].strip()
                             or self.lines[j - 1].lstrip().startswith("#")):
                    j += 1
                self.suppressions.setdefault(j, set()).update(ids)

    def walk(self) -> List[ast.AST]:
        """Every node of the file's AST, computed once (same order as
        ``ast.walk(self.tree)``). Empty for unparsable files."""
        return self._nodes or []

    def call_nodes(self) -> List[ast.Call]:
        """Every ``ast.Call`` in the file, from the shared index."""
        if self._calls is None:
            self._calls = [n for n in self.walk()
                           if isinstance(n, ast.Call)]
        return self._calls

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule_id: str, node, message: str) -> Finding:
        lineno = getattr(node, "lineno", 0) if node is not None else 0
        return Finding(rule_id, self.rel, lineno, message,
                       snippet=self.line_text(lineno))

    def is_suppressed(self, f: Finding) -> bool:
        ids = self.suppressions.get(f.line, set())
        return f.rule in ids or "all" in ids


# ---------------------------------------------------------------------------
# the project index


class Project:
    """Everything the analyzer reads, resolved once.

    ``lint_paths`` are what local rules run over. Cross rules also read
    *reference corpora* that are not themselves linted: the docs tree
    and the test suite (registry-drift rules check call sites against
    both). All the knobs default to this repo's layout but are
    parameters so the fixture suite can lint tiny synthetic projects.
    """

    def __init__(self, root, lint_paths: Optional[List[str]] = None, *,
                 docs_dir: str = "docs",
                 tests_dir: str = "tests",
                 readme: str = "README.md",
                 handler_files: Tuple[str, ...] = (
                     "difacto_tpu/serve/server.py",
                     "difacto_tpu/serve/router.py"),
                 sender_files: Tuple[str, ...] = (
                     "difacto_tpu/serve/client.py",
                     "difacto_tpu/serve/fleet.py",
                     "tools/", "bench.py", "launch.py"),
                 kinds_file: str = "difacto_tpu/utils/faultinject.py",
                 metrics_doc: str = "docs/observability.md",
                 metrics_impl_files: Tuple[str, ...] = (
                     "difacto_tpu/obs/metrics.py",),
                 exclude: Tuple[str, ...] = ("__pycache__",)):
        self.root = Path(root).resolve()
        self.docs_dir = docs_dir
        self.tests_dir = tests_dir
        self.readme = readme
        self.handler_files = handler_files
        self.sender_files = sender_files
        self.kinds_file = kinds_file
        self.metrics_doc = metrics_doc
        self.metrics_impl_files = metrics_impl_files
        self.exclude = exclude
        self.files: List[SourceFile] = []
        for p in self._expand(lint_paths or ["."]):
            rel = p.relative_to(self.root).as_posix()
            try:
                text = p.read_text(encoding="utf-8")
            except OSError as e:
                sf = SourceFile(p, rel, "")
                sf.parse_error = f"unreadable: {e}"
                self.files.append(sf)
                continue
            self.files.append(SourceFile(p, rel, text))
        self._docs_cache: Optional[str] = None
        self._tests_cache: Optional[str] = None

    def _expand(self, paths: List[str]) -> List[Path]:
        out: List[Path] = []
        for raw in paths:
            p = (self.root / raw).resolve()
            if p.is_dir():
                for q in sorted(p.rglob("*.py")):
                    if any(part in self.exclude for part in q.parts):
                        continue
                    out.append(q)
            elif p.suffix == ".py" and p.exists():
                out.append(p)
        seen, uniq = set(), []
        for p in out:
            if p not in seen:
                seen.add(p)
                uniq.append(p)
        return uniq

    # -- reference corpora -------------------------------------------------

    def docs_text(self) -> str:
        """Concatenated docs tree + README (registry rules grep this)."""
        if self._docs_cache is None:
            parts = []
            d = self.root / self.docs_dir
            if d.is_dir():
                for p in sorted(d.rglob("*.md")):
                    parts.append(p.read_text(encoding="utf-8",
                                             errors="replace"))
            r = self.root / self.readme
            if r.exists():
                parts.append(r.read_text(encoding="utf-8", errors="replace"))
            self._docs_cache = "\n".join(parts)
        return self._docs_cache

    def tests_text(self) -> str:
        if self._tests_cache is None:
            parts = []
            d = self.root / self.tests_dir
            if d.is_dir():
                for p in sorted(d.rglob("*.py")):
                    parts.append(p.read_text(encoding="utf-8",
                                             errors="replace"))
            self._tests_cache = "\n".join(parts)
        return self._tests_cache

    def match_files(self, specs: Iterable[str]) -> List[SourceFile]:
        """Lint files whose relpath equals a spec or lives under a
        ``dir/`` spec."""
        out = []
        for sf in self.files:
            for spec in specs:
                if sf.rel == spec or (spec.endswith("/")
                                      and sf.rel.startswith(spec)):
                    out.append(sf)
                    break
        return out


# ---------------------------------------------------------------------------
# AST helpers shared by the rules


def call_name(node: ast.Call) -> str:
    """Best-effort dotted name of a call target: ``threading.Thread``,
    ``socket.socket``, ``open`` ... empty string when dynamic."""
    return dotted(node.func)


def dotted(node) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


def node_key(node) -> str:
    """Matching key for an lvalue/receiver: ``x`` for Name x, ``.x`` for
    any ``<obj>.x`` attribute (so ``self._t.join()`` matches the
    ``self._t = Thread(...)`` binding regardless of the object half)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return "." + node.attr
    return ""


def str_const(node) -> Optional[str]:
    if isinstance(node, ast.Constant):
        if isinstance(node.value, str):
            return node.value
        if isinstance(node.value, bytes):
            try:
                return node.value.decode("ascii")
            except UnicodeDecodeError:
                return None
    return None


def enclosing_function(node):
    cur = getattr(node, "parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = getattr(cur, "parent", None)
    return None


def statement_of(node):
    cur = node
    while cur is not None and not isinstance(cur, ast.stmt):
        cur = getattr(cur, "parent", None)
    return cur


def import_aliases(tree: ast.AST, module: str) -> set:
    """Names under which ``module`` is visible in this file, including
    ``from module import f`` members mapped as ``name -> member`` via
    a ``name:member`` entry? No — returns just the module aliases; use
    :func:`from_imports` for members."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == module:
                    out.add(a.asname or a.name)
    return out


def from_imports(tree: ast.AST, module: str) -> Dict[str, str]:
    """``from module import x as y`` -> ``{y: x}``."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == module:
            for a in node.names:
                out[a.asname or a.name] = a.name
    return out


# ---------------------------------------------------------------------------
# running


@dataclass
class RunResult:
    findings: List[Finding] = field(default_factory=list)
    expired: List[dict] = field(default_factory=list)  # baseline leftovers
    files: int = 0
    # per-pass wall time: rule id -> seconds (cross rules measured once,
    # local rules summed across files), plus the analyzer total — the CI
    # JSON report carries both so the 30s budget can be attributed when
    # it tightens
    rule_seconds: Dict[str, float] = field(default_factory=dict)
    lint_seconds: float = 0.0

    @property
    def active(self) -> List[Finding]:
        return [f for f in self.findings if f.active]

    def counts(self) -> dict:
        return {
            "files": self.files,
            "total": len(self.findings),
            "active": len(self.active),
            "suppressed": sum(f.suppressed for f in self.findings),
            "baselined": sum(f.baselined for f in self.findings),
            "expired_baseline": len(self.expired),
        }


def run_project(project: Project,
                rule_ids: Optional[Iterable[str]] = None,
                local_files: Optional[set] = None) -> RunResult:
    """Run rules over the project. ``local_files`` (a set of repo-
    relative paths) restricts LOCAL rules to those files — the
    ``--changed-only`` incremental mode; cross-file and concurrency
    rules always see the whole tree (their findings can live in files
    the change never touched)."""
    import time as _time

    rules = all_rules()
    if rule_ids is not None:
        unknown = set(rule_ids) - set(rules)
        if unknown:
            raise ValueError(f"unknown rule(s): {sorted(unknown)}")
        rules = {rid: rules[rid] for rid in rule_ids}
    res = RunResult(files=len(project.files))
    t_run0 = _time.monotonic()
    by_file = {sf.rel: sf for sf in project.files}
    for sf in project.files:
        if local_files is not None and sf.rel not in local_files:
            continue
        if sf.parse_error is not None:
            res.findings.append(Finding(
                "parse-error", sf.rel, 0,
                f"cannot analyze: {sf.parse_error}"))
            continue
        for r in rules.values():
            if not r.cross:
                t0 = _time.monotonic()
                res.findings.extend(r.check(sf))
                res.rule_seconds[r.rule_id] = \
                    res.rule_seconds.get(r.rule_id, 0.0) \
                    + (_time.monotonic() - t0)
    for r in rules.values():
        if r.cross:
            t0 = _time.monotonic()
            res.findings.extend(r.check(project))
            res.rule_seconds[r.rule_id] = \
                res.rule_seconds.get(r.rule_id, 0.0) \
                + (_time.monotonic() - t0)
    # stable order, then occurrence indices for identical snippets
    res.findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    seen: Dict[Tuple[str, str, str], int] = {}
    for f in res.findings:
        key = (f.rule, f.path, f.snippet)
        f.occurrence = seen.get(key, 0)
        seen[key] = f.occurrence + 1
        sf = by_file.get(f.path)
        if sf is not None and sf.is_suppressed(f):
            f.suppressed = True
    res.lint_seconds = _time.monotonic() - t_run0
    return res


# ---------------------------------------------------------------------------
# baseline


def load_baseline(path) -> Dict[str, dict]:
    p = Path(path)
    if not p.exists():
        return {}
    data = json.loads(p.read_text(encoding="utf-8"))
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(f"baseline {path}: unsupported version "
                         f"{data.get('version')!r}")
    return dict(data.get("findings", {}))


def apply_baseline(res: RunResult, baseline: Dict[str, dict]) -> None:
    """Mark matching findings baselined; record expired entries (in the
    baseline but no longer produced — prune with ``make lint-baseline``)."""
    matched = set()
    for f in res.findings:
        if f.suppressed:
            continue
        fp = f.fingerprint()
        if fp in baseline:
            f.baselined = True
            matched.add(fp)
    res.expired = [dict(entry, fingerprint=fp)
                   for fp, entry in sorted(baseline.items())
                   if fp not in matched]


def write_baseline(res: RunResult, path) -> int:
    """Grandfather every currently-active finding. Returns the count."""
    entries = {
        f.fingerprint(): {"rule": f.rule, "path": f.path,
                          "message": f.message, "snippet": f.snippet}
        for f in res.findings if not f.suppressed
    }
    payload = {"version": BASELINE_VERSION, "findings": entries}
    Path(path).write_text(json.dumps(payload, indent=1, sort_keys=True)
                          + "\n", encoding="utf-8")
    return len(entries)


# ---------------------------------------------------------------------------
# output formats


def format_text(res: RunResult, verbose: bool = False) -> str:
    out = []
    for f in res.findings:
        if not f.active and not verbose:
            continue
        tag = "" if f.active else (" (suppressed)" if f.suppressed
                                   else " (baselined)")
        out.append(f"{f.path}:{f.line}: [{f.rule}]{tag} {f.message}")
    for e in res.expired:
        out.append(f"baseline: expired entry {e['fingerprint']} "
                   f"[{e.get('rule', '?')}] {e.get('path', '?')} — "
                   f"regenerate with `make lint-baseline`")
    c = res.counts()
    out.append(f"difacto-lint: {c['files']} files, {c['active']} finding(s) "
               f"({c['suppressed']} suppressed, {c['baselined']} baselined, "
               f"{c['expired_baseline']} expired baseline) "
               f"in {res.lint_seconds:.2f}s")
    if verbose and res.rule_seconds:
        slow = sorted(res.rule_seconds.items(), key=lambda kv: -kv[1])[:6]
        out.append("slowest passes: " + ", ".join(
            f"{rid} {s:.2f}s" for rid, s in slow))
    return "\n".join(out)


def format_json(res: RunResult) -> str:
    return json.dumps({
        "version": JSON_VERSION,
        "counts": res.counts(),
        "findings": [f.to_json() for f in res.findings],
        "expired_baseline": res.expired,
        "lint_seconds": round(res.lint_seconds, 3),
        "rule_seconds": {rid: round(s, 3)
                         for rid, s in sorted(res.rule_seconds.items())},
    }, indent=1, sort_keys=True)


def format_sarif(res: RunResult) -> str:
    """SARIF 2.1.0 — what GitHub code scanning ingests (the CI lint job
    uploads this next to the JSON report, so findings land as scanning
    alerts alongside the inline `github`-format annotations). Active
    findings only; the line-number-free fingerprint rides along as the
    partial fingerprint so alerts track across unrelated edits."""
    rules = all_rules()
    used = sorted({f.rule for f in res.active})
    results = []
    for f in res.active:
        results.append({
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": max(f.line, 1)},
                },
            }],
            "partialFingerprints": {
                "difactoLint/v1": f.fingerprint(),
            },
        })
    driver = {
        "name": "difacto-lint",
        "informationUri":
            "https://github.com/difacto-tpu/difacto-tpu"
            "/blob/main/docs/static_analysis.md",
        "rules": [{
            "id": rid,
            "shortDescription": {
                "text": rules[rid].summary if rid in rules
                else "analyzer-internal finding"},
        } for rid in used],
    }
    return json.dumps({
        "$schema": "https://raw.githubusercontent.com/oasis-tcs/"
                   "sarif-spec/master/Schemata/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": driver},
            "results": results,
            "columnKind": "utf16CodeUnits",
        }],
    }, indent=1, sort_keys=True)


def format_github(res: RunResult) -> str:
    """GitHub workflow-command annotations: active findings render
    inline on the PR diff; expired baseline entries surface as notices."""
    out = []
    for f in res.active:
        msg = f.message.replace("%", "%25").replace("\n", "%0A")
        out.append(f"::error file={f.path},line={max(f.line, 1)},"
                   f"title=difacto-lint {f.rule}::{msg}")
    for e in res.expired:
        out.append(f"::notice title=difacto-lint baseline::expired entry "
                   f"{e['fingerprint']} ({e.get('rule', '?')} "
                   f"{e.get('path', '?')}) — run `make lint-baseline`")
    if not out:
        out.append("::notice title=difacto-lint::clean")
    return "\n".join(out)
