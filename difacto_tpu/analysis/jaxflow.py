"""JAX compile/transfer flow analysis (difacto-lint v4).

The tree's two core JAX invariants — "zero steady-state recompiles" on
the serve path and donated in-place slot updates with byte-identical
trajectories — are compile-cache and aliasing properties that the
earlier JAX rules (jax-donate / jax-jit-capture / jax-host-call in
localrules.py) only check one function at a time. This pass is
whole-program: it discovers every jit program in the tree, follows its
call sites through the shared call graph (callgraph.py), and checks
four property families:

- **jax-recompile** — the compile-key model. Every value feeding a
  ``static_argnums`` position at a wrapper call site must be provably
  drawn from a BOUNDED set: constants, config-derived fields
  (``*.param.*``), sticky shape caps (``ShapeSchedule.cap``) and
  bucket rungs (``ops.batch.bucket``), attributes only ever assigned
  from bounded values, and parameters whose every exact caller passes
  bounded values (a depth-capped fixpoint). A static fed straight from
  data (``len(...)``, ``.size``/``.nnz``/``.shape``) compiles a new
  program per distinct value — the exact hazard the executor's bucket
  caps exist to prevent. Also flagged: a jit wrapper built inside a
  loop or invoked immediately (``jit(f)(x)`` — a fresh compile-cache
  entry per call), and non-hashable literals at static positions
  (a ``TypeError`` at trace time).

- **jax-host-sync** — implicit device->host syncs on the hot path,
  interprocedurally. Results of jitted wrappers are *device values*;
  coercing one on the host (``float()``/``int()``/``bool()``/
  ``np.asarray``/``.item()``/``.tolist()``/``print``) blocks on the
  device pipeline. Inside the hot step/dispatch loops (any function
  that calls a jit wrapper from inside a loop, every ``*._loop``, and
  everything they reach over exact call edges) such a coercion must be
  a DECLARED sync: ``utils.jaxtrace.fetch(x)`` — which the runtime
  tracer counts — or carry a reasoned suppression. Taint flows through
  local assignment, tuple unpacking, helper parameters, and helper
  returns (one fixpoint over the hot set).

- **jax-donate-flow** — donation declarations that cannot work:
  a donated index that is also a static (never a buffer), a donated
  index past the target's positional parameters, the same name passed
  at a donated AND a non-donated position of one call (the aliased
  read is undefined), and the cross-edge read-after-donate the local
  jax-donate rule cannot see: the donated argument is the enclosing
  function's parameter, and an exact CALLER keeps reading the buffer
  it passed after the call returns.

- **jax-dtype64** (local) — dtype drift into the fp32 device pipeline:
  ``float64`` mentions inside jit targets (a single np.float64
  intermediate promotes the whole computation), ``dtype=float64`` on
  ``jnp`` device-array creation anywhere, and int32 accumulators
  (``x += ...`` in a loop on an int32-created counter) on paths that
  can overflow past 2^31 rows. Host-side float64 OUTSIDE jit targets
  is deliberate in this tree (exact text parsing, DCN reduction wires,
  the two-loop solver) and is not flagged.

The runtime complement is ``utils/jaxtrace.py`` (``DIFACTO_JAXTRACE=1``)
whose per-site compile counts and fetch counts the tier-1 gate
(tests/test_jaxflow.py) checks against this model: observed jit sites
must be statically known and warm-declared, steady-state compiles must
stop growing, and observed transfers must be declared fetch points.
``tools/jitmap.py`` renders the merged view (``make jitmap``).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import CallGraph, get_callgraph
from .core import (Finding, Project, SourceFile, call_name, dotted,
                   enclosing_function, node_key, rule, statement_of)

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)
_LOOPS = (ast.For, ast.AsyncFor, ast.While)

# calls that quantize a data-dependent value onto a bounded set: the
# sticky shape caps (data/pack_stream.ShapeSchedule.cap, only grows,
# log-many values) and the bucket rungs (ops/batch.bucket)
_BOUNDING_CALLS = {"cap", "bucket"}
# attribute segments that mark config-derived constants (difacto's
# Param dataclasses): bounded for a run's lifetime
_CONFIG_SEGMENTS = {"param", "uparam"}
# data-dependent attributes: feeding one of these to a static position
# is the canonical recompile hazard
_DATA_ATTRS = {"size", "nnz", "shape", "ndim"}
_COERCIONS = {"float", "int", "bool"}
_NP_SINKS = {"asarray", "array"}
_ITEM_SINKS = {"item", "tolist"}

_PROV_DEPTH = 8


def _self_shift(func, fi) -> int:
    """1 when callers' positional args are offset by an implicit
    receiver: the function is a METHOD (first parameter self/cls AND
    the callgraph places it in a class). Nested functions inside a
    method keep the class context but take no receiver."""
    if fi is None or fi.cls is None or not isinstance(func, _FUNC_DEFS):
        return 0
    params = func.args.posonlyargs + func.args.args
    return 1 if params and params[0].arg in ("self", "cls") else 0


def _is_fetch_call(cn: str) -> bool:
    """Only the tracer's own ``jaxtrace.fetch`` is the declared sync —
    other ``.fetch`` methods in the tree (tile caches) move device
    data and must NOT sanction or untaint anything."""
    return cn == "jaxtrace.fetch" or cn.endswith(".jaxtrace.fetch")


def _is_jit_name(cn: str) -> bool:
    return cn in ("jit", "pjit") or cn.endswith(".jit") \
        or cn.endswith(".pjit")


def _is_pallas_name(cn: str) -> bool:
    """``pallas_call`` / ``pl.pallas_call`` / ``jaxtrace.pallas_call``:
    kernel-invocation sites tracked under the same relpath:lineno
    identity as jit/pjit (utils/jaxtrace.pallas_call), so the fused
    table kernels (ops/fused.py) show up in ``make jitmap`` and the
    runtime gate can match what a traced run observed."""
    return cn == "pallas_call" or cn.endswith(".pallas_call")


def _jit_call_parts(call: ast.Call):
    """(is_jit, keywords) for a ``jit(...)`` / ``partial(jit, ...)``
    call — the partial form carries the jit kwargs on the partial."""
    cn = call_name(call)
    if _is_jit_name(cn):
        return True, call.keywords
    if (cn == "partial" or cn.endswith(".partial")) and call.args:
        an = dotted(call.args[0])
        if _is_jit_name(an):
            return True, call.keywords
    return False, []


def _int_tuple(kwval) -> Tuple[int, ...]:
    consts = kwval.elts if isinstance(kwval, (ast.Tuple, ast.List)) \
        else [kwval]
    return tuple(c.value for c in consts
                 if isinstance(c, ast.Constant) and isinstance(c.value, int))


@dataclass
class JitSite:
    site_id: str                    # "rel:lineno" — jaxtrace identity
    sf: SourceFile
    node: ast.AST                   # the jit call / decorator node
    bound: Optional[str]            # node_key of the bound name, or None
    target_name: str                # wrapped function's name (or <lambda>)
    target_node: Optional[ast.AST]  # FunctionDef / Lambda when resolvable
    statics: Tuple[int, ...] = ()
    donates: Tuple[int, ...] = ()
    owner: str = ""                 # qual of the function holding the jit()
    kind: str = "jit"               # "jit" | "pallas" (pallas_call site)
    call_sites: List[ast.Call] = field(default_factory=list)
    unbounded: List[Tuple[ast.Call, int, str]] = field(default_factory=list)

    @property
    def bounded(self) -> bool:
        return not self.unbounded


class JaxModel:
    """The whole-program jit/transfer model. Built once per Project
    (cached — all four rules, jitmap, and the tier-1 gate share it)."""

    def __init__(self, project: Project):
        self.project = project
        self.cg: CallGraph = get_callgraph(project)
        self.sites: Dict[str, JitSite] = {}
        self.fetch_sites: Dict[str, int] = {}    # "rel:lineno" -> lineno
        self.hot_funcs: Set[str] = set()
        self.hot_roots: Set[str] = set()
        self._call_to_site: Dict[int, JitSite] = {}
        self._findings: Dict[str, List[Finding]] = {
            "jax-recompile": [], "jax-host-sync": [], "jax-donate-flow": []}
        self._bounded_memo: Dict[Tuple[str, str], Optional[str]] = {}
        self._attr_inprog: Set[Tuple[str, str]] = set()
        for sf in project.files:
            # the tracer module itself wraps jax.jit — it is the
            # instrument, not a program of the tree
            if sf.tree is not None \
                    and not sf.rel.endswith("utils/jaxtrace.py"):
                self._discover_sites(sf)
        self._index_call_sites()
        self._discover_hot()
        self._check_recompile()
        self._check_host_sync()
        self._check_donate_flow()

    # -------------------------------------------------------- discovery
    def _discover_sites(self, sf: SourceFile) -> None:
        # jit(...) calls (incl. jaxtrace.jit and partial(jax.jit, ...))
        for call in sf.call_nodes():
            is_jit, kws = _jit_call_parts(call)
            if not is_jit:
                continue
            if isinstance(getattr(call, "parent", None), ast.Call) \
                    and call.parent.func is call:  # type: ignore
                pass   # jit(f)(x): recorded below, still model the site
            cn = call_name(call)
            target = None
            tname = "<unknown>"
            args = call.args
            if cn == "partial" or cn.endswith(".partial"):
                args = call.args[1:]
            if args:
                a0 = args[0]
                if isinstance(a0, ast.Lambda):
                    target, tname = a0, "<lambda>"
                elif isinstance(a0, ast.Name):
                    tname = a0.id
                    target = self._find_def(sf, call, a0.id)
                elif isinstance(a0, ast.Attribute):
                    tname = dotted(a0)
            statics: Tuple[int, ...] = ()
            donates: Tuple[int, ...] = ()
            for kw in kws:
                if kw.arg == "static_argnums":
                    statics = _int_tuple(kw.value)
                elif kw.arg == "donate_argnums":
                    donates = _int_tuple(kw.value)
            # a decorator-position partial(jit, ...) wraps the def below
            parent = getattr(call, "parent", None)
            if isinstance(parent, _FUNC_DEFS) \
                    and call in parent.decorator_list:
                target, tname = parent, parent.name
                bound = parent.name
            else:
                bound = self._bound_key(call)
                if args and target is None and tname == "<unknown>":
                    pass
            owner = self.cg.owner_of.get(id(call), sf.rel + "::<module>")
            site = JitSite(f"{sf.rel}:{call.lineno}", sf, call, bound,
                           tname, target, statics, donates, owner)
            self.sites[site.site_id] = site
            self._call_to_site[id(call)] = site
        # bare @jit / @mod.jit decorators (no Call node)
        for node in sf.walk():
            if not isinstance(node, _FUNC_DEFS):
                continue
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    continue       # handled above via call discovery
                dn = dotted(dec)
                if dn and _is_jit_name(dn):
                    owner = self.cg.owner_of.get(
                        id(node), sf.rel + "::<module>")
                    site = JitSite(f"{sf.rel}:{dec.lineno}", sf, dec,
                                   node.name, node.name, node,
                                   owner=owner)
                    self.sites[site.site_id] = site
        # pallas_call kernel sites (ops/fused.py via jaxtrace.pallas_call):
        # modeled like jit sites — same relpath:lineno identity as the
        # runtime tracer — so jitmap shows them and a traced run's
        # observed pallas sites are statically known. No static_argnums
        # surface (every pallas parameter is a trace-time constant of
        # the ENCLOSING jit program, whose own statics the compile-key
        # model already checks), so the sites are warm by construction.
        for call in sf.call_nodes():
            if not _is_pallas_name(call_name(call)):
                continue
            tname = "<unknown>"
            if call.args:
                a0 = call.args[0]
                if isinstance(a0, ast.Name):
                    tname = a0.id
                elif isinstance(a0, ast.Attribute):
                    tname = dotted(a0)
            owner = self.cg.owner_of.get(id(call), sf.rel + "::<module>")
            site = JitSite(f"{sf.rel}:{call.lineno}", sf, call, None,
                           tname, None, owner=owner, kind="pallas")
            self.sites[site.site_id] = site
            self._call_to_site[id(call)] = site
        # declared sync points: utils.jaxtrace.fetch(...)
        for call in sf.call_nodes():
            if _is_fetch_call(call_name(call)):
                self.fetch_sites[f"{sf.rel}:{call.lineno}"] = call.lineno

    def _find_def(self, sf: SourceFile, call: ast.Call, name: str):
        """The FunctionDef a jit() wraps, searched lexically: nested
        defs of the enclosing function first, then module level."""
        scope = enclosing_function(call) or sf.tree
        for n in ast.walk(scope):
            if isinstance(n, _FUNC_DEFS) and n.name == name:
                return n
        for n in sf.walk():
            if isinstance(n, _FUNC_DEFS) and n.name == name:
                return n
        return None

    def _bound_key(self, call: ast.Call) -> Optional[str]:
        stmt = statement_of(call)
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and stmt.value is call:
            return node_key(stmt.targets[0]) or None
        if isinstance(stmt, ast.AnnAssign) and stmt.value is call:
            return node_key(stmt.target) or None
        return None

    def _index_call_sites(self) -> None:
        """Wrapper call sites, matched per file by the bound key
        (``self._packed(...)`` matches the ``._packed = jit(...)``
        binding whatever the receiver half — the node_key contract the
        local jax rules already use) or, for decorated defs, by the
        call graph's exact resolution."""
        by_file: Dict[str, List[JitSite]] = {}
        decorated: Dict[str, JitSite] = {}
        for site in self.sites.values():
            if isinstance(site.target_node, _FUNC_DEFS) \
                    and site.bound == site.target_name:
                qual = self.cg._def_qual.get(id(site.target_node))
                if qual:
                    decorated[qual] = site
            if site.bound:
                by_file.setdefault(site.sf.rel, []).append(site)
        for sf in self.project.files:
            if sf.tree is None:
                continue
            sites = by_file.get(sf.rel, [])
            keys = {s.bound: s for s in sites}
            for call in sf.call_nodes():
                if id(call) in self._call_to_site:
                    continue
                k = node_key(call.func)
                site = keys.get(k)
                if site is not None:
                    site.call_sites.append(call)
        for qual, site in decorated.items():
            for caller, csites in self.cg.calls.items():
                for cs in csites:
                    if cs.kind == "call" and not cs.fuzzy \
                            and qual in cs.targets \
                            and id(cs.node) not in self._call_to_site:
                        if cs.node not in site.call_sites:
                            site.call_sites.append(cs.node)

    # ---------------------------------------------------------- hot set
    def _discover_hot(self) -> None:
        """Hot roots: every function that dispatches a jit wrapper from
        inside a loop (a step/replay loop), plus every ``_loop`` (the
        serve dispatch threads). The hot set is their closure over
        exact call edges — where an implicit sync stalls the pipeline
        every iteration, not once."""
        wrapper_calls: Dict[str, List[ast.Call]] = {}
        for site in self.sites.values():
            for c in site.call_sites:
                owner = self.cg.owner_of.get(id(c))
                if owner:
                    wrapper_calls.setdefault(owner, []).append(c)
        # functions that (transitively, over exact edges) invoke a jit
        # wrapper: a loop that calls one of these dispatches device work
        # every iteration even when the jit call itself lives in a
        # helper (_dispatch_packed and friends)
        invokes: Set[str] = set(wrapper_calls)
        changed = True
        while changed:
            changed = False
            for qual, csites in self.cg.calls.items():
                if qual in invokes:
                    continue
                for cs in csites:
                    if cs.kind == "call" and not cs.fuzzy \
                            and any(t in invokes for t in cs.targets):
                        invokes.add(qual)
                        changed = True
                        break
        wrapper_ids = {id(c) for calls in wrapper_calls.values()
                       for c in calls}
        for qual, csites in self.cg.calls.items():
            for cs in csites:
                dispatches = id(cs.node) in wrapper_ids \
                    or (cs.kind == "call" and not cs.fuzzy
                        and any(t in invokes for t in cs.targets))
                if not dispatches:
                    continue
                cur = getattr(cs.node, "parent", None)
                while cur is not None and not isinstance(cur, _FUNC_DEFS):
                    if isinstance(cur, _LOOPS):
                        self.hot_roots.add(qual)
                        break
                    cur = getattr(cur, "parent", None)
        for qual, fi in self.cg.funcs.items():
            if fi.name == "_loop":
                self.hot_roots.add(qual)
        seen = set(self.hot_roots)
        frontier = list(seen)
        while frontier:
            q = frontier.pop()
            for cs in self.cg.calls.get(q, []):
                if cs.kind != "call" or cs.fuzzy:
                    continue
                for t in cs.targets:
                    if t not in seen and t in self.cg.funcs:
                        seen.add(t)
                        frontier.append(t)
        self.hot_funcs = seen

    # ----------------------------------------------- bounded provenance
    def _bounded(self, sf: SourceFile, func, expr,
                 depth: int = 0) -> Optional[str]:
        """None when ``expr`` is provably drawn from a bounded set;
        otherwise a human-readable reason naming the unbounded source."""
        if depth > _PROV_DEPTH:
            return "provenance chain too deep"
        if isinstance(expr, ast.Constant):
            return None
        if isinstance(expr, (ast.Tuple, ast.List)):
            for e in expr.elts:
                r = self._bounded(sf, func, e, depth + 1)
                if r:
                    return r
            return None
        if isinstance(expr, ast.UnaryOp):
            return self._bounded(sf, func, expr.operand, depth + 1)
        if isinstance(expr, ast.BinOp):
            return self._bounded(sf, func, expr.left, depth + 1) \
                or self._bounded(sf, func, expr.right, depth + 1)
        if isinstance(expr, (ast.BoolOp,)):
            for e in expr.values:
                r = self._bounded(sf, func, e, depth + 1)
                if r:
                    return r
            return None
        if isinstance(expr, ast.Compare):
            return None                     # a bool: two values
        if isinstance(expr, ast.IfExp):
            return self._bounded(sf, func, expr.body, depth + 1) \
                or self._bounded(sf, func, expr.orelse, depth + 1)
        if isinstance(expr, ast.Call):
            cn = call_name(expr)
            tail = cn.rsplit(".", 1)[-1]
            if tail in _BOUNDING_CALLS:
                return None                 # cap()/bucket(): quantized
            if tail == "len":
                return "len(...) is data-dependent"
            if tail in ("int", "min", "max", "abs", "round"):
                for e in expr.args:
                    r = self._bounded(sf, func, e, depth + 1)
                    if r:
                        return r
                return None
            if tail == "bool":
                return None
            return f"value of {cn or '<dynamic>'}(...) not provably bounded"
        if isinstance(expr, ast.Attribute):
            chain = dotted(expr)
            parts = chain.split(".") if chain else []
            if any(p in _CONFIG_SEGMENTS for p in parts[:-1]):
                return None                 # config-derived constant
            if expr.attr in _DATA_ATTRS:
                return f"`.{expr.attr}` is data-dependent — route it " \
                       f"through a ShapeSchedule cap or bucket rung"
            return self._attr_bounded(sf, expr.attr, depth)
        if isinstance(expr, ast.Name):
            return self._name_bounded(sf, func, expr.id, depth)
        if isinstance(expr, ast.Subscript):
            return "subscripted value (payload/tuple element) not " \
                   "provably bounded"
        if isinstance(expr, ast.Starred):
            return self._bounded(sf, func, expr.value, depth + 1)
        return f"{type(expr).__name__} expression not provably bounded"

    def _attr_bounded(self, sf: SourceFile, attr: str,
                      depth: int) -> Optional[str]:
        """``<obj>.attr`` is bounded when every assignment to ``.attr``
        in the same file has a bounded RHS (and at least one exists —
        an attribute this file never sets is somebody else's data)."""
        memo_key = (sf.rel, "." + attr)
        if memo_key in self._bounded_memo:
            return self._bounded_memo[memo_key]
        if memo_key in self._attr_inprog:
            return None                     # optimistic on cycles
        self._attr_inprog.add(memo_key)
        try:
            stores = []
            for node in sf.walk():
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and node_key(node.targets[0]) == "." + attr:
                    stores.append(node)
                elif isinstance(node, ast.AnnAssign) \
                        and node_key(node.target) == "." + attr \
                        and node.value is not None:
                    stores.append(node)
            if not stores:
                res: Optional[str] = \
                    f"`.{attr}` is never assigned in {sf.rel} — " \
                    f"not provably bounded"
            else:
                res = None
                for st in stores:
                    f = enclosing_function(st)
                    res = self._bounded(sf, f, st.value, depth + 1)
                    if res:
                        res = f"`.{attr}` assigned from an unbounded " \
                              f"value at {sf.rel}:{st.lineno} ({res})"
                        break
            self._bounded_memo[memo_key] = res
            return res
        finally:
            self._attr_inprog.discard(memo_key)

    def _name_bounded(self, sf: SourceFile, func, name: str,
                      depth: int) -> Optional[str]:
        # local / enclosing assignments first; a tuple-unpack target
        # remembers its POSITION so `(a, b) = payload` can check just
        # the matching element of the caller's literal payload tuple
        scope = func if func is not None else sf.tree
        # self-referential rebinding (`u_cap = max(u_cap, bucket(n))`)
        # recurses through itself: optimistic on cycles — the base
        # binding and every step still get checked on their own
        cyc_key = (f"name@{id(scope)}", name)
        if cyc_key in self._attr_inprog:
            return None
        self._attr_inprog.add(cyc_key)
        try:
            return self._name_bounded_inner(sf, func, scope, name, depth)
        finally:
            self._attr_inprog.discard(cyc_key)

    def _name_bounded_inner(self, sf: SourceFile, func, scope, name: str,
                            depth: int) -> Optional[str]:
        assigns: List[Tuple[ast.AST, Optional[int]]] = []
        for node in ast.walk(scope):
            if isinstance(node, _FUNC_DEFS) and node is not scope:
                continue
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        assigns.append((node.value, None))
                    elif isinstance(t, ast.Tuple):
                        for pos, e in enumerate(t.elts):
                            if isinstance(e, ast.Name) and e.id == name:
                                assigns.append((node.value, pos))
            elif isinstance(node, ast.AugAssign) \
                    and isinstance(node.target, ast.Name) \
                    and node.target.id == name:
                return f"`{name}` is an accumulating local " \
                       f"(augmented assignment)"
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                for e in ast.walk(node.target):
                    if isinstance(e, ast.Name) and e.id == name:
                        return f"`{name}` iterates a runtime sequence"
        if assigns:
            for v, pos in assigns:
                r = self._elem_bounded(sf, func, v, pos, depth)
                if r:
                    return r
            return None
        # a parameter: bounded iff every exact caller passes bounded
        if isinstance(func, _FUNC_DEFS):
            params = [a.arg for a in (func.args.posonlyargs
                                      + func.args.args)]
            if name in params:
                return self._param_bounded(sf, func, params.index(name),
                                           name, depth)
            # closure variable: resolve in the lexically enclosing def
            outer = enclosing_function(func)
            if outer is not None:
                return self._name_bounded(sf, outer, name, depth + 1)
        # module-level constant
        mod_assigns = [
            node.value for node in sf.tree.body
            if isinstance(node, ast.Assign)
            for t in node.targets
            if isinstance(t, ast.Name) and t.id == name
        ]
        if mod_assigns:
            for v in mod_assigns:
                r = self._bounded(sf, func, v, depth + 1)
                if r:
                    return r
            return None
        return f"`{name}` has no visible bounded binding"

    def _elem_bounded(self, sf: SourceFile, func, value, pos: Optional[int],
                      depth: int) -> Optional[str]:
        """Boundedness of one unpacked element: select ``elts[pos]``
        when the value is a literal tuple, and thread the position
        through a parameter so ``(a, b) = payload`` checks element
        ``pos`` of each caller's literal payload tuple."""
        if pos is not None and isinstance(value, (ast.Tuple, ast.List)) \
                and pos < len(value.elts):
            return self._bounded(sf, func, value.elts[pos], depth + 1)
        if pos is not None and isinstance(value, ast.Name) \
                and isinstance(func, _FUNC_DEFS):
            params = [a.arg for a in (func.args.posonlyargs
                                      + func.args.args)]
            local_tuples = [
                node.value for node in ast.walk(func)
                if isinstance(node, ast.Assign)
                for t in node.targets
                if isinstance(t, ast.Name) and t.id == value.id
            ]
            if local_tuples:
                for v in local_tuples:
                    r = self._elem_bounded(sf, func, v, pos, depth + 1)
                    if r:
                        return r
                return None
            if value.id in params:
                return self._param_bounded(sf, func,
                                           params.index(value.id),
                                           value.id, depth, elem=pos)
        return self._bounded(sf, func, value, depth + 1)

    def _param_bounded(self, sf: SourceFile, func, idx: int, name: str,
                       depth: int,
                       elem: Optional[int] = None) -> Optional[str]:
        qual = self.cg._def_qual.get(id(func))
        if qual is None:
            return f"parameter `{name}` of an unindexed function"
        memo_key = (qual, name if elem is None else f"{name}[{elem}]")
        if memo_key in self._bounded_memo:
            return self._bounded_memo[memo_key]
        if memo_key in self._attr_inprog:
            return None
        self._attr_inprog.add(memo_key)
        try:
            fi = self.cg.funcs.get(qual)
            # methods: caller positional j maps to param j+1 — keyed on
            # the first parameter being self/cls (a nested function
            # keeps its class CONTEXT in the callgraph but receives no
            # implicit receiver)
            shift = _self_shift(func, fi)
            callers = []
            for caller_q, csites in self.cg.calls.items():
                for cs in csites:
                    if cs.kind == "call" and not cs.fuzzy \
                            and qual in cs.targets:
                        callers.append((caller_q, cs.node))
            if not callers:
                res: Optional[str] = \
                    f"parameter `{name}` has no resolvable callers"
            else:
                res = None
                for caller_q, cnode in callers:
                    pos = idx - shift
                    arg_expr = None
                    if 0 <= pos < len(cnode.args):
                        arg_expr = cnode.args[pos]
                    else:
                        for kw in cnode.keywords:
                            if kw.arg == name:
                                arg_expr = kw.value
                    if arg_expr is None:
                        continue            # defaulted: checked below
                    c_fi = self.cg.funcs.get(caller_q)
                    c_sf = c_fi.sf if c_fi is not None else sf
                    c_func = c_fi.node if c_fi is not None else None
                    if elem is None:
                        r = self._bounded(c_sf, c_func, arg_expr,
                                          depth + 1)
                    else:
                        r = self._elem_bounded(c_sf, c_func, arg_expr,
                                               elem, depth + 1)
                    if r:
                        res = f"caller {caller_q.split('::')[-1]} at " \
                              f"{c_sf.rel}:{cnode.lineno} passes " \
                              f"`{name}` from an unbounded value ({r})"
                        break
            self._bounded_memo[memo_key] = res
            return res
        finally:
            self._attr_inprog.discard(memo_key)

    # --------------------------------------------------- rule: recompile
    def _check_recompile(self) -> None:
        out = self._findings["jax-recompile"]
        for sid in sorted(self.sites):
            site = self.sites[sid]
            if site.kind == "pallas":
                # a pallas_call is (re)built per TRACE of its enclosing
                # jit program — immediate invocation and construction
                # inside traced loops are the API's normal shape; the
                # compile cache that matters belongs to the enclosing
                # jit site, which this rule checks on its own
                continue
            call = site.node
            # jit(f)(x): a fresh wrapper (and compile-cache entry) per
            # invocation — bind the wrapper once instead
            parent = getattr(call, "parent", None)
            if isinstance(call, ast.Call) and isinstance(parent, ast.Call) \
                    and parent.func is call:
                out.append(site.sf.finding(
                    "jax-recompile", call,
                    f"jit wrapper for `{site.target_name}` is created "
                    f"and invoked in one expression — every execution "
                    f"builds a fresh wrapper and compile-cache entry; "
                    f"bind the jitted function once and reuse it"))
            # jit(...) inside a loop: one wrapper per iteration
            cur = parent
            while cur is not None and not isinstance(cur, _FUNC_DEFS):
                if isinstance(cur, _LOOPS):
                    out.append(site.sf.finding(
                        "jax-recompile", call,
                        f"jit wrapper for `{site.target_name}` is "
                        f"created inside a loop — each iteration "
                        f"compiles from scratch; hoist the jit() out"))
                    break
                cur = getattr(cur, "parent", None)
            if not site.statics:
                continue
            for cs in site.call_sites:
                func = enclosing_function(cs)
                nonhash: List[int] = []
                loose: List[Tuple[int, str]] = []
                for i in sorted(site.statics):
                    if i >= len(cs.args):
                        continue
                    arg = cs.args[i]
                    if isinstance(arg, (ast.List, ast.Dict, ast.Set)) \
                            or (isinstance(arg, ast.Call)
                                and call_name(arg).rsplit(".", 1)[-1]
                                in ("array", "asarray")):
                        site.unbounded.append(
                            (cs, i, "non-hashable static"))
                        nonhash.append(i)
                        continue
                    reason = self._bounded(self._sf_of(cs, site),
                                           func, arg)
                    if reason:
                        site.unbounded.append((cs, i, reason))
                        loose.append((i, reason))
                if nonhash:
                    out.append(self._sf_of(cs, site).finding(
                        "jax-recompile", cs,
                        f"static_argnums position(s) {nonhash} of "
                        f"`{site.target_name}` receive non-hashable "
                        f"values — jit statics must be hashable "
                        f"(TypeError at trace time)"))
                if loose:
                    # one finding per CALL SITE: one reasoned pragma on
                    # the dispatch line covers every loose static there
                    positions = [i for i, _ in loose]
                    out.append(self._sf_of(cs, site).finding(
                        "jax-recompile", cs,
                        f"static_argnums position(s) {positions} of "
                        f"jitted `{site.target_name}` ({sid}) are not "
                        f"provably drawn from a bounded set: "
                        f"{loose[0][1]} — every distinct value compiles "
                        f"a new program; route them through a "
                        f"ShapeSchedule cap / bucket rung, or suppress "
                        f"with the boundedness argument"))

    def _sf_of(self, node, site: JitSite) -> SourceFile:
        # call sites matched by bound key live in the site's own file;
        # decorated-def call sites can live anywhere in the project
        owner = self.cg.owner_of.get(id(node))
        if owner:
            fi = self.cg.funcs.get(owner)
            if fi is not None:
                return fi.sf
        return site.sf

    # --------------------------------------------------- rule: host sync
    def _check_host_sync(self) -> None:
        out = self._findings["jax-host-sync"]
        wrapper_by_call = self._call_to_wrapper_index()
        # which hot functions RETURN device values (callers taint their
        # results), and which parameters are fed device values — one
        # fixpoint over the hot set
        device_returns: Set[str] = set()
        param_taint: Dict[str, Set[str]] = {}
        changed = True
        rounds = 0
        while changed and rounds < 6:
            changed = False
            rounds += 1
            for qual in sorted(self.hot_funcs):
                fi = self.cg.funcs.get(qual)
                if fi is None or fi.node is None:
                    continue
                tainted = self._taint_names(
                    fi, wrapper_by_call, device_returns,
                    param_taint.get(qual, set()))
                # returns a device value?
                for n in ast.walk(fi.node):
                    if isinstance(n, ast.Return) and n.value is not None \
                            and self._expr_tainted(
                                n.value, tainted, wrapper_by_call,
                                device_returns, fi):
                        if qual not in device_returns:
                            device_returns.add(qual)
                            changed = True
                        break
                # propagate into callee parameters
                for cs in self.cg.calls.get(qual, []):
                    if cs.kind != "call" or cs.fuzzy:
                        continue
                    for t in cs.targets:
                        if t not in self.hot_funcs:
                            continue
                        ti = self.cg.funcs.get(t)
                        if ti is None or ti.node is None:
                            continue
                        params = [a.arg for a in
                                  (ti.node.args.posonlyargs
                                   + ti.node.args.args)]
                        shift = _self_shift(ti.node, ti)
                        for j, a in enumerate(cs.node.args):
                            pj = j + shift
                            if pj < len(params) and self._expr_tainted(
                                    a, tainted, wrapper_by_call,
                                    device_returns, fi):
                                cur = param_taint.setdefault(t, set())
                                if params[pj] not in cur:
                                    cur.add(params[pj])
                                    changed = True
        # flag sinks
        for qual in sorted(self.hot_funcs):
            fi = self.cg.funcs.get(qual)
            if fi is None or fi.node is None:
                continue
            if fi.sf.rel.endswith("utils/jaxtrace.py"):
                continue    # fetch() IS the declared sync

            tainted = self._taint_names(
                fi, wrapper_by_call, device_returns,
                param_taint.get(qual, set()))
            for call in ast.walk(fi.node):
                if not isinstance(call, ast.Call):
                    continue
                cn = call_name(call)
                tail = cn.rsplit(".", 1)[-1]
                sink = None
                if cn in _COERCIONS and call.args:
                    sink = call.args[0]
                elif tail in _NP_SINKS and call.args \
                        and cn.partition(".")[0] in ("np", "numpy"):
                    sink = call.args[0]
                elif isinstance(call.func, ast.Attribute) \
                        and call.func.attr in _ITEM_SINKS:
                    sink = call.func.value
                elif cn == "print":
                    for a in call.args:
                        if self._expr_tainted(a, tainted, wrapper_by_call,
                                              device_returns, fi):
                            sink = a
                            break
                if sink is None:
                    continue
                if not self._expr_tainted(sink, tainted, wrapper_by_call,
                                          device_returns, fi):
                    continue
                if self._inside_fetch(call):
                    continue
                what = dotted(sink) or type(sink).__name__
                out.append(fi.sf.finding(
                    "jax-host-sync", call,
                    f"device value `{what}` is coerced to host by "
                    f"`{cn or call.func.attr}` inside the hot "
                    f"step/dispatch path "
                    f"({qual.split('::', 1)[1]}) — an implicit blocking "
                    f"device->host sync every iteration; batch the "
                    f"fetch, or declare the sync with "
                    f"utils.jaxtrace.fetch(x) so the runtime tracer "
                    f"audits it"))

    def _call_to_wrapper_index(self) -> Dict[int, JitSite]:
        idx: Dict[int, JitSite] = {}
        for site in self.sites.values():
            for c in site.call_sites:
                idx[id(c)] = site
        return idx

    def _taint_names(self, fi, wrapper_by_call, device_returns,
                     pre_tainted: Set[str]) -> Set[str]:
        """Names in ``fi`` holding device values: results of jit
        wrapper calls / device-returning hot helpers, via (tuple)
        assignment, plus device-tainted parameters."""
        tainted = set(pre_tainted)
        for _ in range(3):                   # tiny local fixpoint
            grew = False
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Assign):
                    continue
                if not self._expr_tainted(node.value, tainted,
                                          wrapper_by_call,
                                          device_returns, fi):
                    continue
                for t in node.targets:
                    for e in ast.walk(t):
                        if isinstance(e, ast.Name) \
                                and e.id not in tainted:
                            tainted.add(e.id)
                            grew = True
            if not grew:
                break
        return tainted

    def _expr_tainted(self, expr, tainted: Set[str], wrapper_by_call,
                      device_returns, fi) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in tainted
        if isinstance(expr, ast.Call):
            if id(expr) in wrapper_by_call:
                return True
            cn = call_name(expr)
            tail = cn.rsplit(".", 1)[-1]
            if _is_fetch_call(cn):
                return False                 # declared sync: host after
            if tail in _NP_SINKS | _COERCIONS | _ITEM_SINKS:
                return False                 # already host
            # calls into device-returning hot helpers
            owner = self.cg.owner_of.get(id(expr))
            if owner is not None:
                cs = self.cg.by_node.get(id(expr))
                if cs is not None and cs.kind == "call" and not cs.fuzzy:
                    if any(t in device_returns for t in cs.targets):
                        return True
            return False
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self._expr_tainted(e, tainted, wrapper_by_call,
                                          device_returns, fi)
                       for e in expr.elts)
        if isinstance(expr, ast.Subscript):
            return self._expr_tainted(expr.value, tainted,
                                      wrapper_by_call, device_returns, fi)
        if isinstance(expr, ast.Starred):
            return self._expr_tainted(expr.value, tainted,
                                      wrapper_by_call, device_returns, fi)
        if isinstance(expr, ast.BinOp):
            return self._expr_tainted(expr.left, tainted, wrapper_by_call,
                                      device_returns, fi) \
                or self._expr_tainted(expr.right, tainted,
                                      wrapper_by_call, device_returns, fi)
        return False

    @staticmethod
    def _inside_fetch(node) -> bool:
        cur = getattr(node, "parent", None)
        while cur is not None and not isinstance(cur, ast.stmt):
            if isinstance(cur, ast.Call) and _is_fetch_call(call_name(cur)):
                return True
            cur = getattr(cur, "parent", None)
        return False

    # ------------------------------------------------- rule: donate flow
    def _check_donate_flow(self) -> None:
        out = self._findings["jax-donate-flow"]
        for sid in sorted(self.sites):
            site = self.sites[sid]
            if not site.donates:
                continue
            overlap = set(site.donates) & set(site.statics)
            if overlap:
                out.append(site.sf.finding(
                    "jax-donate-flow", site.node,
                    f"donate_argnums {sorted(overlap)} of "
                    f"`{site.target_name}` are also static_argnums — "
                    f"statics are compile-time values, not buffers; "
                    f"nothing can be donated there"))
            if isinstance(site.target_node, _FUNC_DEFS):
                npos = len(site.target_node.args.posonlyargs) \
                    + len(site.target_node.args.args)
                past = [i for i in site.donates if i >= npos]
                if past:
                    out.append(site.sf.finding(
                        "jax-donate-flow", site.node,
                        f"donate_argnums {past} of `{site.target_name}` "
                        f"point past its {npos} positional parameters — "
                        f"the donation silently never happens"))
            for cs in site.call_sites:
                names = {}
                for j, a in enumerate(cs.args):
                    if isinstance(a, ast.Name):
                        names.setdefault(a.id, []).append(j)
                for nm, positions in names.items():
                    don = [j for j in positions if j in site.donates]
                    other = [j for j in positions
                             if j not in site.donates]
                    if don and other:
                        out.append(self._sf_of(cs, site).finding(
                            "jax-donate-flow", cs,
                            f"`{nm}` is passed to `{site.target_name}` "
                            f"at donated position {don[0]} AND "
                            f"non-donated position {other[0]} — the "
                            f"non-donated alias reads a deleted buffer"))
                self._cross_edge_donate(site, cs, out)

    def _cross_edge_donate(self, site: JitSite, cs: ast.Call,
                           out: List[Finding]) -> None:
        """The donated argument is the enclosing function's parameter:
        exact callers must not read the buffer they passed after the
        call returns (the interprocedural half of jax-donate)."""
        func = enclosing_function(cs)
        if not isinstance(func, _FUNC_DEFS):
            return
        qual = self.cg._def_qual.get(id(func))
        if qual is None:
            return
        fi = self.cg.funcs.get(qual)
        params = [a.arg for a in (func.args.posonlyargs + func.args.args)]
        shift = _self_shift(func, fi)
        stmt = statement_of(cs)
        # x = f(x) rebinding inside the wrapper's own function makes the
        # flow safe for the LOCAL name; the caller's buffer is donated
        # regardless — but only a param that is NOT rebound into the
        # return value propagates the hazard conservatively: we flag
        # only when the callee neither rebinds nor returns the result
        for i in sorted(site.donates):
            if i >= len(cs.args) or not isinstance(cs.args[i], ast.Name):
                continue
            pname = cs.args[i].id
            if pname not in params:
                continue
            pidx = params.index(pname)
            for caller_q, csites in self.cg.calls.items():
                for outer in csites:
                    if outer.kind != "call" or outer.fuzzy \
                            or qual not in outer.targets:
                        continue
                    pos = pidx - shift
                    if not (0 <= pos < len(outer.node.args)):
                        continue
                    passed = outer.node.args[pos]
                    if not isinstance(passed, ast.Name):
                        continue
                    c_fi = self.cg.funcs.get(caller_q)
                    if c_fi is None or c_fi.node is None:
                        continue
                    ostmt = statement_of(outer.node)
                    rebound: Set[str] = set()
                    if isinstance(ostmt, ast.Assign):
                        for t in ostmt.targets:
                            for n in ast.walk(t):
                                if isinstance(n, ast.Name):
                                    rebound.add(n.id)
                    if passed.id in rebound:
                        continue
                    for n in ast.walk(c_fi.node):
                        if isinstance(n, ast.Name) \
                                and n.id == passed.id \
                                and isinstance(n.ctx, ast.Load) \
                                and n.lineno > ostmt.end_lineno:
                            out.append(c_fi.sf.finding(
                                "jax-donate-flow", n,
                                f"`{passed.id}` is read here after "
                                f"being passed to "
                                f"{qual.split('::', 1)[1]} (line "
                                f"{ostmt.lineno}), which donates it to "
                                f"jitted `{site.target_name}` "
                                f"(donate_argnums={i}) — the buffer is "
                                f"deleted inside the callee; rebind or "
                                f"stop reading it"))
                            break

    # ------------------------------------------------------------ views
    def known_warm(self) -> Set[str]:
        """Jit sites whose every static at every call site is bounded —
        or whose unbounded call sites all carry a reasoned
        jax-recompile suppression. These are the sites the tier-1
        JAXTRACE gate accepts in the steady state."""
        out = set()
        by_rel = {sf.rel: sf for sf in self.project.files}
        for sid, site in self.sites.items():
            ok = True
            for cs, _i, _r in site.unbounded:
                sf = self._sf_of(cs, site)
                sf = by_rel.get(sf.rel, sf)
                if "jax-recompile" not in sf.suppressions.get(
                        cs.lineno, set()):
                    ok = False
                    break
            if ok:
                out.add(sid)
        return out

    def declared_fetches(self) -> Set[str]:
        return set(self.fetch_sites)

    def to_json(self) -> dict:
        return {
            "sites": {
                sid: {
                    "target": site.target_name,
                    "bound": site.bound,
                    "kind": site.kind,
                    "static_argnums": list(site.statics),
                    "donate_argnums": list(site.donates),
                    "call_sites": sorted(
                        {f"{self._sf_of(c, site).rel}:{c.lineno}"
                         for c in site.call_sites}),
                    "warm_bounded": sid in self.known_warm(),
                    "unbounded": [
                        {"call": f"{self._sf_of(c, site).rel}:{c.lineno}",
                         "static": i, "reason": r}
                        for c, i, r in site.unbounded],
                }
                for sid, site in sorted(self.sites.items())
            },
            "fetch_sites": sorted(self.fetch_sites),
            "hot_roots": sorted(self.hot_roots),
        }


def get_jax_model(project: Project) -> JaxModel:
    m = getattr(project, "_jax_model", None)
    if m is None or m.project is not project:
        m = JaxModel(project)
        project._jax_model = m  # type: ignore[attr-defined]
    return m


# ---------------------------------------------------------------------------
# rule registrations


@rule("jax-recompile",
      "jit statics must come from a bounded set (the compile-key model)",
      cross=True)
def check_jax_recompile(project: Project) -> List[Finding]:
    return list(get_jax_model(project)._findings["jax-recompile"])


@rule("jax-host-sync",
      "no implicit device->host coercions on the hot dispatch path",
      cross=True)
def check_jax_host_sync(project: Project) -> List[Finding]:
    return list(get_jax_model(project)._findings["jax-host-sync"])


@rule("jax-donate-flow",
      "donation declarations must alias, and donated buffers must not "
      "be read by callers", cross=True)
def check_jax_donate_flow(project: Project) -> List[Finding]:
    return list(get_jax_model(project)._findings["jax-donate-flow"])


# --------------------------------------------------------------- local rule


_F64 = ("float64",)


def _mentions_float64(node) -> bool:
    if isinstance(node, ast.Attribute) and node.attr in _F64:
        return True
    if isinstance(node, ast.Constant) and node.value == "float64":
        return True
    return False


@rule("jax-dtype64",
      "no float64 drift into the fp32 device pipeline; no int32 "
      "accumulators on overflow paths")
def check_jax_dtype64(sf: SourceFile) -> List[Finding]:
    from .localrules import _jitted_functions
    out: List[Finding] = []
    # float64 inside jit targets: one float64 intermediate promotes the
    # whole fp32 computation on device
    for fn in _jitted_functions(sf):
        for n in ast.walk(ast.Module(body=fn.body, type_ignores=[])):
            if _mentions_float64(n):
                out.append(sf.finding(
                    "jax-dtype64", n,
                    f"float64 inside jitted `{fn.name}` promotes the "
                    f"fp32 pipeline (or fails under the default x64 "
                    f"disable) — keep device math in float32, or do "
                    f"the float64 reduction on host"))
    # dtype=float64 on jnp device-array creation anywhere
    for call in sf.call_nodes():
        cn = call_name(call)
        if not cn.startswith("jnp."):
            continue
        for kw in call.keywords:
            if kw.arg == "dtype" and _mentions_float64(kw.value):
                out.append(sf.finding(
                    "jax-dtype64", call,
                    f"`{cn}(dtype=float64)` creates a float64 device "
                    f"array — the fp32 pipeline promotes on first "
                    f"contact; use float32 (host-side float64 staging "
                    f"is fine, convert before device_put)"))
    # int32 accumulators in loops: row counters overflow past 2^31
    int32_names: Dict[str, Set[str]] = {}
    for fn in [n for n in sf.walk() if isinstance(n, _FUNC_DEFS)]:
        names: Set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            t = node.targets[0]
            if not isinstance(t, ast.Name):
                continue
            v = node.value
            is32 = False
            if isinstance(v, ast.Call):
                vn = call_name(v)
                if vn.rsplit(".", 1)[-1] == "int32":
                    is32 = True
                for kw in v.keywords:
                    if kw.arg == "dtype" and (
                            (isinstance(kw.value, ast.Attribute)
                             and kw.value.attr == "int32")
                            or (isinstance(kw.value, ast.Constant)
                                and kw.value.value == "int32")):
                        is32 = True
            if is32:
                names.add(t.id)
        if names:
            int32_names[fn.name] = names
            for node in ast.walk(fn):
                if isinstance(node, ast.AugAssign) \
                        and isinstance(node.target, ast.Name) \
                        and node.target.id in names:
                    cur = getattr(node, "parent", None)
                    in_loop = False
                    while cur is not None and cur is not fn:
                        if isinstance(cur, _LOOPS):
                            in_loop = True
                            break
                        cur = getattr(cur, "parent", None)
                    if in_loop:
                        out.append(sf.finding(
                            "jax-dtype64", node,
                            f"`{node.target.id}` is an int32-created "
                            f"accumulator incremented in a loop — row "
                            f"counters overflow past 2^31 on "
                            f"production-size streams; count in int64"))
    return out
