"""Command-line front end: ``python -m difacto_tpu.analysis`` /
``tools/lint.py`` / ``make lint``.

Defaults match this repo's layout: lint ``difacto_tpu/ tools/
launch.py bench.py`` against the checked-in baseline at
``.lint-baseline.json`` (when present). ``tests/`` and ``docs/`` are
*reference corpora* for the cross-file registry rules, not lint
targets — the test suite deliberately tears sockets and swallows
exceptions.

Exit codes: 0 clean, 1 findings, 2 usage/internal error (core.py).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from . import core

DEFAULT_PATHS = ["difacto_tpu", "tools", "launch.py", "bench.py"]
DEFAULT_BASELINE = ".lint-baseline.json"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="difacto-lint",
        description="AST-based project analyzer (docs/static_analysis.md)")
    p.add_argument("paths", nargs="*", default=None,
                   help=f"files/dirs to lint (default: {DEFAULT_PATHS})")
    p.add_argument("--root", default=".",
                   help="project root (docs/, tests/, baseline live here)")
    p.add_argument("--format",
                   choices=("text", "json", "github", "sarif"),
                   default="text", dest="fmt")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: <root>/{DEFAULT_BASELINE} "
                        f"when it exists; 'none' disables)")
    p.add_argument("--write-baseline", action="store_true",
                   help="grandfather all current findings into the "
                        "baseline and exit 0 (make lint-baseline)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule ids to run (default: all)")
    p.add_argument("--no-cross", action="store_true",
                   help="skip cross-file registry rules (partial runs)")
    p.add_argument("--changed-only", action="store_true",
                   help="incremental mode: run LOCAL rules only on files "
                        "changed vs the merge-base (cross-file and "
                        "concurrency rules still see the whole tree); "
                        "make lint-changed")
    p.add_argument("--base", default=None,
                   help="merge-base ref for --changed-only (default: "
                        "origin/main, then main)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="text format: also print suppressed/baselined")
    return p


def changed_files(root, base=None):
    """Repo-relative ``.py`` paths changed vs the merge-base with
    ``base`` (default: origin/main, then main), plus worktree/index
    edits and untracked files — the --changed-only lint set. Returns
    None when git is unusable (callers fall back to a full run)."""
    import subprocess

    def git(*args):
        return subprocess.run(["git", "-C", str(root), *args],
                              capture_output=True, text=True, timeout=30)

    try:
        if git("rev-parse", "--git-dir").returncode != 0:
            return None
        names = set()
        merge_base = None
        for ref in ([base] if base else ["origin/main", "main"]):
            r = git("merge-base", "HEAD", ref)
            if r.returncode == 0:
                merge_base = r.stdout.strip()
                break
        if merge_base:
            r = git("diff", "--name-only", merge_base, "HEAD")
            if r.returncode == 0:
                names |= set(r.stdout.split())
        r = git("diff", "--name-only", "HEAD")   # worktree + index
        if r.returncode == 0:
            names |= set(r.stdout.split())
        r = git("ls-files", "--others", "--exclude-standard")
        if r.returncode == 0:
            names |= set(r.stdout.split())
        return {n for n in names if n.endswith(".py")}
    except (OSError, subprocess.SubprocessError):
        return None


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    rules = core.all_rules()
    if args.list_rules:
        for rid, r in sorted(rules.items()):
            scope = "cross" if r.cross else "local"
            print(f"{rid:18s} [{scope}] {r.summary}")
        return 0

    rule_ids = None
    if args.rules:
        rule_ids = [s.strip() for s in args.rules.split(",") if s.strip()]
    elif args.no_cross:
        rule_ids = [rid for rid, r in rules.items() if not r.cross]
    if args.no_cross and args.rules:
        rule_ids = [rid for rid in rule_ids
                    if rid in rules and not rules[rid].cross]

    root = Path(args.root).resolve()
    paths = args.paths or [p for p in DEFAULT_PATHS if (root / p).exists()]
    local_files = None
    if args.changed_only:
        local_files = changed_files(root, args.base)
        if local_files is None:
            print("difacto-lint: --changed-only needs git; running the "
                  "full tree", file=sys.stderr)
    try:
        project = core.Project(root, paths)
        res = core.run_project(project, rule_ids,
                               local_files=local_files)
    except ValueError as e:
        print(f"difacto-lint: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline
    if baseline_path is None:
        cand = root / DEFAULT_BASELINE
        baseline_path = str(cand) if cand.exists() else "none"
    if args.write_baseline:
        target = baseline_path if baseline_path != "none" \
            else str(root / DEFAULT_BASELINE)
        n = core.write_baseline(res, target)
        print(f"difacto-lint: baselined {n} finding(s) -> {target}")
        return 0
    if baseline_path != "none":
        try:
            core.apply_baseline(res, core.load_baseline(baseline_path))
        except (ValueError, OSError) as e:
            print(f"difacto-lint: bad baseline: {e}", file=sys.stderr)
            return 2

    if args.fmt == "json":
        print(core.format_json(res))
    elif args.fmt == "github":
        print(core.format_github(res))
    elif args.fmt == "sarif":
        print(core.format_sarif(res))
    else:
        print(core.format_text(res, verbose=args.verbose))
    return 1 if res.active else 0


if __name__ == "__main__":
    sys.exit(main())
